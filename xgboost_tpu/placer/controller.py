"""Placement controller: observed load -> target assignment -> deltas.

One control loop (SERVING.md "Autonomous placement"):

1. **Lease** — ``POST /placer/lease`` on the router grants a
   single-holder lease; a standby placer that is refused skips the
   tick.  The holder renews every tick, so holder death hands over
   within ``placer_lease_sec``.
2. **Observe** — the router's ``/metrics`` exposition yields per-tenant
   request counters (``xgbtpu_tenant_requests_total{model=}``, parsed
   by :func:`~xgboost_tpu.fleet.rollout.scrape_labeled_samples`);
   counter deltas over the tick interval become per-tenant EWMA rates.
   ``/fleet/members`` yields the replica set, each replica's catalog
   advertisement, and its device budget (heartbeat payload).
3. **Plan** — greedy bin-pack, hottest tenant first: every managed
   tenant gets ``placer_replication`` hosts (``placer_hot_replication``
   once its load share reaches ``placer_hot_fraction``), existing
   assignments are kept wherever still valid (stickiness bounds
   remap), and NEW slots are anchored on the
   :class:`~xgboost_tpu.fleet.membership.HashRing` over replica ids —
   so a fleet change moves only the tenants whose anchors moved, never
   a full reshuffle.  Device budgets are respected where possible; a
   tenant that fits nowhere is still placed (least-used replica) and
   flagged, because an over-budget replica degrades while an orphaned
   tenant hard-404s.
4. **Converge** — diff target against the fleet's ADVERTISED hosting
   and push manifest deltas: attach = ``POST /-/catalog {"add": ...}``
   then ``POST /-/reload?model=`` to warm; detach only once the model
   has enough OTHER in-rotation advertisers (a detach can never orphan
   a tenant).  The router's map follows within one heartbeat (the
   heartbeat-diff path in fleet/membership.py).
5. **Snapshot** — the target plan is written through
   ``atomic_write``+CRC on every change and restored on startup, so a
   SIGKILL'd placer resumes ITS OWN last plan instead of replanning
   from a cold load map; the plan is also recorded on the router
   (``POST /placer/plan``) for observability and takeover hand-off.

Every decision is an obs event (``placer.*``) and every tick a span.
"""

from __future__ import annotations

import json
import os
import socket
import time
import urllib.request
from typing import Dict, List, Optional

from xgboost_tpu.fleet.membership import HashRing
from xgboost_tpu.fleet.rollout import scrape_labeled_samples
from xgboost_tpu.obs import event, span
from xgboost_tpu.obs.metrics import placer_metrics, swallowed_error

#: the router-side counter family the load signal is scraped from
TENANT_LOAD_FAMILY = "xgbtpu_tenant_requests_total"


class PlacementController:
    """Drives one fleet router's catalog placement.

    ``manifest`` is the set of tenant models under management
    (name -> model file path, same shape as ``catalog=``); models
    OUTSIDE it (each replica's default, other operators' tenants) are
    never touched.  Call :meth:`tick` on a cadence (or use
    :func:`run_placer`); each tick is self-contained and idempotent —
    a converged fleet produces no pushes."""

    def __init__(self, router_url: str, manifest: Dict[str, str],
                 plan_path: str = "", placer_id: str = "",
                 tick_sec: float = 2.0, lease_sec: float = 10.0,
                 replication: int = 1, hot_replication: int = 2,
                 hot_fraction: float = 0.5, load_alpha: float = 0.3,
                 vnodes: int = 64, http_timeout: float = 5.0):
        self.router_url = router_url.rstrip("/")
        self.manifest = {str(k): str(v) for k, v in manifest.items()}
        self.plan_path = str(plan_path)
        self.placer_id = placer_id or f"{socket.gethostname()}:{os.getpid()}"
        self.tick_sec = float(tick_sec)
        self.lease_sec = float(lease_sec)
        self.replication = max(int(replication), 1)
        self.hot_replication = max(int(hot_replication), self.replication)
        self.hot_fraction = float(hot_fraction)
        self.load_alpha = float(load_alpha)
        self.http_timeout = float(http_timeout)
        self._ring = HashRing(vnodes)
        # per-tenant EWMA request rates (req/s) from counter deltas
        self.loads: Dict[str, float] = {}
        self._last_counts: Dict[str, float] = {}
        self._last_scrape = 0.0          # monotonic
        # the target assignment: tenant -> sorted replica ids
        self.target: Dict[str, List[str]] = {}
        self.plan_seq = 0
        self.metrics = placer_metrics()
        self.metrics.tenants.set(len(self.manifest))
        self._restore_plan()

    # --------------------------------------------------------------- http
    def _get(self, path: str) -> bytes:
        with urllib.request.urlopen(self.router_url + path,
                                    timeout=self.http_timeout) as r:
            return r.read()

    def _post_json(self, url: str, payload: dict) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req,
                                    timeout=self.http_timeout) as r:
            return json.loads(r.read())

    # -------------------------------------------------------------- lease
    def _acquire_lease(self) -> bool:
        """Renew (or contend for) the router's single-holder placer
        lease; False = another placer is driving, stand by."""
        try:
            grant = self._post_json(self.router_url + "/placer/lease",
                                    {"placer_id": self.placer_id,
                                     "lease_sec": self.lease_sec})
            return bool(grant.get("granted"))
        except OSError as e:
            # router unreachable: nothing to place against this tick
            swallowed_error("placer.lease", e)
            return False

    # ------------------------------------------------------------ observe
    def observe_load(self) -> Dict[str, float]:
        """Fold the router's per-tenant request counters into the EWMA
        rate map.  Counter RESETS (router restart) clamp the delta at
        zero instead of going negative."""
        text = self._get("/metrics").decode("utf-8", "replace")
        counts = scrape_labeled_samples(text, TENANT_LOAD_FAMILY)
        now = time.monotonic()
        dt = now - self._last_scrape if self._last_scrape else 0.0
        for t in self.manifest:
            c = counts.get(t, 0.0)
            prev = self._last_counts.get(t)
            if prev is not None and dt > 0:
                rate = max(c - prev, 0.0) / dt
                if t in self.loads:
                    self.loads[t] += self.load_alpha * (rate
                                                        - self.loads[t])
                else:
                    self.loads[t] = rate
            self._last_counts[t] = c
        self._last_scrape = now
        return dict(self.loads)

    # --------------------------------------------------------------- plan
    @staticmethod
    def _replica_map(members: dict) -> Dict[str, dict]:
        return {d["replica_id"]: d for d in members.get("replicas", [])
                if d.get("in_rotation")}

    def _model_cost(self, tenant: str, reps: Dict[str, dict]) -> int:
        """Device-byte cost of placing ``tenant``: the largest live
        advertisement wins (a resident engine's real footprint), file
        size is the cold fallback."""
        best = 0
        for d in reps.values():
            adv = (d.get("models_detail") or {}).get(tenant) or {}
            best = max(best, int(adv.get("bytes") or 0))
        if best:
            return best
        try:
            return os.path.getsize(self.manifest[tenant])
        except OSError:
            return 0

    def plan(self, members: dict) -> Dict[str, List[str]]:
        """Compute the target assignment for the current fleet + load.

        Deterministic in its inputs (same members, loads, and previous
        target -> same plan), which is what makes the chaos cell's
        "resumed placer converges to the same target" assertion
        meaningful."""
        reps = self._replica_map(members)
        rids = sorted(reps)
        if not rids:
            return {t: list(v) for t, v in self.target.items()}
        self._ring.rebuild(rids)
        budget = {r: int((reps[r].get("device") or {})
                         .get("budget_bytes") or 0) for r in rids}
        # usage baseline: bytes already resident for models OUTSIDE the
        # managed manifest (each replica's default model etc.)
        usage = {}
        for r in rids:
            usage[r] = sum(
                int((adv or {}).get("bytes") or 0)
                for m, adv in (reps[r].get("models_detail") or {}).items()
                if m not in self.manifest)
        total = sum(self.loads.get(t, 0.0) for t in self.manifest)
        order = sorted(self.manifest,
                       key=lambda t: (-self.loads.get(t, 0.0), t))
        target: Dict[str, List[str]] = {}
        for t in order:
            share = (self.loads.get(t, 0.0) / total) if total > 0 else 0.0
            floor = (self.hot_replication if share >= self.hot_fraction
                     else self.replication)
            floor = min(max(floor, 1), len(rids))
            cost = self._model_cost(t, reps)

            def fits(r: str) -> bool:
                return (budget[r] == 0
                        or usage[r] + cost <= budget[r]
                        or t in (reps[r].get("models") or []))

            chosen: List[str] = []
            # stickiness first: keep every still-valid assignment (this
            # is what bounds remap — a load shift on tenant X never
            # moves tenant Y's hosts)
            for r in self.target.get(t, []):
                if r in reps and len(chosen) < floor and fits(r):
                    chosen.append(r)
                    usage[r] += cost
            # new slots anchor on the ring: stable for a fixed replica
            # set, and a replica death moves only ITS tenants to their
            # ring successors
            slot = 0
            while len(chosen) < floor and slot < floor + len(rids):
                eligible = [r for r in rids
                            if r not in chosen and fits(r)]
                if not eligible:
                    # nothing fits: least-used replica takes it anyway
                    # (over budget beats orphaned), flagged for the
                    # operator
                    spill = [r for r in rids if r not in chosen]
                    if not spill:
                        break
                    pick = min(spill, key=lambda r: (usage[r], r))
                    event("placer.over_budget", model=t, replica=pick,
                          cost_bytes=cost, budget_bytes=budget[pick])
                else:
                    pick = self._ring.route(f"{t}#{slot}", set(eligible))
                    if pick is None:
                        pick = eligible[0]
                chosen.append(pick)
                usage[pick] += cost
                slot += 1
            target[t] = sorted(chosen)
        return target

    # ----------------------------------------------------------- converge
    def converge(self, members: dict) -> dict:
        """Push the deltas between the target assignment and what the
        fleet currently ADVERTISES.  Detach is orphan-safe: a replica
        sheds a tenant only while enough other in-rotation replicas
        advertise it."""
        reps = self._replica_map(members)
        pushed = {"attach": 0, "detach": 0, "errors": 0}
        advertisers = {t: {r for r, d in reps.items()
                           if t in (d.get("models") or [])}
                       for t in self.manifest}
        for t, want in sorted(self.target.items()):
            if t not in self.manifest:
                continue
            have = advertisers.get(t, set())
            for r in want:
                if r in reps and r not in have:
                    self.metrics.moves.inc("attach")
                    if self._push_attach(reps[r], t):
                        pushed["attach"] += 1
                    else:
                        pushed["errors"] += 1
            keep = len(have & set(want))
            for r in sorted(have - set(want)):
                # never shed below the number of target hosts that
                # already advertise: the LAST copy moves only after its
                # replacement is up
                if keep < max(len(want), 1):
                    break
                self.metrics.moves.inc("detach")
                if self._push_detach(reps[r], t):
                    pushed["detach"] += 1
                else:
                    pushed["errors"] += 1
        placed = sum(1 for t in self.manifest if advertisers.get(t))
        self.metrics.tenants.set(len(self.manifest))
        self.metrics.tenants_placed.set(placed)
        converged = (pushed["attach"] == 0 and pushed["detach"] == 0
                     and pushed["errors"] == 0
                     and all(set(self.target.get(t, []))
                             <= advertisers.get(t, set())
                             for t in self.manifest))
        self.metrics.converged.set(1.0 if converged else 0.0)
        pushed["converged"] = converged
        return pushed

    def _push_attach(self, rep: dict, tenant: str) -> bool:
        self.metrics.pushes.inc()
        url = rep["url"]
        try:
            with span("placer.push", replica=rep["replica_id"],
                      model=tenant, kind="attach"):
                self._post_json(url + "/-/catalog",
                                {"add": {tenant: self.manifest[tenant]}})
                # warm eagerly: the first tenant request should not pay
                # the admission build (path is per-tenant, so reload is
                # scoped); lazy admission is the fallback on failure
                self._post_json(f"{url}/-/reload?model={tenant}", {})
            event("placer.attach", replica=rep["replica_id"],
                  model=tenant)
            return True
        except OSError as e:
            self.metrics.push_errors.inc()
            event("placer.push_error", replica=rep["replica_id"],
                  model=tenant, kind="attach",
                  error=f"{type(e).__name__}: {e}")
            return False

    def _push_detach(self, rep: dict, tenant: str) -> bool:
        self.metrics.pushes.inc()
        try:
            with span("placer.push", replica=rep["replica_id"],
                      model=tenant, kind="detach"):
                self._post_json(rep["url"] + "/-/catalog",
                                {"remove": [tenant]})
            event("placer.detach", replica=rep["replica_id"],
                  model=tenant)
            return True
        except OSError as e:
            self.metrics.push_errors.inc()
            event("placer.push_error", replica=rep["replica_id"],
                  model=tenant, kind="detach",
                  error=f"{type(e).__name__}: {e}")
            return False

    # ----------------------------------------------------------- snapshot
    def _snapshot_plan(self) -> None:
        """Persist the target plan (atomic, fsync'd, CRC-footered like
        every durable artifact) so a SIGKILL'd placer resumes exactly
        this assignment.  Best-effort: a full disk must not stop
        placement."""
        if not self.plan_path:
            return
        from xgboost_tpu.reliability.integrity import (add_footer,
                                                       atomic_write)
        payload = json.dumps({"seq": self.plan_seq,
                              "target": self.target,
                              "manifest": self.manifest},
                             sort_keys=True).encode()
        try:
            atomic_write(self.plan_path, add_footer(payload))
        except OSError as e:
            swallowed_error("placer.snapshot_plan", e)

    def _restore_plan(self) -> None:
        if not self.plan_path or not os.path.exists(self.plan_path):
            return
        try:
            from xgboost_tpu.reliability.integrity import \
                verify_model_bytes
            with open(self.plan_path, "rb") as f:
                state = json.loads(verify_model_bytes(f.read(),
                                                      self.plan_path))
            self.target = {str(t): [str(r) for r in rs]
                           for t, rs in state.get("target", {}).items()
                           if str(t) in self.manifest}
            self.plan_seq = int(state.get("seq", 0))
            event("placer.resume", seq=self.plan_seq,
                  tenants=len(self.target), plan_path=self.plan_path)
        except Exception as e:
            # corrupt/stale snapshot: replan from scratch — the greedy
            # pack is deterministic, so a cold start still converges
            swallowed_error("placer.restore_plan", e)

    def _record_plan(self) -> None:
        """Mirror the plan onto the router (observability + takeover);
        best-effort — the CRC snapshot is the durable copy."""
        try:
            self._post_json(self.router_url + "/placer/plan",
                            {"placer_id": self.placer_id,
                             "plan": {"seq": self.plan_seq,
                                      "target": self.target}})
        except OSError as e:
            swallowed_error("placer.record_plan", e)

    # ---------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One full control-loop iteration; returns a report dict."""
        if not self._acquire_lease():
            self.metrics.standby_ticks.inc()
            return {"standby": True}
        self.metrics.ticks.inc()
        with span("placer.tick", placer_id=self.placer_id):
            try:
                members = json.loads(self._get("/fleet/members"))
                self.observe_load()
            except (OSError, ValueError) as e:
                swallowed_error("placer.observe", e)
                return {"standby": False, "error": str(e)}
            target = self.plan(members)
            if target != self.target:
                self.target = target
                self.plan_seq += 1
                self.metrics.plans.inc()
                event("placer.plan", seq=self.plan_seq,
                      target={t: list(v) for t, v in target.items()})
                self._snapshot_plan()
            self._record_plan()
            report = self.converge(members)
        report["standby"] = False
        report["seq"] = self.plan_seq
        return report


def run_placer(router_url: str, manifest: Dict[str, str],
               supervisor: Optional[object] = None,
               block: bool = True, **kwargs) -> PlacementController:
    """CLI entry (``task=placer``): run the placement loop until
    SIGTERM/Ctrl-C.  ``supervisor`` (an
    :class:`~xgboost_tpu.placer.elastic.ElasticSupervisor`) ticks on
    the same cadence when given.  ``block=False`` returns the built
    controller without looping (tests drive ticks by hand)."""
    from xgboost_tpu.reliability.deadline import jittered
    ctl = PlacementController(router_url, manifest, **kwargs)
    if not block:
        return ctl
    import signal as _signal
    stop: List[int] = []
    try:
        _signal.signal(_signal.SIGTERM, lambda *_: stop.append(1))
    except ValueError:
        pass  # non-main thread: rely on KeyboardInterrupt/stop()
    try:
        while not stop:
            ctl.tick()
            if supervisor is not None:
                supervisor.tick()
            time.sleep(jittered(max(ctl.tick_sec, 0.05)))
    except KeyboardInterrupt:
        pass
    return ctl

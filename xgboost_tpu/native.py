"""ctypes binding to the native IO runtime (native/xgtpu_io.cpp).

Loads ``libxgtpu_io.so`` (building it with the repo Makefile on first
use when a toolchain is available) and exposes:

  - :func:`parse_libsvm_native` — multithreaded libsvm parsing
    (reference ``src/io/libsvm_parser.h``'s OMP chunk parser);
  - :class:`PageWriter` / :class:`PageReader` — external-memory sparse
    page spill files with a background prefetch thread (reference
    ``src/io/sparse_batch_page.h`` + ``src/utils/thread_buffer.h``).

Everything degrades to pure-Python equivalents when the library cannot
be built (``available()`` returns False).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

# module-level import so __del__ can still account failures during
# interpreter shutdown, when function-local imports start failing
from xgboost_tpu.obs.metrics import swallowed_error

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libxgtpu_io.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

i64p = ctypes.POINTER(ctypes.c_int64)
i32p = ctypes.POINTER(ctypes.c_int32)
f32p = ctypes.POINTER(ctypes.c_float)


def _build() -> bool:
    if os.environ.get("XGTPU_NO_NATIVE_BUILD"):
        return False
    import sys
    print("xgboost_tpu: building native IO library (first use; set "
          "XGTPU_NO_NATIVE_BUILD=1 to skip and use the Python parser)",
          file=sys.stderr)
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "lib"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception as e:
        # no toolchain -> pure-Python fallback; the degradation is
        # counted so a fleet silently parsing at 1/8 speed shows up
        swallowed_error("native.build", e)
        return False


def _declare(lib) -> None:
    lib.XGTParseLibSVM.restype = ctypes.c_void_p
    lib.XGTParseLibSVM.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_int)]
    lib.XGTCSRSizes.argtypes = [ctypes.c_void_p, i64p, i64p]
    lib.XGTCSRCopy.argtypes = [ctypes.c_void_p, i64p, i32p, f32p, f32p]
    lib.XGTCSRFree.argtypes = [ctypes.c_void_p]
    lib.XGTPageWriterCreate.restype = ctypes.c_void_p
    lib.XGTPageWriterCreate.argtypes = [ctypes.c_char_p]
    lib.XGTPageWriterPush.restype = ctypes.c_int
    lib.XGTPageWriterPush.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      i64p, i32p, f32p]
    lib.XGTPageWriterClose.argtypes = [ctypes.c_void_p]
    lib.XGTPageReaderCreate.restype = ctypes.c_void_p
    lib.XGTPageReaderCreate.argtypes = [ctypes.c_char_p]
    lib.XGTPageReaderNext.restype = ctypes.c_int
    lib.XGTPageReaderNext.argtypes = [ctypes.c_void_p, i64p, i64p]
    lib.XGTPageReaderCopy.argtypes = [ctypes.c_void_p, i64p, i32p, f32p]
    lib.XGTPageReaderReset.argtypes = [ctypes.c_void_p]
    lib.XGTPageReaderFree.argtypes = [ctypes.c_void_p]


def get_lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _declare(lib)
            _lib = lib
        except OSError:
            _load_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def _as_i64p(a): return a.ctypes.data_as(i64p)
def _as_i32p(a): return a.ctypes.data_as(i32p)
def _as_f32p(a): return a.ctypes.data_as(f32p)


def parse_libsvm_native(path: str, rank: int = 0, nparts: int = 1,
                        nthread: int = 0
                        ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                            np.ndarray, np.ndarray]]:
    """(indptr, indices, values, labels) or None if native unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    status = ctypes.c_int(0)
    h = lib.XGTParseLibSVM(path.encode(), nthread, rank, nparts,
                           ctypes.byref(status))
    if not h:
        if status.value == 2:
            # match the pure-Python fallback, which raises ValueError
            # from int()/float() on malformed tokens
            raise ValueError(f"malformed libsvm input in {path!r}")
        import errno
        raise FileNotFoundError(errno.ENOENT, "cannot open libsvm file",
                                path)
    try:
        n_rows = ctypes.c_int64()
        nnz = ctypes.c_int64()
        lib.XGTCSRSizes(h, ctypes.byref(n_rows), ctypes.byref(nnz))
        indptr = np.empty(n_rows.value + 1, np.int64)
        indices = np.empty(nnz.value, np.int32)
        values = np.empty(nnz.value, np.float32)
        labels = np.empty(n_rows.value, np.float32)
        lib.XGTCSRCopy(h, _as_i64p(indptr), _as_i32p(indices),
                       _as_f32p(values), _as_f32p(labels))
    finally:
        lib.XGTCSRFree(h)
    return indptr, indices, values, labels


class PageWriter:
    """Spill CSR row pages to a binary page file."""

    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native IO runtime unavailable")
        self._lib = lib
        self._h = lib.XGTPageWriterCreate(path.encode())
        if not self._h:
            raise IOError(f"cannot create {path!r}")

    def push(self, indptr: np.ndarray, indices: np.ndarray,
             values: np.ndarray) -> None:
        indptr = np.ascontiguousarray(indptr, np.int64)
        indices = np.ascontiguousarray(indices, np.int32)
        values = np.ascontiguousarray(values, np.float32)
        if len(indptr) < 1:
            raise ValueError("indptr must have at least one element")
        rc = self._lib.XGTPageWriterPush(
            self._h, len(indptr) - 1, _as_i64p(indptr), _as_i32p(indices),
            _as_f32p(values))
        if rc != 0:
            raise IOError("page write failed")

    def close(self) -> None:
        if self._h:
            self._lib.XGTPageWriterClose(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()  # flush the C++ stream even without close()
        except Exception as e:
            swallowed_error("native.page_writer_del", e, emit_event=False)


class PageReader:
    """Iterate (indptr, indices, values) pages with background prefetch."""

    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native IO runtime unavailable")
        self._lib = lib
        self._h = lib.XGTPageReaderCreate(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path!r} (bad magic?)")

    def __iter__(self):
        return self

    def __next__(self):
        n_rows = ctypes.c_int64()
        nnz = ctypes.c_int64()
        if not self._lib.XGTPageReaderNext(self._h, ctypes.byref(n_rows),
                                           ctypes.byref(nnz)):
            raise StopIteration
        indptr = np.empty(n_rows.value + 1, np.int64)
        indices = np.empty(nnz.value, np.int32)
        values = np.empty(nnz.value, np.float32)
        self._lib.XGTPageReaderCopy(self._h, _as_i64p(indptr),
                                    _as_i32p(indices), _as_f32p(values))
        return indptr, indices, values

    def reset(self) -> None:
        self._lib.XGTPageReaderReset(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.XGTPageReaderFree(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception as e:
            swallowed_error("native.page_reader_del", e, emit_event=False)

"""Typed configuration for xgboost_tpu.

The reference flows every parameter as string ``(name, value)`` pairs
through ``SetParam`` cascades (reference ``src/learner/learner-inl.hpp:79-124``,
``src/tree/param.h:15-107``).  Here the canonical store is one typed
dataclass; the string-pair ingestion surface (CLI ``k=v``, Python dicts)
is kept for parity, including the reference's alias table
(eta/learning_rate, gamma/min_split_loss, lambda/reg_lambda,
alpha/reg_alpha — reference ``src/tree/param.h:79-107``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# accepted alias -> dataclass field name (reference param.h SetParam)
_ALIASES: Dict[str, str] = {
    "learning_rate": "eta",
    "min_split_loss": "gamma",
    "lambda": "reg_lambda",
    "alpha": "reg_alpha",
    "gbm": "booster",  # CLI uses 'gbm'; wrapper/xgboost.py uses 'booster'
}


def canonical_name(name: str) -> str:
    return _ALIASES.get(name, name)


def params_to_dict(params) -> Dict[str, Any]:
    """Normalize a params dict OR (name, value) pair sequence to a dict,
    collecting repeated ``eval_metric`` entries into a list (the
    reference wrapper's pair-list idiom for watching several metrics)."""
    if isinstance(params, dict):
        return dict(params)
    out: Dict[str, Any] = {}
    ems: List[str] = []
    for k, v in (params or ()):
        if k == "eval_metric":
            ems.extend(v if isinstance(v, (list, tuple)) else [v])
        else:
            out[k] = v
    if ems:
        out["eval_metric"] = ems
    return out


@dataclasses.dataclass
class TrainParam:
    """All training hyperparameters.

    Tree params mirror reference ``src/tree/param.h:15-107``; learner
    params mirror ``src/learner/learner-inl.hpp:427-454``; gblinear
    params mirror ``src/gbm/gblinear-inl.hpp:196-226``.
    """

    # -- tree booster params (reference src/tree/param.h) --
    eta: float = 0.3
    gamma: float = 0.0  # min_split_loss
    max_depth: int = 6
    min_child_weight: float = 1.0
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    max_delta_step: float = 0.0
    subsample: float = 1.0
    colsample_bytree: float = 1.0
    colsample_bylevel: float = 1.0
    default_direction: int = 0  # 0=learn, 1=left, 2=right
    sketch_eps: float = 0.03
    sketch_ratio: float = 2.0
    # TPU-native binning: number of histogram bins (incl. reserved missing
    # bin 0).  The reference's analog is max_sketch_size=sketch_ratio/sketch_eps.
    max_bin: int = 256
    # dsplit=row cut proposal on device: per-shard sketches merged over the
    # mesh axis (parallel/sketch_device.py — rabit SerializeReducer analog,
    # histmaker-inl.hpp:417-424).  0 = host-side global sketch; -1 = auto:
    # device sketch whenever the job is MULTI-PROCESS (the distributed
    # default — no host should aggregate full columns), host sketch in
    # single-controller mode (keeps single-device bit-equality).
    # Split-loaded matrices (parallel/sharded.py) always device-sketch.
    device_sketch: int = -1
    # histogram accumulation precision (recorded in saved models):
    # "auto" = bf16 MXU kernel on TPU / exact scatter elsewhere;
    # "fp32" forces exact-f32 histograms; "bf16" forces the MXU pass;
    # "fixed" forces int32 fixed-point scatter accumulation (exactly
    # associative -> model bytes bitwise invariant to the data-mesh
    # device count; ops/histogram.FIXED_SCALE documents resolution).
    # XGBTPU_HIST remains an env override (test seam).
    hist_precision: str = "auto"
    # histogram subtraction + row compaction (build only the smaller
    # child per parent, derive the sibling as parent - small) is NOT a
    # config param: measured on v5e, XLA row compaction costs an order
    # of magnitude more than the kernel time it saves (PROFILE.md
    # round 3), so the public surface carries no known-10x-slower knob
    # (advisor, round 4).  The A/B stays reachable for kernel work via
    # env XGBTPU_HIST_SUBTRACTION=1 (numerics tested equal).
    # bin-count alignment quantum for the int8 MXU histogram kernel:
    # the one-hot operand tiles sublanes in 32s, so an unaligned bin
    # count (e.g. 67) pads to the next multiple (96) and wastes up to
    # a third of the kernel (~19% round rate at the bench shape).
    # -1 auto = align to 32 when the pallas kernel is active; 0 = keep
    # every proposed cut (exact sketch resolution)
    hist_bin_align: int = -1
    # EMA-gain feature screening (xgboost_tpu.stream, PIPELINE.md):
    # fraction of the per-feature EMA split-gain mass the fused
    # histogram build must keep — the trainer restricts its (C, N, F)
    # working set to the smallest feature prefix covering it.  0 (and
    # >= 1) disables screening; the off path is bit-identical to not
    # having the knob.  Only the streaming trainer maintains the EMA;
    # embedders can drive Booster.set_feature_screen directly.
    ema_fs: float = 0.0
    # EMA decay per micro-cycle for the per-feature gain shares
    ema_fs_decay: float = 0.9
    # screening floor: never screen below this many surviving features
    ema_fs_min_features: int = 8
    # gblinear coordinate-descent block size: 1 = exact sequential CD
    # (convergent under feature correlation); >1 = shotgun-style parallel
    # updates within each block (reference gblinear-inl.hpp:76-105)
    linear_block: int = 1

    # -- gbtree params (reference src/gbm/gbtree-inl.hpp:389-428) --
    num_parallel_tree: int = 1
    # chunked tree-parallel prediction (models/tree.py): how many trees
    # traverse at once under vmap; the ensemble pads to the
    # padded_tree_count ladder so one compilation serves every size in
    # a chunk band.  -1 auto = 32 on TPU (batched compare-selects
    # replace the per-tree chain of dependent level launches), scan on
    # CPU (measured SLOWER there — tools/predict_microbench.py;
    # PROFILE.md round 6); 0/1 = force the sequential scan baseline;
    # >1 = force that chunk width.  XGBTPU_PREDICT_TREE_CHUNK env
    # overrides for A/Bs.
    predict_tree_chunk: int = -1
    # segmented round fusion (learner.update_many): how many boosting
    # rounds run per fused _scan_rounds dispatch — the host is touched
    # only at segment boundaries (eval lines, periodic saves and
    # checkpoints all still land per round / per boundary, bit-identical
    # to the per-round path).  -1 auto = choose from the fitted round
    # model (ROUND_MODEL.json: segment long enough that the fixed
    # per-dispatch cost is <=10% of the dispatch, clamped to [1, 64]);
    # 0 = per-round dispatch (the A/B baseline); >0 = that segment
    # size.  XGBTPU_ROUNDS_PER_DISPATCH env overrides for A/Bs.
    rounds_per_dispatch: int = -1
    # multi-root trees (reference TreeParam::num_roots, tree/param.h):
    # rows enter the tree at per-row roots given by the root_index meta
    # field (data.h:39-58); trees reserve ceil(log2 num_roots) top levels
    # as root slots
    num_roots: int = 1
    updater: str = "grow_histmaker,prune"
    # exact-greedy (grow_colmaker) cap on distinct values per feature
    max_exact_bin: int = 4096

    # -- learner params (reference src/learner/learner-inl.hpp) --
    booster: str = "gbtree"  # gbtree | gblinear
    objective: str = "reg:linear"
    base_score: float = 0.5
    num_class: int = 0
    scale_pos_weight: float = 1.0
    eval_metric: Tuple[str, ...] = ()
    seed: int = 0
    seed_per_iteration: bool = False
    dsplit: str = "auto"  # auto | row | col
    # distributed AUC on split-loaded eval data: "exact" merges
    # per-shard (value, pos_w, neg_w) runs into the true global AUC;
    # "approx" keeps the reference's mean-of-per-shard-AUCs
    # (evaluation-inl.hpp:405-414).  Exact gathers one 24-byte run per
    # distinct predicted value per shard; shards exceeding
    # dist_auc_max_runs fall back to approx with a warning.
    dist_auc: str = "exact"
    dist_auc_max_runs: int = 1 << 22
    nthread: int = 0
    silent: int = 0
    # profiling (SURVEY.md §5.1): 1 = per-round phase timing,
    # 2 = also capture a jax.profiler trace into profile_dir
    profile: int = 0
    profile_dir: str = ""
    # observability (OBSERVABILITY.md): obs_log= appends spans/events
    # to a crash-safe JSONL timeline (tools/obs_report.py renders it;
    # XGBTPU_OBS_LOG is the env equivalent); metrics_port= serves live
    # /metrics + /healthz during task=train from a daemon thread
    # (0 = ephemeral port, printed at startup; -1 = off).  Either one
    # enables per-round phase instrumentation — same cost contract as
    # profile=1 (a device barrier per phase, no fused round loop).
    obs_log: str = ""
    metrics_port: int = -1

    # -- gblinear params (reference src/gbm/gblinear-inl.hpp) --
    lambda_bias: float = 0.0

    # -- ranking objective params (reference src/learner/objective-inl.hpp:283-300)
    num_pairsample: int = 1
    fix_list_weight: float = 0.0
    # rank gradient implementation: "device" = on-device pair sampling +
    # delta weights (rank_device.py; fused-scan eligible, no per-round
    # host transfer); "host" = reference-faithful numpy path
    rank_impl: str = "device"

    # unknown/extra params are preserved (the reference tolerates and
    # forwards unrecognized names through SetParam cascades)
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------------
    @classmethod
    def field_names(cls) -> List[str]:
        return [f.name for f in dataclasses.fields(cls) if f.name != "extras"]

    def set_param(self, name: str, value: Any) -> "TrainParam":
        """Set one parameter (string values are coerced), returning self."""
        name = canonical_name(name)
        if name == "eval_metric":
            # repeated eval_metric appends, like the reference EvalSet
            if isinstance(value, str):
                value = (*self.eval_metric, value)
            else:
                value = tuple(value)
            self.eval_metric = value
            return self
        if name == "default_direction" and isinstance(value, str):
            value = {"learn": 0, "left": 1, "right": 2}.get(value, value)
        if name in self.field_names():
            setattr(self, name, _coerce(value, getattr(self, name)))
        else:
            self.extras[name] = value
        return self

    @classmethod
    def from_dict(cls, params: Optional[Dict[str, Any]]) -> "TrainParam":
        """Build from a dict OR a sequence of (name, value) pairs — the
        reference wrapper accepts both (``list(param.items()) +
        [('eval_metric', ...)]`` is its idiom for repeated metrics,
        wrapper/xgboost.py train callers)."""
        p = cls()
        for k, v in params_to_dict(params).items():
            p.set_param(k, v)
        return p

    def to_dict(self) -> Dict[str, Any]:
        d = {k: getattr(self, k) for k in self.field_names()}
        d["eval_metric"] = list(self.eval_metric)
        d.update(self.extras)
        return d

    # number of output groups (trees per boosting round for gbtree)
    @property
    def num_output_group(self) -> int:
        return max(1, self.num_class)


def _coerce(value: Any, current: Any) -> Any:
    """Coerce a (possibly string) value to the current field value's type."""
    target = type(current) if current is not None else str
    if isinstance(value, str):
        if target is bool:
            return value.lower() in ("1", "true", "yes")
        if target is int:
            return int(float(value))
        if target is float:
            return float(value)
        return value
    if target is bool:
        return bool(value)
    if target is int:
        return int(value)
    if target is float:
        return float(value)
    return value


# ------------------------------------------------------------- serving
# task=serve parameters (xgboost_tpu.serving).  Single source of truth:
# the classic CLI (``python -m xgboost_tpu task=serve serve_port=...``)
# and the module runner (``python -m xgboost_tpu.serving --port ...``)
# both derive their surfaces from this table, so ``--help``-style
# discovery stays complete as knobs are added.  Values are
# (default, help); the default's type drives coercion.  xgtpu-lint
# XGT010 (ANALYSIS.md v2) enforces that every key here is consumed
# outside this table — a knob row nothing reads fails tier-1.
SERVE_PARAMS: Dict[str, Tuple[Any, str]] = {
    "serve_host": ("127.0.0.1", "bind address for the HTTP server"),
    "serve_port": (8080, "HTTP port (0 = ephemeral, printed at startup)"),
    "serve_min_bucket": (8, "smallest power-of-two row bucket"),
    "serve_max_bucket": (8192, "largest row bucket; bigger requests are "
                               "chunked through it"),
    "serve_max_batch_rows": (1024, "max rows coalesced into one device "
                                   "call by the micro-batcher"),
    "serve_max_wait_ms": (2.0, "micro-batch window: how long the first "
                               "request waits for company"),
    "serve_queue_rows": (8192, "bounded queue size in rows; overflow "
                               "rejects with HTTP 503"),
    "serve_poll_sec": (1.0, "model-file hot-reload poll interval "
                            "(0 disables watching)"),
    "serve_keep_versions": (2, "previous model versions kept warm for "
                               "instant rollback"),
    "serve_warmup": (1, "pre-compile every row bucket at startup "
                        "(recompile-free steady state)"),
    "serve_drain_sec": (30.0, "SIGTERM drain grace: max seconds to wait "
                              "for in-flight requests before exit"),
    "serve_max_body_mb": (64.0, "largest accepted request body; bigger "
                                "Content-Length is rejected with 413 "
                                "before buffering"),
    "serve_featurestore_mb": (0.0, "device byte budget for the "
                                   "hot-entity feature store backing "
                                   "POST /predict_by_id (0 disables; "
                                   "LRU-evicts past the budget)"),
    "serve_router_url": ("", "fleet router base URL (e.g. "
                             "http://127.0.0.1:8000); the replica "
                             "registers there and renews a heartbeat "
                             "lease (empty = standalone, no fleet)"),
    "serve_replica_id": ("", "stable replica identity used with the "
                            "fleet router (default host:port; a "
                            "restarted replica re-registering under "
                            "its old id is the recover path)"),
    "serve_advertise_url": ("", "endpoint the router should dial for "
                                "this replica (default the bind "
                                "address; REQUIRED for cross-host "
                                "fleets binding 0.0.0.0)"),
}


def serve_params_help() -> str:
    """One line per task=serve parameter, for CLI usage text."""
    return "\n".join(f"  {name:<22} {help_} (default {default!r})"
                     for name, (default, help_) in SERVE_PARAMS.items())


# --------------------------------------------------------------- fleet
# task=fleet_router parameters (xgboost_tpu.fleet) — same single-table
# discipline as SERVE_PARAMS: the classic CLI derives its surface from
# this dict, so usage text stays complete as knobs are added.
FLEET_PARAMS: Dict[str, Tuple[Any, str]] = {
    "fleet_host": ("127.0.0.1", "bind address for the router"),
    "fleet_port": (8000, "router HTTP port (0 = ephemeral, printed at "
                         "startup)"),
    "fleet_lease_sec": (10.0, "replica heartbeat lease: a replica that "
                              "stops renewing leaves rotation within "
                              "this window"),
    "fleet_hc_sec": (2.0, "health-check interval: the router probes "
                          "each replica's /healthz (draining/degraded "
                          "replicas leave rotation; 0 disables)"),
    "fleet_inflight": (256, "global in-flight request budget; requests "
                            "past it are shed with HTTP 503"),
    "fleet_breaker_failures": (3, "consecutive dispatch failures that "
                                  "trip a replica's circuit breaker "
                                  "open"),
    "fleet_breaker_cooldown_sec": (5.0, "seconds an open breaker waits "
                                        "before allowing one half-open "
                                        "probe request"),
    "fleet_retry": (1, "retry a failed /predict once on a different "
                       "healthy replica (predictions are idempotent; "
                       "the retry spends the request's REMAINING "
                       "deadline budget after a jittered backoff)"),
    "fleet_timeout_sec": (30.0, "per-hop forward timeout to a replica "
                                "(shrunk to the remaining deadline "
                                "budget when the request carries one)"),
    "fleet_deadline_ms": (0.0, "default end-to-end deadline stamped "
                               "(X-Deadline-Ms) on requests that carry "
                               "none; expired requests are rejected 504 "
                               "before any dispatch (0 = off)"),
    "fleet_slow_eject_factor": (3.0, "eject a replica from least-"
                                     "loaded dispatch when its latency "
                                     "EWMA exceeds this multiple of "
                                     "its peers' median (0 disables; "
                                     "entity-id owners are exempt — "
                                     "sticky routes have no failover)"),
    "fleet_slow_eject_cooldown_sec": (5.0, "seconds an ejected replica "
                                           "waits before one probe "
                                           "request decides "
                                           "readmission"),
    "fleet_max_body_mb": (64.0, "largest accepted request body (413 "
                                "past it, before buffering)"),
    "fleet_canaries": (1, "default canary replica count for POST "
                          "/fleet/rollout"),
    "fleet_soak_sec": (3.0, "default canary soak window before the "
                            "rollout gate reads canary /metrics"),
    "fleet_gate_error_rate": (0.02, "rollout gate: max canary error "
                                    "rate (errors/requests) during the "
                                    "soak"),
    "fleet_gate_p99_ms": (250.0, "rollout gate: max canary p99 request "
                                 "latency in milliseconds"),
    "fleet_state_path": ("", "membership snapshot file (CRC-footered, "
                             "atomically rewritten on membership "
                             "changes and each health pass): a "
                             "restarted router restores its replica "
                             "set from here instead of waiting for "
                             "heartbeats (empty = stateless restart)"),
}


def fleet_params_help() -> str:
    """One line per task=fleet_router parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in FLEET_PARAMS.items())


# ------------------------------------------------------------- pipeline
# task=pipeline parameters (xgboost_tpu.pipeline, PIPELINE.md) — same
# single-table discipline as SERVE_PARAMS/FLEET_PARAMS: the classic CLI
# derives its surface from this dict, xgtpu-lint XGT010 enforces that
# every key is consumed outside config.py, and the inventory rides
# ANALYSIS_CONTRACTS.json.
PIPELINE_PARAMS: Dict[str, Tuple[Any, str]] = {
    "pipeline_publish_path": ("", "model file the serving tier polls; "
                                  "each gated candidate is atomically "
                                  "published here (REQUIRED; also the "
                                  "warm-start incumbent)"),
    "pipeline_dir": ("./pipeline", "pipeline working directory: cycle "
                                   "state, candidate model, checkpoint "
                                   "ring, quarantine, gated-hash "
                                   "ledger"),
    "pipeline_rounds_per_cycle": (5, "boosting rounds appended to the "
                                     "incumbent per cycle"),
    "pipeline_cycles": (1, "cycles to run before exiting (0 = run "
                           "forever)"),
    "pipeline_data": ("", "fresh training data per cycle; a {cycle} "
                          "placeholder substitutes the cycle index "
                          "(falls back to data=)"),
    "pipeline_holdout": ("", "held-out eval window the gate scores "
                             "candidate vs incumbent on (REQUIRED "
                             "unless a custom DataSource provides "
                             "one)"),
    "pipeline_metric": ("", "gate metric name (empty = the "
                            "objective's default metric)"),
    "pipeline_min_delta": (0.0, "gate: minimum improvement over the "
                                "incumbent required to publish "
                                "(> 0 demands strict improvement)"),
    "pipeline_max_regression": (0.0, "gate: tolerated worsening vs the "
                                     "incumbent when pipeline_min_delta "
                                     "<= 0 (fresh-data drift allowance)"),
    "pipeline_router_url": ("", "fleet router base URL: publish through "
                                "the canary rollout lane (POST "
                                "/fleet/rollout) instead of a direct "
                                "atomic swap (empty = direct)"),
    "pipeline_publish_timeout_sec": (600.0, "rollout-lane publish "
                                            "timeout; must outlive the "
                                            "router's canary soak "
                                            "window"),
    "pipeline_sleep_sec": (0.0, "pause between cycles (and after an "
                                "idle cycle with no fresh data)"),
}


def pipeline_params_help() -> str:
    """One line per task=pipeline parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in PIPELINE_PARAMS.items())


# ---------------------------------------------------------------- lanes
# task=lanes parameters (xgboost_tpu.pipeline.lanes, PIPELINE.md
# "Gang-batched lanes") — gang-batched multi-tenant continuous
# training: one pipeline per catalog tenant, same-shape lanes
# vmap-stacked into ONE device dispatch per round segment.  Per-lane
# gate knobs reuse the pipeline_* table (metric, min_delta,
# max_regression, router_url, publish_timeout_sec, sleep_sec apply to
# every lane).  Same single-table discipline as PIPELINE_PARAMS
# (XGT010 + contracts inventory).
LANE_PARAMS: Dict[str, Tuple[Any, str]] = {
    "lanes": ("", "tenant lane manifest: inline 'name=publish_path' "
                  "pairs (comma-separated) or a 'name = publish_path' "
                  "config file — one continuous-training pipeline per "
                  "tenant (REQUIRED for task=lanes)"),
    "lanes_dir": ("./lanes", "root working directory; each lane keeps "
                             "its own cycle state, checkpoint ring, "
                             "quarantine and gated-hash ledger under "
                             "<lanes_dir>/<name>"),
    "lane_stack": (-1, "gang-batched execution: 1 = vmap-stack "
                       "same-shape lanes into one device dispatch per "
                       "round segment, 0 = independent host-loop "
                       "pipelines (the A/B baseline), -1 = auto "
                       "(XGBTPU_LANE_STACK env, default stacked)"),
    "lane_window_ms": (200.0, "rendezvous window: a cycle's boosting "
                              "dispatches when every active lane has "
                              "arrived or this many ms passed since "
                              "the first arrival; late lanes join the "
                              "next batch (model bytes never depend "
                              "on batch composition — only dispatch "
                              "sharing does)"),
    "lane_max_workers": (0, "concurrent lane threads (0 = auto: all "
                            "lanes when stacked — threads idle at the "
                            "rendezvous while the device works — else "
                            "min(lanes, 8) for the host loop)"),
    "lane_data": ("", "per-lane training data: {lane} and {cycle} "
                      "placeholders substitute the lane name and "
                      "cycle index (falls back to data=)"),
    "lane_holdout": ("", "per-lane gate holdout; a {lane} placeholder "
                         "substitutes the lane name"),
    "lane_rounds_per_cycle": (5, "boosting rounds appended per cycle "
                                 "in every lane (equal-shape lanes "
                                 "share one compiled stacked scan)"),
    "lane_cycles": (1, "cycles each lane runs before exiting (0 = run "
                       "forever)"),
}


def lane_params_help() -> str:
    """One line per task=lanes parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in LANE_PARAMS.items())


# --------------------------------------------------------------- stream
# task=stream parameters (xgboost_tpu.stream, PIPELINE.md streaming
# section) — same single-table discipline as PIPELINE_PARAMS: the
# classic CLI derives its surface from this dict, xgtpu-lint XGT010
# enforces that every key is consumed outside config.py, and the
# inventory rides ANALYSIS_CONTRACTS.json.
STREAM_PARAMS: Dict[str, Tuple[Any, str]] = {
    "stream_publish_path": ("", "model file the serving tier polls; "
                                "each gated candidate is atomically "
                                "published here (REQUIRED; also the "
                                "warm-start incumbent)"),
    "stream_workdir": ("./stream", "stream working directory: cycle "
                                   "state, checkpoint ring, quarantine, "
                                   "gated-hash ledger, per-cycle drift "
                                   "plans/sketches"),
    "stream_dir": ("", "spool directory producers drop batch-*.npz row "
                       "batches into; micro-cycle manifests commit "
                       "under it (REQUIRED)"),
    "stream_rounds_per_cycle": (5, "boosting rounds appended to the "
                                   "incumbent per micro-cycle"),
    "stream_cycles": (1, "micro-cycles to run before exiting (0 = run "
                         "forever)"),
    "stream_min_batches": (1, "batches that must arrive before a "
                              "micro-cycle composes (fewer = idle/"
                              "collecting)"),
    "stream_max_batches": (8, "most batches one micro-cycle claims "
                              "(bounds cycle latency under backlog)"),
    "stream_catchup_backlog": (16, "unclaimed-batch backlog at which "
                                   "the source reports catch_up state"),
    "stream_max_backlog": (256, "unclaimed-batch cap: past it push() "
                                "raises StreamBacklogFull "
                                "(backpressure)"),
    "stream_holdout_cycles": (4, "sliding-holdout window: the gate "
                                 "judges on the previous N cycles' "
                                 "batches"),
    "stream_metric": ("", "gate metric name (empty = the objective's "
                          "default metric)"),
    "stream_min_delta": (0.0, "gate: minimum improvement over the "
                              "incumbent required to publish"),
    "stream_max_regression": (0.0, "gate: tolerated worsening vs the "
                                   "incumbent when stream_min_delta "
                                   "<= 0 (drift allowance)"),
    "stream_router_url": ("", "fleet router base URL: publish through "
                              "the canary rollout lane (empty = direct "
                              "atomic swap)"),
    "stream_sleep_sec": (0.05, "pause between cycles and after an idle "
                               "poll with no fresh batches"),
    "stream_drift_threshold": (0.25, "per-feature PSI at which drift "
                                     "FIRES (triggers one online cut "
                                     "refresh on the rising edge)"),
    "stream_drift_clear": (0.1, "PSI below which a fired drift state "
                                "clears (hysteresis: no refresh storm "
                                "while scores oscillate)"),
    "stream_drift_window": (4, "sliding window of per-cycle sketches "
                               "the drift score compares against the "
                               "reference"),
    "stream_sketch_size": (256, "pruned quantile-summary size per "
                                "feature for drift tracking and online "
                                "cut proposal"),
    "stream_lane": ("", "tenant lane name: tags events/log lines and "
                        "scopes router publishes to that model's "
                        "replicas"),
}


def stream_params_help() -> str:
    """One line per task=stream parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in STREAM_PARAMS.items())


# -------------------------------------------------------------- catalog
# Multi-tenant model catalog (xgboost_tpu.catalog, SERVING.md): knobs
# shared by task=serve (the replica-side catalog) and task=fleet_router
# (per-tenant quotas).  Same single-table discipline as SERVE_PARAMS:
# one row here is the whole public surface for a knob, XGT010 enforces
# that every key is consumed outside config.py, and the inventory rides
# ANALYSIS_CONTRACTS.json.
CATALOG_PARAMS: Dict[str, Tuple[Any, str]] = {
    "catalog": ("", "model catalog manifest: inline "
                    "'name=path,name=path' pairs, or a path to a "
                    "'name = path' config file (one model per line). "
                    "Empty = single-model serving (a catalog of one)"),
    "catalog_default": ("", "model served by bare /predict (no "
                            "?model=); default: the model= file when "
                            "given, else the manifest's first entry"),
    "serve_catalog_mb": (0.0, "shared device byte budget across ALL "
                              "resident catalog models (engines + "
                              "per-model feature stores); past it the "
                              "coldest non-default models are evicted "
                              "(0 = unlimited, everything stays "
                              "resident)"),
    "catalog_hysteresis_sec": (3.0, "minimum residency before a model "
                                    "becomes evictable — bounds "
                                    "admit/evict thrash when the "
                                    "working set exceeds the budget"),
    "tenant_inflight": (0, "router: per-tenant in-flight request "
                           "budget; a tenant past it sheds 503 without "
                           "touching its neighbors (0 = no per-tenant "
                           "cap)"),
    "tenant_rate": (0.0, "router: per-tenant sustained request rate "
                         "limit in req/s (token bucket; over-rate "
                         "requests shed 429; 0 = unlimited)"),
    "tenant_burst": (8.0, "router: token-bucket burst size — requests "
                          "a tenant may send back-to-back before "
                          "tenant_rate applies"),
}


def catalog_params_help() -> str:
    """One line per catalog parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in CATALOG_PARAMS.items())


# --------------------------------------------------------------- placer
# Autonomous placement + elastic fleet (xgboost_tpu.placer, SERVING.md
# "Autonomous placement"): knobs for task=placer — the control plane
# that decides which replicas host which catalog models and how many
# replicas the fleet should run.  Same single-table discipline as the
# other *_PARAMS tables (XGT010 + contracts inventory section
# "placer").
PLACER_PARAMS: Dict[str, Tuple[Any, str]] = {
    "placer_router_url": ("", "base URL of the fleet router whose "
                              "catalog the placer manages (required "
                              "for task=placer)"),
    "placer_catalog": ("", "tenant manifest the placer places: inline "
                           "'name=path,name=path' pairs or a 'name = "
                           "path' config file — same syntax as "
                           "catalog="),
    "placer_plan_path": ("", "CRC-footered snapshot of the target "
                             "assignment; a restarted placer resumes "
                             "this plan instead of replanning from "
                             "scratch (empty = no snapshot)"),
    "placer_id": ("", "placer identity for the router-side single-"
                      "holder lease (default: host:pid)"),
    "placer_tick_sec": (2.0, "control-loop period: scrape load, "
                             "replan, push manifest deltas (jittered "
                             "±20%)"),
    "placer_lease_sec": (10.0, "router-side placer lease: a standby "
                               "placer takes over this long after the "
                               "holder's last renewal"),
    "placer_replication": (1, "replication floor — every tenant is "
                              "placed on at least this many in-"
                              "rotation replicas (capped by fleet "
                              "size)"),
    "placer_hot_replication": (2, "replication floor for HOT tenants "
                                  "(load share >= placer_hot_fraction)"),
    "placer_hot_fraction": (0.5, "a tenant whose share of observed "
                                 "request load meets this fraction is "
                                 "hot and gets the raised floor"),
    "placer_load_alpha": (0.3, "EWMA smoothing for per-tenant request "
                               "rates scraped from the router's "
                               "xgbtpu_tenant_* counters"),
    "placer_util_low": (0.2, "elastic band floor: fleet in-flight/"
                             "slot utilization (EWMA) below this "
                             "drains one replica"),
    "placer_util_high": (0.75, "elastic band ceiling: utilization "
                               "above this spawns one replica"),
    "placer_util_alpha": (0.3, "EWMA smoothing for the fleet "
                               "utilization signal"),
    "placer_replica_slots": (8, "nominal concurrent requests one "
                                "replica absorbs; utilization = "
                                "in-flight / (slots * replicas)"),
    "placer_cooldown_sec": (10.0, "minimum gap between elastic "
                                  "resizes, so one burst cannot "
                                  "thrash the fleet size"),
    "placer_min_replicas": (1, "elastic supervisor never drains the "
                               "fleet below this many replicas"),
    "placer_max_replicas": (8, "elastic supervisor never spawns the "
                               "fleet above this many replicas"),
}


def placer_params_help() -> str:
    """One line per task=placer parameter, for CLI usage text."""
    return "\n".join(f"  {name:<26} {help_} (default {default!r})"
                     for name, (default, help_) in PLACER_PARAMS.items())


def parse_config_file(path: str) -> List[Tuple[str, str]]:
    """Parse a ``name = value`` config file.

    Mirrors the reference's ConfigIterator (``src/utils/config.h``): one
    ``name = value`` pair per line, ``#`` comments, quoted strings allowed.
    Returns pairs in file order (later pairs override earlier on apply).
    """
    pairs: List[Tuple[str, str]] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            name, value = line.split("=", 1)
            name = name.strip()
            value = value.strip().strip('"').strip("'")
            if name:
                pairs.append((name, value))
    return pairs

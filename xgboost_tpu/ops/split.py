"""Split gain math and vectorized best-split search.

Gain/weight formulas re-implement reference ``TrainParam::CalcGain`` /
``CalcWeight`` (``src/tree/param.h:109-152``) including the L1 soft
threshold and the max_delta_step variant.  Split enumeration replaces the
reference's per-feature forward/backward sorted scans
(``updater_colmaker-inl.hpp:362-414``) and histogram scans
(``updater_histmaker-inl.hpp:175-258``) with one vectorized argmax over
``(feature, cut, default_direction)`` per node, with the reference's
deterministic lowest-feature-wins tie-break (``param.h:335-405``) falling
out of argmax-first-occurrence over a feature-major layout.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# plain float, not jnp: a module-level device constant would initialize
# the XLA backend at import time, breaking jax.distributed.initialize()
# (which must run first in multi-host workers — parallel/launch.py)
NEG = -1e30
RT_EPS = 1e-6  # reference rt_eps accept threshold


class SplitConfig(NamedTuple):
    """Static split hyperparameters (subset of TrainParam used on device)."""
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    max_delta_step: float = 0.0
    min_child_weight: float = 1.0
    gamma: float = 0.0
    eta: float = 0.3
    default_direction: int = 0  # 0=learn, 1=left, 2=right


def _threshold_l1(w, alpha):
    return jnp.sign(w) * jnp.maximum(jnp.abs(w) - alpha, 0.0)


def calc_weight(G, H, cfg: SplitConfig):
    """Leaf weight (reference CalcWeight, param.h:138-152)."""
    dw = -_threshold_l1(G, cfg.reg_alpha) / (H + cfg.reg_lambda)
    if cfg.max_delta_step != 0.0:
        dw = jnp.clip(dw, -cfg.max_delta_step, cfg.max_delta_step)
    return jnp.where(H < cfg.min_child_weight, 0.0, dw)


def calc_gain(G, H, cfg: SplitConfig):
    """Node objective reduction (reference CalcGain, param.h:109-126).

    Note: unlike CalcWeight, the plain-gain path has no min_child_weight
    zeroing here — the reference's histogram updaters enforce
    min_child_weight explicitly on both children (histmaker-inl.hpp:230-239),
    which find_best_splits replicates.
    """
    if cfg.max_delta_step == 0.0:
        t = _threshold_l1(G, cfg.reg_alpha) if cfg.reg_alpha != 0.0 else G
        return t * t / (H + cfg.reg_lambda)
    w = calc_weight(G, H, cfg)
    ret = G * w + 0.5 * (H + cfg.reg_lambda) * w * w
    if cfg.reg_alpha != 0.0:
        ret = ret + cfg.reg_alpha * jnp.abs(w)
    return -2.0 * ret


class BestSplit(NamedTuple):
    gain: jax.Array          # (n_node,) loss_chg of best split (f32)
    feature: jax.Array       # (n_node,) int32
    cut_index: jax.Array     # (n_node,) int32  (left iff bin <= cut_index+1)
    default_left: jax.Array  # (n_node,) bool
    valid: jax.Array         # (n_node,) bool — accept split?
    # chosen split's left-child sums (incl. the default-direction missing
    # mass): lets the grower DERIVE the next level's node stats instead
    # of a full node_stats pass over the rows (right child = node - left)
    left_g: jax.Array = None  # (n_node,) f32
    left_h: jax.Array = None  # (n_node,) f32


def find_best_splits(hist: jax.Array, nstats: jax.Array, n_cuts: jax.Array,
                     cfg: SplitConfig, feature_mask: jax.Array | None = None
                     ) -> BestSplit:
    """Vectorized best split per node from a level histogram.

    Args:
      hist:    (n_node, F, B, 2) grad/hess histogram (bin 0 = missing).
      nstats:  (n_node, 2) per-node (G, H) totals.
      n_cuts:  (F,) number of valid cut indices per feature.
      feature_mask: optional (F,) bool — colsample mask.
    """
    n_node, F, B, _ = hist.shape
    C = B - 2  # number of candidate cut positions (splits after bins 1..C)
    cum = jnp.cumsum(hist, axis=2)              # (n_node, F, B, 2)
    miss = hist[:, :, 0, :]                     # (n_node, F, 2)
    total = nstats[:, None, None, :]            # (n_node, 1, 1, 2)

    # left sums excluding missing, for cut j: bins 1..j+1  -> cum[.., j+1] - miss
    left_excl = cum[:, :, 1:C + 1, :] - miss[:, :, None, :]  # (n_node, F, C, 2)
    # default right: missing goes right;  default left: missing joins left
    left_dr = left_excl
    left_dl = left_excl + miss[:, :, None, :]
    left = jnp.stack([left_dr, left_dl], axis=3)     # (n_node, F, C, 2dir, 2)
    right = total[:, :, :, None, :] - left

    GL, HL = left[..., 0], left[..., 1]
    GR, HR = right[..., 0], right[..., 1]
    root_gain = calc_gain(nstats[:, 0], nstats[:, 1], cfg)  # (n_node,)
    loss_chg = (calc_gain(GL, HL, cfg) + calc_gain(GR, HR, cfg)
                - root_gain[:, None, None, None])

    ok = (HL >= cfg.min_child_weight) & (HR >= cfg.min_child_weight)
    cut_ids = jnp.arange(C, dtype=jnp.int32)
    ok &= (cut_ids[None, :, None] < n_cuts[:, None, None])[None]
    if feature_mask is not None:
        ok &= feature_mask[None, :, None, None]
    if cfg.default_direction == 1:    # forced left
        ok &= jnp.array([False, True])[None, None, None, :]
    elif cfg.default_direction == 2:  # forced right
        ok &= jnp.array([True, False])[None, None, None, :]
    loss_chg = jnp.where(ok, loss_chg, NEG)

    flat = loss_chg.reshape(n_node, F * C * 2)
    best = jnp.argmax(flat, axis=1)     # first max -> lowest fid (tie-break)
    # max() rather than flat[best]: the gather is slow as a vmap-batched
    # op on TPU, and max/argmax scan the same array
    best_gain = flat.max(axis=1)
    feature = (best // (C * 2)).astype(jnp.int32)
    cut_index = ((best // 2) % C).astype(jnp.int32)
    default_left = (best % 2).astype(jnp.bool_)
    # accept: positive reduction (reference loss_chg > rt_eps,
    # histmaker-inl.hpp:253).  gamma is NOT applied here: the prune updater
    # post-prunes loss_chg < min_split_loss bottom-up
    # (updater_prune-inl.hpp:42-72), which keeps a weak split whose
    # descendants are strong — pre-pruning would not.
    valid = best_gain > RT_EPS
    # winner's left-child sums, gather-free (one-hot contraction over the
    # flat candidate axis — batched gathers serialize on TPU)
    sel = jax.nn.one_hot(best, flat.shape[1], dtype=jnp.float32)
    left_g = (GL.reshape(n_node, -1) * sel).sum(axis=1)
    left_h = (HL.reshape(n_node, -1) * sel).sum(axis=1)
    return BestSplit(best_gain, feature, cut_index, default_left, valid,
                     left_g, left_h)


def find_best_splits_native(hist: jax.Array, nstats: jax.Array,
                            n_cuts: jax.Array, cfg: SplitConfig,
                            feature_mask: jax.Array | None = None
                            ) -> BestSplit:
    """:func:`find_best_splits` on the histogram kernel's NATIVE layout
    ``(F, B, 2, n_node)`` — node minor, exactly how the pallas kernel
    writes it.  Skipping the (n_node, F, B, 2) relayout saves ~0.47
    ms/round at the bench shape (round-5 trace), and the cumsum runs
    along a sublane dim with nodes riding the lanes.  Candidate order,
    tie-breaks and math are identical to the standard layout (same
    (feature, cut, dir) flattening, argmax-first tie-break) — pinned
    bitwise by
    tests/test_pallas_hist.py::test_native_split_finder_matches_standard.
    """
    F, B, _, n_node = hist.shape
    C = B - 2
    cum = jnp.cumsum(hist, axis=1)               # (F, B, 2, M)
    miss = hist[:, 0, :, :]                      # (F, 2, M)
    total = nstats.T[None, None, None, :, :]     # (1, 1, 1, 2, M)

    left_excl = cum[:, 1:C + 1, :, :] - miss[:, None, :, :]  # (F, C, 2, M)
    left = jnp.stack([left_excl, left_excl + miss[:, None, :, :]],
                     axis=2)                     # (F, C, 2dir, 2, M)
    right = total - left

    GL, HL = left[..., 0, :], left[..., 1, :]    # (F, C, 2dir, M)
    GR, HR = right[..., 0, :], right[..., 1, :]
    root_gain = calc_gain(nstats[:, 0], nstats[:, 1], cfg)   # (M,)
    loss_chg = (calc_gain(GL, HL, cfg) + calc_gain(GR, HR, cfg)
                - root_gain[None, None, None, :])

    ok = (HL >= cfg.min_child_weight) & (HR >= cfg.min_child_weight)
    cut_ids = jnp.arange(C, dtype=jnp.int32)
    ok &= (cut_ids[None, :] < n_cuts[:, None])[:, :, None, None]
    if feature_mask is not None:
        ok &= feature_mask[:, None, None, None]
    if cfg.default_direction == 1:    # forced left
        ok &= jnp.array([False, True])[None, None, :, None]
    elif cfg.default_direction == 2:  # forced right
        ok &= jnp.array([True, False])[None, None, :, None]
    loss_chg = jnp.where(ok, loss_chg, NEG)

    flat = loss_chg.reshape(F * C * 2, n_node)
    best = jnp.argmax(flat, axis=0).astype(jnp.int32)
    best_gain = flat.max(axis=0)
    feature = (best // (C * 2)).astype(jnp.int32)
    cut_index = ((best // 2) % C).astype(jnp.int32)
    default_left = (best % 2).astype(jnp.bool_)
    valid = best_gain > RT_EPS
    ids = jnp.arange(F * C * 2, dtype=jnp.int32)
    sel = (ids[:, None] == best[None, :]).astype(jnp.float32)
    left_g = (GL.reshape(F * C * 2, n_node) * sel).sum(axis=0)
    left_h = (HL.reshape(F * C * 2, n_node) * sel).sum(axis=0)
    return BestSplit(best_gain, feature, cut_index, default_left, valid,
                     left_g, left_h)

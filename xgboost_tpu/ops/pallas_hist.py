"""Pallas TPU histogram kernel — the one custom kernel in the framework.

Replaces the reference's per-thread histogram accumulation
(``src/tree/updater_histmaker-inl.hpp:296-348``) for the hot path.  A
scatter-add over (node, feature, bin) cells serializes on TPU; this
kernel reformulates the histogram as MXU matmuls:

  For a row tile of R rows and one feature f:
      onehot[b, r]   = 1 iff binned[f, r] == b               (B, R)
      gh_exp[r, l]   = gh[r, l // M] * (pos[r] == l % M)     (R, 2M)
      hist_f        += onehot @ gh_exp                       (B, 2M)

  i.e. the per-node gradient/hessian sums of every bin fall out of a
  single (B x R) @ (R x 2M) matmul with the level's M nodes (and the
  grad/hess channel) packed into the MXU lane dimension.  At the deepest
  default level (depth 6, M = 64) the lane dim is exactly 128 — a full
  MXU pass.  Inactive rows (pos < 0, i.e. parked / padding /
  subsampled-out shards) contribute nothing because the node mask never
  matches.

Bins are consumed feature-major ((F, N), int32) so every block satisfies
the TPU (8, 128) tile rule; the (N, F) -> (F, N) transpose happens once
per jit trace (CSE collapses the per-level copies inside one tree).

Grid: (feature_tiles, row_tiles), row tiles innermost so each feature
tile's output block accumulates across row tiles in VMEM.

The XLA scatter in :mod:`xgboost_tpu.ops.histogram` remains the portable
fallback (CPU mesh tests, interpret-free debugging).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                 n_bin: int, m_pad: int, f_tile: int, precision_mode: str,
                 rpl: int):
    """One (node_tile, feature_tile, row_tile) grid step.

    binned_ref: (f_tile, R) u8|int32 bin ids, feature-major
    pos_ref:    (1, R) int32 node position (-1 = inactive)
    gh_ref:     (2, R) f32|int32 grad/hess
    out_ref:    (f_tile * n_bin, 2 * m_pad) accumulator for the m_pad
                nodes of THIS node tile (grid dim 0) — deep levels
                (n_node > m_pad) tile the node dim so the block never
                outgrows VMEM.
    rpl:        row tiles per accumulator block.  The solo call passes
                its whole row-tile count (init fires once, at row tile
                0); the LANE-stacked call (gang-batched multi-tenant
                training, _hist_pallas_lanes_pre) packs L tenants'
                rows end-to-end along the row grid with one output
                block per (lane, node tile) — init fires at each
                lane's first row tile.

    EVERY per-row operand keeps rows in the LANE dim: TPU arrays tile
    to (8, 128), so (N, 1)/(N, 2) operands are physically inflated
    128x/64x — the per-level reshape copies of the old (R, 1) pos
    alone cost ~5 ms/round at 1M rows (round-4 trace).  gh_exp is
    therefore built (2M, R) and the dot contracts both operands' lane
    dim (the natural NT matmul).
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    m_base = pl.program_id(0) * m_pad  # first global node of this tile

    @pl.when(pl.program_id(2) % rpl == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[0:1, :]                                    # (1, R)
    # gh_exp[l, r] = gh[l // m_pad, r] masked by (pos[r] == l % m_pad)
    sub = jax.lax.broadcasted_iota(jnp.int32, (m2, r_tile), 0)
    node_of_sub = m_base + jnp.where(sub < m_pad, sub, sub - m_pad)
    ghsel = jnp.where(sub < m_pad, gh_ref[0:1, :], gh_ref[1:2, :])
    active = (pos == node_of_sub)                            # (2M, R)

    # TPU matmul default precision truncates f32 operands to bf16; fp32
    # mode must request HIGHEST for exact (parity-testable) histograms.
    # In bf16 mode, materialize the operands in bf16 up front: the MXU
    # would truncate them anyway, and halving the one-hot's VMEM
    # footprint is a measured ~20% kernel win (tools/hist_microbench.py).
    # int8 mode (gh arrives PRE-QUANTIZED as int32, one-hot is int8,
    # products accumulate exactly in int32): the v5e MXU runs int8 at
    # 2x the bf16 rate with half the operand bytes — measured ~9x on
    # the kernel, 0.55 vs ~4.7 ms/level (tools/hist_int8_proto.py).
    if precision_mode == "int8":
        gh_exp = jnp.where(active, ghsel, 0).astype(jnp.int8)
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.int8
        acc_dtype = jnp.int32
    elif precision_mode == "fp32":
        gh_exp = jnp.where(active, ghsel, 0.0)
        prec = jax.lax.Precision.HIGHEST  # HIGH: unsupported by Mosaic
        hot_dtype = jnp.float32
        acc_dtype = jnp.float32
    else:
        gh_exp = jnp.where(active, ghsel, 0.0).astype(jnp.bfloat16)
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        acc_dtype = jnp.float32
    # bins may arrive u8 (the entry's resident pre-transposed operand —
    # zero per-round transpose/layout-copy cost) or int32 (the
    # in-graph transpose fallback); widen in-register either way
    bins = binned_ref[:].astype(jnp.int32)                   # (f_tile, R)
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)  # (B, R)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (1,)), ((), ())),
            precision=prec,
            preferred_element_type=acc_dtype)                # (B, 2M)
        out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc


def resolve_precision(precision: str, n_rows: int) -> str:
    """int8 needs int32-safe cell accumulators (N * 127 < 2^31)."""
    if precision == "int8" and n_rows * 127 >= 2 ** 31:
        return "bf16"
    return precision


def _tiling(N: int, F: int, n_bin: int):
    """(r_tile, f_tile, n_pad, f_pad) — level-independent (f_tile's
    lane bound max(2M, 128) = 128 for every m_pad <= 64)."""
    # read at trace time: changing it after the first same-shape call
    # has no effect (jit cache) — set it before the first training
    # round.  2048 measured best on v5e at 1M x 28
    # (tools/hist_microbench.py); >= 8192 fails Mosaic compilation.
    r_tile = int(os.environ.get("XGBTPU_HIST_RTILE", "2048"))
    # feature tile sized so the output block (f_tile*B, 2M) f32 stays
    # ~<=1MB of VMEM
    f_tile = max(1, min(F, (256 * 1024) // (max(n_bin, 1) * 128)))
    # TPU tile rule: a block's sublane dim must be a multiple of 8 OR
    # equal the full array dim
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    return (r_tile, f_tile, _round_up(max(N, 1), r_tile),
            _round_up(F, f_tile))


def quantize_gh(gh: jax.Array) -> tuple:
    """Symmetric per-channel int8 quantization of (..., N, 2) grad/hess
    (batched leading axes quantize per slice): (gh_q int32, scale f32).
    Quantize ONCE per round — g is fixed within a round; int8 products
    accumulate exactly in int32 so this is the only error source
    (~scale/254 per element, vs bf16's ~0.2% relative truncation)."""
    scale = jnp.maximum(jnp.max(jnp.abs(gh), axis=-2), 1e-30)
    gh_q = jnp.clip(jnp.round(gh / scale[..., None, :] * 127.0),
                    -127, 127).astype(jnp.int32)
    return gh_q, scale


def host_transpose_bins(binned_host, n_bin: int):
    """HOST-side (F, n_pad) u8 pre-transpose — built once per dataset
    and kept device-resident (standard layout) so the kernel pays zero
    per-round transpose and none of the per-pallas-call layout copies
    the in-graph transpose incurs (~7 ms/round at 1M x 28, round-4
    trace).  Returns None when the feature dim would be tiled (u8
    sublane tiles need 32-multiples; only the full-dim case is
    supported — F <= f_tile, true for the default bin counts)."""
    import numpy as np
    N, F = binned_host.shape
    r_tile, f_tile, n_pad, f_pad = _tiling(N, F, n_bin)
    if f_tile != F or n_bin > 256:
        # u8 can't hold >256 bin ids (binning emits uint16 there), and
        # a tiled feature dim would break the u8 (32, 128) tile rule
        return None
    bt = np.zeros((F, n_pad), np.uint8)
    bt[:, :N] = np.asarray(binned_host, np.uint8).T
    return bt


def transpose_bins(binned: jax.Array, n_bin: int) -> jax.Array:
    """(N, F) bins -> the kernel's padded (f_pad, n_pad) int32 operand.
    Compute ONCE per tree: left per level, XLA re-materializes the
    112 MB transpose+pad inside the fused round scan every level
    (measured ~7 ms/round of copies at 1M x 28 — round-4 trace)."""
    N, F = binned.shape
    r_tile, f_tile, n_pad, f_pad = _tiling(N, F, n_bin)
    binned_t = binned.astype(jnp.int32).T
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
    return binned_t


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas(binned: jax.Array, gh: jax.Array,
                                 pos: jax.Array, n_node: int, n_bin: int,
                                 precision: str = "fp32",
                                 interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.build_level_histogram``.

    Args match the XLA version; ``precision`` selects the MXU mode:
    "fp32" (HIGHEST, exact f32 — parity-testable against the scatter),
    "bf16" (DEFAULT, ~3x faster; operands truncated to bf16 inside the
    MXU, accumulation still f32), or "int8" (gradients quantized per
    call to 8 bits, int32-exact accumulation, ~9x the bf16 kernel —
    element error ~s/254 vs bf16's ~0.2% relative truncation).

    Returns (n_node, F, n_bin, 2) float32.
    """
    N, F = binned.shape
    precision = resolve_precision(precision, N)
    binned_t = transpose_bins(binned, n_bin)
    if precision == "int8":
        gh_in, scale = quantize_gh(gh)
    else:
        gh_in, scale = gh.astype(jnp.float32), None
    return _hist_pallas_pre(binned_t, gh_in, scale, pos, (N, F), n_node,
                            n_bin, precision, interpret)


def _hist_pallas_pre(binned_t, gh_in, scale, pos, nf, n_node: int,
                     n_bin: int, precision: str, interpret: bool,
                     native: bool = False) -> jax.Array:
    """Kernel invocation on PREPARED operands (transpose_bins /
    quantize_gh hoisted to once per tree/round by the grow loop).

    ``native=True`` returns the kernel's own ``(F, B, 2, n_node)``
    layout (node minor) without the relayout transpose — consumed by
    split.find_best_splits_native; callers gate on n_node <= 64
    (single node tile)."""
    N, F = nf
    r_tile, f_tile, n_pad, f_pad = _tiling(N, F, n_bin)
    # deep levels tile the node dim at 64 (lane dim 2*64 = one full MXU
    # pass) so the accumulator block stays VMEM-bounded at any depth
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    # rows ride the LANE dim of every per-row operand (see _hist_kernel)
    pos_t = jnp.pad(pos.astype(jnp.int32), (0, n_pad - N),
                    constant_values=-1)[None, :]             # (1, n_pad)
    gh_t = jnp.pad(gh_in.T, ((0, 0), (0, n_pad - N)))        # (2, n_pad)

    out_dtype = jnp.int32 if precision == "int8" else jnp.float32
    kernel = functools.partial(_hist_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, precision_mode=precision,
                               rpl=n_pad // r_tile)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((1, r_tile), lambda mi, fi, ri: (0, ri)),
            pl.BlockSpec((2, r_tile), lambda mi, fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec((1, f_tile * n_bin, 2 * m_pad),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * n_bin, 2 * m_pad),
                                       out_dtype),
        interpret=interpret,
    )(binned_t, pos_t, gh_t)

    if native:
        assert n_m_tiles == 1, "native layout needs a single node tile"
        out = out.reshape(f_pad, n_bin, 2, m_pad)[:F, :, :, :n_node]
        if precision == "int8":
            out = (out.astype(jnp.float32)
                   * (scale / 127.0)[None, None, :, None])
        return out
    # (m_tiles, f_pad*B, 2M) -> (m_tiles, F, B, 2, M) -> (m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, f_pad, n_bin, 2, m_pad)
    out = out.transpose(0, 4, 1, 2, 3).reshape(
        n_m_tiles * m_pad, f_pad, n_bin, 2)
    out = out[:n_node, :F, :, :]
    if precision == "int8":
        # dequantize the exact int32 sums back to f32 cell values
        out = out.astype(jnp.float32) * (scale / 127.0)[None, None, None, :]
    return out


def _hist_pallas_lanes_pre(binned_t, gh_in, scale, pos, nf, n_node: int,
                           n_bin: int, precision: str, interpret: bool,
                           native: bool = False) -> jax.Array:
    """LANE-stacked kernel invocation: a leading axis L batches WHOLE
    tenant datasets (gang-batched multi-tenant training — each lane has
    its own bins, so the tree-batched kernel's shared one-hot does not
    apply).  Lanes pack end-to-end along the ROW grid dimension at
    per-lane n_pad granularity, and the output index map gives every
    (lane, node tile) its own accumulator block: each lane's block sees
    exactly the row-tile sequence (content, order, and tile grouping)
    of that lane's solo :func:`_hist_pallas_pre` call, so per-lane
    results are BITWISE identical to solo — including signed zeros —
    in every precision mode.  One launch, L x the solo grid.

    binned_t (L, f_pad, n_pad); gh_in (L, N, 2) f32|int32;
    scale (L, 2) f32 in int8 mode else None; pos (L, N) int32.
    Returns (L, n_node, F, B, 2) f32 — or (L, F, B, 2, n_node) when
    ``native`` (n_node <= 64, as solo)."""
    L = binned_t.shape[0]
    N, F = nf
    r_tile, f_tile, n_pad, f_pad = _tiling(N, F, n_bin)
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    rpl = n_pad // r_tile  # row tiles per lane == per accumulator block
    pos_t = jnp.pad(pos.astype(jnp.int32), ((0, 0), (0, n_pad - N)),
                    constant_values=-1).reshape(1, L * n_pad)
    gh_t = jnp.pad(gh_in, ((0, 0), (0, n_pad - N), (0, 0)))
    gh_t = gh_t.transpose(2, 0, 1).reshape(2, L * n_pad)
    bt = binned_t.transpose(1, 0, 2).reshape(f_pad, L * n_pad)

    out_dtype = jnp.int32 if precision == "int8" else jnp.float32
    kernel = functools.partial(_hist_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, precision_mode=precision,
                               rpl=rpl)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, L * rpl),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((1, r_tile), lambda mi, fi, ri: (0, ri)),
            pl.BlockSpec((2, r_tile), lambda mi, fi, ri: (0, ri)),
        ],
        out_specs=pl.BlockSpec(
            (1, f_tile * n_bin, 2 * m_pad),
            lambda mi, fi, ri: (ri // rpl * n_m_tiles + mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (L * n_m_tiles, f_pad * n_bin, 2 * m_pad), out_dtype),
        interpret=interpret,
    )(bt, pos_t, gh_t)

    out = out.reshape(L, n_m_tiles, f_pad, n_bin, 2, m_pad)
    if native:
        assert n_m_tiles == 1, "native layout needs a single node tile"
        out = out.reshape(L, f_pad, n_bin, 2, m_pad)[:, :F, :, :, :n_node]
        if precision == "int8":
            out = (out.astype(jnp.float32)
                   * (scale / 127.0)[:, None, None, :, None])
        return out
    out = out.transpose(0, 1, 5, 2, 3, 4).reshape(
        L, n_m_tiles * m_pad, f_pad, n_bin, 2)
    out = out[:, :n_node, :F, :, :]
    if precision == "int8":
        out = (out.astype(jnp.float32)
               * (scale / 127.0)[:, None, None, None, :])
    return out


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas_lanes(binned: jax.Array, gh: jax.Array,
                                       pos: jax.Array, n_node: int,
                                       n_bin: int, precision: str = "fp32",
                                       interpret: bool = False) -> jax.Array:
    """Lane-stacked histogram from RAW per-lane operands: binned
    (L, N, F), gh (L, N, 2), pos (L, N) -> (L, n_node, F, B, 2) f32,
    bitwise equal to stacking L solo
    :func:`build_level_histogram_pallas` calls.  Selected by the
    batched-bins branch of the histogram custom_vmap rules, i.e. by
    ``jax.vmap`` over tenant lanes (gang-batched multi-tenant
    training)."""
    L, N, F = binned.shape
    precision = resolve_precision(precision, N)
    binned_t = jax.vmap(lambda b: transpose_bins(b, n_bin))(binned)
    if precision == "int8":
        gh_in, scale = quantize_gh(gh)               # per-lane (L, 2)
    else:
        gh_in, scale = gh.astype(jnp.float32), None
    return _hist_pallas_lanes_pre(binned_t, gh_in, scale, pos, (N, F),
                                  n_node, n_bin, precision, interpret)


def _batched_hist_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                         n_bin: int, m_pad: int, f_tile: int, t_tile: int,
                         precision_mode: str):
    """Tree-batched variant of :func:`_hist_kernel`: the (B, R) one-hot
    is built ONCE per (feature, row tile) and contracted against a
    (R, t_tile*2M) operand whose lane l encodes (tree, grad/hess, node):
    t = l // 2M, hess = (l % 2M) >= M, node = l % M.  Per-tree positions
    and gradients differ; the bins (and hence the one-hot — the VPU-
    bound part of the kernel) do not, so a K-class round's histogram
    cost approaches one class's instead of K's.

    The tree dim is grid-tiled (grid dim 1) so lanes and the output
    block stay VMEM-bounded at any ensemble width (num_parallel_tree
    forests): per step only ``t_tile`` trees' lanes are resident.

    binned_ref: (f_tile, R) int32;  pos_ref: (t_tile, R) int32;
    gh_ref: (2*t_tile, R) f32|int32, per-tree (g_t, h_t) sublane pairs.
    out_ref: (1, 1, f_tile*n_bin, t_tile*2*m_pad).
    Rows ride the LANE dim of every per-row operand and gh_exp is
    (lanes, R) with an NT dot, for the same physical-tiling reason as
    :func:`_hist_kernel`.
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    lanes = t_tile * m2
    m_base = pl.program_id(0) * m_pad

    @pl.when(pl.program_id(3) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    sub = jax.lax.broadcasted_iota(jnp.int32, (lanes, r_tile), 0)
    t_of = sub // m2
    within = sub - t_of * m2
    node_of = m_base + jnp.where(within < m_pad, within, within - m_pad)
    is_h = within >= m_pad

    # per-sublane gh/pos selected by tree id via t_tile broadcast
    # compares (tiles are small; dynamic gathers would serialize)
    gh_dtype = jnp.int32 if precision_mode == "int8" else jnp.float32
    ghsel = jnp.zeros((lanes, r_tile), gh_dtype)
    possel = jnp.zeros((lanes, r_tile), jnp.int32)
    for t in range(t_tile):
        sel = t_of == t
        gval = jnp.where(is_h, gh_ref[2 * t + 1:2 * t + 2, :],
                         gh_ref[2 * t:2 * t + 1, :])
        ghsel = jnp.where(sel, gval, ghsel)
        possel = jnp.where(sel, pos_ref[t:t + 1, :], possel)

    if precision_mode == "int8":
        gh_exp = jnp.where(possel == node_of, ghsel, 0).astype(jnp.int8)
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.int8
        acc_dtype = jnp.int32
    elif precision_mode == "fp32":
        gh_exp = jnp.where(possel == node_of, ghsel, 0.0)
        prec = jax.lax.Precision.HIGHEST
        hot_dtype = jnp.float32
        acc_dtype = jnp.float32
    else:
        gh_exp = jnp.where(possel == node_of, ghsel,
                           0.0).astype(jnp.bfloat16)
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        acc_dtype = jnp.float32

    bins = binned_ref[:].astype(jnp.int32)
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (1,)), ((), ())),
            precision=prec, preferred_element_type=acc_dtype)
        out_ref[0, 0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas_batched(binned: jax.Array, gh: jax.Array,
                                         pos: jax.Array, n_node: int,
                                         n_bin: int, precision: str = "fp32",
                                         interpret: bool = False) -> jax.Array:
    """Tree-batched histogram: gh (T, N, 2), pos (T, N), binned (N, F).

    Returns (T, n_node, F, n_bin, 2) f32, bitwise equal (in fp32 mode)
    to stacking T calls of :func:`build_level_histogram_pallas`.
    Selected by the custom_vmap rule of
    :func:`xgboost_tpu.ops.histogram.build_level_histogram`, i.e. by
    ``jax.vmap`` of tree growth over an ensemble axis.
    """
    T, N, _ = gh.shape
    F = binned.shape[1]
    precision = resolve_precision(precision, N)
    if precision == "int8":
        gh, scale = quantize_gh(gh)                  # per-tree (T, 2)
    else:
        scale = None
    return _hist_pallas_batched_pre(
        transpose_bins_batched(binned, n_bin, T, min(n_node, 64),
                               precision), gh, scale,
        pos, (N, F), n_node, n_bin, precision, interpret)


def _hist_pallas_batched_prequant(binned, gh_in, scale, pos, n_node: int,
                                  n_bin: int, precision: str,
                                  interpret: bool,
                                  native: bool = False) -> jax.Array:
    """Batched kernel from RAW bins + pre-quantized gradients (the
    ensemble vmap rule of the prep path: batched tiling depends on the
    tree count, so the transpose happens here per call).  ``native``
    emits (T, F, B, 2, n_node) in the same single relayout pass the
    standard order takes."""
    T, N, _ = gh_in.shape
    F = binned.shape[1]
    return _hist_pallas_batched_pre(
        transpose_bins_batched(binned, n_bin, T, min(n_node, 64),
                               precision), gh_in,
        scale, pos, (N, F), n_node, n_bin, precision, interpret,
        native=native)


def transpose_bins_batched(binned, n_bin: int, T: int, m_pad: int,
                           precision: str):
    """Padded (f_pad, n_pad) int32 operand for the BATCHED kernel (its
    r/f tiling depends on the tree count, level and precision)."""
    N, F = binned.shape
    r_tile, f_tile, _, n_pad, f_pad, *_ = _tiling_batched(
        N, F, n_bin, T, m_pad, precision)
    binned_t = binned.astype(jnp.int32).T
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
    return binned_t


def _t_tile_of(T, m2, n_bin):
    """Trees per grid step: t_tile trees give lanes = t_tile*2M and an
    output block of f_tile*B x lanes f32, both VMEM-bounded at ANY
    ensemble width (num_parallel_tree forests)."""
    return max(1, min(T, max(1, 768 // m2),
                      (2 << 20) // (8 * max(n_bin, 1) * m2 * 4)))


def _tiling_batched(N, F, n_bin, T, m_pad, precision):
    """Per-LEVEL r/f tiling for the batched kernel (the batched path
    re-transposes its bins per call, so no cross-level layout sharing
    is needed).  Returns (r_tile, f_tile, t_tile, n_pad, f_pad,
    lanes)."""
    r_tile = int(os.environ.get("XGBTPU_HIST_RTILE", "2048"))
    m2 = 2 * m_pad
    t_tile = _t_tile_of(T, m2, n_bin)
    lanes = t_tile * m2
    # the (r_tile, lanes) gh_exp operand: cap at ~3MB of VMEM or Mosaic
    # fails to place the kernel (seen at fp32, lanes=768, r_tile=2048).
    # int8 mode's ghsel/possel INTERMEDIATES are int32, so it budgets
    # like fp32 (scoped-vmem OOM otherwise — seen at 6 trees, B=64)
    esize = 2 if precision == "bf16" else 4
    r_cap = max(512, ((3 << 20) // (max(lanes, 128) * esize))
                // 512 * 512)
    r_tile = min(r_tile, r_cap)
    # f_tile: multiple of 8 (or the whole feature dim), output block
    # f_tile*B x lanes f32 <= ~2MB
    f_tile = max(8, min(F, (512 * 1024) // (max(n_bin, 1) *
                                            max(lanes, 128))))
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    return (r_tile, f_tile, t_tile, _round_up(max(N, 1), r_tile),
            _round_up(F, f_tile), lanes)


def _hist_pallas_batched_pre(binned_t, gh, scale, pos, nf, n_node: int,
                             n_bin: int, precision: str,
                             interpret: bool,
                             native: bool = False) -> jax.Array:
    N, F = nf
    T = gh.shape[0]
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    m2 = 2 * m_pad
    r_tile, f_tile, t_tile, n_pad, f_pad, lanes = _tiling_batched(
        N, F, n_bin, T, m_pad, precision)
    t_tiles = -(-T // t_tile)
    T_pad = t_tiles * t_tile
    if n_pad != N or T_pad != T:
        gh = jnp.pad(gh, ((0, T_pad - T), (0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, ((0, T_pad - T), (0, n_pad - N)),
                      constant_values=-1)

    # per-tree (g, h) SUBLANE pairs, rows in lanes (see _hist_kernel's
    # physical-tiling note): (T, N, 2) -> (2T, N)
    gh_flat = gh.transpose(0, 2, 1).reshape(2 * T_pad, n_pad)
    pos_t = pos.astype(jnp.int32)                    # (T_pad, N)

    kernel = functools.partial(_batched_hist_kernel, n_bin=n_bin,
                               m_pad=m_pad, f_tile=f_tile, t_tile=t_tile,
                               precision_mode=precision)
    out_dtype = jnp.int32 if precision == "int8" else jnp.float32
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, t_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, ti, fi, ri: (fi, ri)),
            pl.BlockSpec((t_tile, r_tile), lambda mi, ti, fi, ri: (ti, ri)),
            pl.BlockSpec((2 * t_tile, r_tile),
                         lambda mi, ti, fi, ri: (ti, ri)),
        ],
        out_specs=pl.BlockSpec((1, 1, f_tile * n_bin, lanes),
                               lambda mi, ti, fi, ri: (mi, ti, fi, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n_m_tiles, t_tiles, f_pad * n_bin, lanes), out_dtype),
        interpret=interpret,
    )(binned_t, pos_t,
      gh_flat if precision == "int8" else gh_flat.astype(jnp.float32))

    # (m_tiles, t_tiles, f_pad*B, t_tile*2M) -> (T, m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, t_tiles, f_pad, n_bin, t_tile, 2, m_pad)
    if native:
        # ONE relayout straight to (T, F, B, 2, m_tiles*M) — composing
        # the standard transpose with a to-native pass would copy the
        # whole histogram twice per level
        out = out.transpose(1, 4, 2, 3, 5, 0, 6).reshape(
            T_pad, f_pad, n_bin, 2, n_m_tiles * m_pad)
        out = out[:T, :F, :, :, :n_node]
        if precision == "int8":
            out = (out.astype(jnp.float32)
                   * (scale / 127.0)[:, None, None, :, None])
        return out
    out = out.transpose(1, 4, 0, 6, 2, 3, 5).reshape(
        T_pad, n_m_tiles * m_pad, f_pad, n_bin, 2)
    out = out[:T, :n_node, :F, :, :]
    if precision == "int8":
        out = (out.astype(jnp.float32)
               * (scale / 127.0)[:, None, None, None, :])
    return out


def _nst_kernel(pos_ref, gh_ref, out_ref, *, m_pad: int):
    """Per-node (G, H) sums for one row tile: ones @ gh_exp on the MXU."""
    r_tile = pos_ref.shape[0]
    m2 = 2 * m_pad

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
    ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
    gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel, 0.0)
    ones = jnp.ones((8, r_tile), jnp.float32)
    out_ref[:] += jax.lax.dot_general(
        ones, gh_exp, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_node", "interpret"))
def node_stats_pallas(gh: jax.Array, pos: jax.Array, n_node: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.node_stats``: (n_node, 2) f32.

    Exact (HIGHEST-precision dot against a ones matrix — sums of f32
    values, bit-comparable to the scatter up to addition order).
    """
    N = gh.shape[0]
    r_tile = 2048
    n_pad = _round_up(max(N, 1), r_tile)
    if n_pad != N:
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)
    kernel = functools.partial(_nst_kernel, m_pad=n_node)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // r_tile,),
        in_specs=[
            pl.BlockSpec((r_tile, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((8, 2 * n_node), lambda ri: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 2 * n_node), jnp.float32),
        interpret=interpret,
    )(pos.reshape(-1, 1).astype(jnp.int32), gh.astype(jnp.float32))
    return out[0].reshape(2, n_node).T  # (n_node, 2)

"""Pallas TPU histogram kernel — the one custom kernel in the framework.

Replaces the reference's per-thread histogram accumulation
(``src/tree/updater_histmaker-inl.hpp:296-348``) for the hot path.  A
scatter-add over (node, feature, bin) cells serializes on TPU; this
kernel reformulates the histogram as MXU matmuls:

  For a row tile of R rows and one feature f:
      onehot[b, r]   = 1 iff binned[f, r] == b               (B, R)
      gh_exp[r, l]   = gh[r, l // M] * (pos[r] == l % M)     (R, 2M)
      hist_f        += onehot @ gh_exp                       (B, 2M)

  i.e. the per-node gradient/hessian sums of every bin fall out of a
  single (B x R) @ (R x 2M) matmul with the level's M nodes (and the
  grad/hess channel) packed into the MXU lane dimension.  At the deepest
  default level (depth 6, M = 64) the lane dim is exactly 128 — a full
  MXU pass.  Inactive rows (pos < 0, i.e. parked / padding /
  subsampled-out shards) contribute nothing because the node mask never
  matches.

Bins are consumed feature-major ((F, N), int32) so every block satisfies
the TPU (8, 128) tile rule; the (N, F) -> (F, N) transpose happens once
per jit trace (CSE collapses the per-level copies inside one tree).

Grid: (feature_tiles, row_tiles), row tiles innermost so each feature
tile's output block accumulates across row tiles in VMEM.

The XLA scatter in :mod:`xgboost_tpu.ops.histogram` remains the portable
fallback (CPU mesh tests, interpret-free debugging).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                 n_bin: int, m_pad: int, f_tile: int, precision_mode: str):
    """One (node_tile, feature_tile, row_tile) grid step.

    binned_ref: (f_tile, R) int32 bin ids, feature-major
    pos_ref:    (R, 1) int32 node position (-1 = inactive)
    gh_ref:     (R, 2) f32 grad/hess
    out_ref:    (f_tile * n_bin, 2 * m_pad) f32 accumulator for the
                m_pad nodes of THIS node tile (grid dim 0) — deep levels
                (n_node > m_pad) tile the node dim so the block never
                outgrows VMEM.
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    m_base = pl.program_id(0) * m_pad  # first global node of this tile

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    # gh_exp[r, l] = gh[r, l // m_pad] masked by (pos[r] == l % m_pad);
    # built with broadcast selects (no lane concat, no relayout).
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = m_base + jnp.where(lane < m_pad, lane, lane - m_pad)
    g = gh_ref[:, 0:1]
    h = gh_ref[:, 1:2]
    ghsel = jnp.where(lane < m_pad, g, h)                    # (R, 2M)
    active = (pos[:, None] == node_of_lane)                  # (R, 2M)
    gh_exp = jnp.where(active, ghsel, 0.0)

    # TPU matmul default precision truncates f32 operands to bf16; fp32
    # mode must request HIGHEST for exact (parity-testable) histograms.
    # In bf16 mode, materialize the operands in bf16 up front: the MXU
    # would truncate them anyway, and halving the one-hot's VMEM
    # footprint is a measured ~20% kernel win (tools/hist_microbench.py).
    if precision_mode == "fp32":
        prec = jax.lax.Precision.HIGHEST  # HIGH: unsupported by Mosaic
        hot_dtype = jnp.float32
    else:
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        gh_exp = gh_exp.astype(hot_dtype)
    bins = binned_ref[:]                                     # (f_tile, R)
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)  # (B, R)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32)              # (B, 2M)
        out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas(binned: jax.Array, gh: jax.Array,
                                 pos: jax.Array, n_node: int, n_bin: int,
                                 precision: str = "fp32",
                                 interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.build_level_histogram``.

    Args match the XLA version; ``precision`` selects the MXU pass count:
    "fp32" (HIGHEST, exact f32 — parity-testable against the scatter) or
    "bf16" (DEFAULT, ~3x faster; operands truncated to bf16 inside the
    MXU, accumulation still f32).

    Returns (n_node, F, n_bin, 2) float32.
    """
    N, F = binned.shape
    # read at trace time: changing it after the first same-shape call has
    # no effect (jit cache) — set it before the first training round.
    # 2048 measured best on v5e at 1M x 28 (tools/hist_microbench.py);
    # larger tiles hit Mosaic compile failures at 8192+.
    r_tile = int(os.environ.get("XGBTPU_HIST_RTILE", "2048"))
    # deep levels tile the node dim at 64 (lane dim 2*64 = one full MXU
    # pass) so the accumulator block stays VMEM-bounded at any depth
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    # feature tile sized so the output block (f_tile*B, 2M) f32 stays
    # ~<=1MB of VMEM
    f_tile = max(1, min(F, (256 * 1024) // (max(n_bin, 1) *
                                            max(2 * m_pad, 128))))
    # TPU tile rule: a block's sublane dim must be a multiple of 8 OR
    # equal the full array dim.  Tile in multiples of 8 when tiling at
    # all; otherwise take the whole (un-padded) feature dim.
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = _round_up(F, f_tile)

    binned_t = binned.astype(jnp.int32).T                    # (F, N)
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)

    kernel = functools.partial(_hist_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, precision_mode=precision)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, f_tile * n_bin, 2 * m_pad),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * n_bin, 2 * m_pad),
                                       jnp.float32),
        interpret=interpret,
    )(binned_t, pos.reshape(-1, 1).astype(jnp.int32),
      gh.astype(jnp.float32))

    # (m_tiles, f_pad*B, 2M) -> (m_tiles, F, B, 2, M) -> (m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, f_pad, n_bin, 2, m_pad)
    out = out.transpose(0, 4, 1, 2, 3).reshape(
        n_m_tiles * m_pad, f_pad, n_bin, 2)
    return out[:n_node, :F, :, :]


def _nst_kernel(pos_ref, gh_ref, out_ref, *, m_pad: int):
    """Per-node (G, H) sums for one row tile: ones @ gh_exp on the MXU."""
    r_tile = pos_ref.shape[0]
    m2 = 2 * m_pad

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
    ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
    gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel, 0.0)
    ones = jnp.ones((8, r_tile), jnp.float32)
    out_ref[:] += jax.lax.dot_general(
        ones, gh_exp, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_node", "interpret"))
def node_stats_pallas(gh: jax.Array, pos: jax.Array, n_node: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.node_stats``: (n_node, 2) f32.

    Exact (HIGHEST-precision dot against a ones matrix — sums of f32
    values, bit-comparable to the scatter up to addition order).
    """
    N = gh.shape[0]
    r_tile = 2048
    n_pad = _round_up(max(N, 1), r_tile)
    if n_pad != N:
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)
    kernel = functools.partial(_nst_kernel, m_pad=n_node)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // r_tile,),
        in_specs=[
            pl.BlockSpec((r_tile, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((8, 2 * n_node), lambda ri: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 2 * n_node), jnp.float32),
        interpret=interpret,
    )(pos.reshape(-1, 1).astype(jnp.int32), gh.astype(jnp.float32))
    return out[0].reshape(2, n_node).T  # (n_node, 2)

"""Pallas TPU histogram kernel — the one custom kernel in the framework.

Replaces the reference's per-thread histogram accumulation
(``src/tree/updater_histmaker-inl.hpp:296-348``) for the hot path.  A
scatter-add over (node, feature, bin) cells serializes on TPU; this
kernel reformulates the histogram as MXU matmuls:

  For a row tile of R rows and one feature f:
      onehot[b, r]   = 1 iff binned[f, r] == b               (B, R)
      gh_exp[r, l]   = gh[r, l // M] * (pos[r] == l % M)     (R, 2M)
      hist_f        += onehot @ gh_exp                       (B, 2M)

  i.e. the per-node gradient/hessian sums of every bin fall out of a
  single (B x R) @ (R x 2M) matmul with the level's M nodes (and the
  grad/hess channel) packed into the MXU lane dimension.  At the deepest
  default level (depth 6, M = 64) the lane dim is exactly 128 — a full
  MXU pass.  Inactive rows (pos < 0, i.e. parked / padding /
  subsampled-out shards) contribute nothing because the node mask never
  matches.

Bins are consumed feature-major ((F, N), int32) so every block satisfies
the TPU (8, 128) tile rule; the (N, F) -> (F, N) transpose happens once
per jit trace (CSE collapses the per-level copies inside one tree).

Grid: (feature_tiles, row_tiles), row tiles innermost so each feature
tile's output block accumulates across row tiles in VMEM.

The XLA scatter in :mod:`xgboost_tpu.ops.histogram` remains the portable
fallback (CPU mesh tests, interpret-free debugging).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                 n_bin: int, m_pad: int, f_tile: int, precision_mode: str):
    """One (node_tile, feature_tile, row_tile) grid step.

    binned_ref: (f_tile, R) int32 bin ids, feature-major
    pos_ref:    (R, 1) int32 node position (-1 = inactive)
    gh_ref:     (R, 2) f32 grad/hess
    out_ref:    (f_tile * n_bin, 2 * m_pad) f32 accumulator for the
                m_pad nodes of THIS node tile (grid dim 0) — deep levels
                (n_node > m_pad) tile the node dim so the block never
                outgrows VMEM.
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    m_base = pl.program_id(0) * m_pad  # first global node of this tile

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    # gh_exp[r, l] = gh[r, l // m_pad] masked by (pos[r] == l % m_pad);
    # built with broadcast selects (no lane concat, no relayout).
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = m_base + jnp.where(lane < m_pad, lane, lane - m_pad)
    g = gh_ref[:, 0:1]
    h = gh_ref[:, 1:2]
    ghsel = jnp.where(lane < m_pad, g, h)                    # (R, 2M)
    active = (pos[:, None] == node_of_lane)                  # (R, 2M)
    gh_exp = jnp.where(active, ghsel, 0.0)

    # TPU matmul default precision truncates f32 operands to bf16; fp32
    # mode must request HIGHEST for exact (parity-testable) histograms.
    # In bf16 mode, materialize the operands in bf16 up front: the MXU
    # would truncate them anyway, and halving the one-hot's VMEM
    # footprint is a measured ~20% kernel win (tools/hist_microbench.py).
    if precision_mode == "fp32":
        prec = jax.lax.Precision.HIGHEST  # HIGH: unsupported by Mosaic
        hot_dtype = jnp.float32
    else:
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        gh_exp = gh_exp.astype(hot_dtype)
    bins = binned_ref[:]                                     # (f_tile, R)
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)  # (B, R)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=prec,
            preferred_element_type=jnp.float32)              # (B, 2M)
        out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas(binned: jax.Array, gh: jax.Array,
                                 pos: jax.Array, n_node: int, n_bin: int,
                                 precision: str = "fp32",
                                 interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.build_level_histogram``.

    Args match the XLA version; ``precision`` selects the MXU pass count:
    "fp32" (HIGHEST, exact f32 — parity-testable against the scatter) or
    "bf16" (DEFAULT, ~3x faster; operands truncated to bf16 inside the
    MXU, accumulation still f32).

    Returns (n_node, F, n_bin, 2) float32.
    """
    N, F = binned.shape
    # read at trace time: changing it after the first same-shape call has
    # no effect (jit cache) — set it before the first training round.
    # 2048 measured best on v5e at 1M x 28 (tools/hist_microbench.py);
    # larger tiles hit Mosaic compile failures at 8192+.
    r_tile = int(os.environ.get("XGBTPU_HIST_RTILE", "2048"))
    # deep levels tile the node dim at 64 (lane dim 2*64 = one full MXU
    # pass) so the accumulator block stays VMEM-bounded at any depth
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    # feature tile sized so the output block (f_tile*B, 2M) f32 stays
    # ~<=1MB of VMEM
    f_tile = max(1, min(F, (256 * 1024) // (max(n_bin, 1) *
                                            max(2 * m_pad, 128))))
    # TPU tile rule: a block's sublane dim must be a multiple of 8 OR
    # equal the full array dim.  Tile in multiples of 8 when tiling at
    # all; otherwise take the whole (un-padded) feature dim.
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = _round_up(F, f_tile)

    binned_t = binned.astype(jnp.int32).T                    # (F, N)
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)

    kernel = functools.partial(_hist_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, precision_mode=precision)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, f_tile * n_bin, 2 * m_pad),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * n_bin, 2 * m_pad),
                                       jnp.float32),
        interpret=interpret,
    )(binned_t, pos.reshape(-1, 1).astype(jnp.int32),
      gh.astype(jnp.float32))

    # (m_tiles, f_pad*B, 2M) -> (m_tiles, F, B, 2, M) -> (m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, f_pad, n_bin, 2, m_pad)
    out = out.transpose(0, 4, 1, 2, 3).reshape(
        n_m_tiles * m_pad, f_pad, n_bin, 2)
    return out[:n_node, :F, :, :]


def _batched_hist_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                         n_bin: int, m_pad: int, f_tile: int, t_tile: int,
                         precision_mode: str):
    """Tree-batched variant of :func:`_hist_kernel`: the (B, R) one-hot
    is built ONCE per (feature, row tile) and contracted against a
    (R, t_tile*2M) operand whose lane l encodes (tree, grad/hess, node):
    t = l // 2M, hess = (l % 2M) >= M, node = l % M.  Per-tree positions
    and gradients differ; the bins (and hence the one-hot — the VPU-
    bound part of the kernel) do not, so a K-class round's histogram
    cost approaches one class's instead of K's.

    The tree dim is grid-tiled (grid dim 1) so lanes and the output
    block stay VMEM-bounded at any ensemble width (num_parallel_tree
    forests): per step only ``t_tile`` trees' lanes are resident.

    binned_ref: (f_tile, R) int32;  pos_ref: (R, t_tile) int32;
    gh_ref: (R, 2*t_tile) f32, INTERLEAVED per tree (g_t, h_t pairs) so
    tree tiles are contiguous lane blocks;
    out_ref: (1, 1, f_tile*n_bin, t_tile*2*m_pad) f32.
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    lanes = t_tile * m2
    m_base = pl.program_id(0) * m_pad

    @pl.when(pl.program_id(3) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, lanes), 1)
    t_of = lane // m2
    within = lane - t_of * m2
    node_of = m_base + jnp.where(within < m_pad, within, within - m_pad)
    is_h = within >= m_pad

    # per-lane gh/pos selected by tree id via t_tile broadcast compares
    # (tiles are small; dynamic lane gathers would serialize)
    gh = gh_ref[:]                                   # (R, 2*t_tile)
    pos = pos_ref[:]                                 # (R, t_tile)
    ghsel = jnp.zeros((r_tile, lanes), jnp.float32)
    possel = jnp.zeros((r_tile, lanes), jnp.int32)
    for t in range(t_tile):
        sel = t_of == t
        gval = jnp.where(is_h, gh[:, 2 * t + 1:2 * t + 2],
                         gh[:, 2 * t:2 * t + 1])
        ghsel = jnp.where(sel, gval, ghsel)
        possel = jnp.where(sel, pos[:, t:t + 1], possel)
    gh_exp = jnp.where(possel == node_of, ghsel, 0.0)

    if precision_mode == "fp32":
        prec = jax.lax.Precision.HIGHEST
        hot_dtype = jnp.float32
    else:
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        gh_exp = gh_exp.astype(hot_dtype)

    bins = binned_ref[:]
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=prec, preferred_element_type=jnp.float32)
        out_ref[0, 0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret"))
def build_level_histogram_pallas_batched(binned: jax.Array, gh: jax.Array,
                                         pos: jax.Array, n_node: int,
                                         n_bin: int, precision: str = "fp32",
                                         interpret: bool = False) -> jax.Array:
    """Tree-batched histogram: gh (T, N, 2), pos (T, N), binned (N, F).

    Returns (T, n_node, F, n_bin, 2) f32, bitwise equal (in fp32 mode)
    to stacking T calls of :func:`build_level_histogram_pallas`.
    Selected by the custom_vmap rule of
    :func:`xgboost_tpu.ops.histogram.build_level_histogram`, i.e. by
    ``jax.vmap`` of tree growth over an ensemble axis.
    """
    T, N, _ = gh.shape
    F = binned.shape[1]
    r_tile = int(os.environ.get("XGBTPU_HIST_RTILE", "2048"))
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    m2 = 2 * m_pad
    # tile the tree dim so per-step lanes and the output block stay
    # VMEM-bounded at ANY ensemble width: t_tile trees give lanes =
    # t_tile*2M and an output block of f_tile*B x lanes f32 (<= ~2MB
    # with the minimum legal f_tile of 8)
    t_tile = max(1, min(T, max(1, 768 // m2),
                        (2 << 20) // (8 * max(n_bin, 1) * m2 * 4)))
    t_tiles = -(-T // t_tile)
    T_pad = t_tiles * t_tile
    lanes = t_tile * m2
    # the (r_tile, lanes) gh_exp operand: cap at ~3MB of VMEM or Mosaic
    # fails to place the kernel (seen at fp32, lanes=768, r_tile=2048)
    esize = 4 if precision == "fp32" else 2
    r_cap = max(512, ((3 << 20) // (max(lanes, 1) * esize)) // 512 * 512)
    r_tile = min(r_tile, r_cap)
    # f_tile: multiple of 8 (or the whole feature dim), output block
    # f_tile*B x lanes f32 <= ~2MB
    f_tile = max(8, min(F, (512 * 1024) // (max(n_bin, 1) *
                                            max(lanes, 128))))
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = _round_up(F, f_tile)

    binned_t = binned.astype(jnp.int32).T
    if n_pad != N or f_pad != F or T_pad != T:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, T_pad - T), (0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, ((0, T_pad - T), (0, n_pad - N)),
                      constant_values=-1)

    # interleaved per-tree (g, h) lane pairs so a t_tile block is one
    # contiguous lane slice: (T, N, 2) -> (N, 2T)
    gh_flat = gh.transpose(1, 0, 2).reshape(n_pad, 2 * T_pad)
    pos_t = pos.T.astype(jnp.int32)                  # (N, T_pad)

    kernel = functools.partial(_batched_hist_kernel, n_bin=n_bin,
                               m_pad=m_pad, f_tile=f_tile, t_tile=t_tile,
                               precision_mode=precision)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, t_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, ti, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, t_tile), lambda mi, ti, fi, ri: (ri, ti)),
            pl.BlockSpec((r_tile, 2 * t_tile),
                         lambda mi, ti, fi, ri: (ri, ti)),
        ],
        out_specs=pl.BlockSpec((1, 1, f_tile * n_bin, lanes),
                               lambda mi, ti, fi, ri: (mi, ti, fi, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (n_m_tiles, t_tiles, f_pad * n_bin, lanes), jnp.float32),
        interpret=interpret,
    )(binned_t, pos_t, gh_flat.astype(jnp.float32))

    # (m_tiles, t_tiles, f_pad*B, t_tile*2M) -> (T, m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, t_tiles, f_pad, n_bin, t_tile, 2, m_pad)
    out = out.transpose(1, 4, 0, 6, 2, 3, 5).reshape(
        T_pad, n_m_tiles * m_pad, f_pad, n_bin, 2)
    return out[:T, :n_node, :F, :, :]


def _nst_kernel(pos_ref, gh_ref, out_ref, *, m_pad: int):
    """Per-node (G, H) sums for one row tile: ones @ gh_exp on the MXU."""
    r_tile = pos_ref.shape[0]
    m2 = 2 * m_pad

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
    ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
    gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel, 0.0)
    ones = jnp.ones((8, r_tile), jnp.float32)
    out_ref[:] += jax.lax.dot_general(
        ones, gh_exp, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("n_node", "interpret"))
def node_stats_pallas(gh: jax.Array, pos: jax.Array, n_node: int,
                      interpret: bool = False) -> jax.Array:
    """Pallas drop-in for ``histogram.node_stats``: (n_node, 2) f32.

    Exact (HIGHEST-precision dot against a ones matrix — sums of f32
    values, bit-comparable to the scatter up to addition order).
    """
    N = gh.shape[0]
    r_tile = 2048
    n_pad = _round_up(max(N, 1), r_tile)
    if n_pad != N:
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)
    kernel = functools.partial(_nst_kernel, m_pad=n_node)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // r_tile,),
        in_specs=[
            pl.BlockSpec((r_tile, 1), lambda ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((8, 2 * n_node), lambda ri: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 2 * n_node), jnp.float32),
        interpret=interpret,
    )(pos.reshape(-1, 1).astype(jnp.int32), gh.astype(jnp.float32))
    return out[0].reshape(2, n_node).T  # (n_node, 2)

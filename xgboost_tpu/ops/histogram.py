"""Gradient/hessian histogram accumulation.

The TPU-native replacement for the reference's per-thread histogram
loops (``src/tree/updater_histmaker-inl.hpp:296-348``): one scatter-add
over ``(node, feature, bin)`` cells per tree level, executed on device.
Every (active) row contributes exactly one bin per feature — including
the reserved missing bin 0 — so the per-node totals equal the bin-sums
of any single feature.

A Pallas kernel variant lives in :mod:`xgboost_tpu.ops.pallas_hist`
(selected automatically on TPU); this XLA scatter is the portable path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def build_level_histogram(binned: jax.Array, gh: jax.Array, pos: jax.Array,
                          n_node: int, n_bin: int) -> jax.Array:
    """Accumulate per-(node, feature, bin) grad/hess sums for one level.

    Args:
      binned: (N, F) integer bin ids (0 = missing).
      gh:     (N, 2) grad/hess per row (zeros for subsampled-out rows).
      pos:    (N,) level-local node position in [0, n_node), -1 = inactive.
      n_node: static number of nodes at this level (2**depth).
      n_bin:  static number of bins B.

    Returns: (n_node, F, B, 2) float32.
    """
    N, F = binned.shape
    f_ids = jnp.arange(F, dtype=jnp.int32)[None, :]
    flat = (pos[:, None] * F + f_ids) * n_bin + binned.astype(jnp.int32)
    # inactive rows (pos < 0) -> out-of-bounds index, dropped by the scatter
    flat = jnp.where(pos[:, None] < 0, n_node * F * n_bin, flat)
    hist = jnp.zeros((n_node * F * n_bin, 2), dtype=jnp.float32)
    hist = hist.at[flat].add(gh[:, None, :], mode="drop")
    return hist.reshape(n_node, F, n_bin, 2)


def node_stats(gh: jax.Array, pos: jax.Array, n_node: int) -> jax.Array:
    """Per-node (G, H) sums via segment-sum (reference GetNodeStats,
    ``updater_basemaker-inl.hpp:266-306``).  Returns (n_node, 2)."""
    idx = jnp.where(pos < 0, n_node, pos)
    out = jnp.zeros((n_node, 2), dtype=jnp.float32)
    return out.at[idx].add(gh, mode="drop")

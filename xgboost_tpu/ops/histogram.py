"""Gradient/hessian histogram accumulation.

The TPU-native replacement for the reference's per-thread histogram
loops (``src/tree/updater_histmaker-inl.hpp:296-348``): one scatter-add
over ``(node, feature, bin)`` cells per tree level, executed on device.
Every (active) row contributes exactly one bin per feature — including
the reserved missing bin 0 — so the per-node totals equal the bin-sums
of any single feature.

A Pallas kernel variant lives in :mod:`xgboost_tpu.ops.pallas_hist`
(selected automatically on TPU); this XLA scatter is the portable path.
Selection: env ``XGBTPU_HIST`` = ``pallas`` | ``pallas_bf16`` | ``scatter``
overrides; default is the Pallas kernel on TPU backends, scatter elsewhere.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp


def _impl(precision: str = "auto") -> str:
    if precision == "fixed":
        # deterministic fixed-point accumulation: always the scatter
        # path (on every backend) with int32 cells — see FIXED_SCALE.
        return "scatter"
    forced = os.environ.get("XGBTPU_HIST", "")
    if forced:
        if forced not in ("pallas", "pallas_bf16", "pallas_int8",
                          "scatter"):
            raise ValueError(
                f"XGBTPU_HIST={forced!r}: expected one of "
                "'pallas', 'pallas_bf16', 'pallas_int8', 'scatter'")
        return forced
    # evaluated at trace time; the default backend decides the kernel.
    # `precision` is the named TrainParam hist_precision (recorded in
    # saved models — VERDICT r2: accuracy-affecting precision must be a
    # visible parameter, not an env-var default): fp32 selects exact-f32
    # histograms; bf16 takes the bf16 MXU pass (~0.0002 AUC on higgs-1M
    # for ~1.5x round speed); int8 — the TPU auto default since round 4
    # — quantizes gradients to 8 bits per call with int32-exact
    # accumulation (measured ~9x kernel / ~2.4x round speed over bf16;
    # higgs-1M AUC matches bf16 to the bench's reporting precision).
    if jax.default_backend() != "tpu":
        return "scatter"
    if precision == "fp32":
        return "pallas"
    if precision == "bf16":
        return "pallas_bf16"
    return "pallas_int8"


@functools.lru_cache(maxsize=None)
def _pallas_hist_vmappable(n_node: int, n_bin: int, precision: str,
                           interpret: bool):
    """Pallas histogram wrapped in custom_vmap: ``jax.vmap`` over an
    ensemble axis (multiclass groups / num_parallel_tree forests,
    SURVEY.md §2.4.5) dispatches to the tree-batched kernel that builds
    the one-hot once and packs trees into MXU lanes, instead of vmap's
    default grid-prepend batching of the per-tree kernel (measured ~2x
    slower than even sequential launches).  lru-cached so the wrapped
    identity is stable for jit caches."""
    from jax.custom_batching import custom_vmap
    from xgboost_tpu.ops.pallas_hist import (
        build_level_histogram_pallas, build_level_histogram_pallas_batched,
        build_level_histogram_pallas_lanes)

    @custom_vmap
    def hist(binned, gh, pos):
        return build_level_histogram_pallas(
            binned, gh, pos, n_node, n_bin, precision=precision,
            interpret=interpret)

    @hist.def_vmap
    def _rule(axis_size, in_batched, binned, gh, pos):
        binned_b, gh_b, pos_b = in_batched
        if binned_b:
            # batched BINS = tenant lanes (gang-batched multi-tenant
            # training): no one-hot sharing possible, but the lane
            # kernel grid-packs L whole datasets into one launch with
            # per-lane accumulators — bitwise equal per lane to the
            # solo kernel (the stacked-vs-solo model byte contract)
            gg = gh if gh_b else jnp.broadcast_to(
                gh, (axis_size,) + gh.shape)
            pp = pos if pos_b else jnp.broadcast_to(
                pos, (axis_size,) + pos.shape)
            out = build_level_histogram_pallas_lanes(
                binned, gg, pp, n_node, n_bin, precision=precision,
                interpret=interpret)
            return out, True
        gg = gh if gh_b else jnp.broadcast_to(gh, (axis_size,) + gh.shape)
        pp = pos if pos_b else jnp.broadcast_to(pos, (axis_size,) + pos.shape)
        out = build_level_histogram_pallas_batched(
            binned, gg, pp, n_node, n_bin, precision=precision,
            interpret=interpret)
        return out, True

    return hist


# hist_precision="fixed": gradients are rounded to multiples of
# 1/FIXED_SCALE and accumulated in int32.  Integer addition is exactly
# associative, so the per-(node, feature, bin) sums — and therefore the
# grown trees — are bitwise identical for ANY grouping of the rows:
# single device, or row shards combined by `lax.psum` over a data mesh
# of any size (the mesh-fused parity contract,
# tests/test_mesh_fused.py).  Resolution: |g| <= 2^20/FIXED_SCALE per
# row before saturation matters; cells overflow at ~2^31/(FIXED_SCALE
# * max|g|) rows per (node, bin) — ~1M unit-scale rows at 2^11.
FIXED_SCALE = 2048.0


def dequantize_hist(hist: jax.Array) -> jax.Array:
    """Undo the "fixed" mode's int32 fixed-point encoding AFTER the
    cross-shard reduction (identity on float histograms/node stats)."""
    if jnp.issubdtype(hist.dtype, jnp.integer):
        return hist.astype(jnp.float32) * jnp.float32(1.0 / FIXED_SCALE)
    return hist


class HistPrep(NamedTuple):
    """Once-per-tree precompute for the level loop (prepare_hist):
    leaving these per level costs ~7 ms/round of re-materialized
    transposes + ~2 ms of re-quantization at 1M x 28 (round-4 trace).
    ``gh_in`` is f32 grad/hess, or int32 quantized with ``scale`` set
    in int8 mode."""
    binned: jax.Array            # the original (N, F) bins
    binned_t: jax.Array          # (f_pad, n_pad) int32 kernel operand
    gh_in: jax.Array             # (N, 2) f32 | int32
    scale: object                # (2,) f32 in int8 mode, else None
    precision: str               # resolved mode: fp32 | bf16 | int8


def prepare_hist(binned, gh, n_bin: int, precision: str = "auto",
                 binned_t=None):
    """Build a :class:`HistPrep` for the pallas path, or None when the
    scatter fallback is active (callers pass prep straight through to
    :func:`build_level_histogram`).  ``binned_t`` is an optional
    RESIDENT pre-transposed operand (pallas_hist.host_transpose_bins,
    built once per dataset by the learner entry)."""
    impl = _impl(precision)
    if not impl.startswith("pallas"):
        return None
    from xgboost_tpu.ops import pallas_hist as ph
    mode = {"pallas_bf16": "bf16", "pallas_int8": "int8",
            "pallas": "fp32"}[impl]
    mode = ph.resolve_precision(mode, binned.shape[0])
    if mode == "int8":
        gh_in, scale = ph.quantize_gh(gh)
    else:
        gh_in, scale = gh.astype(jnp.float32), None
    if binned_t is None:
        binned_t = ph.transpose_bins(binned, n_bin)
    return HistPrep(binned, binned_t, gh_in, scale, mode)


@functools.lru_cache(maxsize=None)
def _pallas_hist_pre_vmappable(n_node: int, n_bin: int, precision: str,
                               interpret: bool, has_scale: bool,
                               native: bool = False):
    """custom_vmap wrapper over PREPARED operands: the unbatched call
    runs the kernel on the hoisted transpose/quantization; a vmapped
    ensemble axis dispatches to the tree-batched kernel from the raw
    bins (its tiling depends on the tree count, so it re-transposes —
    cheap at ensemble workloads' row counts).

    ``native`` returns the kernel's (F, B, 2, n_node) layout (see
    pallas_hist._hist_pallas_pre); the batched rule asks the batched
    kernel for the native order directly (its single relayout pass
    emits either order — no extra transpose either way)."""
    from jax.custom_batching import custom_vmap
    from xgboost_tpu.ops import pallas_hist as ph

    def _nf(binned):
        return (binned.shape[0], binned.shape[1])

    if has_scale:
        @custom_vmap
        def hist(binned, binned_t, gh_in, scale, pos):
            return ph._hist_pallas_pre(binned_t, gh_in, scale, pos,
                                       _nf(binned), n_node, n_bin,
                                       precision, interpret,
                                       native=native)

        @hist.def_vmap
        def _rule(axis_size, in_batched, binned, binned_t, gh_in,
                  scale, pos):
            def bc(x, b):
                return x if b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
            if in_batched[0]:
                # batched bins = tenant lanes: the lane kernel rides
                # the prepared operands straight through (per-lane
                # int8 scales dequantize per lane after the launch)
                out = ph._hist_pallas_lanes_pre(
                    bc(binned_t, in_batched[1]),
                    bc(gh_in, in_batched[2]),
                    bc(scale, in_batched[3]), bc(pos, in_batched[4]),
                    (binned.shape[1], binned.shape[2]), n_node, n_bin,
                    precision, interpret, native=native)
                return out, True
            out = ph._hist_pallas_batched_prequant(
                binned, bc(gh_in, in_batched[2]),
                bc(scale, in_batched[3]), bc(pos, in_batched[4]),
                n_node, n_bin, precision, interpret, native=native)
            return out, True
    else:
        @custom_vmap
        def hist(binned, binned_t, gh_in, pos):
            return ph._hist_pallas_pre(binned_t, gh_in, None, pos,
                                       _nf(binned), n_node, n_bin,
                                       precision, interpret,
                                       native=native)

        @hist.def_vmap
        def _rule(axis_size, in_batched, binned, binned_t, gh_in, pos):
            def bc(x, b):
                return x if b else jnp.broadcast_to(
                    x, (axis_size,) + x.shape)
            if in_batched[0]:
                # batched bins = tenant lanes (see the has_scale rule)
                out = ph._hist_pallas_lanes_pre(
                    bc(binned_t, in_batched[1]),
                    bc(gh_in, in_batched[2]), None,
                    bc(pos, in_batched[3]),
                    (binned.shape[1], binned.shape[2]), n_node, n_bin,
                    precision, interpret, native=native)
                return out, True
            out = ph._hist_pallas_batched_prequant(
                binned, bc(gh_in, in_batched[2]), None,
                bc(pos, in_batched[3]), n_node, n_bin, precision,
                interpret, native=native)
            return out, True

    return hist


def build_level_histogram(binned: jax.Array, gh: jax.Array, pos: jax.Array,
                          n_node: int, n_bin: int,
                          precision: str = "auto",
                          prep=None, native: bool = False) -> jax.Array:
    """Accumulate per-(node, feature, bin) grad/hess sums for one level.

    Args:
      binned: (N, F) integer bin ids (0 = missing).
      gh:     (N, 2) grad/hess per row (zeros for subsampled-out rows).
      pos:    (N,) level-local node position in [0, n_node), -1 = inactive.
      n_node: static number of nodes at this level (2**depth).
      n_bin:  static number of bins B.
      precision: hist_precision TrainParam (auto | fp32 | bf16 | int8 |
              fixed).  "fixed" returns INT32 fixed-point sums (see
              FIXED_SCALE) — callers apply :func:`dequantize_hist`
              after their cross-shard reduction.
      prep:   optional :class:`HistPrep` from :func:`prepare_hist` —
              the level loop hoists the bins transpose and gradient
              quantization to once per tree instead of once per level.

    Returns: (n_node, F, B, 2) float32 — or the kernel-native
    (F, B, 2, n_node) when ``native`` (prep path only, n_node <= 64).
    """
    if prep is not None:
        fn = _pallas_hist_pre_vmappable(
            n_node, n_bin, prep.precision,
            jax.default_backend() != "tpu",
            prep.scale is not None, native)
        if prep.scale is not None:
            return fn(prep.binned, prep.binned_t, prep.gh_in,
                      prep.scale, pos)
        return fn(prep.binned, prep.binned_t, prep.gh_in, pos)
    assert not native, "native layout requires the pallas prep path"
    impl = _impl(precision)
    if impl.startswith("pallas"):
        precision = {"pallas_bf16": "bf16", "pallas_int8": "int8",
                     "pallas": "fp32"}[impl]
        fn = _pallas_hist_vmappable(
            n_node, n_bin, precision, jax.default_backend() != "tpu")
        return fn(binned, gh, pos)
    N, F = binned.shape
    f_ids = jnp.arange(F, dtype=jnp.int32)[None, :]
    flat = (pos[:, None] * F + f_ids) * n_bin + binned.astype(jnp.int32)
    # inactive rows (pos < 0) -> out-of-bounds index, dropped by the scatter
    flat = jnp.where(pos[:, None] < 0, n_node * F * n_bin, flat)
    if precision == "fixed":
        q = jnp.round(gh * FIXED_SCALE).astype(jnp.int32)
        hist = jnp.zeros((n_node * F * n_bin, 2), dtype=jnp.int32)
        hist = hist.at[flat].add(q[:, None, :], mode="drop")
        return hist.reshape(n_node, F, n_bin, 2)
    hist = jnp.zeros((n_node * F * n_bin, 2), dtype=jnp.float32)
    hist = hist.at[flat].add(gh[:, None, :], mode="drop")
    return hist.reshape(n_node, F, n_bin, 2)


def node_stats(gh: jax.Array, pos: jax.Array, n_node: int,
               precision: str = "auto") -> jax.Array:
    """Per-node (G, H) sums via segment-sum (reference GetNodeStats,
    ``updater_basemaker-inl.hpp:266-306``).  Returns (n_node, 2) —
    int32 fixed-point under ``precision="fixed"`` (same contract as
    :func:`build_level_histogram`: reduce first, then
    :func:`dequantize_hist`)."""
    if precision == "fixed":
        idx = jnp.where(pos < 0, n_node, pos)
        q = jnp.round(gh * FIXED_SCALE).astype(jnp.int32)
        out = jnp.zeros((n_node, 2), dtype=jnp.int32)
        return out.at[idx].add(q, mode="drop")
    if _impl().startswith("pallas"):
        from xgboost_tpu.ops.pallas_hist import node_stats_pallas
        return node_stats_pallas(gh, pos, n_node,
                                 interpret=jax.default_backend() != "tpu")
    idx = jnp.where(pos < 0, n_node, pos)
    out = jnp.zeros((n_node, 2), dtype=jnp.float32)
    return out.at[idx].add(gh, mode="drop")


def stats_from_histogram_native(hist: jax.Array) -> jax.Array:
    """Per-node (G, H) totals from the NATIVE (F, B, 2, n_node) layout:
    bin sums of feature 0 (same identity as stats_from_histogram)."""
    return hist[0].sum(axis=0).T


def stats_from_histogram(hist: jax.Array) -> jax.Array:
    """Per-node (G, H) totals as the bin-sums of feature 0 — every active
    row lands in exactly one bin of every feature (missing included), so
    any single feature's bin sums are the node totals.  Reusing the level
    histogram saves a full pass over the rows and keeps totals bitwise
    consistent with the children's partial sums under reduced-precision
    histogram accumulation."""
    return hist[:, 0, :, :].sum(axis=1)

"""Reader for the reference's binary model format.

Parses the C-struct model files written by the reference learner
(``src/learner/learner-inl.hpp:229-234`` SaveModel: LearnerModelParam +
objective/gbm names, then the booster blob) so models trained by the
reference CLI can be loaded, cross-checked and served by this framework
(SURVEY.md §M2).  Both on-disk encodings are handled: raw ``binf`` and
the base64 text-safe ``bs64`` mode (``learner-inl.hpp:209-252``,
``src/utils/base64-inl.h``).

Binary layout (all little-endian, struct-aligned as written by the
reference's ``fo.Write(&param, sizeof(param))``):

- learner ``ModelParam``: float base_score (already margin-transformed,
  ``learner-inl.hpp:151``), uint num_feature, int num_class, int[31]
  reserved  (``learner-inl.hpp:427-454``).
- two length-prefixed strings (uint64 len + bytes): objective name, gbm
  name.
- gbtree ``ModelParam`` (``gbtree-inl.hpp:430-484``): int num_trees,
  num_roots, num_feature, [4B pad], int64 num_pbuffer, int
  num_output_group, size_leaf_vector, int[31] reserved, [4B pad] — 160
  bytes total (verified against reference-written files).
- per tree (``model.h:26-330``): ``Param`` (6 ints + 31 reserved =
  148B), then num_nodes × ``Node`` {int parent, cleft, cright; uint
  sindex; float info} (20B), then num_nodes × ``RTreeNodeStat``
  {float loss_chg, sum_hess, base_weight; int leaf_child_cnt} (16B).
- int32 tree_info[num_trees] (per-tree class group).
- optional prediction buffer (ignored).

The converted ensemble is exact: per-feature cut sets are the model's
own distinct thresholds, so the binned traversal ``bin(v) <= j+1``
reproduces the reference's ``fvalue < split_cond`` routing bit-for-bit
(``model.h:534-566``), including the missing-value default direction
carried in sindex's top bit.
"""

from __future__ import annotations

import base64
import struct
from typing import List, Optional

import numpy as np

_LEARNER_PARAM = struct.Struct("<fIi124x")
_GBTREE_PARAM = struct.Struct("<iii4xqii128x")
_TREE_PARAM = struct.Struct("<6i124x")
_GBLINEAR_PARAM = struct.Struct("<Ii128x")
_NODE_DT = np.dtype([("parent", "<i4"), ("cleft", "<i4"), ("cright", "<i4"),
                     ("sindex", "<u4"), ("info", "<f4")])
_STAT_DT = np.dtype([("loss_chg", "<f4"), ("sum_hess", "<f4"),
                     ("base_weight", "<f4"), ("leaf_child_cnt", "<i4")])


def _read_str(data: bytes, off: int):
    (ln,) = struct.unpack_from("<Q", data, off)
    off += 8
    if ln >= (1 << 32):  # old-format compat gap (learner-inl.hpp:171-175)
        off += 4
        ln >>= 32
    s = data[off:off + ln].decode()
    return s, off + ln


def parse_reference_model(data: bytes) -> dict:
    """Parse reference model bytes into a plain dict (format-level only)."""
    if data[:4] == b"bs64":
        data = base64.b64decode(b"".join(data[5:].split()))
    elif data[:4] == b"binf":
        data = data[4:]
    # else: headerless pre-magic stream, parse from byte 0
    base_margin, num_feature, num_class = _LEARNER_PARAM.unpack_from(data, 0)
    off = _LEARNER_PARAM.size
    name_obj, off = _read_str(data, off)
    name_gbm, off = _read_str(data, off)
    out = {"base_margin": base_margin, "num_feature": num_feature,
           "num_class": num_class, "objective": name_obj, "gbm": name_gbm}
    if name_gbm == "gblinear":
        nf, nog = _GBLINEAR_PARAM.unpack_from(data, off)
        off += _GBLINEAR_PARAM.size
        (wlen,) = struct.unpack_from("<Q", data, off)
        off += 8
        w = np.frombuffer(data, "<f4", count=wlen, offset=off)
        out["num_output_group"] = nog
        out["weights"] = w.reshape(nf + 1, nog).astype(np.float32)
        return out
    if name_gbm != "gbtree":
        raise ValueError(f"unknown booster in reference model: {name_gbm!r}")
    num_trees, _roots, gb_nf, _npb, nog, slv = _GBTREE_PARAM.unpack_from(
        data, off)
    off += _GBTREE_PARAM.size
    if slv != 0:
        raise ValueError("size_leaf_vector != 0 models are not supported")
    trees = []
    for _ in range(num_trees):
        _, n_nodes, _, _, _, t_slv = _TREE_PARAM.unpack_from(data, off)
        off += _TREE_PARAM.size
        nodes = np.frombuffer(data, _NODE_DT, count=n_nodes, offset=off)
        off += _NODE_DT.itemsize * n_nodes
        stats = np.frombuffer(data, _STAT_DT, count=n_nodes, offset=off)
        off += _STAT_DT.itemsize * n_nodes
        if t_slv:
            (lv_len,) = struct.unpack_from("<Q", data, off)
            off += 8 + 4 * lv_len
        trees.append((nodes, stats))
    tree_info = np.frombuffer(data, "<i4", count=num_trees, offset=off)
    out["num_output_group"] = max(1, nog)
    out["trees"] = trees
    out["tree_info"] = tree_info.astype(np.int32)
    return out


def _tree_depth(nodes: np.ndarray) -> int:
    depth, frontier = 0, [(0, 0)]
    best = 0
    while frontier:
        nid, d = frontier.pop()
        best = max(best, d)
        if nodes["cleft"][nid] != -1:
            frontier.append((int(nodes["cleft"][nid]), d + 1))
            frontier.append((int(nodes["cright"][nid]), d + 1))
    return best


def load_reference_model(src):
    """Load a reference-format model (file path or raw ``bytes``) into a
    served-ready Booster."""
    import jax.numpy as jnp

    from xgboost_tpu.binning import CutMatrix, pack_cuts
    from xgboost_tpu.learner import Booster
    from xgboost_tpu.models.tree import TreeArrays, tree_capacity

    if isinstance(src, bytes):
        parsed = parse_reference_model(src)
    else:
        with open(src, "rb") as f:
            parsed = parse_reference_model(f.read())

    params = {"objective": parsed["objective"],
              "num_class": parsed["num_class"]}
    if parsed["gbm"] == "gblinear":
        params["booster"] = "gblinear"
        bst = Booster(params)  # num_output_group derives from num_class
        bst._init_obj()
        bst.num_feature = parsed["num_feature"]
        from xgboost_tpu.models.gblinear import GBLinear
        gbl = GBLinear(bst.param, parsed["num_feature"])
        # reference layout: weight[(num_feature+1) * K], bias LAST
        # (gblinear-inl.hpp:252-259)
        gbl.weight = jnp.asarray(parsed["weights"][:-1])
        gbl.bias = jnp.asarray(parsed["weights"][-1])
        bst.gbtree = gbl
        bst.param.base_score = _margin_to_base_score(
            bst.obj, parsed["base_margin"])
        return bst

    trees, tree_info = parsed["trees"], parsed["tree_info"]
    nf = parsed["num_feature"]
    # cuts = the model's own thresholds per feature -> exact traversal
    thresholds: List[List[float]] = [[] for _ in range(nf)]
    for nodes, _ in trees:
        split = nodes["cleft"] != -1
        for f, thr in zip(nodes["sindex"][split] & 0x7FFFFFFF,
                          nodes["info"][split]):
            thresholds[int(f)].append(np.float32(thr))
    per_feature = [np.unique(np.asarray(t, np.float32)) if t
                   else np.asarray([np.float32("inf")])
                   for t in thresholds]
    cuts = pack_cuts(per_feature)

    max_depth = max((_tree_depth(n) for n, _ in trees), default=1)
    max_depth = max(max_depth, 1)
    params["max_depth"] = max_depth
    bst = Booster(params)  # num_output_group derives from num_class
    bst._init_obj()
    bst.num_feature = nf
    from xgboost_tpu.models.gbtree import GBTree
    gbt = GBTree(bst.param, cuts)
    cap = tree_capacity(max_depth)
    for nodes, stats in trees:
        arr = {"feature": np.full(cap, -1, np.int32),
               "cut_index": np.zeros(cap, np.int32),
               "threshold": np.zeros(cap, np.float32),
               "default_left": np.zeros(cap, bool),
               "is_leaf": np.zeros(cap, bool),
               "leaf_value": np.zeros(cap, np.float32),
               "gain": np.zeros(cap, np.float32),
               "sum_hess": np.zeros(cap, np.float32)}
        frontier = [(0, 0)]  # (reference nid, perfect-layout slot)
        while frontier:
            nid, slot = frontier.pop()
            arr["sum_hess"][slot] = stats["sum_hess"][nid]
            arr["leaf_value"][slot] = stats["base_weight"][nid]
            if nodes["cleft"][nid] == -1:
                arr["is_leaf"][slot] = True
                arr["leaf_value"][slot] = nodes["info"][nid]
                continue
            f = int(nodes["sindex"][nid] & 0x7FFFFFFF)
            thr = np.float32(nodes["info"][nid])
            arr["feature"][slot] = f
            arr["threshold"][slot] = thr
            arr["cut_index"][slot] = int(np.searchsorted(
                cuts.cut_values[f, :cuts.n_cuts[f]], thr))
            arr["default_left"][slot] = bool(nodes["sindex"][nid] >> 31)
            arr["gain"][slot] = stats["loss_chg"][nid]
            frontier.append((int(nodes["cleft"][nid]), 2 * slot + 1))
            frontier.append((int(nodes["cright"][nid]), 2 * slot + 2))
        gbt.trees.append(TreeArrays(**{k: jnp.asarray(v)
                                       for k, v in arr.items()}))
    gbt.tree_group = [int(g) for g in tree_info]
    bst.gbtree = gbt
    bst.param.base_score = _margin_to_base_score(
        bst.obj, parsed["base_margin"])
    return bst


# ------------------------------------------------------------------ writer

def _write_str(out: list, s: str) -> None:
    out.append(struct.pack("<Q", len(s)))
    out.append(s.encode())


def _tree_to_reference(tree, n_roots: int = 1):
    """Convert one perfect-layout tree to reference (nodes, stats) arrays.

    Allocation order: roots first (ids 0..R-1, TreeModel::InitModel),
    then children in BFS order (AddChilds appends pairs) — any
    parent/cleft/cright topology parses, but BFS keeps ids compact.
    """
    feature = np.asarray(tree.feature)
    threshold = np.asarray(tree.threshold)
    default_left = np.asarray(tree.default_left)
    is_leaf = np.asarray(tree.is_leaf)
    leaf_value = np.asarray(tree.leaf_value)
    gain = np.asarray(tree.gain)
    sum_hess = np.asarray(tree.sum_hess)

    from xgboost_tpu.models.tree import root_level
    first = (1 << root_level(n_roots)) - 1
    roots = list(range(first, first + n_roots))

    def is_split(slot: int) -> bool:
        return (not is_leaf[slot]) and feature[slot] >= 0

    # breadth-first id assignment over REACHABLE slots
    ids = {}
    order = []
    queue = list(roots)
    while queue:
        slot = queue.pop(0)
        ids[slot] = len(order)
        order.append(slot)
        if is_split(slot):
            queue.append(2 * slot + 1)
            queue.append(2 * slot + 2)

    n = len(order)
    nodes = np.zeros(n, _NODE_DT)
    stats = np.zeros(n, _STAT_DT)
    for slot in order:
        nid = ids[slot]
        stats["sum_hess"][nid] = sum_hess[slot]
        stats["base_weight"][nid] = leaf_value[slot]
        if is_split(slot):
            left, right = ids[2 * slot + 1], ids[2 * slot + 2]
            nodes["cleft"][nid] = left
            nodes["cright"][nid] = right
            # parent packs the is-left-child bit in the sign bit
            # (model.h set_parent)
            nodes["parent"][left] = np.uint32(nid | (1 << 31)).view(np.int32)
            nodes["parent"][right] = nid
            nodes["sindex"][nid] = (np.uint32(feature[slot])
                                    | (np.uint32(1) << 31
                                       if default_left[slot]
                                       else np.uint32(0)))
            nodes["info"][nid] = threshold[slot]
            stats["loss_chg"][nid] = gain[slot]
        else:
            nodes["cleft"][nid] = -1
            nodes["cright"][nid] = -1
            nodes["info"][nid] = leaf_value[slot]
    for r in roots:
        nodes["parent"][ids[r]] = -1
    return nodes, stats


def save_reference_model(booster, path: Optional[str] = None,
                         base64_mode: bool = False,
                         num_pbuffer: Optional[int] = None) -> bytes:
    """Serialize a Booster into the reference's binary model format, so
    reference tooling (CLI ``task=pred``/``train``/``eval``, the C API,
    the R package) can consume models trained here — the write half of
    this module (reference SaveModel: ``learner-inl.hpp:209-252``,
    ``gbtree-inl.hpp:42-78``, ``model.h:320-330``).

    ``num_pbuffer``: prediction-buffer row capacity baked into the model
    (reference semantics: the row count of the matrices cached at train
    time; consumers that cache matrices — continued training, eval —
    abort on a smaller value, gbtree-inl.hpp BufferOffset check).
    Default: the total rows of this Booster's cached matrices, matching
    what the reference itself would have written.  A ZEROED buffer is
    emitted (pred_counter 0 = "no trees applied" — consumers recompute).

    Returns the bytes; also writes them to ``path`` when given.
    ``base64_mode`` emits the text-safe ``bs64`` encoding.
    """
    assert booster.gbtree is not None, "nothing to save"
    if num_pbuffer is None:
        num_pbuffer = sum(e.n_real for e in booster._cache.values())
    obj = booster.obj
    if obj is None:
        booster._init_obj()
        obj = booster.obj
    out: list = []
    base_margin = float(obj.prob_to_margin(booster.param.base_score))
    num_class = int(booster.param.num_class)
    nf = int(booster.num_feature)
    out.append(_LEARNER_PARAM.pack(base_margin, nf, num_class))
    _write_str(out, booster.param.objective)
    gbm = "gblinear" if booster.param.booster == "gblinear" else "gbtree"
    _write_str(out, gbm)

    if gbm == "gblinear":
        w = np.asarray(booster.gbtree.weight, np.float32)
        b = np.asarray(booster.gbtree.bias, np.float32)[None, :]
        K = w.shape[1]
        out.append(_GBLINEAR_PARAM.pack(nf, K))
        flat = np.concatenate([w, b]).astype("<f4")  # bias LAST
        out.append(struct.pack("<Q", flat.size))
        out.append(flat.tobytes())
    else:
        gbt = booster.gbtree
        n_roots = max(1, booster.param.num_roots)
        trees = gbt.trees
        K = max(1, booster.param.num_output_group)
        out.append(_GBTREE_PARAM.pack(len(trees), n_roots, nf,
                                      int(num_pbuffer),
                                      K if K > 1 else 1, 0))
        for t in trees:
            nodes, stats = _tree_to_reference(t, n_roots)
            out.append(_TREE_PARAM.pack(n_roots, len(nodes), 0,
                                        int(booster.param.max_depth), nf, 0))
            out.append(nodes.tobytes())
            out.append(stats.tobytes())
        out.append(np.asarray(gbt.tree_group, "<i4").tobytes())
        if num_pbuffer:
            # zeroed pred_buffer AND pred_counter, each PredBufferSize =
            # num_pbuffer * num_output_group entries (gbtree-inl.hpp:58-61
            # resizes BOTH by PredBufferSize); counter 0 means "no trees
            # applied", so consumers recompute from scratch
            n_ent = int(num_pbuffer) * (K if K > 1 else 1)
            out.append(b"\x00" * (4 * n_ent))
            out.append(b"\x00" * (4 * n_ent))

    payload = b"".join(out)
    if base64_mode:
        data = b"bs64\t" + base64.b64encode(payload) + b"\n"
    else:
        data = b"binf" + payload
    if path is not None:
        # reference-format exports are durable model files: same
        # tmp+rename discipline as the native save path (XGT003)
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(path, data)
    return data


def _margin_to_base_score(obj, margin: float) -> float:
    """Invert prob_to_margin: the reference stores base_score already
    margin-transformed (learner-inl.hpp:151)."""
    if obj.prob_to_margin(0.3) == 0.3:  # identity transform family
        return float(margin)
    return float(1.0 / (1.0 + np.exp(-margin)))  # logistic family

"""Device-side LambdaRank gradients (VERDICT r2 item 4).

The host implementation (:mod:`xgboost_tpu.rank_obj`) pulls the full
margin to the host every round and loops groups in Python — fine at
MQ2008 scale, a wall at pod scale.  This module keeps the whole round
on device:

  - STATIC per-dataset structures (labels and groups don't change
    between rounds) are built once on the host: per-row group id /
    start / size, the label-sorted order within each group, each row's
    label-bucket bounds in that order, and per-group IDCG.
  - Per round, everything else is jitted device work: one unstable
    2-key sort gives pred-order positions within groups; partner
    sampling draws a uniform different-label row per (row, pairsample)
    via PRNG ``fold_in`` (reference samples per bucket element the
    same way, objective-inl.hpp:323-344); NDCG (:435-480) / MAP
    (:483-570) delta weights use the same math as the host path.

  - RECEIVE-SIDE accumulation (round 4): the reference adds each
    sampled pair's gradient to BOTH rows — a scatter-add on TPU.
    Instead, every row accumulates its self-side term plus an
    importance-corrected estimate of the mass it receives as OTHER
    rows' partner: pair weights are symmetric in the pair and the
    received sign equals the self sign, so the received term is the
    self term scaled by n_other(self)/n_other(partner) — the
    likelihood ratio between "self sampled partner" and "partner
    sampled self".  Expectation identical to the reference's
    two-sided accumulation; no scatter, and the partner-side reads
    collapse into ONE stacked gather.

Randomness differs from the host path (jax PRNG vs numpy MT) — pair
sampling is Monte Carlo either way (the receive-side estimator changes
the per-round noise, not the expected gradient); tests compare trained
METRICS, not gradients.  Rank objectives become fused-scan eligible
through ``Objective.fused_grad(info)`` (no per-round host transfer).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-16


class RankPrep(NamedTuple):
    """Static per-dataset device structures (all (N,) unless noted)."""
    group_of: jax.Array     # int32 group id, -1 = group-less (padding) row
    g_start: jax.Array      # int32 first row index of the row's group
    g_size: jax.Array       # int32 rows in the row's group
    lab_order: jax.Array    # int32: row ids sorted by (group, -label)
    lab_rank: jax.Array     # int32: this row's position in lab_order space
    #                         (within-group, 0-based)
    b_lo: jax.Array         # int32 label-bucket start (within-group pos)
    b_sz: jax.Array         # int32 label-bucket size
    idcg: jax.Array         # f32 per-row copy of the group's IDCG
    label: jax.Array        # f32 labels (device)


def build_prep(labels: np.ndarray, group_ptr: np.ndarray, n_pad: int
               ) -> RankPrep:
    """Host-side one-off construction (labels/groups are static)."""
    labels = np.asarray(labels, np.float32)
    gptr = np.asarray(group_ptr, np.int64)
    n = n_pad
    group_of = np.full(n, -1, np.int32)
    g_start = np.zeros(n, np.int32)
    g_size = np.ones(n, np.int32)
    lab_order = np.arange(n, dtype=np.int32)
    lab_rank = np.zeros(n, np.int32)
    b_lo = np.zeros(n, np.int32)
    b_sz = np.ones(n, np.int32)
    idcg = np.zeros(n, np.float32)
    lab_full = np.zeros(n, np.float32)
    lab_full[:len(labels)] = labels
    for g in range(len(gptr) - 1):
        s, e = int(gptr[g]), int(gptr[g + 1])
        group_of[s:e] = g
        g_start[s:e] = s
        g_size[s:e] = e - s
        lg = labels[s:e]
        order = np.argsort(-lg, kind="stable")
        lab_order[s:e] = s + order
        lab_rank[s + order] = np.arange(e - s)
        ls = lg[order]
        # bucket bounds per sorted position
        starts = np.concatenate(
            [[0], np.nonzero(ls[1:] != ls[:-1])[0] + 1, [e - s]])
        for bi in range(len(starts) - 1):
            i, j = starts[bi], starts[bi + 1]
            rows = s + order[i:j]
            b_lo[rows] = i
            b_sz[rows] = j - i
        rel = ls.astype(np.int64)
        disc = 1.0 / np.log(np.arange(e - s) + 2.0)
        idcg[s:e] = np.sum((2.0 ** rel - 1.0) * disc)
    return RankPrep(*(jnp.asarray(x) for x in (
        group_of, g_start, g_size, lab_order, lab_rank, b_lo, b_sz, idcg,
        lab_full)))


@functools.partial(jax.jit, static_argnames=("kind", "num_pairsample",
                                             "fix_list_weight"))
def rank_gradient(pred: jax.Array, key: jax.Array, prep: RankPrep,
                  kind: str, num_pairsample: int = 1,
                  fix_list_weight: float = 0.0) -> jax.Array:
    """(N, 2) grad/hess for one LambdaRank round, fully on device."""
    n = pred.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    valid = (prep.group_of >= 0) & (prep.g_size > prep.b_sz)

    # within-group pred-order positions.  Group-less (padding) rows must
    # sort LAST so group g's rows occupy sorted slots [g_start, g_end)
    # exactly (groups are contiguous row ranges from 0).  Unstable sort
    # with row-id payload (pred ties ordered arbitrarily, as in any
    # sort-based ranker); one scatter inverts the permutation.
    gkey = jnp.where(prep.group_of < 0, jnp.int32(2**31 - 1),
                     prep.group_of)
    _, _, order = jax.lax.sort((gkey, -pred, rows), dimension=0,
                               num_keys=2, is_stable=False)
    # invert the permutation by SORTING (order, iota): keys are distinct
    # so the unstable sort is exact and the payload lands as inv.  The
    # scatter formulation (zeros.at[order].set(rows)) costs ~5.9 ms at
    # 1M rows on v5e; the second sort ~1.0 ms (tools/rank_inv_ab.py)
    _, inv = jax.lax.sort((order, rows), dimension=0, num_keys=1,
                          is_stable=False)
    posn = inv - prep.g_start                         # (N,) pred-order pos

    # MAP needs pred-order cumulative hit statistics per group
    if kind == "map":
        hit_sorted = (prep.label[order] > 0).astype(jnp.float32)
        within = rows - prep.g_start[order]
        inv_i = 1.0 / (within.astype(jnp.float32) + 1.0)
        hits_sorted = _seg_cumsum(hit_sorted, prep.g_start[order], rows)
        acc1_s = _seg_cumsum(hit_sorted * hits_sorted * inv_i,
                             prep.g_start[order], rows)
        acc2_s = _seg_cumsum(hit_sorted * (hits_sorted - 1.0) * inv_i,
                             prep.g_start[order], rows)
        acc3_s = _seg_cumsum(hit_sorted * (hits_sorted + 1.0) * inv_i,
                             prep.g_start[order], rows)
        # back to row space, indexed by pred-order position:
        # value at (group, pos) lives at order[g_start + pos]
        def at_pos(arr_sorted, p):
            return arr_sorted[prep.g_start + p]
        hits_of = lambda p: at_pos(hits_sorted, p)  # noqa: E731
        acc = (acc1_s, acc2_s, acc3_s)
    g_out = jnp.zeros(n, jnp.float32)
    h_out = jnp.zeros(n, jnp.float32)

    # partner-side reads collapse into ONE stacked gather (measured on
    # v5e: a 1M-row gather costs ~5-8 ms regardless of row width).
    # Positions ride as f32 — exact below 2^24; past that (a single
    # >16M-row group) they take a separate int32 gather instead
    posn_in_tab = n < (1 << 24)
    n_other_f = jnp.maximum(prep.g_size - prep.b_sz, 1).astype(
        jnp.float32)
    tab = jnp.stack([prep.label, pred,
                     posn.astype(jnp.float32) if posn_in_tab
                     else jnp.zeros(n, jnp.float32),
                     n_other_f], axis=1)              # (N, 4)

    scale = 1.0 / num_pairsample
    for k in range(num_pairsample):
        kk = jax.random.fold_in(key, k)
        n_other = jnp.maximum(prep.g_size - prep.b_sz, 1)
        u = jax.random.randint(kk, (n,), 0, 1 << 30) % n_other
        lab_pos = jnp.where(u < prep.b_lo, u, u + prep.b_sz)
        partner = prep.lab_order[prep.g_start + lab_pos]  # (N,) row ids

        part = tab[partner]                            # (N, 4)
        lab_self = prep.label
        lab_p = part[:, 0]
        hi = lab_self > lab_p                          # self is the pos side
        pred_p = part[:, 1]
        posn_p = part[:, 2].astype(jnp.int32) if posn_in_tab \
            else posn[partner]
        ratio = n_other_f / part[:, 3]                 # receive-side IS weight

        p_pos_pos = jnp.where(hi, posn, posn_p)        # pred-order positions
        p_neg_pos = jnp.where(hi, posn_p, posn)
        lab_hi = jnp.maximum(lab_self, lab_p)
        lab_lo = jnp.minimum(lab_self, lab_p)

        if kind == "pairwise":
            w = jnp.ones(n, jnp.float32)
        elif kind == "ndcg":
            w = _ndcg_delta(lab_hi, lab_lo,
                            p_pos_pos.astype(jnp.float32),
                            p_neg_pos.astype(jnp.float32), prep.idcg)
        elif kind == "map":
            acc1_s, acc2_s, acc3_s = acc
            i1 = jnp.minimum(p_pos_pos, p_neg_pos)
            i2 = jnp.maximum(p_pos_pos, p_neg_pos)
            lab1 = (jnp.where(p_pos_pos <= p_neg_pos, lab_hi, lab_lo)
                    > 0).astype(jnp.float32)
            lab2 = (jnp.where(p_pos_pos <= p_neg_pos, lab_lo, lab_hi)
                    > 0).astype(jnp.float32)
            a1 = lambda p: acc1_s[prep.g_start + p]  # noqa: E731
            a2 = lambda p: acc2_s[prep.g_start + p]  # noqa: E731
            a3 = lambda p: acc3_s[prep.g_start + p]  # noqa: E731
            w = _map_delta(a1(i2), a1(jnp.maximum(i1 - 1, 0)),
                           a2(jnp.maximum(i2 - 1, 0)), a2(i1),
                           a3(jnp.maximum(i2 - 1, 0)), a3(i1),
                           hits_of(i1), hits_of(i2),
                           i1.astype(jnp.float32),
                           i2.astype(jnp.float32),
                           lab1, lab2, i1, i2, hits_of(prep.g_size - 1))
        else:
            raise ValueError(f"unknown rank kind {kind!r}")

        wv = w * scale
        if fix_list_weight != 0.0:
            wv = wv * fix_list_weight / prep.g_size.astype(jnp.float32)
        wv = jnp.where(valid, wv, 0.0)

        p = jax.nn.sigmoid(jnp.where(hi, pred - pred_p, pred_p - pred))
        g = (p - 1.0) * wv
        h = jnp.maximum(p * (1.0 - p), _EPS) * 2.0 * wv
        # self side (hi ? +g : -g) PLUS the receive-side estimate: the
        # sign a row receives as its partner's partner equals its self
        # sign (pair weights are role-symmetric; the partner of a pos
        # row is neg and vice versa), so both sides fold into one
        # (1 + ratio) factor — no scatter-add (see module docstring)
        both = 1.0 + ratio
        g_out = g_out + jnp.where(hi, g, -g) * both
        h_out = h_out + h * both

    return jnp.stack([g_out, h_out], axis=1)


def _seg_cumsum(x_sorted, seg_start_sorted, rows):
    """Cumulative sum within segments of a segment-sorted array:
    cumsum minus the cumsum just before each segment's start."""
    c = jnp.cumsum(x_sorted)
    c0 = jnp.concatenate([jnp.zeros(1, x_sorted.dtype), c])
    return c - c0[seg_start_sorted]


# --------------------------------------------------------------------------
# Group-PADDED gradient (round 4): the TPU-native layout.
#
# The sort-based gradient above pays one 2-key sort + one inverting sort
# + two 1M-row gathers per round (~10.7 ms at the bench shape).  All
# four exist only because rows of one group are scattered across a flat
# (N,) array.  If instead the ENTRY lays rows out group-padded — group
# g owns slots [g*L, (g+1)*L), rows label-sorted within the group, lane
# padding at the end — then per round:
#
#   - pred.reshape(G, L) is free,
#   - the within-group pred-rank is an L-wide broadcast-compare COUNT
#     (no sort, no inverse permutation),
#   - partner sampling happens in lane space (the label-sorted layout
#     makes the reference's bucket-skipping draw a pure index formula,
#     objective-inl.hpp:323-344), and
#   - the partner-side reads become ONE one-hot (G, L, L) x (G, L, C)
#     batched MXU dot (no gathers).
#
# Measured end-to-end (tools/rank_inv_ab.py, 1M rows / 10k groups of
# 100): 3.7 ms vs 15.6 ms for the sort-based path.  The padding also
# costs ~L/mean(group size) extra rows in the grower — the entry
# builder gates on that blow-up staying small.
# --------------------------------------------------------------------------


class PadRankPrep(NamedTuple):
    """Static structures of the group-padded layout.  G groups, all L
    lanes wide; slot (g, j) holds the row with the j-th largest label
    of group g (ties broken by original order), or padding (j >=
    g_size[g]).  Rows past group_ptr[-1] (group-less tail) keep flat
    slots after G*L and get zero gradient."""
    G: int                  # static group count
    L: int                  # static lane width (max group size, 8-aligned)
    n_tail: int             # group-less tail rows after the padded block
    label: jax.Array        # (G, L) f32, 0 in padding lanes
    valid: jax.Array        # (G, L) bool
    g_size: jax.Array       # (G, 1) int32 real rows of the group
    b_lo: jax.Array         # (G, L) int32 label-bucket start (lane space)
    b_sz: jax.Array         # (G, L) int32 label-bucket size
    idcg: jax.Array         # (G, 1) f32
    pad_map: np.ndarray     # HOST (G*L + n_tail,) int32 user row per slot,
    #                         -1 = padding
    user_map: np.ndarray    # HOST (n_user,) int32 slot of each user row


def build_pad_prep(labels: np.ndarray, group_ptr: np.ndarray,
                   lane_align: int = 8) -> PadRankPrep:
    """Host-side one-off construction of the padded layout."""
    labels = np.asarray(labels, np.float32)
    gptr = np.asarray(group_ptr, np.int64)
    n_user = len(labels)
    G = len(gptr) - 1
    sizes = np.diff(gptr).astype(np.int64)
    max_gs = int(sizes.max()) if G else 1
    L = max(lane_align, -(-max_gs // lane_align) * lane_align)
    n_tail = int(n_user - gptr[-1])

    pad_map = np.full(G * L + n_tail, -1, np.int32)
    user_map = np.zeros(n_user, np.int32)
    label_pad = np.zeros((G, L), np.float32)
    valid = np.zeros((G, L), np.bool_)
    b_lo = np.zeros((G, L), np.int32)
    b_sz = np.ones((G, L), np.int32)
    idcg = np.zeros(G, np.float32)
    for g in range(G):
        s, e = int(gptr[g]), int(gptr[g + 1])
        sz = e - s
        lg = labels[s:e]
        order = np.argsort(-lg, kind="stable")
        rows = (s + order).astype(np.int32)
        pad_map[g * L: g * L + sz] = rows
        user_map[rows] = g * L + np.arange(sz, dtype=np.int32)
        ls = lg[order]
        label_pad[g, :sz] = ls
        valid[g, :sz] = True
        starts = np.concatenate(
            [[0], np.nonzero(ls[1:] != ls[:-1])[0] + 1, [sz]])
        for bi in range(len(starts) - 1):
            i, j = int(starts[bi]), int(starts[bi + 1])
            b_lo[g, i:j] = i
            b_sz[g, i:j] = j - i
        rel = ls.astype(np.int64)
        disc = 1.0 / np.log(np.arange(sz) + 2.0)
        idcg[g] = np.sum((2.0 ** rel - 1.0) * disc)
    if n_tail:
        tail_rows = np.arange(gptr[-1], n_user, dtype=np.int32)
        pad_map[G * L:] = tail_rows
        user_map[tail_rows] = G * L + np.arange(n_tail, dtype=np.int32)
    sizes_dev = sizes.astype(np.int32)[:, None] if G else \
        np.ones((0, 1), np.int32)
    return PadRankPrep(
        G, L, n_tail, jnp.asarray(label_pad), jnp.asarray(valid),
        jnp.asarray(sizes_dev), jnp.asarray(b_lo), jnp.asarray(b_sz),
        jnp.asarray(idcg[:, None]), pad_map, user_map)


def _lane_select(onehot_idx: jax.Array, tab: jax.Array, L: int,
                 exact: bool = False) -> jax.Array:
    """``tab[g, onehot_idx[g, i], :]`` as a one-hot batched MXU dot:
    onehot_idx (G, L) int32 lane indices, tab (G, L, C) -> (G, L, C).

    Default bf16 operands: the one-hot is exact, and callers route only
    channels that are small integers (exact in bf16 up to 256 — the
    learner gate clamps L there) or explicitly bf16-tolerant (pred, see
    rank_gradient_padded).  ``exact=True`` keeps f32 operands at
    HIGHEST precision — required for MAP's cumulative-statistic
    channels, whose deltas are differences of O(hits) accumulations
    (bf16's ~0.5 absolute rounding at magnitude ~100 would swamp the
    O(1/hits) true deltas and bias the rectified |weight|)."""
    lane = jnp.arange(L, dtype=jnp.int32)
    eq = onehot_idx[:, :, None] == lane[None, None, :]
    if exact:
        return jax.lax.dot_general(
            eq.astype(jnp.float32), tab.astype(jnp.float32),
            (((2,), (1,)), ((0,), (0,))),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
    return jax.lax.dot_general(
        eq.astype(jnp.bfloat16), tab.astype(jnp.bfloat16),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)


def _ndcg_delta(lab_hi, lab_lo, p_pos, p_neg, idcg):
    """|NDCG swap delta| of a (pos, neg) pair at pred positions
    (p_pos, p_neg) — shared by the sort-based and padded gradients
    (reference objective-inl.hpp:435-480)."""
    pos_li = 1.0 / jnp.log(p_pos + 2.0)
    neg_li = 1.0 / jnp.log(p_neg + 2.0)
    pg = 2.0 ** lab_hi - 1.0
    ng = 2.0 ** lab_lo - 1.0
    original = pg * pos_li + ng * neg_li
    changed = ng * pos_li + pg * neg_li
    return jnp.where(idcg > 0.0,
                     jnp.abs((original - changed)
                             / jnp.maximum(idcg, _EPS)), 0.0)


def _map_delta(a1_i2, a1_i1m, a2_i2m, a2_i1, a3_i2m, a3_i1,
               hits_i1, hits_i2, i1f, i2f, lab1, lab2, i1, i2,
               total_hits):
    """|MAP swap delta| from the cumulative hit statistics at the pair's
    pred positions i1 <= i2 — shared weight formula of both gradient
    paths (reference objective-inl.hpp:483-570)."""
    original = a1_i2 - jnp.where(i1 > 0, a1_i1m, 0.0)
    ch_insert = a3_i2m - a3_i1 + (hits_i1 + 1.0) / (i1f + 1.0)
    ch_remove = a2_i2m - a2_i1 + hits_i2 / (i2f + 1.0)
    changed = jnp.where(lab1 < lab2, ch_insert, ch_remove)
    w = jnp.where(total_hits > 0,
                  jnp.abs((changed - original)
                          / jnp.maximum(total_hits, _EPS)), 0.0)
    return jnp.where((lab1 == lab2) | (i1 == i2), 0.0, w)


def rank_gradient_padded(pred: jax.Array, key: jax.Array,
                         prep: PadRankPrep, kind: str,
                         num_pairsample: int = 1,
                         fix_list_weight: float = 0.0) -> jax.Array:
    """(G*L + n_tail, 2) grad/hess for one LambdaRank round on the
    group-padded layout.  Same pair-sampling semantics and delta-weight
    math as :func:`rank_gradient` (reference objective-inl.hpp:274-570);
    pred positions/partner reads ride the padded lanes instead of
    sorts/gathers.  Partner pred values round through bf16 in the
    one-hot dot (~0.4% on the sigmoid argument — Monte Carlo pair
    sampling noise dominates; trained-metric parity is tested)."""
    G, L = prep.G, prep.L
    P = pred[:G * L].reshape(G, L)
    lane = jnp.arange(L, dtype=jnp.int32)

    # within-group pred-rank: count of strictly-better valid peers
    # (ties broken by lane — the sort path's unstable-tie analog)
    better = (P[:, None, :] > P[:, :, None]) | (
        (P[:, None, :] == P[:, :, None])
        & (lane[None, None, :] < lane[None, :, None]))
    better = better & prep.valid[:, None, :]
    posn = better.sum(axis=2).astype(jnp.int32)            # (G, L)

    n_other = jnp.maximum(prep.g_size - prep.b_sz, 1)      # (G, L)
    n_other_f = n_other.astype(jnp.float32)
    can_pair = prep.valid & (prep.g_size > prep.b_sz)

    if kind == "map":
        hit = (prep.label > 0.0) & prep.valid               # (G, L)
        # hit occupancy in pred-POSITION space: accumulate rows into
        # their positions — the row axis contracts, so the one-hot is
        # (G, L_row, L_pos) and the dot contracts dim 1 (rows).
        # Invalid lanes route to the never-matching position L + 1.
        onehot = (jnp.where(prep.valid, posn, L + 1)[:, :, None]
                  == lane[None, None, :]).astype(jnp.bfloat16)
        hits_at = jax.lax.dot_general(
            onehot, hit.astype(jnp.bfloat16)[:, :, None],
            (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)[..., 0]     # (G, Lpos)
        hits_cum = jnp.cumsum(hits_at, axis=1)              # (G, Lpos)
        posf = lane.astype(jnp.float32)[None, :]
        inv_i = 1.0 / (posf + 1.0)
        acc1 = jnp.cumsum(hits_at * hits_cum * inv_i, axis=1)
        acc2 = jnp.cumsum(hits_at * (hits_cum - 1.0) * inv_i, axis=1)
        acc3 = jnp.cumsum(hits_at * (hits_cum + 1.0) * inv_i, axis=1)
        pos_tab = jnp.stack([acc1, acc2, acc3, hits_cum], axis=2)
        total_hits = hits_cum[:, L - 1:L]                   # (G, 1)

    g_out = jnp.zeros((G, L), jnp.float32)
    h_out = jnp.zeros((G, L), jnp.float32)
    scale = 1.0 / num_pairsample
    posn_f = posn.astype(jnp.float32)
    tab = jnp.stack([prep.label, P, posn_f, n_other_f], axis=2)

    for k in range(num_pairsample):
        kk = jax.random.fold_in(key, k)
        u = jax.random.randint(kk, (G, L), 0, 1 << 30) % n_other
        lab_pos = jnp.where(u < prep.b_lo, u, u + prep.b_sz)  # partner LANE
        part = _lane_select(lab_pos, tab, L)                # (G, L, 4)
        lab_p = part[..., 0]
        pred_p = part[..., 1]
        posn_p = part[..., 2]
        ratio = n_other_f / jnp.maximum(part[..., 3], 1.0)  # IS weight

        hi = prep.label > lab_p
        p_pos = jnp.where(hi, posn_f, posn_p)
        p_neg = jnp.where(hi, posn_p, posn_f)
        lab_hi = jnp.maximum(prep.label, lab_p)
        lab_lo = jnp.minimum(prep.label, lab_p)

        if kind == "pairwise":
            w = jnp.ones((G, L), jnp.float32)
        elif kind == "ndcg":
            w = _ndcg_delta(lab_hi, lab_lo, p_pos, p_neg, prep.idcg)
        elif kind == "map":
            i1 = jnp.minimum(p_pos, p_neg).astype(jnp.int32)
            i2 = jnp.maximum(p_pos, p_neg).astype(jnp.int32)
            lab1 = (jnp.where(p_pos <= p_neg, lab_hi, lab_lo)
                    > 0).astype(jnp.float32)
            lab2 = (jnp.where(p_pos <= p_neg, lab_lo, lab_hi)
                    > 0).astype(jnp.float32)
            # exact f32 selects: the acc channels are O(hits)-magnitude
            # accumulations whose DIFFERENCES carry the weight
            r1 = _lane_select(i1, pos_tab, L, exact=True)
            r1m = _lane_select(jnp.maximum(i1 - 1, 0), pos_tab, L,
                               exact=True)
            r2 = _lane_select(i2, pos_tab, L, exact=True)
            r2m = _lane_select(jnp.maximum(i2 - 1, 0), pos_tab, L,
                               exact=True)
            w = _map_delta(r2[..., 0], r1m[..., 0],
                           r2m[..., 1], r1[..., 1],
                           r2m[..., 2], r1[..., 2],
                           r1[..., 3], r2[..., 3],
                           i1.astype(jnp.float32),
                           i2.astype(jnp.float32),
                           lab1, lab2, i1, i2, total_hits)
        else:
            raise ValueError(f"unknown rank kind {kind!r}")

        wv = w * scale
        if fix_list_weight != 0.0:
            wv = wv * fix_list_weight / prep.g_size.astype(jnp.float32)
        wv = jnp.where(can_pair, wv, 0.0)

        s = jax.nn.sigmoid(jnp.where(hi, P - pred_p, pred_p - P))
        g = (s - 1.0) * wv
        h = jnp.maximum(s * (1.0 - s), _EPS) * 2.0 * wv
        both = 1.0 + ratio
        g_out = g_out + jnp.where(hi, g, -g) * both
        h_out = h_out + h * both

    gh = jnp.stack([g_out.reshape(-1), h_out.reshape(-1)], axis=1)
    if prep.n_tail:
        gh = jnp.concatenate(
            [gh, jnp.zeros((prep.n_tail, 2), jnp.float32)])
    return gh

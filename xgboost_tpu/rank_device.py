"""Device-side LambdaRank gradients (VERDICT r2 item 4).

The host implementation (:mod:`xgboost_tpu.rank_obj`) pulls the full
margin to the host every round and loops groups in Python — fine at
MQ2008 scale, a wall at pod scale.  This module keeps the whole round
on device:

  - STATIC per-dataset structures (labels and groups don't change
    between rounds) are built once on the host: per-row group id /
    start / size, the label-sorted order within each group, each row's
    label-bucket bounds in that order, and per-group IDCG.
  - Per round, everything else is jitted device work: one unstable
    2-key sort gives pred-order positions within groups; partner
    sampling draws a uniform different-label row per (row, pairsample)
    via PRNG ``fold_in`` (reference samples per bucket element the
    same way, objective-inl.hpp:323-344); NDCG (:435-480) / MAP
    (:483-570) delta weights use the same math as the host path.

  - RECEIVE-SIDE accumulation (round 4): the reference adds each
    sampled pair's gradient to BOTH rows — a scatter-add on TPU.
    Instead, every row accumulates its self-side term plus an
    importance-corrected estimate of the mass it receives as OTHER
    rows' partner: pair weights are symmetric in the pair and the
    received sign equals the self sign, so the received term is the
    self term scaled by n_other(self)/n_other(partner) — the
    likelihood ratio between "self sampled partner" and "partner
    sampled self".  Expectation identical to the reference's
    two-sided accumulation; no scatter, and the partner-side reads
    collapse into ONE stacked gather.

Randomness differs from the host path (jax PRNG vs numpy MT) — pair
sampling is Monte Carlo either way (the receive-side estimator changes
the per-round noise, not the expected gradient); tests compare trained
METRICS, not gradients.  Rank objectives become fused-scan eligible
through ``Objective.fused_grad(info)`` (no per-round host transfer).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-16


class RankPrep(NamedTuple):
    """Static per-dataset device structures (all (N,) unless noted)."""
    group_of: jax.Array     # int32 group id, -1 = group-less (padding) row
    g_start: jax.Array      # int32 first row index of the row's group
    g_size: jax.Array       # int32 rows in the row's group
    lab_order: jax.Array    # int32: row ids sorted by (group, -label)
    lab_rank: jax.Array     # int32: this row's position in lab_order space
    #                         (within-group, 0-based)
    b_lo: jax.Array         # int32 label-bucket start (within-group pos)
    b_sz: jax.Array         # int32 label-bucket size
    idcg: jax.Array         # f32 per-row copy of the group's IDCG
    label: jax.Array        # f32 labels (device)


def build_prep(labels: np.ndarray, group_ptr: np.ndarray, n_pad: int
               ) -> RankPrep:
    """Host-side one-off construction (labels/groups are static)."""
    labels = np.asarray(labels, np.float32)
    gptr = np.asarray(group_ptr, np.int64)
    n = n_pad
    group_of = np.full(n, -1, np.int32)
    g_start = np.zeros(n, np.int32)
    g_size = np.ones(n, np.int32)
    lab_order = np.arange(n, dtype=np.int32)
    lab_rank = np.zeros(n, np.int32)
    b_lo = np.zeros(n, np.int32)
    b_sz = np.ones(n, np.int32)
    idcg = np.zeros(n, np.float32)
    lab_full = np.zeros(n, np.float32)
    lab_full[:len(labels)] = labels
    for g in range(len(gptr) - 1):
        s, e = int(gptr[g]), int(gptr[g + 1])
        group_of[s:e] = g
        g_start[s:e] = s
        g_size[s:e] = e - s
        lg = labels[s:e]
        order = np.argsort(-lg, kind="stable")
        lab_order[s:e] = s + order
        lab_rank[s + order] = np.arange(e - s)
        ls = lg[order]
        # bucket bounds per sorted position
        starts = np.concatenate(
            [[0], np.nonzero(ls[1:] != ls[:-1])[0] + 1, [e - s]])
        for bi in range(len(starts) - 1):
            i, j = starts[bi], starts[bi + 1]
            rows = s + order[i:j]
            b_lo[rows] = i
            b_sz[rows] = j - i
        rel = ls.astype(np.int64)
        disc = 1.0 / np.log(np.arange(e - s) + 2.0)
        idcg[s:e] = np.sum((2.0 ** rel - 1.0) * disc)
    return RankPrep(*(jnp.asarray(x) for x in (
        group_of, g_start, g_size, lab_order, lab_rank, b_lo, b_sz, idcg,
        lab_full)))


@functools.partial(jax.jit, static_argnames=("kind", "num_pairsample",
                                             "fix_list_weight"))
def rank_gradient(pred: jax.Array, key: jax.Array, prep: RankPrep,
                  kind: str, num_pairsample: int = 1,
                  fix_list_weight: float = 0.0) -> jax.Array:
    """(N, 2) grad/hess for one LambdaRank round, fully on device."""
    n = pred.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    valid = (prep.group_of >= 0) & (prep.g_size > prep.b_sz)

    # within-group pred-order positions.  Group-less (padding) rows must
    # sort LAST so group g's rows occupy sorted slots [g_start, g_end)
    # exactly (groups are contiguous row ranges from 0).  Unstable sort
    # with row-id payload (pred ties ordered arbitrarily, as in any
    # sort-based ranker); one scatter inverts the permutation.
    gkey = jnp.where(prep.group_of < 0, jnp.int32(2**31 - 1),
                     prep.group_of)
    _, _, order = jax.lax.sort((gkey, -pred, rows), dimension=0,
                               num_keys=2, is_stable=False)
    inv = jnp.zeros(n, jnp.int32).at[order].set(rows)
    posn = inv - prep.g_start                         # (N,) pred-order pos

    # MAP needs pred-order cumulative hit statistics per group
    if kind == "map":
        hit_sorted = (prep.label[order] > 0).astype(jnp.float32)
        within = rows - prep.g_start[order]
        inv_i = 1.0 / (within.astype(jnp.float32) + 1.0)
        hits_sorted = _seg_cumsum(hit_sorted, prep.g_start[order], rows)
        acc1_s = _seg_cumsum(hit_sorted * hits_sorted * inv_i,
                             prep.g_start[order], rows)
        acc2_s = _seg_cumsum(hit_sorted * (hits_sorted - 1.0) * inv_i,
                             prep.g_start[order], rows)
        acc3_s = _seg_cumsum(hit_sorted * (hits_sorted + 1.0) * inv_i,
                             prep.g_start[order], rows)
        # back to row space, indexed by pred-order position:
        # value at (group, pos) lives at order[g_start + pos]
        def at_pos(arr_sorted, p):
            return arr_sorted[prep.g_start + p]
        hits_of = lambda p: at_pos(hits_sorted, p)  # noqa: E731
        acc = (acc1_s, acc2_s, acc3_s)
    g_out = jnp.zeros(n, jnp.float32)
    h_out = jnp.zeros(n, jnp.float32)

    # partner-side reads collapse into ONE stacked gather (measured on
    # v5e: a 1M-row gather costs ~5-8 ms regardless of row width).
    # Positions ride as f32 — exact below 2^24; past that (a single
    # >16M-row group) they take a separate int32 gather instead
    posn_in_tab = n < (1 << 24)
    n_other_f = jnp.maximum(prep.g_size - prep.b_sz, 1).astype(
        jnp.float32)
    tab = jnp.stack([prep.label, pred,
                     posn.astype(jnp.float32) if posn_in_tab
                     else jnp.zeros(n, jnp.float32),
                     n_other_f], axis=1)              # (N, 4)

    scale = 1.0 / num_pairsample
    for k in range(num_pairsample):
        kk = jax.random.fold_in(key, k)
        n_other = jnp.maximum(prep.g_size - prep.b_sz, 1)
        u = jax.random.randint(kk, (n,), 0, 1 << 30) % n_other
        lab_pos = jnp.where(u < prep.b_lo, u, u + prep.b_sz)
        partner = prep.lab_order[prep.g_start + lab_pos]  # (N,) row ids

        part = tab[partner]                            # (N, 4)
        lab_self = prep.label
        lab_p = part[:, 0]
        hi = lab_self > lab_p                          # self is the pos side
        pred_p = part[:, 1]
        posn_p = part[:, 2].astype(jnp.int32) if posn_in_tab \
            else posn[partner]
        ratio = n_other_f / part[:, 3]                 # receive-side IS weight

        p_pos_pos = jnp.where(hi, posn, posn_p)        # pred-order positions
        p_neg_pos = jnp.where(hi, posn_p, posn)
        lab_hi = jnp.maximum(lab_self, lab_p)
        lab_lo = jnp.minimum(lab_self, lab_p)

        if kind == "pairwise":
            w = jnp.ones(n, jnp.float32)
        elif kind == "ndcg":
            pos_loginv = 1.0 / jnp.log(p_pos_pos.astype(jnp.float32) + 2.0)
            neg_loginv = 1.0 / jnp.log(p_neg_pos.astype(jnp.float32) + 2.0)
            pg = 2.0 ** lab_hi - 1.0
            ng = 2.0 ** lab_lo - 1.0
            original = pg * pos_loginv + ng * neg_loginv
            changed = ng * pos_loginv + pg * neg_loginv
            w = jnp.where(prep.idcg > 0.0,
                          jnp.abs((original - changed)
                                  / jnp.maximum(prep.idcg, _EPS)), 0.0)
        elif kind == "map":
            acc1_s, acc2_s, acc3_s = acc
            i1 = jnp.minimum(p_pos_pos, p_neg_pos)
            i2 = jnp.maximum(p_pos_pos, p_neg_pos)
            lab1 = (jnp.where(p_pos_pos <= p_neg_pos, lab_hi, lab_lo)
                    > 0).astype(jnp.float32)
            lab2 = (jnp.where(p_pos_pos <= p_neg_pos, lab_lo, lab_hi)
                    > 0).astype(jnp.float32)
            total_hits = hits_of(prep.g_size - 1)
            a1 = lambda p: acc1_s[prep.g_start + p]  # noqa: E731
            a2 = lambda p: acc2_s[prep.g_start + p]  # noqa: E731
            a3 = lambda p: acc3_s[prep.g_start + p]  # noqa: E731
            original = a1(i2) - jnp.where(i1 > 0, a1(jnp.maximum(i1 - 1, 0)),
                                          0.0)
            ch_insert = (a3(jnp.maximum(i2 - 1, 0)) - a3(i1)
                         + (hits_of(i1) + 1.0)
                         / (i1.astype(jnp.float32) + 1.0))
            ch_remove = (a2(jnp.maximum(i2 - 1, 0)) - a2(i1)
                         + hits_of(i2) / (i2.astype(jnp.float32) + 1.0))
            changed = jnp.where(lab1 < lab2, ch_insert, ch_remove)
            w = jnp.where(total_hits > 0,
                          jnp.abs((changed - original)
                                  / jnp.maximum(total_hits, _EPS)), 0.0)
            w = jnp.where((lab1 == lab2) | (i1 == i2), 0.0, w)
        else:
            raise ValueError(f"unknown rank kind {kind!r}")

        wv = w * scale
        if fix_list_weight != 0.0:
            wv = wv * fix_list_weight / prep.g_size.astype(jnp.float32)
        wv = jnp.where(valid, wv, 0.0)

        p = jax.nn.sigmoid(jnp.where(hi, pred - pred_p, pred_p - pred))
        g = (p - 1.0) * wv
        h = jnp.maximum(p * (1.0 - p), _EPS) * 2.0 * wv
        # self side (hi ? +g : -g) PLUS the receive-side estimate: the
        # sign a row receives as its partner's partner equals its self
        # sign (pair weights are role-symmetric; the partner of a pos
        # row is neg and vice versa), so both sides fold into one
        # (1 + ratio) factor — no scatter-add (see module docstring)
        both = 1.0 + ratio
        g_out = g_out + jnp.where(hi, g, -g) * both
        h_out = h_out + h * both

    return jnp.stack([g_out, h_out], axis=1)


def _seg_cumsum(x_sorted, seg_start_sorted, rows):
    """Cumulative sum within segments of a segment-sorted array:
    cumsum minus the cumsum just before each segment's start."""
    c = jnp.cumsum(x_sorted)
    c0 = jnp.concatenate([jnp.zeros(1, x_sorted.dtype), c])
    return c - c0[seg_start_sorted]

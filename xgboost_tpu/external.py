"""External-memory training: datasets larger than device HBM.

The reference streams 64MB CSR pages from disk with a prefetch thread
and routes all paged training through the histogram updater
(``src/io/page_dmatrix-inl.hpp``, ``learner-inl.hpp:263-267``).  The
TPU-native shape of the same idea (SURVEY.md §5.7):

  1. ingest once into raw CSR pages on disk (native page store,
     ``native/xgtpu_io.cpp``; in-RAM fallback);
  2. one streaming pass builds per-feature quantile sketches
     (merge/prune bounds identical to the in-RAM path) → cuts;
  3. one streaming pass quantizes to a binned ``(N, F)`` small-int
     **memmap** — the only O(N·F) artifact, living on disk/page cache,
     never fully resident;
  4. per tree level, batches of binned rows are staged host→device,
     positions recomputed by partial traversal, and partial histograms
     accumulated — working set is a handful of page_rows batches (one
     synchronously; up to four with the default prefetcher — see
     ``device_batches``), never the data size (the reference builds
     histograms col-batch by col-batch for the same reason,
     ``updater_histmaker-inl.hpp:296-348``).

Margins, gradients and deltas are (N,)-sized — tiny next to the paged
O(N·F) data — and stay DEVICE-resident (host round trips cost seconds
per round on tunnel-attached chips).  When the whole binned matrix fits
the device budget (``fits_device_budget``), the learner skips streaming
entirely and trains through the in-memory fast path; only genuinely
over-budget matrices stream batches host→device.
"""

from __future__ import annotations

import functools
import os
import tempfile
from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.data import DMatrix, MetaInfo, load_meta_sidecars
from xgboost_tpu.models.tree import (GrowConfig, TreeArrays, _traverse_one,
                                     apply_level, bin_of_feature, empty_tree,
                                     table_lookup)
from xgboost_tpu.ops.histogram import build_level_histogram, node_stats
from xgboost_tpu.ops.split import find_best_splits
from xgboost_tpu.sketch import (QuantileSummary, empty_summary, make_summary,
                                merge_summaries, prune_summary, propose_cuts)
from xgboost_tpu.binning import CutMatrix

DEFAULT_PAGE_ROWS = 1 << 16


class ExtMemDMatrix:
    """Paged data matrix (reference DMatrixPage, magic 0xffffab02).

    Construct from a libsvm path (``ExtMemDMatrix("big.svm#cache")`` or
    ``DMatrix("ext:big.svm#cache")``) or from an iterator of
    ``(X_dense, y)`` chunks.  Raw CSR pages are spilled to
    ``<cache>.pages``; after binning, a ``<cache>.binned`` memmap holds
    the quantized matrix.

    A ``!`` path prefix (or ``half_ram=True``) selects the HalfRAM
    variant (reference ``DMatrixHalfRAM``, magic 0xffffab03, selected by
    ``!`` at ``io.cpp:70-73``): raw CSR rows stay paged on disk but the
    compact working set — here the quantized bin matrix — is held in
    host RAM instead of a memmap, trading RAM for batch-access speed.
    """

    is_external = True

    def __init__(self, data, label=None, weight=None,
                 cache: Optional[str] = None,
                 page_rows: int = DEFAULT_PAGE_ROWS, missing: float = np.nan,
                 silent: bool = True, half_ram: bool = False):
        self.info = MetaInfo()
        self.page_rows = page_rows
        self._binned_path: Optional[str] = None
        self._binned_mm: Optional[np.memmap] = None
        self._binned_cuts: Optional[CutMatrix] = None
        self._binned_dtype = np.uint8
        self.feature_names = None
        self._col_cache = None

        self.half_ram = half_ram
        if isinstance(data, str):
            if data.startswith("!"):
                self.half_ram = True
                data = data[1:]
            path, _, cachesuffix = data.partition("#")
            if cache is None:
                cache = cachesuffix or path + ".extcache"
            self.cache_prefix = cache
            self._ingest_libsvm(path, missing, silent)
            load_meta_sidecars(self, path)
        else:
            if cache is None:
                cache = os.path.join(
                    tempfile.mkdtemp(prefix="xgbtpu_ext_"), "m")
            self.cache_prefix = cache
            self._ingest_chunks(iter(data), missing)
        if label is not None:
            self.info.set_field("label", label)
        if weight is not None:
            self.info.set_field("weight", weight)

    # ------------------------------------------------------------- ingest
    def _pages_path(self) -> str:
        return self.cache_prefix + ".pages"

    def _ingest_libsvm(self, path: str, missing: float, silent: bool,
                       chunk_lines: int = 0):
        """Stream-parse text into the page store chunk by chunk.

        The reference never holds a whole text source in memory
        (``libsvm_parser.h`` ThreadedParser streams chunks); parsing
        bounded line blocks keeps host RAM at one chunk + one page, so
        external memory relieves host RAM as well as HBM."""
        from xgboost_tpu.data import iter_libsvm_chunks
        from xgboost_tpu import native
        chunk_lines = chunk_lines or self.page_rows
        # moderate files: the native multithreaded parser is an order of
        # magnitude faster and its whole-file buffering is affordable;
        # past the threshold, stream bounded python chunks instead
        fast_limit = int(os.environ.get("XGTPU_NATIVE_INGEST_LIMIT",
                                        str(1 << 29)))  # 512 MB
        if native.available() and os.path.getsize(path) <= fast_limit:
            indptr, indices, values, labels = native.parse_libsvm_native(
                path) or (None,) * 4
            if indptr is not None:
                writer = self._page_writer()
                n = len(indptr) - 1
                for start in range(0, n, self.page_rows):
                    stop = min(start + self.page_rows, n)
                    self._push_page(writer, indptr[start:stop + 1],
                                    indices, values)
                self._close_writer(writer)
                self._num_col = (int(indices.max()) + 1 if len(indices)
                                 else 0)
                self.info.set_field("label", labels)
                self._num_row = n
                return
        writer = self._page_writer()
        all_labels: List[np.ndarray] = []
        num_col = 0
        n_rows = 0
        for indptr, indices, values, labels in iter_libsvm_chunks(
                path, chunk_lines):
            self._push_page(writer, indptr, indices, values)
            all_labels.append(labels)
            if len(indices):
                num_col = max(num_col, int(indices.max()) + 1)
            n_rows += len(labels)
        self._close_writer(writer)
        self._num_col = num_col
        self.info.set_field(
            "label", np.concatenate(all_labels) if all_labels
            else np.zeros(0, np.float32))
        self._num_row = n_rows

    def _ingest_chunks(self, chunks: Iterator[Tuple[np.ndarray, np.ndarray]],
                       missing: float):
        labels: List[np.ndarray] = []
        writer = self._page_writer()
        n_rows = 0
        num_col = 0
        for X, y in chunks:
            X = np.asarray(X, np.float32)
            num_col = max(num_col, X.shape[1])
            present = ~np.isnan(X) if np.isnan(missing) else X != missing
            counts = present.sum(axis=1)
            indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
            rows, cols = np.nonzero(present)
            self._push_page(writer, indptr, cols.astype(np.int32),
                            X[rows, cols].astype(np.float32))
            labels.append(np.asarray(y, np.float32))
            n_rows += X.shape[0]
        self._close_writer(writer)
        self._num_row = n_rows
        self._num_col = num_col
        if labels:
            self.info.set_field("label", np.concatenate(labels))

    def _write_pages_from_csr(self, indptr, indices, values):
        writer = self._page_writer()
        n = len(indptr) - 1
        for start in range(0, n, self.page_rows):
            stop = min(start + self.page_rows, n)
            self._push_page(writer, indptr[start:stop + 1],
                            indices, values)
        self._close_writer(writer)

    # page-store backends: native lib, or an in-RAM list fallback
    def _page_writer(self):
        from xgboost_tpu import native
        if native.available():
            return native.PageWriter(self._pages_path())
        self._ram_pages: List[tuple] = []
        return None

    def _push_page(self, writer, indptr, indices, values):
        if writer is not None:
            writer.push(indptr, indices, values)
        else:
            base = indptr[0]
            self._ram_pages.append(
                (np.asarray(indptr) - base,
                 np.asarray(indices[base:indptr[-1]], np.int32),
                 np.asarray(values[base:indptr[-1]], np.float32)))

    def _close_writer(self, writer):
        if writer is not None:
            writer.close()

    def iter_raw_pages(self):
        """Yield (indptr, indices, values) CSR pages."""
        from xgboost_tpu import native
        if native.available() and os.path.exists(self._pages_path()):
            with native.PageReader(self._pages_path()) as r:
                for page in r:
                    yield page
        else:
            yield from self._ram_pages

    # ---------------------------------------------------- DMatrix protocol
    @property
    def num_row(self) -> int:
        return self._num_row

    @property
    def num_col(self) -> int:
        return self._num_col

    def get_label(self):
        return self.info.label

    def get_weight(self):
        return self.info.get_weight(self.num_row)

    def get_base_margin(self):
        return self.info.base_margin

    def set_label(self, label):
        self.info.set_field("label", label)

    def set_weight(self, weight):
        self.info.set_field("weight", weight)

    def set_group(self, group):
        self.info.set_field("group", group)

    def set_base_margin(self, margin):
        self.info.set_field("base_margin", margin)

    def slice(self, rindex):
        raise NotImplementedError(
            "slice() is not supported on external-memory matrices")

    # ------------------------------------------------------------- sketch
    def sketch_cuts(self, max_bin: int = 256, sketch_eps: float = 0.03,
                    sketch_ratio: float = 2.0) -> CutMatrix:
        """Streaming per-feature quantile sketch over raw pages (the
        reference's per-batch sketch push, basemaker-inl.hpp:307-385)."""
        F = self.num_col
        maxsize = max(2, int(sketch_ratio / max(sketch_eps, 1.0 / max_bin)))
        summaries: List[QuantileSummary] = [empty_summary() for _ in range(F)]
        for indptr, indices, values in self.iter_raw_pages():
            order = np.argsort(indices, kind="stable")
            sorted_cols = indices[order]
            starts = np.searchsorted(sorted_cols, np.arange(F + 1))
            for f in range(F):
                sel = order[starts[f]:starts[f + 1]]
                if len(sel) == 0:
                    continue
                s = prune_summary(make_summary(values[sel]), maxsize)
                summaries[f] = prune_summary(
                    merge_summaries(summaries[f], s), maxsize)
        from xgboost_tpu.binning import pack_cuts
        return pack_cuts([propose_cuts(s, max_bin - 1) for s in summaries])

    # ------------------------------------------------------------ binning
    def build_binned(self, cuts: CutMatrix) -> None:
        """Quantize raw pages into the on-disk binned memmap.

        Width is the MODEL's feature count (like the in-RAM bin_matrix):
        a matrix whose max observed feature index is below the model's
        num_feature still gets columns for every model feature, so tree
        traversal never gathers out of bounds."""
        width = max(self.num_col, cuts.num_feature)
        self._binned_dtype = np.uint8 if cuts.max_bin <= 256 else np.uint16
        if self.half_ram:
            mm = np.zeros((self.num_row, width), dtype=self._binned_dtype)
        else:
            self._binned_path = self.cache_prefix + ".binned"
            mm = np.memmap(self._binned_path, dtype=self._binned_dtype,
                           mode="w+", shape=(self.num_row, width))
        f_lim = min(self.num_col, cuts.num_feature)
        row0 = 0
        for indptr, indices, values in self.iter_raw_pages():
            n = len(indptr) - 1
            page = np.zeros((n, width), dtype=self._binned_dtype)
            rows = np.repeat(np.arange(n, dtype=np.int64), np.diff(indptr))
            # one argsort groups entries by feature; each feature then
            # costs O(nnz_f log C) — NOT the O(F x nnz) of scanning a
            # boolean `indices == f` mask per feature (VERDICT r2 item 8:
            # wide datasets crawled through ingest)
            order = np.argsort(indices, kind="stable")
            starts = np.searchsorted(indices[order], np.arange(f_lim + 1))
            bins = np.zeros(len(indices), dtype=np.int64)
            for f in range(f_lim):
                sel = order[starts[f]:starts[f + 1]]
                if len(sel) == 0:
                    continue
                bins[sel] = 1 + np.searchsorted(
                    cuts.cut_values[f, :cuts.n_cuts[f]], values[sel],
                    side="right")
            in_lim = indices < f_lim
            page[rows[in_lim], indices[in_lim]] = \
                bins[in_lim].astype(self._binned_dtype)
            mm[row0:row0 + n] = page
            row0 += n
        if self.half_ram:
            self._binned_mm = mm
        else:
            mm.flush()
            self._binned_mm = np.memmap(self._binned_path,
                                        dtype=self._binned_dtype, mode="r",
                                        shape=(self.num_row, width))
        self._binned_cuts = cuts  # identity-tracked: see Booster._entry

    def binned_batches(self, batch_rows: Optional[int] = None):
        """Yield (row_start, binned_np) batches of the quantized matrix."""
        assert self._binned_mm is not None, "call build_binned first"
        step = batch_rows or self.page_rows
        for start in range(0, self.num_row, step):
            yield start, np.asarray(self._binned_mm[start:start + step])

    def fits_device_budget(self) -> bool:
        """True when the whole binned matrix fits the device budget.
        The learner then trains through the in-memory fast path —
        external memory has done its job bounding INGEST/sketch/quantize
        memory — and only genuinely over-budget matrices stream batches
        (the out-of-HBM guarantee: working set is a few page_rows
        batches — up to four with the default prefetcher, one with
        ``XGBTPU_EXT_PREFETCH=0``).

        Budget: ``XGBTPU_EXT_DEVICE_CACHE_MB`` when set; otherwise HALF
        of the device's currently-free memory (ADVICE r2: a fixed
        default can overcommit small-HBM devices — the other half covers
        the working set: histograms, margins, int32 upcasts of bin ids),
        falling back to 2048MB when the backend reports no stats (CPU)."""
        assert self._binned_mm is not None, "call build_binned first"
        # canonical XGBTPU_ prefix; the pre-round-8 XGTPU_ spelling is
        # still honored (it escaped into PROFILE.md-era A/B scripts)
        env = os.environ.get("XGBTPU_EXT_DEVICE_CACHE_MB",
                             os.environ.get("XGTPU_EXT_DEVICE_CACHE_MB"))
        if env is not None:
            budget = int(env) << 20
        else:
            budget = _default_device_budget()
        total = (self.num_row * self._binned_mm.shape[1]
                 * self._binned_mm.dtype.itemsize)
        return total <= budget

    def device_batches(self):
        """Yield (row_start, binned_device) batches (streaming; the
        in-budget case never reaches here — see fits_device_budget).

        Batches are staged by a background prefetch thread (depth-2
        queue): the memmap read + host→device upload of batch i+1
        overlaps the device compute on batch i — the reference's
        ThreadBuffer idea (``utils/thread_buffer.h``) at the device
        boundary.  The streamed working set is then up to FOUR batches
        device-resident (yielded + 2 queued + 1 in-flight put) instead
        of one — still bounded by page_rows, never by data size; the
        default budget's free-HBM halving covers it
        (:func:`_default_device_budget`).  ``XGBTPU_EXT_PREFETCH=0``
        restores synchronous single-batch staging (the A/B seam and
        the fallback for batches sized near free HBM; round-5
        measurement in PROFILE.md; the legacy XGTPU_ spelling still
        works)."""
        if os.environ.get("XGBTPU_EXT_PREFETCH",
                          os.environ.get("XGTPU_EXT_PREFETCH", "1")) == "0":
            for start, b in self.binned_batches():
                yield start, jnp.asarray(b)
            return
        yield from _prefetch_to_device(self.binned_batches())


def _prefetch_to_device(batches, depth: int = 2, observe=None):
    """Stage (start, np_batch) pairs to the device from a worker thread,
    ``depth`` batches ahead (``depth=0`` degrades to synchronous inline
    staging — the A/B baseline).  jax.device_put is thread-safe; the
    consumer's compute dispatches interleave with the worker's uploads
    on the host side, and the device runtime orders them on its stream.
    Exceptions propagate to the consumer.

    Shared upload/compute-overlap seam: paged training and prediction
    consume it through :meth:`ExtMemDMatrix.device_batches`, and the
    learner's blocked one-off prediction (``Learner._predict_fused_
    blocked`` / ``_bin_dense_blocked``) reuses it so row-block f32
    uploads overlap the device quantize+traverse of the previous block
    instead of serializing through the tunnel
    (``XGBTPU_PREDICT_UPLOAD_DEPTH`` picks the prediction-path depth).

    ``observe``, when given, is called with ``(nbytes, seconds)`` per
    upload (the prediction transfer counters); timing then blocks the
    WORKER on upload completion — the consumer still overlaps, and the
    number measures transfer, not dispatch."""
    import queue
    import threading

    def _put(b):
        if observe is None:
            return jax.device_put(b)
        from xgboost_tpu.obs.metrics import timed_device_put
        return timed_device_put(b, observe)

    if depth <= 0:
        def _sync():
            for start, b in batches:
                yield start, _put(b)
        return _sync()

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _END = object()
    stop = threading.Event()

    def worker():
        try:
            for start, b in batches:
                if stop.is_set():
                    return
                q.put((start, _put(b)))
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 - relayed to consumer
            q.put(e)

    def _piped():
        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # early-closed generator: unblock + retire the worker so its
            # memmap reads don't outlive the matrix
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    return _piped()


_budget_cache: Optional[int] = None


def _default_device_budget() -> int:
    """Deterministic per-process device budget: half the free device
    memory sampled ONCE (repeated queries would let allocation state
    flip identical matrices between streamed and in-memory paths), and
    the fixed 2048MB default in multi-process jobs — ranks computing
    different budgets would pick different collective sequences."""
    global _budget_cache
    if _budget_cache is None:
        budget = 2048 << 20
        if jax.process_count() == 1:
            try:
                stats = jax.devices()[0].memory_stats() or {}
                limit = stats.get("bytes_limit")
                if limit:
                    free = limit - stats.get("bytes_in_use", 0)
                    budget = max(free // 2, 0)
            except Exception as e:
                # backends without memory_stats keep the default
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("external.memory_budget", e,
                                emit_event=False)
        _budget_cache = budget
    return _budget_cache


# ------------------------------------------------------------- paged grow
@functools.partial(jax.jit, static_argnames=("depth", "n_bin",
                                              "precision"))
def _paged_level_hist(tree: TreeArrays, binned: jax.Array, gh: jax.Array,
                      depth: int, n_bin: int, precision: str = "auto"):
    """Partial histogram + node stats for one batch at one level: row
    positions are recomputed by traversing the partial tree."""
    node = jnp.zeros_like(binned[:, 0], dtype=jnp.int32)
    alive = jnp.ones(binned.shape[0], jnp.bool_)
    for _ in range(depth):
        f = table_lookup(tree.feature, node)
        at_leaf = table_lookup(tree.is_leaf, node) | (f < 0)
        b = bin_of_feature(binned, jnp.maximum(f, 0))
        go_left = jnp.where(b == 0, table_lookup(tree.default_left, node),
                            b <= table_lookup(tree.cut_index, node) + 1)
        nxt = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
        alive = alive & ~at_leaf
        node = jnp.where(at_leaf, node, nxt)
    n_node = 1 << depth
    pos = jnp.where(alive, node - (n_node - 1), -1)
    hist = build_level_histogram(binned, gh, pos, n_node, n_bin, precision)
    return hist, node_stats(gh, pos, n_node, precision)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _paged_leaf_delta(tree: TreeArrays, binned: jax.Array, max_depth: int):
    return table_lookup(tree.leaf_value,
                        _traverse_one(tree, binned, max_depth))


@functools.partial(jax.jit, static_argnames=("depth", "n_bin", "mesh",
                                              "precision"))
def _paged_level_hist_dp(mesh, tree: TreeArrays, binned: jax.Array,
                         gh: jax.Array, depth: int, n_bin: int,
                         precision: str = "auto"):
    """Distributed batch histogram: rows of one streamed batch shard over
    the mesh 'data' axis, partial histograms psum across shards (the
    reference's paged matrices participating in dsplit=row training,
    learner-inl.hpp:263-267 + histmaker's histred.Allreduce).

    Padding rows carry gh == 0, so they contribute nothing to any cell.
    """
    from jax.sharding import PartitionSpec as P

    def shard_fn(tree, binned, gh):
        hist, nst = _paged_level_hist.__wrapped__(tree, binned, gh,
                                                  depth, n_bin, precision)
        return (jax.lax.psum(hist, "data"), jax.lax.psum(nst, "data"))

    from xgboost_tpu.parallel.mesh import shard_map
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(), P("data"), P("data")),
                   out_specs=(P(), P()), check_vma=False)
    return fn(tree, binned, gh)


def grow_tree_paged(key, dmat: ExtMemDMatrix, gh: np.ndarray,
                    cut_values: jax.Array, n_cuts: jax.Array,
                    cfg: GrowConfig, mesh=None,
                    split_finder=None) -> TreeArrays:
    """Level-by-level growth streaming binned batches host→device.

    With ``mesh``, each batch's rows shard over the 'data' axis and
    partial histograms psum across shards before accumulating across
    batches (distributed external memory: SURVEY.md §5.7 item 2 composed
    with §2.4.2).

    gh: (N, 2) gradients (device or host).  Row subsampling uses a
    deterministic device-side draw.  Returns the grown tree (delta is
    computed by the caller via :func:`_paged_leaf_delta` batch by batch).
    """
    from xgboost_tpu.models.tree import (_default_split_finder,
                                         _sample_features)

    if split_finder is None:
        split_finder = _default_split_finder

    key_rows, key_ftree, key_flevel = jax.random.split(key, 3)
    # gradients are O(N) (not O(N*F)) and stay device-resident; the
    # per-batch host uploads they replaced were the dominant cost of
    # paged training on tunnel-attached chips
    gh_dev = jnp.asarray(gh, jnp.float32)
    if cfg.subsample < 1.0:
        keep = jax.random.uniform(key_rows, (dmat.num_row,)) < cfg.subsample
        gh_dev = gh_dev * keep[:, None].astype(jnp.float32)

    F = int(n_cuts.shape[0])
    fmask_tree = _sample_features(key_ftree, F, cfg.colsample_bytree)

    tree = empty_tree(cfg.max_depth)
    for depth in range(cfg.max_depth + 1):
        n_node = 1 << depth
        hist = None
        nst = None
        for start, batch in dmat.device_batches():
            bgh = gh_dev[start:start + batch.shape[0]]
            if mesh is not None:
                pad = (-batch.shape[0]) % mesh.devices.size
                if pad:
                    batch = jnp.pad(batch, ((0, pad), (0, 0)))
                    bgh = jnp.pad(bgh, ((0, pad), (0, 0)))
                h, s = _paged_level_hist_dp(
                    mesh, tree, batch, bgh, depth, cfg.n_bin,
                    cfg.hist_precision)
            else:
                h, s = _paged_level_hist(tree, batch, bgh, depth,
                                         cfg.n_bin, cfg.hist_precision)
            hist = h if hist is None else hist + h
            nst = s if nst is None else nst + s
        # "fixed" mode batches accumulate exact int32; decode once per
        # level after the cross-batch/cross-shard sums
        from xgboost_tpu.ops.histogram import dequantize_hist
        hist = dequantize_hist(hist)
        nst = dequantize_hist(nst)
        if depth == cfg.max_depth:
            make_leaf = jnp.ones(n_node, jnp.bool_)
            best = None
        else:
            fmask = fmask_tree
            if cfg.colsample_bylevel < 1.0:
                fmask = fmask & _sample_features(
                    jax.random.fold_in(key_flevel, depth), F,
                    cfg.colsample_bylevel)
            best = split_finder(hist, nst, n_cuts, cut_values,
                                fmask, cfg.split)
            can_try = nst[:, 1] >= 2.0 * cfg.split.min_child_weight
            make_leaf = ~(best.valid & can_try)
        tree = apply_level(tree, depth, nst, best, make_leaf, cfg.split)
    return tree

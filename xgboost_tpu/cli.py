"""Command-line driver: ``python -m xgboost_tpu <config> [name=value ...]``.

Mirrors the reference CLI (``src/xgboost_main.cpp:19-323``): a config
file of ``name = value`` pairs plus command-line overrides, dispatching
``task=train|pred|eval|dump|serve``.  Parameter names are kept identical
(``num_round``, ``save_period``, ``model_in``, ``model_out``,
``model_dir``, ``eval[name]=path``, ``test:data``, ``name_pred``,
``pred_margin``, ``ntree_limit``, ``fmap``, ``name_dump``,
``dump_stats``, ``eval_train``, ``dsplit``).

Fault tolerance: where the reference wraps the round loop in rabit
checkpoints (``xgboost_main.cpp:175-229``, two versions per round), this
driver checkpoints the model to ``checkpoint_dir`` at every fused
SEGMENT boundary (per round when fusion is ineligible or
``rounds_per_dispatch=0``) and resumes from the newest VERIFIABLE
checkpoint on restart (SURVEY.md §5.3 TPU mapping: model checkpoint +
restartable loop keyed by round version; deterministic per-iteration
seeding makes the re-trained tail bit-identical, so coarser write
granularity trades only recompute, never correctness; collectives
themselves are not elastically recoverable mid-step under XLA).  Checkpoint writes are atomic +
CRC-footered, a corrupt newest member is quarantined and the older
ring replica used instead (RELIABILITY.md), and ``faults=`` arms I/O
chaos injection the way ``mock=`` arms collective-seam deaths.
"""

from __future__ import annotations

import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

from xgboost_tpu.config import (CATALOG_PARAMS, FLEET_PARAMS,
                                LANE_PARAMS, PIPELINE_PARAMS,
                                PLACER_PARAMS, SERVE_PARAMS,
                                STREAM_PARAMS, parse_config_file)

# process start, for recovery-cost accounting.  perf_counter, not
# wall-clock: these readings are only ever subtracted (XGT006)
_T0 = time.perf_counter()

_USAGE = """\
Usage: python -m xgboost_tpu <config> [name=value ...]

Tasks (task=...):
  train   train a model (data=..., num_round=..., model_out=...)
  pred    write predictions (model_in=..., test:data=..., name_pred=...)
  eval    print eval metrics (model_in=..., eval[name]=path)
  dump    dump trees as text (model_in=..., name_dump=...)
  serve   HTTP prediction service (model_in=...; see parameters below,
          or `python -m xgboost_tpu.serving --help`)
  fleet_router
          fleet front door (xgboost_tpu.fleet, SERVING.md): replicas
          started with serve_router_url=... register here; dispatch is
          least-loaded (/predict) or consistent-hash (/predict_by_id),
          with circuit breakers, load shedding, and canary rollout
          (quickstart: tools/launch_fleet.py)
  pipeline
          continuous training (xgboost_tpu.pipeline, PIPELINE.md):
          warm-start from the published model, append
          pipeline_rounds_per_cycle trees on fresh data, gate the
          candidate against the incumbent on a holdout, and atomically
          publish to the path the serving tier polls — directly or
          through the fleet canary lane (pipeline_router_url=)
  lanes   gang-batched multi-tenant continuous training
          (xgboost_tpu.pipeline.lanes, PIPELINE.md "Gang-batched
          lanes"): one pipeline per lanes= tenant, same-shape lanes
          vmap-stacked into ONE device dispatch per round segment
          (XGBTPU_LANE_STACK=0 for the independent host-loop
          baseline); per-lane gate/publish knobs ride the pipeline_*
          table
  placer  autonomous catalog placement (xgboost_tpu.placer, SERVING.md
          "Autonomous placement"): watch the router's per-tenant load,
          bin-pack placer_catalog models onto in-rotation replicas
          within their device budgets, and converge the fleet by
          pushing manifest deltas (elastic resizing rides
          tools/launch_fleet.py --supervise)

Observability (OBSERVABILITY.md): obs_log=PATH appends a crash-safe
JSONL timeline (render: tools/obs_report.py); metrics_port=N serves
live /metrics + /healthz during task=train (0 = ephemeral, -1 = off).

task=serve parameters:
{serve_params}

task=fleet_router parameters:
{fleet_params}

task=pipeline parameters:
{pipeline_params}

task=stream parameters (streaming drift-aware continuous learning):
{stream_params}

catalog parameters (multi-tenant serving, task=serve + task=fleet_router):
{catalog_params}

task=lanes parameters (gang-batched multi-tenant training):
{lane_params}

task=placer parameters (autonomous placement + elastic fleet):
{placer_params}
"""


class BoostLearnTask:
    """Training/prediction task state (reference BoostLearnTask)."""

    def __init__(self):
        self.silent = 0
        self.use_buffer = 1
        self.num_round = 10
        self.save_period = 0
        self.eval_train = 0
        self.pred_margin = 0
        self.ntree_limit = 0
        self.dump_stats = 0
        self.task = "train"
        self.train_path = ""
        self.test_path = ""
        self.model_in: Optional[str] = None
        self.model_out: Optional[str] = None
        self.save_final = True  # model_out=NONE disables the final save
        self.model_dir = "./"
        self.name_fmap = ""
        self.name_pred = "pred.txt"
        self.name_dump = "dump.txt"
        self.checkpoint_dir: Optional[str] = None
        self.save_base64 = 0  # text-safe model files (reference bs64 mode)
        self.shard_load = 1  # per-rank split loading in distributed mode
        self.mock_spec: List[Tuple[int, int, int]] = []  # fault injection
        self.faults_spec: Optional[str] = None  # I/O chaos (faults=...)
        self.keepalive = 0  # restart-on-WorkerFailure (rabit_demo keepalive)
        self.rank = 0  # process index under multi-host launch
        self._distributed = False
        self.eval_names: List[str] = []
        self.eval_paths: List[str] = []
        self.learner_params: List[Tuple[str, str]] = []
        # task=serve / task=fleet_router knobs, seeded from the config
        # tables (single source of truth for both CLI surfaces)
        self.serve_params = {k: v for k, (v, _) in SERVE_PARAMS.items()}
        self.fleet_params = {k: v for k, (v, _) in FLEET_PARAMS.items()}
        self.pipeline_params = {k: v
                                for k, (v, _) in PIPELINE_PARAMS.items()}
        self.stream_params = {k: v
                              for k, (v, _) in STREAM_PARAMS.items()}
        self.catalog_params = {k: v
                               for k, (v, _) in CATALOG_PARAMS.items()}
        self.placer_params = {k: v
                              for k, (v, _) in PLACER_PARAMS.items()}
        self.lane_params = {k: v for k, (v, _) in LANE_PARAMS.items()}

    # ------------------------------------------------------------- params
    _OWN = {
        "silent": int, "use_buffer": int, "num_round": int,
        "save_period": int, "eval_train": int, "pred_margin": int,
        "ntree_limit": int, "dump_stats": int, "save_base64": int,
        "shard_load": int,
    }

    def set_param(self, name: str, val: str) -> None:
        if name in self._OWN:
            setattr(self, name, self._OWN[name](val))
        elif name == "task":
            self.task = val
        elif name == "data":
            self.train_path = val
        elif name == "test:data":
            self.test_path = val
        elif name == "model_in":
            self.model_in = None if val == "NULL" else val
        elif name == "model_out":
            # NULL -> save numbered file; NONE -> skip the final save
            # (reference xgboost_main.cpp:218-224)
            self.model_out = None if val in ("NULL", "NONE") else val
            self.save_final = val != "NONE"
        elif name == "model_dir":
            self.model_dir = val
        elif name == "fmap":
            self.name_fmap = "" if val == "NULL" else val
        elif name == "name_dump":
            self.name_dump = val
        elif name == "name_pred":
            self.name_pred = val
        elif name == "checkpoint_dir":
            self.checkpoint_dir = val
        elif name == "mock":
            # reference AllreduceMock spec "rank,version,seqno,ntrial"
            # (allreduce_mock.h:57-63).  Stored with the rank; under the
            # multi-host launcher only the matching worker installs the
            # coordinate (single-controller: rank 0 == the process).
            # 3-field specs apply to every rank.  Multiple coordinates:
            # semicolon-separated.  A "stall:" prefix makes the
            # coordinate HANG instead of die (parallel/mock.py stall
            # kind — detectable only by the gang launcher's
            # --watchdog-stall-sec heartbeat watchdog, never by the
            # in-process keepalive loop).
            for part in val.split(";"):
                kind = "die"
                if ":" in part:
                    k, _, part = part.partition(":")
                    kind = k.strip()
                    if kind not in ("die", "stall"):
                        raise ValueError(
                            f"mock={part!r}: unknown kind {kind!r} "
                            "(die|stall)")
                nums = [int(x) for x in part.split(",") if x.strip() != ""]
                if len(nums) == 3:
                    nums = [-1] + nums  # any rank
                if len(nums) != 4:
                    raise ValueError(
                        f"mock={part!r}: expected "
                        "[kind:][rank,]version,seqno,ntrial")
                self.mock_spec.append(tuple(nums) + (kind,))
        elif name == "keepalive":
            self.keepalive = int(val)
        elif name == "faults":
            # I/O + serving chaos injection (reliability/faults.py):
            # "kind[=arg][@path][#times];..." — the file-system sibling
            # of the collective-seam mock= parameter
            self.faults_spec = val
        elif name in self.serve_params:
            self.serve_params[name] = type(SERVE_PARAMS[name][0])(val)
        elif name in self.fleet_params:
            self.fleet_params[name] = type(FLEET_PARAMS[name][0])(val)
        elif name in self.pipeline_params:
            self.pipeline_params[name] = type(PIPELINE_PARAMS[name][0])(val)
        elif name in self.stream_params:
            self.stream_params[name] = type(STREAM_PARAMS[name][0])(val)
        elif name in self.catalog_params:
            self.catalog_params[name] = type(CATALOG_PARAMS[name][0])(val)
        elif name in self.placer_params:
            self.placer_params[name] = type(PLACER_PARAMS[name][0])(val)
        elif name in self.lane_params:
            self.lane_params[name] = type(LANE_PARAMS[name][0])(val)
        else:
            m = re.match(r"eval\[([^\]]+)\]", name)
            if m:
                self.eval_names.append(m.group(1))
                self.eval_paths.append(val)
                return
        # every param also cascades into the learner (reference
        # xgboost_main.cpp:95 "learner.SetParam(name, val)")
        self.learner_params.append((name, val))

    # --------------------------------------------------------------- run
    def run(self, argv: List[str]) -> int:
        if not argv:
            from xgboost_tpu.config import (catalog_params_help,
                                            fleet_params_help,
                                            lane_params_help,
                                            pipeline_params_help,
                                            placer_params_help,
                                            serve_params_help,
                                            stream_params_help)
            print(_USAGE.format(serve_params=serve_params_help(),
                                fleet_params=fleet_params_help(),
                                pipeline_params=pipeline_params_help(),
                                stream_params=stream_params_help(),
                                catalog_params=catalog_params_help(),
                                lane_params=lane_params_help(),
                                placer_params=placer_params_help()))
            return 0
        if os.path.exists(argv[0]) or "=" not in argv[0]:
            for name, val in parse_config_file(argv[0]):
                self.set_param(name, val)
            rest = argv[1:]
        else:
            rest = argv
        for arg in rest:
            name, eq, val = arg.partition("=")
            if eq:
                self.set_param(name, val)
        if self.model_out == "stdout" or self.name_pred == "stdout":
            self.set_param("silent", "1")
            self.save_period = 0
        if self.faults_spec:
            from xgboost_tpu.reliability import faults
            faults.install_spec(self.faults_spec)

        if (self.checkpoint_dir and self.task == "train"
                and not os.environ.get("XGBTPU_NO_JITCACHE")):
            # WARM-CACHE RESTART (RECOVERY.md): persist jit
            # compilations next to the checkpoint ring, so a gang
            # restart after a worker failure reloads compiled
            # executables instead of re-tracing and re-compiling —
            # the dominant recovery cost otherwise.  Must happen
            # before any backend use.
            import jax
            cache_dir = os.path.join(self.checkpoint_dir, "jitcache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)

        # multi-host worker mode (launched by xgboost_tpu.launch or a
        # scheduler exporting XGBTPU_COORD): initialize the distributed
        # runtime BEFORE any backend use, train dsplit=row over the
        # global mesh, auto-silence nonzero ranks and save from rank 0
        # only (reference xgboost_main.cpp:48-50, :242-245)
        from xgboost_tpu.parallel.launch import init_worker
        self._distributed = init_worker()
        if self._distributed:
            import jax
            self.rank = jax.process_index()
            if not any(k == "dsplit" for k, _ in self.learner_params):
                self.set_param("dsplit", "row")
            if self.rank != 0:
                self.silent = max(self.silent, 2)
                if self.task != "train":
                    # pred/eval/dump are process-local: one rank suffices
                    # (and concurrent writes to shared output would race)
                    return 0

        if self._distributed:
            # die HARD on ANY fatal error (rabit workers just die):
            # normal interpreter exit hangs ~minutes in the
            # jax.distributed client teardown trying to reach the
            # coordinator, and the gang launcher cannot restart the job
            # until this process is seen dead — measured 330 s vs
            # sub-second detection (RECOVERY.md).  Covers real failures
            # (bad input, OOM, metric errors), not just the injector.
            try:
                return self._dispatch_marked()
            except SystemExit:
                raise
            except BaseException:
                import traceback

                from xgboost_tpu.reliability.rc import WORKER_CRASH_RC
                traceback.print_exc()
                sys.stderr.flush()
                os._exit(WORKER_CRASH_RC)
        return self._dispatch_marked()

    def _dispatch_marked(self) -> int:
        """Dispatch, then touch the gang ``done-<rank>`` marker on
        success — a re-adopting coordinator cannot ``wait()`` a worker
        it did not spawn, so clean exit must be visible on disk
        (parallel/gang.py)."""
        rc = self._dispatch()
        if rc == 0:
            from xgboost_tpu.parallel import gang
            gang.mark_done()
        return rc

    def _setup_obs(self) -> None:
        """Arm the observability layer (OBSERVABILITY.md) from params:
        ``obs_log=`` opens the JSONL event log (per-rank suffix under
        the multi-host launcher, so timelines never interleave), and
        ``metrics_port=`` serves live ``/metrics`` + ``/healthz`` from
        a daemon thread (rank r binds port+r — per-rank export of the
        collective stats).  Env equivalents: XGBTPU_OBS_LOG, XGBTPU_OBS.
        """
        from xgboost_tpu import obs
        params = self._params_dict()
        obs_path = params.get("obs_log") or os.environ.get("XGBTPU_OBS_LOG")
        if obs_path:
            if self._distributed and self.rank != 0:
                obs_path = f"{obs_path}.rank{self.rank}"
            obs.configure_log(obs_path)
        port = int(params.get("metrics_port", -1))
        if port >= 0 and self.task in ("train", "pipeline"):
            srv = obs.start_metrics_server(
                port=port + self.rank if port > 0 else 0,
                rank=self.rank)
            if self.silent < 2:
                print(f"[obs] training metrics on "
                      f"http://{srv.host}:{srv.port}/metrics "
                      f"(rank {self.rank})", file=sys.stderr)

    def _dispatch(self) -> int:
        """Task dispatch after param parsing + distributed init."""
        self._setup_obs()
        if self.task == "train":
            if not self.mock_spec:
                return self.task_train()
            # fault-injection mode: install the injector; with keepalive,
            # restart from the checkpoint ring on simulated death (the
            # rabit_demo.py:26-40 keepalive wrapper, in-process).  In a
            # multi-host job the gang launcher owns restarts (a single
            # process cannot rejoin a live jax.distributed job), so the
            # failure propagates as a nonzero exit instead.
            from xgboost_tpu.parallel import mock
            trial = int(os.environ.get("XGBTPU_NUM_TRIAL", "0"))
            mine = [spec[1:] for spec in self.mock_spec
                    if spec[0] in (-1, self.rank)]
            while True:
                mock.set_fault_injection(mine, trial)
                try:
                    return self.task_train()
                except mock.WorkerFailure as e:
                    restart = self.keepalive and not self._distributed
                    print(f"{e}; "  # message carries the [mock] tag
                          + ("restarting" if restart else "dead"),
                          file=sys.stderr)
                    if not restart:
                        # distributed: the run() wrapper os._exit()s
                        raise
                    trial += 1
                finally:
                    mock.clear_fault_injection()
        if self.task == "pred":
            return self.task_pred()
        if self.task == "eval":
            return self.task_eval()
        if self.task == "dump":
            return self.task_dump()
        if self.task == "serve":
            return self.task_serve()
        if self.task == "fleet_router":
            return self.task_fleet_router()
        if self.task == "pipeline":
            return self.task_pipeline()
        if self.task == "stream":
            return self.task_stream()
        if self.task == "lanes":
            return self.task_lanes()
        if self.task == "placer":
            return self.task_placer()
        raise ValueError(f"unknown task {self.task!r}")

    # ------------------------------------------------------------- helpers
    def _params_dict(self) -> Dict[str, str]:
        from xgboost_tpu.config import params_to_dict
        return params_to_dict(self.learner_params)

    def _load_data(self, path: str):
        # "ext:" (paged, io.cpp:20-29) and "!" (HalfRAM, io.cpp:70-73)
        # URIs are routed by DMatrix.__new__ itself
        from xgboost_tpu.data import DMatrix
        return DMatrix(path, silent=self.silent != 0)

    def _load_train_data(self):
        """Training data: per-rank SPLIT loading in distributed dsplit=row
        mode (the reference routes distributed text loads through
        rank/npart partitioning, io.cpp:56-61 ->
        simple_dmatrix-inl.hpp:89-96); every other case loads the full
        matrix.  ``shard_load=0`` opts out."""
        path = self.train_path
        params = self._params_dict()
        from xgboost_tpu.metrics import _DIST_METRICS
        metrics = params.get("eval_metric", [])
        metrics = [metrics] if isinstance(metrics, str) else list(metrics)
        eligible = (
            self._distributed and self.shard_load
            and params.get("dsplit", "row") == "row"
            and params.get("booster", "gbtree") != "gblinear"
            and not str(params.get("objective", "")).startswith("rank:")
            and "grow_colmaker" not in str(params.get("updater", ""))
            # eval_train evaluates ON the training matrix: every metric
            # needs a distributed partial-sum form there
            and (not self.eval_train
                 or all(m.partition("@")[0] in _DIST_METRICS
                        for m in metrics))
            and not path.startswith(("ext:", "!")) and "#" not in path
            and path != "stdin" and os.path.exists(path)
            and _looks_like_text(path))
        if eligible:
            try:
                from xgboost_tpu.parallel.sharded import ShardedDMatrix
                return ShardedDMatrix(path, silent=self.silent != 0)
            except (NotImplementedError, ValueError) as e:
                # ValueError: mesh shape unsuitable for block split
                # (non-contiguous per-process devices) — replicated
                # loading still works there
                if self.silent < 2:
                    print(f"[shard_load] replicated-load fallback: {e}",
                          file=sys.stderr)
        return self._load_data(path)

    def _make_booster(self, cache=()):
        from xgboost_tpu.learner import Booster
        bst = Booster(self._params_dict(), cache=list(cache))
        if self.model_in:
            bst.load_model(self.model_in)
            bst.set_param(self._params_dict())
        return bst

    def _save(self, bst, i: Optional[int] = None) -> None:
        if self.rank != 0:  # rank-0-only saves (xgboost_main.cpp:242-245)
            return
        if i is None:
            assert self.model_out is not None
            path = self.model_out
        else:
            path = os.path.join(self.model_dir, f"{i + 1:04d}.model")
        bst.save_model(path, save_base64=bool(self.save_base64))

    # ------------------------------------------------------------- train
    def _train_rounds(self, bst, data, evals, start_round: int,
                      start: float) -> None:
        """The training round driver (reference TaskTrain round loop,
        xgboost_main.cpp:175-229), riding ``Booster.update_many``'s
        segmented fused dispatches: eval lines and numbered saves keep
        per-round granularity/bit-identity, checkpoints write at
        segment boundaries (a mid-segment SIGKILL resumes from the last
        boundary's ring member and retrains bit-identically — per-round
        fold_in seeding).  Ineligible configs (pruning, external
        memory, profiler/obs phases, ...) and rounds_per_dispatch=0
        run the same hooks one round at a time; ``mock=`` faults ride
        the fused path (coordinates replay at segment boundaries)."""

        def plan_cb(k: int) -> None:
            if self.silent or not k:
                return
            n = self.num_round - start_round
            print(f"fusing rounds {start_round}..{self.num_round - 1} "
                  f"in segments of {k} "
                  f"({-(-n // k)} device dispatches)", file=sys.stderr)

        def round_cb(i: int) -> None:
            if not self.silent:
                print(f"boosting round {i}, "
                      f"{time.perf_counter() - start:.0f} sec "
                      "elapsed", file=sys.stderr)

        def eval_cb(i: int, msg: str) -> None:
            if self.silent < 2:
                print(msg, file=sys.stderr)

        def seg_cb(last_i: int) -> None:
            if self.save_period != 0 \
                    and (last_i + 1) % self.save_period == 0:
                self._save(bst, last_i)
            if self.checkpoint_dir and self.rank == 0:
                from xgboost_tpu.obs import event
                from xgboost_tpu.parallel import gang
                if gang.fenced():
                    # split-brain interlock (RECOVERY.md): a fenced
                    # worker must never race the ring with its
                    # replacement.  The fence path exits the process at
                    # the round boundary, so this gate is a second
                    # lock on the same door — kept because the ring is
                    # the one artifact two writers must never share
                    event("ckpt.fenced_skip", version=last_i + 1)
                    return
                _save_checkpoint(self.checkpoint_dir, bst, last_i + 1)

        bst.update_many(data, start_round, self.num_round - start_round,
                        evals=evals or None, plan_callback=plan_cb,
                        round_callback=round_cb, eval_callback=eval_cb,
                        segment_callback=seg_cb,
                        boundary_align=self.save_period)

    def task_train(self) -> int:
        import xgboost_tpu  # noqa: F401  (ensure package import works early)

        data = self._load_train_data()
        evals = [(self._load_data(p), n)
                 for p, n in zip(self.eval_paths, self.eval_names)]
        if self.eval_train:
            evals.append((data, "train"))

        bst = self._make_booster(cache=[data] + [d for d, _ in evals])
        start_round = 0
        if self.checkpoint_dir:
            if self._distributed and self.rank != 0:
                pass  # rank 0's checkpoint is broadcast below
            else:
                bst, start_round = _load_checkpoint(
                    self.checkpoint_dir, bst, self._params_dict())
            if self._distributed:
                # rabit::LoadCheckPoint semantics: the recovered state is
                # broadcast so every rank resumes at the same round even
                # without a shared checkpoint filesystem
                bst, start_round = _broadcast_checkpoint(
                    bst, start_round, self.rank, self._params_dict())
            if start_round and self.rank == 0:
                # recovery-cost accounting (RECOVERY.md): time from
                # process start to the resume point — data reload +
                # distributed re-init + checkpoint load; the jit
                # recompile cost lands inside the first resumed round
                # (or not, with the persistent jit cache below)
                print(f"[ckpt] resume at round {start_round} "
                      f"({time.perf_counter() - _T0:.2f}s from process "
                      "start)", file=sys.stderr)

        start = time.perf_counter()
        # every config drives the segmented fused dispatcher: eval
        # lines, save_period and checkpoint_dir land at per-round /
        # segment-boundary granularity WITHOUT forcing per-round device
        # dispatches (update_many falls back per-round when fusion is
        # ineligible — pruning, external memory, profiler, ...)
        self._train_rounds(bst, data, evals, start_round, start)
        # save final round unless a periodic numbered save already covered
        # it (reference xgboost_main.cpp:219-225: no final save when
        # save_period divides num_round, even with model_out set)
        if self.save_final and (self.save_period == 0
                                or self.num_round % self.save_period != 0):
            if self.model_out is not None:
                self._save(bst)
            else:
                self._save(bst, self.num_round - 1)
        if getattr(bst, "_profiler", None) is not None:
            bst._profiler.print_summary()
            bst._profiler.stop()
        if not self.silent:
            print(f"\nupdating end, "
                  f"{time.perf_counter() - start:.0f} sec in all",
                  file=sys.stderr)
        return 0

    # -------------------------------------------------------------- pred
    def task_pred(self) -> int:
        data = self._load_data(self.test_path)
        bst = self._make_booster()
        assert self.model_in, "model_in not specified"
        if not self.silent:
            print("start prediction...")
        preds = bst.predict(data, output_margin=self.pred_margin != 0,
                            ntree_limit=self.ntree_limit)
        if not self.silent:
            print(f"writing prediction to {self.name_pred}")
        if self.name_pred == "stdout":
            for p in preds.reshape(-1):
                sys.stdout.write(f"{p:g}\n")
        else:
            # streamed into the tmp+rename staging file (XGT003): a
            # killed pred job leaves the previous complete output or
            # the new one, never a torn prefix a downstream consumer
            # would half-read — and a multi-million-row output is never
            # materialized in memory (no CRC footer: text output, not
            # a model file)
            from xgboost_tpu.reliability.integrity import atomic_writer
            with atomic_writer(self.name_pred) as f:
                for p in preds.reshape(-1):
                    f.write(f"{p:g}\n".encode())
        return 0

    # -------------------------------------------------------------- eval
    def task_eval(self) -> int:
        assert self.model_in, "model_in not specified"
        evals = [(self._load_data(p), n)
                 for p, n in zip(self.eval_paths, self.eval_names)]
        bst = self._make_booster(cache=[d for d, _ in evals])
        print(bst.eval_set(evals, 0), file=sys.stderr)
        return 0

    # -------------------------------------------------------------- serve
    def task_serve(self) -> int:
        """Run the HTTP prediction service on model_in (the serving
        subsystem; quickstart in README 'Serving', design in SERVING.md)
        — or on a multi-model catalog manifest (catalog=...,
        xgboost_tpu.catalog), where bare /predict serves the default
        model and ?model=NAME picks a tenant.
        """
        sp = self.serve_params
        cp = self.catalog_params
        assert self.model_in or cp["catalog"], \
            "model_in not specified (or pass catalog=name=path,...)"
        from xgboost_tpu.serving import run_server
        run_server(
            self.model_in or "",
            host=sp["serve_host"], port=sp["serve_port"],
            min_bucket=sp["serve_min_bucket"],
            max_bucket=sp["serve_max_bucket"],
            max_batch_rows=sp["serve_max_batch_rows"],
            max_wait_ms=sp["serve_max_wait_ms"],
            max_queue_rows=sp["serve_queue_rows"],
            poll_sec=sp["serve_poll_sec"],
            keep_versions=sp["serve_keep_versions"],
            warmup=bool(sp["serve_warmup"]),
            drain_sec=sp["serve_drain_sec"],
            max_body_mb=sp["serve_max_body_mb"],
            featurestore_mb=sp["serve_featurestore_mb"],
            catalog=cp["catalog"],
            catalog_default=cp["catalog_default"],
            catalog_mb=cp["serve_catalog_mb"],
            catalog_hysteresis_sec=cp["catalog_hysteresis_sec"],
            router_url=sp["serve_router_url"],
            replica_id=sp["serve_replica_id"],
            advertise_url=sp["serve_advertise_url"],
            quiet=self.silent != 0, block=True)
        return 0

    # ------------------------------------------------------- fleet_router
    def task_fleet_router(self) -> int:
        """Run the fleet routing front door (xgboost_tpu.fleet,
        SERVING.md fleet section).  Replicas join with
        ``task=serve serve_router_url=http://host:port``."""
        from xgboost_tpu.fleet import run_router
        fp = self.fleet_params
        cp = self.catalog_params
        run_router(
            host=fp["fleet_host"], port=fp["fleet_port"],
            lease_sec=fp["fleet_lease_sec"], hc_sec=fp["fleet_hc_sec"],
            inflight_budget=fp["fleet_inflight"],
            breaker_failures=fp["fleet_breaker_failures"],
            breaker_cooldown_sec=fp["fleet_breaker_cooldown_sec"],
            retry=bool(fp["fleet_retry"]),
            forward_timeout=fp["fleet_timeout_sec"],
            max_body_mb=fp["fleet_max_body_mb"],
            deadline_ms=fp["fleet_deadline_ms"],
            slow_eject_factor=fp["fleet_slow_eject_factor"],
            slow_eject_cooldown_sec=fp["fleet_slow_eject_cooldown_sec"],
            state_path=fp["fleet_state_path"],
            tenant_inflight=cp["tenant_inflight"],
            tenant_rate=cp["tenant_rate"],
            tenant_burst=cp["tenant_burst"],
            rollout_defaults={
                "canaries": fp["fleet_canaries"],
                "soak_sec": fp["fleet_soak_sec"],
                "gate_error_rate": fp["fleet_gate_error_rate"],
                "gate_p99_ms": fp["fleet_gate_p99_ms"],
            },
            quiet=self.silent != 0, block=True)
        return 0

    # ------------------------------------------------------------- placer
    def task_placer(self) -> int:
        """Run the autonomous placement controller (xgboost_tpu.placer,
        SERVING.md "Autonomous placement") against a fleet router:
        watch per-tenant load, bin-pack the ``placer_catalog`` models
        onto in-rotation replicas, push manifest deltas until the fleet
        converges.  Loops until SIGTERM/Ctrl-C."""
        from xgboost_tpu.catalog import parse_manifest
        from xgboost_tpu.placer import run_placer
        pp = self.placer_params
        router_url = pp["placer_router_url"]
        if not router_url:
            raise ValueError("task=placer requires placer_router_url=")
        if not pp["placer_catalog"]:
            raise ValueError("task=placer requires placer_catalog= "
                             "(name=path,... or a manifest file)")
        manifest = parse_manifest(pp["placer_catalog"])
        if self.silent < 2:
            print(f"[placer] managing {len(manifest)} tenant(s) on "
                  f"{router_url}", file=sys.stderr)
        run_placer(
            router_url, manifest,
            plan_path=pp["placer_plan_path"],
            placer_id=pp["placer_id"],
            tick_sec=pp["placer_tick_sec"],
            lease_sec=pp["placer_lease_sec"],
            replication=pp["placer_replication"],
            hot_replication=pp["placer_hot_replication"],
            hot_fraction=pp["placer_hot_fraction"],
            load_alpha=pp["placer_load_alpha"],
            block=True)
        return 0

    # ----------------------------------------------------------- pipeline
    def task_pipeline(self) -> int:
        """Run the continuous-training loop (xgboost_tpu.pipeline,
        PIPELINE.md): train → gate → publish against the model file the
        serving tier polls.  ``pipeline_data`` falls back to ``data=``;
        learner hyperparameters (objective, max_depth, ...) pass
        through like ``task=train``."""
        from xgboost_tpu.pipeline import run_pipeline
        pp = self.pipeline_params
        summary = run_pipeline(
            pp["pipeline_publish_path"],
            workdir=pp["pipeline_dir"],
            data=pp["pipeline_data"] or self.train_path,
            holdout=pp["pipeline_holdout"],
            rounds_per_cycle=pp["pipeline_rounds_per_cycle"],
            cycles=pp["pipeline_cycles"],
            metric=pp["pipeline_metric"],
            min_delta=pp["pipeline_min_delta"],
            max_regression=pp["pipeline_max_regression"],
            router_url=pp["pipeline_router_url"],
            publish_timeout_sec=pp["pipeline_publish_timeout_sec"],
            sleep_sec=pp["pipeline_sleep_sec"],
            params=self._params_dict(),
            quiet=self.silent != 0)
        if self.silent < 2:
            print(f"[pipeline] done: {summary}", file=sys.stderr)
        return 0 if summary.get("errors", 0) == 0 else 1

    # -------------------------------------------------------------- lanes
    def task_lanes(self) -> int:
        """Gang-batched multi-tenant continuous training
        (xgboost_tpu.pipeline.lanes, PIPELINE.md "Gang-batched lanes"):
        one train -> gate -> publish pipeline per ``lanes=`` tenant,
        with same-shape lanes vmap-stacked into one device dispatch per
        round segment.  Per-lane gate/publish knobs (metric, deltas,
        router, sleep) come from the pipeline_* table; learner
        hyperparameters pass through like ``task=train``."""
        from xgboost_tpu.catalog import parse_manifest
        from xgboost_tpu.pipeline import run_tenant_lanes
        lp = self.lane_params
        pp = self.pipeline_params
        if not lp["lanes"]:
            raise ValueError("task=lanes requires lanes= "
                             "(name=publish_path,... or a manifest "
                             "file)")
        manifest = parse_manifest(lp["lanes"])
        data = lp["lane_data"] or self.train_path
        holdout = lp["lane_holdout"]
        lanes = {}
        for name, publish_path in manifest.items():
            lanes[name] = dict(
                publish_path=publish_path,
                workdir=os.path.join(lp["lanes_dir"], name),
                data=data.replace("{lane}", name),
                holdout=holdout.replace("{lane}", name),
                rounds_per_cycle=lp["lane_rounds_per_cycle"],
                cycles=lp["lane_cycles"],
                metric=pp["pipeline_metric"],
                min_delta=pp["pipeline_min_delta"],
                max_regression=pp["pipeline_max_regression"],
                router_url=pp["pipeline_router_url"],
                publish_timeout_sec=pp["pipeline_publish_timeout_sec"],
                sleep_sec=pp["pipeline_sleep_sec"],
                params=self._params_dict())
        stacked = (None if lp["lane_stack"] < 0
                   else bool(lp["lane_stack"]))
        if self.silent < 2:
            print(f"[lanes] training {len(lanes)} tenant lane(s) "
                  f"(stacked={'auto' if stacked is None else stacked})",
                  file=sys.stderr)
        out = run_tenant_lanes(
            lanes, quiet=self.silent != 0, stacked=stacked,
            max_workers=lp["lane_max_workers"] or None,
            window_sec=lp["lane_window_ms"] / 1000.0)
        errors = sum(1 for v in out.values() if v.get("status") != "ok")
        if self.silent < 2:
            for name in sorted(out):
                print(f"[lanes] {name}: {out[name]}", file=sys.stderr)
        return 0 if errors == 0 else 1

    # ------------------------------------------------------------- stream
    def task_stream(self) -> int:
        """Run the streaming drift-aware loop (xgboost_tpu.stream,
        PIPELINE.md streaming section): consume row batches from the
        ``stream_dir`` spool as micro-cycles, track per-feature drift,
        refresh cuts online, and publish gated candidates.  Learner
        hyperparameters (objective, ema_fs, ...) pass through like
        ``task=train``."""
        from xgboost_tpu.stream import run_stream
        sp = self.stream_params
        summary = run_stream(
            sp["stream_publish_path"],
            workdir=sp["stream_workdir"],
            stream_dir=sp["stream_dir"],
            rounds_per_cycle=sp["stream_rounds_per_cycle"],
            cycles=sp["stream_cycles"],
            min_batches=sp["stream_min_batches"],
            max_batches=sp["stream_max_batches"],
            catchup_backlog=sp["stream_catchup_backlog"],
            max_backlog=sp["stream_max_backlog"],
            holdout_cycles=sp["stream_holdout_cycles"],
            metric=sp["stream_metric"],
            min_delta=sp["stream_min_delta"],
            max_regression=sp["stream_max_regression"],
            router_url=sp["stream_router_url"],
            sleep_sec=sp["stream_sleep_sec"],
            drift_threshold=sp["stream_drift_threshold"],
            drift_clear=sp["stream_drift_clear"],
            drift_window=sp["stream_drift_window"],
            sketch_size=sp["stream_sketch_size"],
            params=self._params_dict(),
            quiet=self.silent != 0,
            lane=sp["stream_lane"])
        if self.silent < 2:
            print(f"[stream] done: {summary}", file=sys.stderr)
        return 0 if summary.get("errors", 0) == 0 else 1

    # -------------------------------------------------------------- dump
    def task_dump(self) -> int:
        assert self.model_in, "model_in not specified"
        bst = self._make_booster()
        dumps = bst.get_dump(self.name_fmap, with_stats=self.dump_stats != 0)
        from xgboost_tpu.reliability.integrity import atomic_write
        text = "".join(f"booster[{i}]:\n{s}" for i, s in enumerate(dumps))
        atomic_write(self.name_dump, text.encode())
        return 0


def _looks_like_text(path: str) -> bool:
    """Cheap libsvm-text sniff: binary caches (npz/npy magics, NUL bytes)
    route to the magic-sniffing replicated loader."""
    try:
        with open(path, "rb") as f:
            head = f.read(256)
    except OSError:
        return False
    return bool(head) and b"\x00" not in head and not head.startswith(b"PK")


# -------------------------------------------------------- checkpointing
def _ckpt_path(ckpt_dir: str, version: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt-{version:06d}.model")


def _save_checkpoint(ckpt_dir: str, bst, version: int) -> None:
    """Per-round checkpoint (the rabit::CheckPoint analog — the model
    is tiny, so a full save per round is cheap; SURVEY.md §5.3).
    ``save_model`` itself is atomic + CRC-footered (reliability/
    integrity.py), so a crash mid-save can never tear a ring member.
    Cost is accounted like the reference's report_stats checkpoint
    line: a ``ckpt.save`` span in the event log and the
    ``xgbtpu_training_checkpoint_*`` counters."""
    from xgboost_tpu.obs import span, training_metrics
    t0 = time.perf_counter()
    with span("ckpt.save", version=version):
        os.makedirs(ckpt_dir, exist_ok=True)
        bst.save_model(_ckpt_path(ckpt_dir, version))
        # keep only the two most recent checkpoints (ring replica analog)
        kept = sorted(f for f in os.listdir(ckpt_dir)
                      if re.fullmatch(r"ckpt-\d{6}\.model", f))
        for stale in kept[:-2]:
            os.remove(os.path.join(ckpt_dir, stale))
    tm = training_metrics()
    tm.checkpoints.inc()
    tm.checkpoint_seconds.inc(time.perf_counter() - t0)


def _load_checkpoint(ckpt_dir: str, bst, params: dict):
    """Resume from the newest VERIFIABLE checkpoint (rabit's two-replica
    ring made real): when the newest member fails verification — torn
    write, bit flip, unparseable — it is quarantined as ``*.corrupt``
    and the older replica is used instead; version 0 when nothing
    loads (reference xgboost_main.cpp:176-183)."""
    if not os.path.isdir(ckpt_dir):
        return bst, 0
    from xgboost_tpu.obs import event, span
    found = sorted(f for f in os.listdir(ckpt_dir)
                   if re.fullmatch(r"ckpt-\d{6}\.model", f))
    for name in reversed(found):
        path = os.path.join(ckpt_dir, name)
        # ONE read, verified, probed on a THROWAWAY booster, and only
        # then loaded into the real one from the SAME buffer: a failed
        # load can leave its target half-mutated (param/objective
        # adopted from a corrupt header before the state arrays
        # raised), and the real booster must keep the caller's config
        # when the whole ring is bad.  Re-reading between probe and
        # load would let the file change under us after verification.
        try:
            from xgboost_tpu.learner import Booster
            from xgboost_tpu.reliability.integrity import (
                read_file, verify_model_bytes)
            payload = verify_model_bytes(read_file(path), name=path)
            Booster().load_raw(payload, name=path)
        except OSError as e:
            # transient I/O (EIO, EMFILE, permission blip): the bytes
            # may be fine — do NOT quarantine; fall back for THIS
            # restart and let the next one retry the member
            print(f"[ckpt] {name} unreadable ({e}); trying the older "
                  "ring member (file left in place)", file=sys.stderr)
            continue
        except Exception as e:
            from xgboost_tpu.profiling import reliability_metrics
            from xgboost_tpu.reliability.integrity import quarantine
            try:
                qpath = quarantine(path)
                q_msg = f"quarantined as {os.path.basename(qpath)}"
            except OSError as qe:
                # a failed rename must not abort the restart the ring
                # exists to survive
                q_msg = f"quarantine failed ({qe}); left in place"
            reliability_metrics().ring_fallbacks.inc()
            event("ckpt.ring_fallback", member=name, error=str(e))
            print(f"[ckpt] {name} failed verification ({e}); {q_msg}, "
                  "falling back to the older ring member",
                  file=sys.stderr)
            continue
        with span("ckpt.load", member=name, version=int(name[5:11])):
            bst.load_raw(payload, name=path)  # the verified buffer
            bst.set_param(params)
        return bst, int(name[5:11])
    return bst, 0


def _broadcast_checkpoint(bst, start_round: int, rank: int, params: dict):
    """Broadcast rank 0's recovered model + round to every rank
    (rabit::LoadCheckPoint, subtree/rabit/include/rabit.h:166-186)."""
    import numpy as np
    from jax.experimental import multihost_utils as mhu

    raw = bst.save_raw() if (rank == 0 and start_round > 0) else b""
    hdr = mhu.broadcast_one_to_all(
        np.array([len(raw), start_round], np.int64))
    n, rounds = int(hdr[0]), int(hdr[1])
    if n == 0:
        return bst, 0
    buf = np.zeros(n, np.uint8)
    if rank == 0:
        buf[:] = np.frombuffer(raw, np.uint8)
    buf = mhu.broadcast_one_to_all(buf)
    if rank != 0:
        bst.load_raw(buf.tobytes())
        bst.set_param(params)
    return bst, rounds


def main(argv: Optional[List[str]] = None) -> int:
    task = BoostLearnTask()
    task.set_param("seed", "0")
    return task.run(list(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    sys.exit(main())

"""Python side of the C ABI (native/xgtpu_capi.c).

The reference serves non-Python hosts through a C shim over its C++
core (``wrapper/xgboost_wrapper.cpp:113-353``).  Here the compute core
IS Python/JAX, so the C ABI embeds the interpreter and calls into this
bridge: C passes raw pointers as integers, the bridge COPIES the data
at the boundary (callers may free their buffers on return), and keeps
any array/string it returns alive until the owning handle is freed or
the next call of the same kind (the reference's pointer-validity
contract).
"""

from __future__ import annotations

import ctypes
from typing import Dict, List

import numpy as np

_objects: Dict[int, object] = {}
_next_handle = [1]
# return-buffer anchors: (owner_handle, kind) -> object kept alive
_anchors: Dict[tuple, object] = {}


def _new_handle(obj) -> int:
    h = _next_handle[0]
    _next_handle[0] += 1
    _objects[h] = obj
    return h


def _arr(addr: int, length: int, dtype) -> np.ndarray:
    if length == 0:
        return np.zeros(0, dtype=dtype)
    ct = np.ctypeslib.as_ctypes_type(dtype)
    buf = (ct * length).from_address(addr)
    return np.ctypeslib.as_array(buf).copy()


def _anchor(owner: int, kind: str, obj) -> int:
    """Keep obj alive keyed by (owner, kind); return its data address."""
    _anchors[(owner, kind)] = obj
    if isinstance(obj, np.ndarray):
        return obj.ctypes.data
    if isinstance(obj, ctypes.Array):
        return ctypes.addressof(obj)
    raise TypeError(type(obj))


def _anchor_str(owner: int, kind: str, s: str) -> tuple:
    """Anchor a NUL-terminated char buffer; returns (addr, strlen)."""
    raw = s.encode()
    buf = ctypes.create_string_buffer(raw)  # includes the trailing NUL
    return _anchor(owner, kind, buf), len(raw)


# ------------------------------------------------------------------ dmatrix

def dmatrix_from_file(fname: str, silent: int) -> int:
    from xgboost_tpu import DMatrix
    return _new_handle(DMatrix(fname, silent=bool(silent)))


def dmatrix_from_csr(indptr_addr, indices_addr, data_addr,
                     nindptr, nelem) -> int:
    from xgboost_tpu import DMatrix
    indptr = _arr(indptr_addr, nindptr, np.uint64).astype(np.int64)
    indices = _arr(indices_addr, nelem, np.uint32).astype(np.int32)
    values = _arr(data_addr, nelem, np.float32)
    num_col = int(indices.max()) + 1 if nelem else 0
    return _new_handle(DMatrix((indptr, indices, values, num_col)))


def dmatrix_from_csc(colptr_addr, indices_addr, data_addr,
                     nindptr, nelem) -> int:
    from xgboost_tpu import DMatrix
    colptr = _arr(colptr_addr, nindptr, np.uint64).astype(np.int64)
    rows = _arr(indices_addr, nelem, np.uint32).astype(np.int64)
    values = _arr(data_addr, nelem, np.float32)
    ncol = nindptr - 1
    cols = np.repeat(np.arange(ncol, dtype=np.int64), np.diff(colptr))
    order = np.lexsort((cols, rows))  # row-major CSR ordering
    nrow = int(rows.max()) + 1 if nelem else 0
    counts = np.bincount(rows, minlength=nrow)
    indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    return _new_handle(DMatrix((indptr, cols[order].astype(np.int32),
                                values[order], ncol)))


def dmatrix_from_mat(data_addr, nrow, ncol, missing: float) -> int:
    from xgboost_tpu import DMatrix
    X = _arr(data_addr, nrow * ncol, np.float32).reshape(nrow, ncol)
    return _new_handle(DMatrix(X, missing=missing))


def dmatrix_slice(h: int, idx_addr, length) -> int:
    idx = _arr(idx_addr, length, np.int32)
    return _new_handle(_objects[h].slice(idx))


def dmatrix_save_binary(h: int, fname: str, silent: int) -> None:
    _objects[h].save_binary(fname)


def dmatrix_set_float_info(h: int, field: str, addr, length) -> None:
    _objects[h].info.set_field(field, _arr(addr, length, np.float32))


def dmatrix_set_uint_info(h: int, field: str, addr, length) -> None:
    _objects[h].info.set_field(field, _arr(addr, length, np.uint32))


def dmatrix_set_group(h: int, addr, length) -> None:
    _objects[h].info.set_field("group", _arr(addr, length, np.uint32))


def dmatrix_get_float_info(h: int, field: str) -> tuple:
    v = _objects[h].info.get_field(field)
    v = np.zeros(0, np.float32) if v is None else \
        np.ascontiguousarray(v, np.float32)
    return _anchor(h, "finfo", v), len(v)


def dmatrix_get_uint_info(h: int, field: str) -> tuple:
    v = _objects[h].info.get_field(field)
    v = np.zeros(0, np.uint32) if v is None else \
        np.ascontiguousarray(v, np.uint32)
    return _anchor(h, "uinfo", v), len(v)


def dmatrix_num_row(h: int) -> int:
    return int(_objects[h].num_row)


def dmatrix_free(h: int) -> None:
    _objects.pop(h, None)
    for key in [k for k in _anchors if k[0] == h]:
        _anchors.pop(key)


# ------------------------------------------------------------------ booster

def booster_create(dmat_handles: List[int]) -> int:
    from xgboost_tpu import Booster
    cache = [_objects[h] for h in dmat_handles]
    return _new_handle(Booster({}, cache=cache))


def booster_set_param(h: int, name: str, value: str) -> None:
    _objects[h].set_param({name: value})


def booster_update_one_iter(h: int, it: int, dtrain: int) -> None:
    _objects[h].update(_objects[dtrain], it)


def booster_boost_one_iter(h: int, dtrain: int, grad_addr, hess_addr,
                           length) -> None:
    _objects[h].boost(_objects[dtrain],
                      _arr(grad_addr, length, np.float32),
                      _arr(hess_addr, length, np.float32))


def booster_eval_one_iter(h: int, it: int, dmat_handles: List[int],
                          names: List[str]) -> tuple:
    evals = [(_objects[d], n) for d, n in zip(dmat_handles, names)]
    return _anchor_str(h, "eval", _objects[h].eval_set(evals, it))


def booster_predict(h: int, dmat: int, option_mask: int,
                    ntree_limit: int) -> tuple:
    bst = _objects[h]
    out = bst.predict(_objects[dmat],
                      output_margin=bool(option_mask & 1),
                      ntree_limit=ntree_limit,
                      pred_leaf=bool(option_mask & 2))
    out = np.ascontiguousarray(np.asarray(out, np.float32)).ravel()
    return _anchor(h, "pred", out), len(out)


def booster_load_model(h: int, fname: str) -> None:
    _objects[h].load_model(fname)


def booster_save_model(h: int, fname: str) -> None:
    _objects[h].save_model(fname)


def booster_load_model_from_buffer(h: int, addr, length) -> None:
    _objects[h].load_raw(ctypes.string_at(addr, length))


def booster_get_model_raw(h: int) -> tuple:
    raw = np.frombuffer(_objects[h].save_raw(), dtype=np.uint8).copy()
    return _anchor(h, "raw", raw), len(raw)


def booster_dump_model(h: int, fmap: str, with_stats: int) -> tuple:
    """Anchored char** array: (address of pointer table, n_trees)."""
    dumps = _objects[h].get_dump(fmap=fmap or "",
                                 with_stats=bool(with_stats))
    bufs = [ctypes.create_string_buffer(s.encode()) for s in dumps]
    ptrs = (ctypes.c_void_p * max(len(bufs), 1))(
        *[ctypes.addressof(b) for b in bufs])
    _anchors[(h, "dump")] = (bufs, ptrs)
    return ctypes.addressof(ptrs), len(bufs)


def booster_free(h: int) -> None:
    dmatrix_free(h)

"""BoostLearner: objective + booster + metrics orchestration, and the
``train``/``cv`` front-end API.

Mirrors the reference's learner layer (``src/learner/learner-inl.hpp``:
``BoostLearner::UpdateOneIter/EvalOneIter/Predict`` :274-346) and the
Python surface (``wrapper/xgboost.py``: ``Booster`` :246-530, ``train``
with early stopping :533-632, ``cv``/``mknfold``/``aggcv`` :635-740).

Prediction caching: each DMatrix a Booster has seen keeps a device-side
binned matrix and a running margin, advanced incrementally per round —
the reference's pred_buffer/pred_counter design
(``gbtree-inl.hpp:304-353``) without the per-row tree walk.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.binning import _rank0 as _is_rank0
from xgboost_tpu.binning import bin_matrix, compute_cuts
from xgboost_tpu.config import TrainParam
from xgboost_tpu.data import DMatrix, MetaInfo
from xgboost_tpu.metrics import create_metric
from xgboost_tpu.objectives import create_objective

_MAGIC = "xgbtpu001"


def _predict_upload_depth() -> int:
    """Prefetch depth of the one-off prediction upload pipeline: how
    many f32 row blocks stage ahead of the quantize+traverse consuming
    them (external._prefetch_to_device).  2 = double-buffered (block
    k+1 uploads while block k computes); 1 = single lookahead; 0 =
    synchronous.  ``XGBTPU_PREDICT_UPLOAD_DEPTH`` is the A/B seam
    (tools/predict_microbench.py e2e cells)."""
    try:
        return max(0, int(os.environ.get("XGBTPU_PREDICT_UPLOAD_DEPTH",
                                         "2")))
    except ValueError:
        return 2


class _CacheEntry:
    """Per-DMatrix device state (the reference's CacheEntry,
    learner-inl.hpp:495-512)."""

    def __init__(self, dmat: DMatrix, binned: jax.Array, base_margin: jax.Array,
                 info=None, row_valid: Optional[jax.Array] = None,
                 n_real: Optional[int] = None, external: bool = False):
        self.dmat = dmat                 # strong ref: id(dmat) keys the cache
        self.binned = binned
        self.base = base_margin          # (N_pad, K)
        self.info = info if info is not None else dmat.info
        self.row_valid = row_valid       # None, or (N_pad,) bool when padded
        self.n_real = n_real if n_real is not None else dmat.num_row
        self.margin: Optional[jax.Array] = None
        self.applied = 0                 # trees folded into margin
        self.external = external         # paged matrix: margin lives on host
        self.root: Optional[jax.Array] = None  # per-row root slots (N_pad,)
        self.info_version = dmat.info.version  # source-snapshot tracking
        # group-padded rank layout (rank_device.PadRankPrep): rows are
        # RELAID (label-sorted, lane-padded per group), so user-facing
        # outputs must unmap via user_rows() instead of [:n_real]
        self.rank_pad_prep = None

    def user_rows(self, x):
        """User-row view of a host per-row array (rows = first axis):
        a [:n_real] slice for end-padded layouts, the static unmap
        gather for group-padded rank entries."""
        if self.rank_pad_prep is not None:
            return x[self.rank_pad_prep.user_map]
        return x[:self.n_real]


class LaneSpec(NamedTuple):
    """One tenant's gang-batching contract (:meth:`Booster.fused_lane_spec`):
    everything the lane-stacking driver (``pipeline/lanes.py``) needs to
    vmap this booster's next fused rounds alongside its shape-bucket
    peers in ONE device dispatch.  The static fields (cfg, finder and
    gradient identities, K/npar, pred_chunk, shapes) form the bucket
    key — lanes stack only when every static matches, so the stacked
    scan's compiled program is exactly the solo scan's under ``vmap``.
    The device fields are the solo scan's own operands; margins are
    already synced (``_sync_margin``) when the spec is handed out."""
    booster: "Booster"
    entry: _CacheEntry
    n_rows: int              # device row count (N_pad of the entry)
    n_features: int
    n_rounds: int
    first_iteration: int
    seg_k: int               # resolved rounds-per-dispatch segment size
    K: int                   # num_output_group
    npar: int                # num_parallel_tree
    cfg: object              # GrowConfig (hashable; static scan arg)
    split_finder: object     # stable identity or None
    grad_fn: object          # Objective.fused_grad (stable identity)
    pred_chunk: int
    subsample: float         # < 1.0 forbids row padding (N-shaped draws)
    binned: jax.Array        # (N, F) device bins
    margin: jax.Array        # (N, K) synced margins
    label: jax.Array
    weight: jax.Array
    base_key: jax.Array      # PRNGKey(seed) — the solo scan's own key
    cut_values: jax.Array    # (F, W) f32
    n_cuts: jax.Array        # (F,) int32
    row_valid: Optional[jax.Array]   # (N,) bool or None (= all real)


class Booster:
    """Learner handle (reference wrapper/xgboost.py Booster + BoostLearner)."""

    def __init__(self, params: Optional[dict] = None,
                 cache: Sequence[DMatrix] = (), model_file: Optional[str] = None):
        self.param = TrainParam.from_dict(params or {})
        self.obj = None
        self.gbtree = None
        self.num_feature = 0
        self._cache: Dict[int, _CacheEntry] = {}
        self.best_iteration: int = -1
        self.best_score: float = float("nan")
        self.attributes: Dict[str, str] = {}
        # bumped on every whole-model replacement (load_model/load_raw):
        # cache entries stamp it so a margin can never fold trees of two
        # different loaded ensembles (the hot-reload window-mixing guard)
        self._model_gen = 0
        self._mesh = None                  # resolved at _lazy_init (dsplit=row)
        self._col_mesh = None              # resolved at _lazy_init (dsplit=col)
        # EMA-FS feature screen (set_feature_screen): ascending FULL-
        # space feature ids fused training restricts its histogram
        # working set to; None = off (the bit-identical default path)
        self._feature_screen = None
        self._pending_cache = list(cache)  # bound at _lazy_init (needs cuts)
        if model_file is not None:
            self.load_model(model_file)

    # ----------------------------------------------------------- parameters
    def set_param(self, name, value=None):
        if isinstance(name, (dict, list, tuple)):
            from xgboost_tpu.config import params_to_dict
            for k, v in params_to_dict(name).items():
                self.param.set_param(k, v)
        else:
            self.param.set_param(name, value)
        self._reconfigure()

    def _init_obj(self):
        self.obj = create_objective(self.param.objective)
        self.obj.set_param("scale_pos_weight", self.param.scale_pos_weight)
        self.obj.set_param("num_class", self.param.num_class)
        self.obj.set_param("num_pairsample", self.param.num_pairsample)
        self.obj.set_param("fix_list_weight", self.param.fix_list_weight)
        self.obj.set_param("rank_impl", self.param.rank_impl)
        self.obj.set_param("seed", self.param.seed)

    def _reconfigure(self):
        """Propagate changed params into live objective/booster state, so
        continued training (xgb_model=...) honors new hyperparameters."""
        if self.obj is not None:
            self._init_obj()
        if self.gbtree is not None and self.param.booster != "gblinear":
            from xgboost_tpu.models.gbtree import make_grow_config
            self.gbtree.param = self.param
            self.gbtree.cfg = make_grow_config(self.param,
                                               self.gbtree.cuts.max_bin)
            # updater / sketch params may have changed the split finder
            self.gbtree._split_finder_cache = None
            self.gbtree._base_key_cache = None  # seed may have changed

    def set_feature_screen(self, kept=None) -> None:
        """Restrict FUSED training's histogram working set to ``kept``
        full-space feature ids (EMA-FS, ``ema_fs`` > 0 — see
        xgboost_tpu.stream): the (C, N, F) histogram build touches only
        the surviving columns, and grown trees are remapped back to the
        full feature space so model bytes, prediction, and eval are
        screen-free.  ``None`` clears the screen (the default path,
        bit-identical to a build that never heard of screening).  The
        screen applies only where it is safe and profitable — single
        device, in-memory dense entries, fused segments; every other
        path ignores it."""
        if kept is None:
            self._feature_screen = None
            return
        ids = sorted({int(i) for i in kept})
        if not ids or ids[0] < 0:
            raise ValueError(
                "feature screen must keep >= 1 valid feature id")
        self._feature_screen = tuple(ids)

    def rebind_cuts(self, cuts) -> None:
        """Swap the quantile cut matrix under the live model (online
        cut refresh — xgboost_tpu.stream): delegates the exact
        threshold-preserving remap to :meth:`GBTree.rebind_cuts`, then
        invalidates every cached binned entry/margin exactly like a
        whole-model load (the ``_load_np`` discipline) — stale bin ids
        quantized under the old cuts must never feed a gradient."""
        if self.gbtree is None or self.param.booster == "gblinear":
            raise ValueError(
                "rebind_cuts needs an initialized gbtree model")
        self.gbtree.rebind_cuts(cuts)
        self._cache.clear()
        self._model_gen += 1

    # ------------------------------------------------------------- init
    def _lazy_init(self, dtrain: DMatrix):
        if self.obj is None:
            self._init_obj()
        if self.gbtree is None:
            if self.param.booster == "gblinear":
                from xgboost_tpu.models.gblinear import GBLinear
                self.num_feature = dtrain.num_col
                self.gbtree = GBLinear(self.param, dtrain.num_col)
            else:
                from xgboost_tpu.models.gbtree import GBTree
                from xgboost_tpu.models.updaters import parse_updaters
                self.num_feature = dtrain.num_col
                if getattr(dtrain, "is_sharded", False):
                    # per-rank split loading: no process holds full
                    # columns, so the cut proposal MUST be the device
                    # sketch over the global mesh (SURVEY.md §5.8)
                    if self.param.dsplit == "col":
                        raise NotImplementedError(
                            "ShardedDMatrix is row-block loaded; "
                            "dsplit=col needs feature-shard loading "
                            "(load replicated for column split)")
                    if "grow_colmaker" in parse_updaters(self.param.updater):
                        raise NotImplementedError(
                            "updater=grow_colmaker (exact greedy) needs "
                            "cuts at every distinct value, which no "
                            "process can propose from a row shard; load "
                            "replicated for exact-greedy training")
                    if self.param.objective.startswith("rank:"):
                        raise NotImplementedError(
                            "ranking objectives need global group "
                            "structure, which row-block split loading "
                            "cannot provide; load replicated for "
                            "rank:* training")
                    from xgboost_tpu.parallel.sketch_device import \
                        sketch_cuts_global
                    self._mesh = dtrain.mesh
                    vals, w = dtrain.device_raw()
                    cuts = sketch_cuts_global(
                        self._mesh, vals, w, self.param.max_bin,
                        self.param.sketch_eps, self.param.sketch_ratio)
                    del vals, w  # transient raw floats: free before binning
                elif getattr(dtrain, "is_external", False):
                    # streaming sketch over raw pages (SURVEY.md §5.7);
                    # paged matrices always use the histogram method, as
                    # in the reference (learner-inl.hpp:263-267) — even
                    # for updater=grow_colmaker (exact_raw is cleared
                    # below: paged training is binned end to end)
                    cuts = dtrain.sketch_cuts(self.param.max_bin,
                                              self.param.sketch_eps,
                                              self.param.sketch_ratio)
                elif ("grow_colmaker" in parse_updaters(self.param.updater)
                        and self.param.dsplit == "row"):
                    # dsplit=row exact: cuts at every distinct value up
                    # to max_exact_bin (the reference itself switches
                    # away from exact under row split,
                    # learner-inl.hpp:91-93 — this quantized form is
                    # already more than it offers there)
                    from xgboost_tpu.binning import compute_cuts_exact
                    cuts = compute_cuts_exact(dtrain,
                                              self.param.max_exact_bin)
                elif "grow_colmaker" in parse_updaters(self.param.updater):
                    # TRUE exact-greedy (models/colmaker.py): bin-free —
                    # sorted raw-value scans at ANY cardinality; the
                    # CutMatrix is a placeholder (nothing is quantized).
                    # Under dsplit=col each shard scans its own raw
                    # columns (colsplit.grow_tree_exact_colsplit — the
                    # DistColMaker analog, exact at any cardinality,
                    # round 5; previously capped at max_exact_bin cuts)
                    from xgboost_tpu.binning import CutMatrix
                    cuts = CutMatrix(
                        np.full((dtrain.num_col, 1), np.inf, np.float32),
                        np.zeros(dtrain.num_col, np.int32))
                elif self.param.dsplit == "row" and (
                        self.param.device_sketch > 0
                        or (self.param.device_sketch < 0
                            and jax.process_count() > 1)):
                    # distributed cut proposal: per-shard device sketches
                    # merged over the mesh axis — no host needs a full
                    # column (SerializeReducer analog, SURVEY.md §5.8)
                    from xgboost_tpu.parallel import mesh as pmesh
                    from xgboost_tpu.parallel.sketch_device import \
                        sketch_cuts_mesh
                    if self._mesh is None:
                        self._mesh = (pmesh.get_mesh()
                                      or pmesh.data_parallel_mesh())
                    cuts = sketch_cuts_mesh(
                        self._mesh, dtrain.to_dense(), dtrain.info.weight,
                        self.param.max_bin, self.param.sketch_eps,
                        self.param.sketch_ratio)
                else:
                    # explicit hist_bin_align>0 lifts the trim-margin
                    # cap (unconditional alignment); auto keeps
                    # binning.DEFAULT_TRIM_MARGIN
                    margin_kw = ({"bin_align_margin": None}
                                 if int(self.param.hist_bin_align) > 0
                                 else {})
                    cuts = compute_cuts(dtrain, self.param.max_bin,
                                        self.param.sketch_eps,
                                        self.param.sketch_ratio,
                                        bin_align=self._bin_align(),
                                        **margin_kw)
                self.gbtree = GBTree(self.param, cuts)
                if getattr(dtrain, "is_external", False):
                    # paged matrices route through the binned pipeline
                    # regardless of updater (see the sketch branch above)
                    self.gbtree.exact_raw = False
        if getattr(dtrain, "is_sharded", False) and self._mesh is None:
            # continued training (loaded model) on a split-loaded matrix:
            # mesh resolution belongs HERE, not in the entry builder
            self._mesh = dtrain.mesh
        if self.param.booster == "gblinear":
            # distributed gblinear (dsplit=row): rows shard over the mesh,
            # Gf/Hf reductions psum (VERDICT r2 item 10)
            from xgboost_tpu.parallel import mesh as pmesh
            if self.param.dsplit == "row" and self._mesh is None:
                self._mesh = pmesh.get_mesh() or pmesh.data_parallel_mesh()
        if self.param.booster != "gblinear":
            from xgboost_tpu.parallel import mesh as pmesh
            if self.param.dsplit == "row" and self._mesh is None:
                self._mesh = pmesh.get_mesh() or pmesh.data_parallel_mesh()
            elif self.param.dsplit == "col" and self._col_mesh is None:
                from xgboost_tpu.parallel.colsplit import feature_parallel_mesh
                m = pmesh.get_mesh()
                self._col_mesh = (m if m is not None
                                  and "feat" in m.axis_names
                                  else feature_parallel_mesh())
        for d in self._pending_cache:
            self._entry(d)
        self._pending_cache = []

    @property
    def _K(self) -> int:
        return max(1, self.param.num_output_group)

    def _base_margin_of(self, dmat: DMatrix, n: int) -> jax.Array:
        bm = dmat.info.base_margin
        if bm is not None:
            return jnp.asarray(np.asarray(bm, np.float32).reshape(n, self._K))
        base = self.obj.prob_to_margin(self.param.base_score)
        return jnp.full((n, self._K), base, jnp.float32)

    def _entry(self, dmat: DMatrix) -> _CacheEntry:
        key = id(dmat)
        if (key in self._cache
                and getattr(self._cache[key], "model_gen", 0)
                != self._model_gen):
            # the whole model was replaced (registry hot-reload /
            # load_model) since this entry was built: its incremental
            # margin folds the OLD ensemble's trees — rebuild rather
            # than mix tree windows (load_raw also clears the cache;
            # this stamp is the belt for entries handed out earlier or
            # a gbtree swapped in directly)
            del self._cache[key]
        if (key in self._cache and self._cache[key].external
                and dmat._binned_cuts is not self.gbtree.cuts):
            # another model re-quantized this matrix meanwhile: re-bin and
            # rebuild our margins from scratch
            self._cache[key] = self._build_ext_entry(dmat)
            self._cache[key].model_gen = self._model_gen
        if (key in self._cache
                and self._cache[key].info is not dmat.info
                and self._cache[key].info_version != dmat.info.version):
            # sharded entries snapshot the MetaInfo; a set_label/set_weight
            # after caching must rebuild the snapshot (stale device labels
            # would silently feed the gradients otherwise)
            del self._cache[key]
        if (key in self._cache
                and self._cache[key].rank_pad_prep is not None
                and (self._cache[key].info_version != dmat.info.version
                     or self.obj is None
                     or not self.param.objective.startswith("rank:")
                     or getattr(self.obj, "rank_impl", None) != "device")):
            # the group-padded rank layout is DERIVED from labels +
            # group_ptr (any set_field invalidates the relayout) and
            # only the device rank gradient understands it (a set_param
            # switching objective/rank_impl must rebuild a plain entry)
            del self._cache[key]
        if key not in self._cache:
            if self.num_feature and dmat.num_col > self.num_feature:
                raise ValueError(
                    f"data has {dmat.num_col} features, model was trained "
                    f"with {self.num_feature}")
            if getattr(dmat, "is_sharded", False):
                if self.param.booster == "gblinear":
                    raise NotImplementedError(
                        "gblinear works on raw feature columns; per-rank "
                        "split loading currently supports gbtree only")
                self._cache[key] = self._make_shard_loaded_entry(dmat)
            elif getattr(dmat, "is_external", False):
                self._cache[key] = self._build_ext_entry(dmat)
            elif self.param.booster == "gblinear":
                if self._mesh is not None:
                    # dsplit=row: rows shard over the mesh (the dense X
                    # plays the role binned ids play for gbtree)
                    self._cache[key] = self._make_sharded_entry(
                        dmat, binned_np=self.gbtree.host_matrix(dmat))
                else:
                    binned = self.gbtree.device_matrix(dmat)
                    self._cache[key] = _CacheEntry(
                        dmat, binned, self._base_margin_of(dmat, dmat.num_row))
            elif self._mesh is not None:
                self._cache[key] = self._make_sharded_entry(dmat)
            elif getattr(self.gbtree, "exact_raw", False):
                # exact mode is bin-free: entries hold RAW values (NaN =
                # missing); trees route by value comparison.  Under
                # dsplit=col the feature axis pads to the mesh with
                # all-NaN columns ONCE per matrix, before the single
                # device upload (they sort into the finder's trash
                # segment regardless of has_missing and can never win
                # a split); the host copy pads too so the rank build
                # sees the sharded width
                raw, has_miss, raw_host = self._raw_dense(
                    dmat, pad_multiple=(self._col_mesh.devices.size
                                        if self._col_mesh is not None
                                        else 1))
                entry = _CacheEntry(
                    dmat, raw,
                    self._base_margin_of(dmat, dmat.num_row))
                # static per-dataset fact: lets the exact grower elide
                # the default-left scan + end-of-scan candidates
                entry.exact_has_missing = has_miss
                entry.exact_ranks = None  # built lazily on first boost
                entry.exact_host = raw_host  # dropped after rank build
                self._cache[key] = entry
            elif self._rank_pad_ok(dmat):
                self._cache[key] = self._make_rank_padded_entry(dmat)
            else:
                binned_host = bin_matrix(dmat, self.gbtree.cuts)
                binned = jnp.asarray(binned_host)
                if self._col_mesh is not None:
                    # pad the feature axis ONCE per matrix (padding per
                    # boosting round would re-copy the whole matrix)
                    from xgboost_tpu.parallel.colsplit import pad_features
                    binned = pad_features(
                        binned, self._col_mesh.devices.size, axis=1)
                entry = _CacheEntry(
                    dmat, binned, self._base_margin_of(dmat, dmat.num_row))
                from xgboost_tpu.ops.histogram import _impl
                if (self._mesh is None and self._col_mesh is None
                        and _impl(self.param.hist_precision
                                  ).startswith("pallas")):
                    # resident pre-transposed histogram operand (zero
                    # per-round transpose/layout-copy cost; see
                    # pallas_hist.host_transpose_bins) — single-chip
                    # pallas path only: sharded paths re-transpose and
                    # the scatter fallback never reads it
                    from xgboost_tpu.ops.pallas_hist import \
                        host_transpose_bins
                    bt = host_transpose_bins(binned_host,
                                             self.gbtree.cfg.n_bin)
                    entry.binned_t = None if bt is None \
                        else jnp.asarray(bt)
                self._cache[key] = entry
            self._attach_root(self._cache[key], dmat)
            self._cache[key].model_gen = self._model_gen
        entry = self._cache[key]
        if (entry.info is dmat.info
                and entry.info_version != dmat.info.version):
            # plain entries SHARE the MetaInfo: label/weight freshness
            # rides info._dev_cache invalidation, but root and base
            # margin are entry-level snapshots — refresh them (and the
            # margin built on base) on any set_field
            entry.root = None
            self._attach_root(entry, dmat)
            if entry.external:
                # streaming-external entries keep the base HOST-side
                entry.base = np.asarray(
                    self._base_margin_of(dmat, dmat.num_row))
            else:
                entry.base = self._base_margin_of(dmat, dmat.num_row)
            entry.margin = None
            entry.applied = 0
            entry.info_version = dmat.info.version
        return entry

    def _attach_root(self, entry: _CacheEntry, dmat) -> None:
        """Per-row root slots (multi-root trees, reference root_index
        data.h:39-58), padded to the entry's device row count."""
        ri = getattr(dmat.info, "root_index", None)
        if ri is None or max(1, self.param.num_roots) <= 1:
            return
        if getattr(dmat, "is_sharded", False):
            raise NotImplementedError(
                "root_index on split-loaded matrices is not supported "
                "(per-rank placement of the root vector is unwired); "
                "load replicated for multi-root training")
        if entry.external:
            raise NotImplementedError(
                "root_index on external-memory matrices is not supported")
        n_dev = entry.binned.shape[0]
        r = np.zeros(n_dev, np.int32)
        r[:len(ri)] = np.asarray(ri, np.int64).astype(np.int32)
        if self._mesh is not None and not getattr(dmat, "is_sharded", False):
            from xgboost_tpu.parallel.dp import shard_rows
            entry.root = shard_rows(self._mesh, r)
        else:
            entry.root = jnp.asarray(r)

    def _build_ext_entry(self, dmat) -> _CacheEntry:
        """Entry for an external-memory matrix (not necessarily cached)."""
        if getattr(self.gbtree, "exact_raw", False):
            raise NotImplementedError(
                "exact-mode (grow_colmaker) models route on raw values; "
                "external-memory matrices are binned — load this matrix "
                "in memory (DMatrix) for exact-mode predict/eval/train")
        if self._col_mesh is not None:
            raise NotImplementedError(
                "external-memory matrices do not support dsplit=col "
                "(the reference routes paged matrices to the histogram "
                "row-split path too, learner-inl.hpp:263-267)")
        # (re)quantize when the matrix was binned with a DIFFERENT
        # model's cuts — reusing a stale memmap would silently compare
        # this model's cut indices against another model's bins
        if dmat._binned_mm is None or dmat._binned_cuts is not self.gbtree.cuts:
            dmat.build_binned(self.gbtree.cuts)
        # when the whole binned matrix fits the device budget, external
        # memory has done its job (bounded INGEST/sketch/quantize memory)
        # and training can take the in-memory fast path — one launch per
        # tree (or per fused run) instead of per (level, batch).  The
        # reference's HalfRAM variant is the same idea one level down
        # (page_dmatrix-inl.hpp:230-245: rows on disk, working set in
        # RAM); here the working set is the binned matrix in HBM.
        if dmat.fits_device_budget():
            binned_np = np.asarray(dmat._binned_mm)
            if self._mesh is not None:
                return self._make_sharded_entry(dmat, binned_np=binned_np)
            return _CacheEntry(
                dmat, jnp.asarray(binned_np),
                jnp.asarray(self._base_margin_of(dmat, dmat.num_row)))
        return _CacheEntry(
            dmat, None, np.asarray(self._base_margin_of(dmat, dmat.num_row)),
            external=True)

    def _make_sharded_entry(self, dmat: DMatrix,
                            binned_np: Optional[np.ndarray] = None
                            ) -> _CacheEntry:
        """Pad rows to the mesh size and shard over the 'data' axis (the
        reference's per-rank row-shard loading, simple_dmatrix-inl.hpp:89-96,
        realized as device placement under one controller).  ``binned_np``
        skips re-binning (in-budget external matrices pass their memmap)."""
        from xgboost_tpu.parallel.dp import shard_rows
        n = dmat.num_row
        pad = (-n) % self._mesh.size
        if binned_np is None:
            binned_np = bin_matrix(dmat, self.gbtree.cuts)
        if pad:
            binned_np = np.pad(binned_np, ((0, pad), (0, 0)))
        # host numpy -> global sharding directly: in multi-process mode
        # every process holds the full (replicated) host copy and
        # device_put places only its addressable shards
        binned = shard_rows(self._mesh, binned_np)
        row_valid = shard_rows(self._mesh, np.arange(n + pad) < n)
        info = _pad_info(dmat.info, n, pad, self._K)
        # device-resident SHARDED gradient inputs (row-aligned with the
        # margin); also avoids re-uploading label/weight every round
        if info.label is not None:
            info._dev_cache["label"] = shard_rows(
                self._mesh, np.asarray(info.label, np.float32))
        info._dev_cache[("weight", n + pad)] = shard_rows(
            self._mesh, np.asarray(info.get_weight(n + pad), np.float32))
        base = np.broadcast_to(
            np.asarray(self._base_margin_of(dmat, n)), (n, self._K))
        base = np.concatenate(
            [base, np.zeros((pad, self._K), np.float32)]) if pad else base
        base = shard_rows(self._mesh, np.asarray(base, np.float32))
        return _CacheEntry(dmat, binned, base, info=info,
                           row_valid=row_valid, n_real=n)

    def _make_shard_loaded_entry(self, dmat) -> _CacheEntry:
        """Entry for a per-rank split-loaded matrix: every process bins
        ONLY its local row block; the global arrays are assembled from
        process-local data (``jax.make_array_from_process_local_data``)
        — the reference's per-rank shard loading
        (simple_dmatrix-inl.hpp:89-96) without any replicated host copy.

        Bit-compatibility: the global (padded) row layout is identical
        to :meth:`_make_sharded_entry`'s device placement of a
        replicated load over the same mesh, so training produces
        byte-identical models (tested in tests/test_launch.py)."""
        if self.param.objective.startswith("rank:"):
            raise NotImplementedError(
                "ranking objectives need global group structure, which "
                "row-block split loading cannot provide; load "
                "replicated for rank:*")
        n_loc = dmat.local_num_row
        K = self._K
        binned_local = bin_matrix(dmat._local, self.gbtree.cuts)
        binned = dmat.make_global(dmat.pad_local(binned_local))
        row_valid = dmat.row_valid_global()

        # the entry's info snapshot holds LOCAL host metadata (for label
        # validation + local metric partials) and GLOBAL device arrays
        # for the gradient kernels
        info = MetaInfo()
        info.label = dmat.info.label
        info.weight = dmat.info.weight
        info.base_margin = dmat.info.base_margin
        if info.label is not None:
            info._dev_cache["label"] = dmat.make_global(
                dmat.pad_local(np.asarray(info.label, np.float32)))
        info._dev_cache[("weight", dmat.padded_global_rows)] = \
            dmat.make_global(dmat.pad_local(
                np.asarray(dmat.info.get_weight(n_loc), np.float32)))

        if getattr(dmat, "_full_base_margin", None) is not None:
            # sidecar base_margin holds GLOBAL (N, K) values; slice rows
            # here where K is known (multiclass-safe)
            base_local = np.asarray(
                dmat._full_base_margin, np.float32).reshape(
                    dmat.global_num_row, K)[dmat.row_start:dmat.row_end]
        elif dmat.info.base_margin is not None:
            base_local = np.asarray(
                dmat.info.base_margin, np.float32).reshape(n_loc, K)
        else:
            base_local = np.full(
                (n_loc, K), self.obj.prob_to_margin(self.param.base_score),
                np.float32)
        base = dmat.make_global(dmat.pad_local(base_local))
        entry = _CacheEntry(dmat, binned, base, info=info,
                            row_valid=row_valid, n_real=dmat.global_num_row)
        return entry

    def _bin_align(self) -> int:
        """Bin-count alignment quantum for the cut proposal (see
        binning.align_cut_lists): 32 when the pallas histogram kernel
        will consume the bins (its int8 one-hot tiles sublanes in 32s),
        else 0.  hist_bin_align overrides (0 = never, >0 = quantum)."""
        hba = int(self.param.hist_bin_align)
        if hba >= 0:
            return hba
        from xgboost_tpu.ops.histogram import _impl
        return 32 if _impl(self.param.hist_precision
                           ).startswith("pallas") else 0

    def _announce_rank_path(self, entry) -> None:
        """One stderr line (first boost only) naming the LambdaRank
        gradient path chosen for the TRAINING matrix.  The group-padded
        and sort-based device paths train numerically DIFFERENT models
        (bf16 partner dot + lane tie-breaks vs unstable sort order —
        metric-parity tested, bit divergence documented in
        rank_device.py); the gate that picks between them is a
        heuristic, so the choice must be visible without reading
        docstrings (advisor, round 4).  Called from the boost path —
        not the entry builder — so eval-set entries never announce and
        the mesh-sharded branch (always sort-based) is covered too.
        ``XGBTPU_RANK_PAD=0`` forces sort-based; ``silent=1`` mutes."""
        if (getattr(self, "_rank_path_told", False)
                or not self.param.objective.startswith("rank:")
                or getattr(self.obj, "rank_impl", None) != "device"):
            return
        self._rank_path_told = True
        path = ("group-padded" if entry.rank_pad_prep is not None
                else "sort-based")
        if int(getattr(self.param, "silent", 0)) == 0 and _is_rank0():
            print(f"[rank] LambdaRank gradient path: {path} "
                  "(set XGBTPU_RANK_PAD=0 to force sort-based; "
                  "see README 'Ranking')", file=sys.stderr)

    def _rank_pad_ok(self, dmat) -> bool:
        """Gate for the group-padded rank layout (rank_device round 4):
        device LambdaRank, single chip, in-memory gbtree, grouped data
        with modest group sizes and small integer labels (bf16-exact in
        the one-hot partner dot).  ``XGBTPU_RANK_PAD=0`` disables."""
        info = dmat.info
        if (os.environ.get("XGBTPU_RANK_PAD", "1") == "0"
                or self.obj is None
                or not self.param.objective.startswith("rank:")
                or getattr(self.obj, "rank_impl", None) != "device"
                or self._col_mesh is not None
                or self._K != 1
                or info.group_ptr is None or len(info.group_ptr) < 2
                or info.label is None
                or (getattr(info, "root_index", None) is not None
                    and max(1, self.param.num_roots) > 1)):
            return False
        gptr = np.asarray(info.group_ptr, np.int64)
        sizes = np.diff(gptr)
        if len(sizes) == 0 or sizes.min() <= 0:
            return False
        G = len(sizes)
        L = max(8, int(-(-sizes.max() // 8) * 8))
        n = dmat.num_row
        # clamped at 256: lane positions/counts up to L must stay exact
        # in the bf16 one-hot partner dot (256 = 2^8 is the last exact
        # odd-step integer; see rank_device._lane_select)
        max_lane = min(256, int(os.environ.get("XGBTPU_RANK_PAD_MAXLANE",
                                               "256")))
        la = np.asarray(info.label)
        # padding blow-up economics: extra rows cost grower time
        # (~14 ms per 1M-row round) against the ~7.7 ms/1M the padded
        # gradient saves (tools/rank_inv_ab.py) — breakeven ~1.45x.
        # Small datasets take the padded path more liberally (absolute
        # cost is negligible; one code path to exercise).
        blow = (G * L + (n - int(gptr[-1]))) / max(n, 1)
        return (L <= max_lane
                and G * L * L <= (1 << 28)       # (G, L, L) plane budget
                and (blow <= 1.4 or (n <= 200_000 and blow <= 3.0))
                and bool(np.all(la >= 0)) and bool(np.all(la < 32))
                and bool(np.all(la == np.round(la))))

    def _make_rank_padded_entry(self, dmat) -> _CacheEntry:
        """Entry in the group-padded rank layout: group g owns slots
        [g*L, (g+1)*L), rows label-sorted within the group (the
        reference's bucket-skipping partner draw becomes a pure lane
        formula), padding slots carry bin 0 / zero gradients.  The
        per-round LambdaRank gradient then runs sort-free and
        gather-free (rank_device.rank_gradient_padded; measured 3.2 vs
        10.9 ms at 1M rows / 10k groups — tools/rank_inv_ab.py)."""
        from xgboost_tpu.rank_device import build_pad_prep
        info = dmat.info
        tag = ("rank_pad_prep",)
        if tag not in info._dev_cache:
            info._dev_cache[tag] = build_pad_prep(
                np.asarray(info.label, np.float32),
                np.asarray(info.group_ptr, np.int64))
        prep = info._dev_cache[tag]
        n_slots = prep.G * prep.L + prep.n_tail
        occupied = prep.pad_map >= 0                      # (n_slots,)
        src = prep.pad_map[occupied]

        binned_host = bin_matrix(dmat, self.gbtree.cuts)
        binned_pad = np.zeros((n_slots, binned_host.shape[1]),
                              binned_host.dtype)
        binned_pad[occupied] = binned_host[src]
        base = np.asarray(self._base_margin_of(dmat, dmat.num_row))
        base_pad = np.full((n_slots, self._K),
                           float(base.reshape(-1)[0]) if base.size
                           else 0.0, np.float32)
        base_pad[occupied] = base.reshape(dmat.num_row, self._K)[src]
        entry = _CacheEntry(
            dmat, jnp.asarray(binned_pad), jnp.asarray(base_pad),
            row_valid=jnp.asarray(occupied), n_real=dmat.num_row)
        entry.rank_pad_prep = prep
        from xgboost_tpu.ops.histogram import _impl
        if _impl(self.param.hist_precision).startswith("pallas"):
            from xgboost_tpu.ops.pallas_hist import host_transpose_bins
            bt = host_transpose_bins(binned_pad, self.gbtree.cfg.n_bin)
            entry.binned_t = None if bt is None else jnp.asarray(bt)
        return entry

    def _raw_dense(self, dmat, pad_multiple: int = 1):
        """Dense raw-value matrix for exact mode (NaN = missing),
        feature-padded/truncated to the model width.  Returns
        (device matrix, has_missing, host matrix) — has_missing is a
        static per-dataset fact the exact grower specializes on; the
        host copy feeds the one-off rank build for training matrices.
        ``pad_multiple`` additionally pads the feature axis with
        all-NaN columns to a multiple (exact column split's shard
        width) BEFORE the single host→device transfer; pad columns do
        not flip has_missing (they sort into the finder's trash
        segment regardless — see colmaker._find_exact_splits)."""
        X = dmat.to_dense(missing=np.nan)
        X = X[:, :self.num_feature]
        has_missing = bool(np.isnan(X).any())
        if X.shape[1] < self.num_feature:
            X = np.pad(X, ((0, 0), (0, self.num_feature - X.shape[1])),
                       constant_values=np.nan)
            has_missing = True
        pad = (-X.shape[1]) % max(1, pad_multiple)
        if pad:
            X = np.pad(X, ((0, 0), (0, pad)), constant_values=np.nan)
        return jnp.asarray(X), has_missing, X

    def _replicated(self, x):
        """Make a device value fully addressable for host pulls: in
        multi-process mode sharded arrays live partly on other hosts, so
        metric evaluation / prediction output all-gathers them first
        (rides ICI on real pods; the reference instead allreduces metric
        partial sums — same communication role)."""
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and self._mesh is not None):
            if getattr(self, "_replicate_fn", None) is None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                self._replicate_fn = jax.jit(
                    lambda v: v,
                    out_shardings=NamedSharding(self._mesh, P()))
            x = self._replicate_fn(x)
        return x

    def _sync_margin(self, entry: _CacheEntry):
        """Fold not-yet-applied trees into the cached margin, one round's
        worth at a time (fixed shapes -> one compilation)."""
        if entry.external:
            self._sync_margin_ext(entry)
            return
        if (self.param.booster != "gblinear"
                and entry.applied > self.gbtree.num_trees):
            # the ensemble SHRANK under this entry (a reload to an
            # older model, or an ntree window raced a swap): the cached
            # margin folds trees that no longer exist — rebuild from
            # base instead of serving a mixed window
            entry.margin = None
            entry.applied = 0
        if entry.margin is None:
            entry.margin = jnp.broadcast_to(
                entry.base, (entry.binned.shape[0], self._K)).astype(jnp.float32)
        if self.param.booster == "gblinear":
            entry.margin = self.gbtree.predict_margin(entry.binned, entry.base)
            entry.applied = self.gbtree.version
            return
        per_round = self._K * max(1, self.param.num_parallel_tree)
        while entry.applied < self.gbtree.num_trees:
            chunk = self.gbtree.trees[entry.applied:entry.applied + per_round]
            first_group = self.gbtree.tree_group[entry.applied]
            entry.margin = self.gbtree.predict_incremental(
                entry.binned, entry.margin, chunk, first_group,
                root=entry.root)
            entry.applied += len(chunk)

    def _sync_margin_ext(self, entry: _CacheEntry):
        """Margin for an external-memory matrix, rebuilt by streaming
        binned batches through the not-yet-applied trees.

        The margin is DEVICE-resident (it is O(N), tiny next to the
        paged O(N*F) data): round-tripping it through the host cost
        seconds per round on tunnel-attached chips (PROFILE.md)."""
        if entry.margin is None:
            entry.margin = jnp.broadcast_to(
                jnp.asarray(entry.base),
                (entry.n_real, self._K)).astype(jnp.float32)
            entry.applied = 0
        if entry.applied >= self.gbtree.num_trees:
            return
        from xgboost_tpu.models.tree import predict_margin_binned
        chunk_trees = self.gbtree.trees[entry.applied:]
        groups = self.gbtree.tree_group[entry.applied:]
        stack = jax.tree.map(lambda *xs: jnp.stack(xs), *chunk_trees)
        group = jnp.asarray(groups, jnp.int32)
        # batches are contiguous ordered row ranges: one concat + one
        # add instead of a full-margin scatter per batch
        parts = [predict_margin_binned(
                     stack, group, batch, jnp.zeros((), jnp.float32),
                     self.gbtree.cfg.max_depth, self._K,
                     tree_chunk=self.gbtree.pred_chunk)
                 for _, batch in entry.dmat.device_batches()]
        entry.margin = jnp.asarray(entry.margin) + jnp.concatenate(parts)
        entry.applied = self.gbtree.num_trees

    # ------------------------------------------------------------ profiling
    @property
    def profiler(self):
        """Lazily created RoundProfiler when param profile>=1 (the
        report_stats analog, SURVEY.md §5.1) — or, at level 0, when the
        observability layer is on (``obs_log=``/``metrics_port=``/
        ``XGBTPU_OBS=1``): phase spans, the event-log timeline and the
        live training metrics all need the per-phase boundaries, which
        also means per-round host control (no fused multi-round launch)
        and a device barrier per phase — the same cost contract as
        ``profile=1`` (PROFILE.md)."""
        if getattr(self, "_profiler", None) is not None:
            return self._profiler
        if self.param.profile <= 0:
            from xgboost_tpu import obs
            if not obs.phases_enabled():
                return None
        from xgboost_tpu.obs import RoundProfiler
        self._profiler = RoundProfiler(
            self.param.profile, self.param.profile_dir or None)
        self._profiler.start()
        return self._profiler

    # ------------------------------------------------------------- training
    def update(self, dtrain: DMatrix, iteration: int, fobj=None):
        """One boosting round (reference BoostLearner::UpdateOneIter,
        learner-inl.hpp:274-281; custom-objective path Booster.update,
        wrapper/xgboost.py:335-355)."""
        prof = self.profiler
        if prof is None:
            return self._update(dtrain, iteration, fobj)
        prof.begin_round(iteration)
        try:
            return self._update(dtrain, iteration, fobj, prof)
        finally:
            prof.end_round()

    def _update(self, dtrain: DMatrix, iteration: int, fobj=None, prof=None):
        from contextlib import nullcontext
        ph = (lambda name: prof.phase(name)) if prof else \
            (lambda name: nullcontext())
        self._lazy_init(dtrain)
        with ph("predict") as p:
            entry = self._entry(dtrain)
            self._announce_rank_path(entry)
            self._sync_margin(entry)
            if prof:
                p.block(entry.margin)
        if fobj is None:
            with ph("gradient") as p:
                margin = entry.margin
                if getattr(self.obj, "needs_host_margin", False):
                    # ranking objectives sample pairs host-side from the
                    # full margin; all-gather it in multi-process mode
                    margin = self._replicated(margin)
                if entry.rank_pad_prep is not None:
                    gh = self.obj.get_gradient(
                        jnp.asarray(margin), entry.info, iteration,
                        entry.margin.shape[0],
                        pad_prep=entry.rank_pad_prep)
                else:
                    gh = self.obj.get_gradient(
                        jnp.asarray(margin), entry.info,
                        iteration, entry.margin.shape[0])
                if prof:
                    p.block(gh)
        else:
            if getattr(dtrain, "is_sharded", False):
                raise NotImplementedError(
                    "custom objectives need the full prediction/gradient "
                    "vectors on each host; load replicated (DMatrix) for "
                    "custom-objective training")
            # custom objective sees only the real rows; gradients are
            # zero-padded back to the device row count below in boost()
            pred = np.asarray(self._replicated(
                self.obj.pred_transform(entry.margin)))
            pred = entry.user_rows(pred)
            if pred.shape[1] == 1:
                pred = pred[:, 0]
            grad, hess = fobj(pred, dtrain)
            return self.boost(dtrain, grad, hess)
        with ph("grow") as p:
            self._do_boost(dtrain, entry, gh, iteration)
            if prof and entry.margin is not None:
                p.block(entry.margin)

    def _resolve_rounds_per_dispatch(self, n_rows: int,
                                     override=None) -> int:
        """Segment size K for fused training dispatches.  Priority:
        env ``XGBTPU_ROUNDS_PER_DISPATCH`` > explicit ``override`` >
        the ``rounds_per_dispatch`` train param.  ``-1`` (auto) sizes
        the segment from the fitted round model (ROUND_MODEL.json) so
        the fixed per-dispatch cost amortizes to <=10% of the dispatch
        — ``K >= 9 * fixed / (per_row * rows)`` — clamped to [1, 64]
        (past 64 the fixed term is noise and longer segments only delay
        eval lines / checkpoints).  ``0`` = per-round dispatch, the A/B
        baseline."""
        import math
        env = os.environ.get("XGBTPU_ROUNDS_PER_DISPATCH")
        if env not in (None, ""):
            k = int(env)
        elif override is not None:
            k = int(override)
        else:
            k = int(self.param.rounds_per_dispatch)
        if k >= 0:
            return k
        from xgboost_tpu.parallel.commcost import fitted_round_model
        m = fitted_round_model() or {}
        # baked defaults = the committed ROUND_MODEL.json fit, so auto
        # still sizes sanely when the file is missing
        fixed = float(m.get("fixed_round_s", 4.465e-3))
        per_row = float(m.get("per_row_s", 9.974e-9))
        per_round = per_row * max(1, int(n_rows))
        if per_round <= 0.0 or fixed <= 0.0:
            return 16
        return max(1, min(64, math.ceil(9.0 * fixed / per_round)))

    def update_many(self, dtrain: DMatrix, first_iteration: int,
                    n_rounds: int, fobj=None, *, evals=None, feval=None,
                    eval_callback=None, round_callback=None,
                    segment_callback=None, plan_callback=None,
                    boundary_align: int = 0,
                    rounds_per_dispatch=None) -> None:
        """Run ``n_rounds`` boosting rounds in fused SEGMENTS: K rounds
        per ``_scan_rounds`` dispatch (``rounds_per_dispatch``; auto
        sizes K from the fitted round model), touching the host only at
        segment boundaries.  Watchlist evaluation runs device-resident
        inside the scan — eval lines print per round AFTER the segment's
        dispatch, byte-identical to the per-round path's — and the
        stacked per-round trees each dispatch returns keep checkpoint
        granularity at segment boundaries with per-round model bytes
        available.  The fused path bit-matches the sequential path
        (same per-round keys and kernels) — the reference's round loop
        is host-side by construction (xgboost_main.cpp:183-217); here
        it compiles into the program.

        Falls back to per-round :meth:`update` (same callbacks, one
        boundary per round) when fusion is ineligible — custom/host
        objective, pruning, refresh, column split, profiler/obs
        phases, external-memory matrices — or when the resolved
        segment size is 0 (the per-round A/B baseline).  Every
        fallback is LOUD: ``xgbtpu_train_fused_fallback_total`` and a
        ``train.fused_fallback`` event record the first blocking
        reason, so chaos/bench runs meant to measure the fused path
        can assert it never silently degraded.  Fault injection
        (``mock=``) no longer forces the fallback: the fused driver
        replays the injector's (version, seqno) coordinates at
        segment boundaries.

        Driver hooks (all optional; the CLI and ContinuousTrainer ride
        these instead of owning round loops):

        - ``evals``/``feval``: watchlist ``[(dmat, name), ...]`` and
          custom metric — eval lines are built per round on BOTH paths.
        - ``eval_callback(iteration, msg)``: one formatted eval line.
        - ``round_callback(iteration)``: per-round liveness, ONLY on
          the per-round path (a fused segment has no between-round
          host point by design).
        - ``segment_callback(last_iteration)``: a segment completed
          through ``last_iteration`` (per-round path: every round) —
          checkpoint/save hook.
        - ``plan_callback(k)``: the resolved segment size (0 =
          per-round), reported once before training.
        - ``boundary_align``: force segment boundaries at iteration
          multiples (periodic ``save_period`` saves need the model
          materialized exactly there).
        """
        from xgboost_tpu.models.updaters import parse_updaters

        self._lazy_init(dtrain)
        entry = self._entry(dtrain)
        self._announce_rank_path(entry)
        ups = parse_updaters(self.param.updater)
        evals = list(evals) if evals else []

        def fgrad():
            if entry.rank_pad_prep is not None:
                return self.obj.fused_grad(entry.info,
                                           pad_prep=entry.rank_pad_prep)
            return self.obj.fused_grad(entry.info)
        # Eligibility as (reason, blocked) pairs so a fallback is LOUD:
        # chaos/bench runs that mean to measure the fused path verify
        # the fused_fallback counter stayed 0.  Fault injection (mock)
        # no longer blocks fusion — do_boost_fused replays the
        # injector's round/seqno coordinates before each dispatch.
        # Sharded watchlist sets ride the scan carry like any mesh
        # entry; their eval lines reduce metric partials via
        # ShardedDMatrix.allsum (_eval_parts_sharded) — only a custom
        # feval (needs the full vector on one host) excludes them.
        # External-memory sets still page batches per round.
        checks = (
            ("custom_objective", fobj is not None),
            ("single_round", n_rounds <= 1),
            ("booster", self.param.booster != "gbtree"),
            ("external_train", bool(entry.external)),
            ("col_split", self._col_mesh is not None),
            # escape hatch: sequential per-round launches (the fused
            # scan always grows the round's ensemble vmapped)
            ("seq_boost_env", bool(os.environ.get("XGBTPU_SEQ_BOOST"))),
            ("profiler", self.profiler is not None),
            ("prune", self.param.gamma > 0.0 and "prune" in ups),
            ("multi_root", max(1, self.param.num_roots) != 1),
            ("exact", bool(getattr(self.gbtree, "exact_raw", False))),
            ("refresh", "refresh" in ups),
            ("no_grow_updater",
             not any(u.startswith("grow") for u in ups)),
            ("no_fused_grad", fgrad() is None),
            ("external_eval",
             any(self._entry(d).external for d, _ in evals)),
            ("sharded_eval_feval", feval is not None and any(
                getattr(d, "is_sharded", False) for d, _ in evals)),
        )
        blockers = [name for name, blocked in checks if blocked]
        fused_ok = not blockers
        k = (self._resolve_rounds_per_dispatch(
            dtrain.num_row, rounds_per_dispatch) if fused_ok else 0)
        if plan_callback is not None:
            plan_callback(k)
        if not fused_ok or k <= 0:
            if n_rounds > 1 and self.param.booster == "gbtree":
                why = blockers or ["rounds_per_dispatch_0"]
                from xgboost_tpu.obs import trace, training_metrics
                training_metrics().fused_fallback.inc(why[0])
                trace.event("train.fused_fallback", reasons=why,
                            first_iteration=first_iteration,
                            n_rounds=n_rounds)
            from contextlib import nullcontext
            for i in range(first_iteration, first_iteration + n_rounds):
                if round_callback is not None:
                    round_callback(i)
                self.update(dtrain, i, fobj)
                if evals:
                    prof = self.profiler
                    with prof.phase("eval") if prof else nullcontext():
                        msg = self.eval_set(evals, i, feval)
                    if eval_callback is not None:
                        eval_callback(i, msg)
                if segment_callback is not None:
                    segment_callback(i)
            return
        self.obj.validate_labels(entry.info)  # host check, once per info
        self._sync_margin(entry)
        # (entry, is_train) per watchlist slot: a slot that IS the
        # training matrix reads the scan's grow-time margin (the
        # prediction-buffer shortcut) instead of carrying a second copy
        espec = []
        for dmat, name in evals:
            e = self._entry(dmat)
            if e is not entry:
                self._sync_margin(e)
            espec.append((dmat, name, e, e is entry))
        etransform = self.obj.fused_eval_transform() if espec else None
        # EMA-FS (ema_fs > 0 + set_feature_screen): fused segments grow
        # over the screened (C, N, F_kept) working set.  Confined to the
        # plain single-device dense path — meshes, paged matrices, exact
        # mode and rank relayouts keep the full feature set (the screen
        # is a throughput optimization, never a correctness dependency);
        # grown trees come back remapped to the full space.
        screen = None
        if (self.param.ema_fs > 0
                and self._feature_screen is not None
                and self._mesh is None
                and not entry.external
                and not getattr(self.gbtree, "exact_raw", False)
                and entry.rank_pad_prep is None
                and len(self._feature_screen) < int(entry.binned.shape[1])
                and all(not e.external and e.rank_pad_prep is None
                        and not getattr(d, "is_sharded", False)
                        for d, _, e, t in espec if not t)):
            screen = self._feature_screen
            kept_dev = jnp.asarray(screen, jnp.int32)

            def _screened(e):
                # per-entry screened-column cache, keyed on the kept
                # set: re-gathering (N, F_kept) columns every segment
                # would cancel the histogram win
                if getattr(e, "screen_key", None) != screen:
                    e.screen_binned = jnp.take(e.binned, kept_dev,
                                               axis=1)
                    e.screen_key = screen
                return e.screen_binned
        align = max(0, int(boundary_align))
        done = 0
        while done < n_rounds:
            first = first_iteration + done
            seg = min(k, n_rounds - done)
            if align:
                # stop at the next aligned boundary so periodic saves
                # see the model at exactly that round (segment lengths
                # stay O(distinct) -> bounded scan compiles)
                seg = min(seg, align - first % align)
            margin_f, emargins_f, eouts = self.gbtree.do_boost_fused(
                _screened(entry) if screen is not None else entry.binned,
                entry.margin, entry.info, fgrad(),
                first, seg, row_valid=entry.row_valid, mesh=self._mesh,
                binned_t=(None if screen is not None
                          else getattr(entry, "binned_t", None)),
                eval_binned=tuple(
                    (_screened(e) if screen is not None else e.binned)
                    for _, _, e, t in espec if not t),
                eval_margins=tuple(e.margin for _, _, e, t in espec
                                   if not t),
                eval_is_train=tuple(t for _, _, _, t in espec),
                etransform=etransform,
                rowwise_grad=entry.rank_pad_prep is None,
                feature_screen=screen)
            entry.margin = margin_f
            entry.applied = self.gbtree.num_trees
            ei = 0
            for _, _, e, is_train in espec:
                if is_train:
                    continue
                e.margin = emargins_f[ei]
                e.applied = self.gbtree.num_trees
                ei += 1
            if espec:
                # eval lines for every round of the segment, from the
                # ONE dispatch's stacked outputs
                from xgboost_tpu.obs import training_metrics
                for r in range(seg):
                    parts = [f"[{first + r}]"]
                    for si, (dmat, name, e, _) in enumerate(espec):
                        if getattr(dmat, "is_sharded", False):
                            # split-loaded set: metric partials on the
                            # LOCAL shard of the round's transformed
                            # outputs, reduced via allsum — no process
                            # ever holds the full prediction vector
                            local = dmat.local_block_of(eouts[si][r])
                            self._eval_parts_sharded(
                                dmat, name,
                                local[:dmat.local_num_row], parts)
                            continue
                        tr = e.user_rows(np.asarray(self._replicated(
                            eouts[si][r])))
                        self._eval_parts(dmat, name, tr, parts, feval)
                    msg = "\t".join(parts)
                    training_metrics().observe_eval(_parse_eval(msg))
                    if eval_callback is not None:
                        eval_callback(first + r, msg)
            done += seg
            if segment_callback is not None:
                segment_callback(first + seg - 1)

    def fused_lane_spec(self, dtrain: DMatrix, first_iteration: int,
                        n_rounds: int, rounds_per_dispatch=None):
        """Gang-batching eligibility + operand bundle for this booster's
        next ``n_rounds`` fused rounds (PIPELINE.md "Gang-batched
        lanes").  Returns ``(LaneSpec, None)`` when the lane-stacking
        driver may vmap this booster with same-bucket peers, else
        ``(None, reason)`` — the reasons mirror :meth:`update_many`'s
        fused checks plus the stacking-only restrictions (any mesh,
        rank relayouts, an active feature screen): a declined lane runs
        solo through the normal :meth:`update_many` path, which decides
        its own fused-vs-per-round route.

        Side effects on eligibility match the fused path exactly:
        labels are host-validated once and the entry margin is synced,
        so the returned ``margin``/``binned`` are the solo scan's own
        operands and a stacked dispatch is bit-identical per lane.
        """
        from xgboost_tpu.models.updaters import parse_updaters
        if self.param.booster != "gbtree":
            return None, "booster"
        self._lazy_init(dtrain)
        entry = self._entry(dtrain)
        ups = parse_updaters(self.param.updater)
        grad_fn = (None if entry.rank_pad_prep is not None
                   else self.obj.fused_grad(entry.info))
        checks = (
            ("no_rounds", n_rounds < 1),
            ("external_train", bool(entry.external)),
            ("mesh", self._mesh is not None),
            ("col_split", self._col_mesh is not None),
            ("seq_boost_env", bool(os.environ.get("XGBTPU_SEQ_BOOST"))),
            ("profiler", self.profiler is not None),
            ("prune", self.param.gamma > 0.0 and "prune" in ups),
            ("multi_root", max(1, self.param.num_roots) != 1),
            ("exact", bool(getattr(self.gbtree, "exact_raw", False))),
            ("refresh", "refresh" in ups),
            ("no_grow_updater",
             not any(u.startswith("grow") for u in ups)),
            ("rank_layout", entry.rank_pad_prep is not None),
            ("no_fused_grad", grad_fn is None),
            ("feature_screen", self.param.ema_fs > 0
             and self._feature_screen is not None),
        )
        blockers = [name for name, blocked in checks if blocked]
        if blockers:
            return None, blockers[0]
        k = self._resolve_rounds_per_dispatch(dtrain.num_row,
                                              rounds_per_dispatch)
        if k <= 0:
            return None, "rounds_per_dispatch_0"
        self.obj.validate_labels(entry.info)  # host check, once per info
        self._sync_margin(entry)
        N = int(entry.binned.shape[0])
        return LaneSpec(
            booster=self, entry=entry, n_rows=N,
            n_features=int(entry.binned.shape[1]),
            n_rounds=int(n_rounds),
            first_iteration=int(first_iteration), seg_k=int(k),
            K=self._K, npar=max(1, self.param.num_parallel_tree),
            cfg=self.gbtree.cfg,
            split_finder=self.gbtree._split_finder(),
            grad_fn=grad_fn, pred_chunk=self.gbtree.pred_chunk,
            subsample=float(self.param.subsample),
            binned=entry.binned, margin=entry.margin,
            label=entry.info.label_dev(),
            weight=entry.info.weight_dev(N),
            base_key=self.gbtree.base_key(),
            cut_values=self.gbtree.cut_values_dev,
            n_cuts=self.gbtree.n_cuts_dev,
            row_valid=entry.row_valid), None

    def absorb_lane_segment(self, spec: LaneSpec, stacks, margin,
                            n_rounds: int) -> None:
        """Install one gang segment's per-lane outputs back into this
        booster: the lane's flattened ``(n_rounds*K*npar, ...)`` tree
        stack joins the ensemble and the scanned margin replaces the
        entry's cached one (sliced back to the entry's own row count by
        the caller).  Mirrors what :meth:`update_many` does after
        ``do_boost_fused``."""
        self.gbtree.absorb_round_stacks(stacks, n_rounds)
        spec.entry.margin = margin
        spec.entry.applied = self.gbtree.num_trees

    def boost(self, dtrain: DMatrix, grad, hess):
        """Boost from user-supplied gradients (reference
        XGBoosterBoostOneIter, wrapper/xgboost_wrapper.cpp:310-317)."""
        if getattr(dtrain, "is_sharded", False):
            raise NotImplementedError(
                "boost() takes full gradient vectors; split-loaded "
                "matrices have no full-vector host view")
        self._lazy_init(dtrain)
        entry = self._entry(dtrain)
        self._sync_margin(entry)
        g = np.asarray(grad, np.float32).reshape(dtrain.num_row, self._K)
        h = np.asarray(hess, np.float32).reshape(dtrain.num_row, self._K)
        n_dev = (entry.binned.shape[0] if entry.binned is not None
                 else entry.margin.shape[0])  # external: no binned array
        if entry.rank_pad_prep is not None:
            # group-padded layout: user rows scatter to their slots
            gp = np.zeros((n_dev, self._K), np.float32)
            hp = np.zeros((n_dev, self._K), np.float32)
            gp[entry.rank_pad_prep.user_map] = g
            hp[entry.rank_pad_prep.user_map] = h
            g, h = gp, hp
        elif n_dev - dtrain.num_row:  # zero-gradient padding rows
            pad = n_dev - dtrain.num_row
            g = np.concatenate([g, np.zeros((pad, self._K), np.float32)])
            h = np.concatenate([h, np.zeros((pad, self._K), np.float32)])
        gh = jnp.stack([jnp.asarray(g), jnp.asarray(h)], axis=-1)
        self._do_boost(dtrain, entry, gh, self.gbtree.num_boosted_rounds
                       if self.param.booster != "gblinear"
                       else self.gbtree.version)

    def _do_boost(self, dtrain, entry, gh, iteration):
        # fault-injection seam (reference AllreduceMock, allreduce_mock.h:
        # 37-44): every boosting round is a "version"; each collective
        # launch inside it bumps the seqno (parallel/mock.py)
        from xgboost_tpu.parallel import mock
        mock.begin_round(iteration)
        # deterministic per-iteration seeding: the reference forces
        # seed_per_iteration in distributed mode for replayable recovery
        # (learner-inl.hpp:275-277); fold_in gives that always.
        key = jax.random.fold_in(
            jax.random.PRNGKey(self.param.seed), iteration)
        if self.param.booster == "gblinear":
            self.gbtree.do_boost(entry.binned, gh, dtrain.info,
                                 mesh=self._mesh)
            entry.applied = self.gbtree.version  # recompute on next sync
            entry.margin = None
            self._sync_margin(entry)
            return
        from xgboost_tpu.models.updaters import parse_updaters
        ups = parse_updaters(self.param.updater)
        if entry.external:
            if "refresh" in ups:
                raise NotImplementedError(
                    "updater=refresh is not supported on external-memory "
                    "matrices")
            deltas = self.gbtree.do_boost_paged(entry.dmat, gh, key,
                                                mesh=self._mesh)
            entry.margin = jnp.asarray(entry.margin) + deltas
            entry.applied = self.gbtree.num_trees
            return
        grows = any(u.startswith("grow") or u == "distcol" for u in ups)
        if grows and getattr(self.gbtree, "exact_raw", False) \
                and getattr(entry, "exact_ranks", None) is None:
            # one-off: dense-rank structures for the single-key sort
            # (colmaker.build_exact_ranks; host argsort on the matrix
            # _raw_dense already densified, then resident on device
            # for every subsequent round)
            from xgboost_tpu.models.colmaker import build_exact_ranks
            rk, uq = build_exact_ranks(entry.exact_host)
            entry.exact_ranks = (jnp.asarray(rk), jnp.asarray(uq))
            entry.exact_host = None
        if grows:
            _, delta = self.gbtree.do_boost(
                entry.binned, gh, key, row_valid=entry.row_valid,
                mesh=self._mesh, col_mesh=self._col_mesh,
                root=entry.root,
                exact_has_missing=getattr(entry, "exact_has_missing",
                                          True),
                exact_ranks=getattr(entry, "exact_ranks", None),
                binned_t=getattr(entry, "binned_t", None))
            entry.margin = entry.margin + delta
            entry.applied = self.gbtree.num_trees
        if "refresh" in ups:
            # refresh pass (reference updater=refresh): recompute stats +
            # leaf values of ALL trees on this data.  In a mixed pipeline
            # ("grow_histmaker,refresh") it runs after growth on the same
            # gradient snapshot, like the reference's sequential updaters.
            self.gbtree.do_refresh(entry.binned, gh,
                                   row_valid=entry.row_valid,
                                   mesh=self._mesh, root=entry.root)
            if "prune" in ups and self.param.gamma > 0.0 and not grows:
                # "refresh,prune": prune against the refreshed gains
                from xgboost_tpu.models.updaters import prune_tree
                for i, t in enumerate(self.gbtree.trees):
                    self.gbtree.trees[i], _ = prune_tree(
                        t, self.param.gamma, self.gbtree.cfg.n_roots)
                self.gbtree._stack_cache = None
            # leaf values changed: every cached margin is stale
            for e in self._cache.values():
                e.margin = None
                e.applied = 0
            self._sync_margin(entry)

    def _predict_block_rows(self, data) -> int:
        """Row-block size for one-off dense prediction uploads: whole
        matrix while under the ``2^31``-byte single-buffer guard, else
        256 MB f32 blocks (thousands of rows even at wide F; with the
        depth-2 prefetch queue at most ~4 blocks are in flight
        device-side).  ``XGBTPU_BIN_BLOCK_BYTES`` overrides (test
        seam)."""
        Fm = self.gbtree.cuts.num_feature
        N = data.num_row
        budget = int(os.environ.get("XGBTPU_BIN_BLOCK_BYTES", 0))
        if not budget and N * Fm * 4 <= (1 << 31):
            return max(N, 1)
        return max(1, (budget or (1 << 28)) // (4 * max(Fm, 1)))

    def _dense_block_fn(self, data):
        """``(s, e) -> (e-s, Fm) f32`` dense row blocks (NaN = missing).

        When ``Booster.predict`` wrapped a plain C-contiguous f32
        ndarray of model width, blocks are zero-copy VIEWS of the
        caller's own buffer — the CSR round-trip and the per-block
        densify copy are skipped entirely and the caller's memory
        uploads directly (round-7 satellite; NaN is the missing marker
        on both paths, so blocks are value-identical).  Otherwise
        blocks densify straight from the CSR arrays: the host working
        set is ONE f32 block, never a full N x F densify."""
        Fm = self.gbtree.cuts.num_feature
        src = getattr(data, "_predict_dense_src", None)
        if src is None and hasattr(data, "predict_dense_src"):
            # a lazily-CSR DMatrix built straight from a dense ndarray
            # (data.py): the caller's buffer is the upload source and
            # the CSR arrays never materialize for this predict
            src = data.predict_dense_src()
        if src is not None and src.shape[1] == Fm:
            return lambda s, e: src[s:e]

        def dense_block(s, e):
            Xb = np.full((e - s, Fm), np.nan, np.float32)
            lo, hi = data.indptr[s], data.indptr[e]
            rows = np.repeat(np.arange(e - s),
                             np.diff(data.indptr[s:e + 1]))
            cols = data.indices[lo:hi]
            keep = cols < Fm
            Xb[rows[keep], cols[keep]] = data.values[lo:hi][keep]
            return Xb

        return dense_block

    def _bin_dense_blocked(self, data: DMatrix):
        """Device-side quantization of a dense-enough matrix, chunked
        over row blocks past the ``2^31``-byte single-buffer guard (a
        20M x 28 one-off prediction used to silently fall back to the
        seconds-long host ``searchsorted`` loop).

        This is the TWO-STEP path (binned matrix materialized in HBM):
        ``pred_leaf`` and the ``XGBTPU_PREDICT_FUSED=0`` baseline use
        it; the margin fast path fuses quantize into the traversal
        program instead (:meth:`_predict_fused_blocked`).  Blocks stage
        through :func:`external._prefetch_to_device` at the
        ``XGBTPU_PREDICT_UPLOAD_DEPTH`` lookahead, and every upload
        feeds the ``xgbtpu_predict_transfer_*`` counters."""
        from xgboost_tpu.binning import bin_dense_device
        from xgboost_tpu.obs.metrics import predict_metrics
        cv = self.gbtree.cuts.cut_values
        N = data.num_row
        block = self._predict_block_rows(data)
        blk = self._dense_block_fn(data)
        pm = predict_metrics()
        if N <= block:
            from xgboost_tpu.obs.metrics import timed_device_put
            return bin_dense_device(
                timed_device_put(blk(0, N), pm.observe_transfer), cv)
        from xgboost_tpu.external import _prefetch_to_device

        def host_blocks():
            for s in range(0, N, block):
                yield s, blk(s, min(s + block, N))

        parts = [bin_dense_device(xb, cv)
                 for _, xb in _prefetch_to_device(
                     host_blocks(), depth=_predict_upload_depth(),
                     observe=pm.observe_transfer)]
        return jnp.concatenate(parts, axis=0)

    def _fused_predict_ok(self, data, pred_leaf: bool) -> bool:
        """Gate for the fused one-off margin path: margins only
        (pred_leaf needs the leaf matrix), non-empty input (the block
        pipeline has nothing to concatenate at N=0; the two-step path
        already returns the (0,) result), single-device placement (the
        mesh path keeps the two-step upload), no multi-root routing
        (root vectors would need per-block slicing), and the
        ``XGBTPU_PREDICT_FUSED`` A/B seam (0 = two-step baseline)."""
        return (not pred_leaf
                and data.num_row > 0
                and os.environ.get("XGBTPU_PREDICT_FUSED", "1") != "0"
                and self._mesh is None and self._col_mesh is None
                and not (getattr(data.info, "root_index", None) is not None
                         and max(1, self.param.num_roots) > 1))

    def _predict_fused_blocked(self, data, ntree_limit: int = 0):
        """One-off dense prediction margins through the FUSED
        quantize+traverse program (round 7 — the transfer wall): raw
        f32 row blocks upload through the
        ``XGBTPU_PREDICT_UPLOAD_DEPTH``-deep prefetch pipeline (block
        k+1's upload overlaps block k's quantize+traverse), margins
        come out of ONE compiled program per block, and the binned
        matrix never exists outside it — no second HBM buffer, no extra
        launch boundary.  Every upload feeds the
        ``xgbtpu_predict_transfer_*`` counters.  Bit-identical to the
        two-step path: the quantize sub-graph is
        ``binning.bin_dense_device`` itself and traversal is
        row-independent, so per-block margins concatenate to exactly
        the whole-matrix result (tests/test_predict_fused.py)."""
        from xgboost_tpu.external import _prefetch_to_device
        from xgboost_tpu.obs.metrics import predict_metrics
        N = data.num_row
        K = self._K
        block = self._predict_block_rows(data)
        blk = self._dense_block_fn(data)
        bm = data.info.base_margin
        if bm is None:
            base_all = None
            base0 = jnp.full((), self.obj.prob_to_margin(
                self.param.base_score), jnp.float32)
        else:
            base_all = np.asarray(bm, np.float32).reshape(N, K)
            base0 = None
        pm = predict_metrics()
        if N <= block:
            # single block (virtually all under-guard predicts): skip
            # the prefetch worker thread/queue — inline timed upload,
            # one fused program call (mirrors _bin_dense_blocked)
            from xgboost_tpu.obs.metrics import timed_device_put
            xd = timed_device_put(blk(0, N), pm.observe_transfer)
            base = (base0 if base_all is None
                    else jnp.asarray(base_all))
            return self.gbtree.predict_margin_fused(xd, base, ntree_limit)

        def host_blocks():
            for s in range(0, N, block):
                yield s, blk(s, min(s + block, N))

        parts = []
        for s, xd in _prefetch_to_device(host_blocks(),
                                         depth=_predict_upload_depth(),
                                         observe=pm.observe_transfer):
            base = (base0 if base_all is None
                    else jnp.asarray(base_all[s:s + xd.shape[0]]))
            parts.append(self.gbtree.predict_margin_fused(
                xd, base, ntree_limit))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=0)

    # ------------------------------------------------------------ inference
    def predict(self, data: DMatrix, output_margin: bool = False,
                ntree_limit: int = 0, pred_leaf: bool = False) -> np.ndarray:
        """(reference BoostLearner::Predict, learner-inl.hpp:332-346 and
        Booster.predict, wrapper/xgboost.py:422-450).

        ``data`` may also be a plain 2-D ndarray / jax.Array / nested
        list (NaN = missing): it is wrapped into a transient DMatrix
        here, so callers (serving engine, sklearn wrapper) don't each
        re-implement the wrapping."""
        assert self.gbtree is not None, "model not trained/loaded"
        if not hasattr(data, "num_row"):  # any DMatrix flavor has it
            arr = np.asarray(data, dtype=np.float32)
            data = DMatrix(arr)
            if arr.ndim == 2 and arr.flags.c_contiguous:
                # upload the caller's own buffer: the UPLOAD path skips
                # the CSR→dense densify copy per block and ships views
                # of arr instead (NaN is the missing marker on both
                # paths; see _dense_block_fn).  The DMatrix above is
                # CSR-LAZY (data.py): this one-off predict reads only
                # num_nonmissing() + these views, so the ~2x
                # values/indices/indptr copy is never built at all
                data._predict_dense_src = arr

        def _counted(out):
            """Attribute prediction traffic in /metrics by the rows
            actually RETURNED: sharded ranks count their local shard
            (num_row is the global count), and a predict that raises
            counts nothing.  The serving engine feeds the same
            family."""
            if self.param.booster != "gblinear":
                from xgboost_tpu.obs.metrics import predict_metrics
                predict_metrics().rows.inc(out.shape[0])
            return out
        if getattr(data, "is_sharded", False):
            # split-loaded matrix: each process returns predictions for
            # ITS OWN rows only (no host holds the full output)
            if self.param.booster == "gblinear":
                raise NotImplementedError(
                    "gblinear works on raw feature columns; per-rank "
                    "split loading currently supports gbtree only")
            entry = self._cache.get(id(data))
            if entry is None:
                # transient, NOT registered (the buffer_offset=-1 path —
                # registering every served matrix would grow the cache
                # unboundedly)
                entry = self._make_shard_loaded_entry(data)
            if pred_leaf:
                leaves = self.gbtree.predict_leaf(entry.binned, ntree_limit)
                return _counted(
                    data.local_block_of(leaves)[:data.local_num_row])
            if ntree_limit == 0:
                self._sync_margin(entry)
                margin = entry.margin
            else:
                margin = self.gbtree.predict_margin(
                    entry.binned, entry.base, ntree_limit)
            out = data.local_block_of(self.obj.pred_transform(
                margin, output_margin=output_margin))[:data.local_num_row]
            if out.ndim == 2 and out.shape[1] == 1:
                out = out[:, 0]
            return _counted(out)
        cached = self._cache.get(id(data))
        if cached is None and getattr(data, "is_external", False):
            # one-off external prediction: build a transient entry WITHOUT
            # registering it (the buffer_offset=-1 path — registering every
            # served matrix would grow the cache unboundedly)
            cached = self._build_ext_entry(data)
        if cached is not None and cached.external:
            if pred_leaf:
                leaves = [np.asarray(self.gbtree.predict_leaf(
                    batch, ntree_limit))
                    for _, batch in data.device_batches()]
                return _counted(np.concatenate(leaves, axis=0))
            if ntree_limit == 0:
                self._sync_margin(cached)
                margin = cached.margin
            else:
                margin = np.concatenate(
                    [np.asarray(self.gbtree.predict_margin(
                        batch,
                        np.asarray(cached.base)[s:s + batch.shape[0]],
                        ntree_limit))
                     for s, batch in data.device_batches()], axis=0)
            out = np.asarray(self.obj.pred_transform(
                jnp.asarray(margin), output_margin=output_margin))
            if out.ndim == 2 and out.shape[1] == 1:
                out = out[:, 0]
            return _counted(out)
        fused = False
        if cached is None:
            # one-off prediction: no cache registration (the reference's
            # buffer_offset = -1 path, learner-inl.hpp:332-346)
            if self.num_feature and data.num_col > self.num_feature:
                raise ValueError(
                    f"data has {data.num_col} features, model was trained "
                    f"with {self.num_feature}")
            # the density gate counts actual non-missing values, even
            # for ndarray inputs carrying _predict_dense_src: a
            # mostly-NaN ndarray must keep the O(nnz) host-binning
            # path (u8 upload), not ship the full f32 matrix — the
            # direct-buffer view is an UPLOAD optimization for inputs
            # that are dense anyway, not a routing override.
            # num_nonmissing() == len(data.values) bit for bit, but a
            # lazily-CSR dense DMatrix answers it WITHOUT building the
            # ~2x values/indices/indptr copy this gate alone would
            # otherwise force (data.py)
            nnz = (data.num_nonmissing()
                   if hasattr(data, "num_nonmissing")
                   else len(data.values))
            dense_enough = (nnz
                            >= 0.25 * data.num_row * max(data.num_col, 1))
            if self.param.booster == "gblinear":
                binned = self.gbtree.device_matrix(data)
            elif getattr(self.gbtree, "exact_raw", False):
                # exact mode routes on RAW values (no bins exist)
                binned = self._raw_dense(data)[0]
            elif dense_enough and self._fused_predict_ok(data, pred_leaf):
                # FUSED quantize+traverse (round 7): raw f32 blocks
                # upload (prefetch-overlapped) and margins come out of
                # one compiled program per block — the binned matrix
                # never exists outside it.  The margin branch below
                # routes to the fused block pipeline.
                binned = None
                fused = True
            elif dense_enough:
                # quantize ON DEVICE: the host searchsorted loop costs
                # seconds at 1M rows where the fused compare-reduce is
                # ~2 ms (binning.bin_dense_device); the per-block f32
                # densify is the only host work left.  Sparse inputs
                # (<25% dense) keep the O(nnz) bin_matrix path —
                # densifying them host-side costs more memory/transfer
                # than the device quantize saves (advisor, round 4).
                # Matrices past the 2^31-byte single-buffer guard no
                # longer cliff to the seconds-long host path: they
                # quantize in CSR-densified row blocks (prefetch-
                # staged, upload overlapping quantize, bounded host +
                # device working set)
                binned = self._bin_dense_blocked(data)
            else:
                from xgboost_tpu.obs.metrics import (predict_metrics,
                                                     timed_device_put)
                binned = timed_device_put(
                    bin_matrix(data, self.gbtree.cuts),
                    predict_metrics().observe_transfer)
            base = (None if fused
                    else self._base_margin_of(data, data.num_row))
        else:
            binned, base = cached.binned, cached.base
        if cached is not None:
            root = cached.root
        elif (getattr(data.info, "root_index", None) is not None
                and max(1, self.param.num_roots) > 1):
            root = jnp.asarray(
                np.asarray(data.info.root_index, np.int64), jnp.int32)
        else:
            root = None
        if pred_leaf:
            leaves = np.asarray(self._replicated(
                self.gbtree.predict_leaf(binned, ntree_limit, root=root)))
            return _counted(cached.user_rows(leaves)
                            if cached is not None else leaves)
        if cached is not None and ntree_limit == 0:
            self._sync_margin(cached)
            margin = cached.margin
        elif fused:
            margin = self._predict_fused_blocked(data, ntree_limit)
        else:
            margin = self.gbtree.predict_margin(binned, base, ntree_limit,
                                                root=root)
        out = self.obj.pred_transform(margin, output_margin=output_margin)
        out = np.asarray(self._replicated(out))
        if cached is not None:
            out = cached.user_rows(out)
        if out.ndim == 2 and out.shape[1] == 1:
            out = out[:, 0]
        return _counted(out)

    # ----------------------------------------------------------- evaluation
    def _metrics(self, feval=None) -> List:
        names = list(self.param.eval_metric)
        if not names and feval is None:
            names = [self.obj.default_metric]
        return [create_metric(n) for n in names]

    def _eval_parts(self, dmat, name: str, tr, parts: List[str],
                    feval) -> None:
        """Append one watchlist set's ``name-metric:value`` fields to
        ``parts`` from its transformed predictions ``tr`` (user rows,
        host numpy) — shared by the per-round eval path (:meth:`eval_set`)
        and the segmented fused driver (:meth:`update_many`), which
        computes a ``tr`` per round of a segment from ONE stacked
        dispatch output.  Same host float64 metric math on the same f32
        values -> byte-identical eval text on both paths."""
        labels = np.asarray(dmat.get_label())
        weights = np.asarray(dmat.get_weight())
        gptr = dmat.info.group_ptr
        for m in self._metrics(feval):
            p = tr if tr.shape[1] > 1 else tr[:, 0]
            if getattr(m, "needs_fold_index", False):
                val = m(p, labels, weights, gptr,
                        fold_index=dmat.info.fold_index)
            else:
                val = m(p, labels, weights, gptr)
            parts.append(f"{name}-{m.metric_name}:{val:.6f}")
        if feval is not None:
            # feval comes LAST so early stopping tracks it (reference
            # wrapper/xgboost.py appends custom eval after built-ins)
            preds = tr[:, 0] if tr.shape[1] == 1 else tr
            mname, val = feval(preds, dmat)
            parts.append(f"{name}-{mname}:{val:.6f}")

    def eval_set(self, evals: Sequence[Tuple[DMatrix, str]], iteration: int = 0,
                 feval=None) -> str:
        """Formatted eval line (reference EvalSet::Eval, evaluation.h:62-95:
        ``[iter]\\tname-metric:value``)."""
        parts = [f"[{iteration}]"]
        for dmat, name in evals:
            entry = self._entry(dmat)
            self._sync_margin(entry)
            if getattr(dmat, "is_sharded", False):
                self._eval_sharded(dmat, entry, name, parts, feval)
                continue
            tr = entry.user_rows(np.asarray(self._replicated(
                self.obj.eval_transform(entry.margin))))
            self._eval_parts(dmat, name, tr, parts, feval)
        msg = "\t".join(parts)
        # latest eval scores ride the training metrics as gauges
        # (xgbtpu_training_eval_score{key="train-error"}), scrapeable
        # mid-run via metrics_port= (OBSERVABILITY.md)
        from xgboost_tpu.obs import training_metrics
        training_metrics().observe_eval(_parse_eval(msg))
        return msg

    def _eval_sharded(self, dmat, entry, name: str, parts: List[str],
                      feval) -> None:
        """Distributed evaluation for a split-loaded matrix: each process
        computes metric partials on ITS shard only, then partial sums
        reduce across processes — the reference's rabit::Allreduce of
        (sum, wsum) in EvalEWiseBase (evaluation-inl.hpp:45) instead of
        the all-gather the replicated path uses."""
        if feval is not None:
            raise NotImplementedError(
                "custom feval needs the full prediction vector on one "
                "host; load the eval set replicated (DMatrix) instead")
        local = dmat.local_block_of(self.obj.eval_transform(entry.margin))
        self._eval_parts_sharded(dmat, name, local[:dmat.local_num_row],
                                 parts)

    def _eval_parts_sharded(self, dmat, name: str, preds,
                            parts: List[str]) -> None:
        """The partial-sum metric core shared by the per-round sharded
        eval path (:meth:`_eval_sharded`) and the mesh-fused driver
        (:meth:`update_many`, which hands in the LOCAL user rows of one
        round's transformed scan outputs).  ``preds`` is this process's
        (local_num_row, K) transformed prediction block."""
        labels = np.asarray(dmat.info.label)
        weights = np.asarray(dmat.info.get_weight(dmat.local_num_row))
        for m in self._metrics():
            if not hasattr(m, "partial_fn"):
                from xgboost_tpu.metrics import _DIST_METRICS
                raise NotImplementedError(
                    f"metric {m.metric_name!r} has no distributed "
                    "partial-sum form; supported on split-loaded data: "
                    f"{sorted(_DIST_METRICS)}")
            p = preds if preds.shape[1] > 1 else preds[:, 0]
            if (m.metric_name == "auc"
                    and self.param.dist_auc != "approx"):
                # EXACT global AUC: allgather per-shard value runs and
                # merge.  Payload is one 24-byte run per DISTINCT
                # predicted value — for continuous margins that is
                # ~local_rows runs (24 MB/shard at 1M rows), fine as
                # an end-of-training eval, heavy as an every-round
                # one; past dist_auc_max_runs the reference's
                # mean-of-shards approximation kicks in with a loud
                # one-time warning (it is also always available
                # explicitly via dist_auc=approx).
                from xgboost_tpu.metrics import (auc_compress,
                                                 auc_exact_from_runs)
                runs = auc_compress(p, labels, weights)
                limit = int(getattr(self.param, "dist_auc_max_runs",
                                    1 << 22))
                # the exact-vs-approx decision must be GLOBAL: ranks
                # branching on shard-local run counts would execute
                # mismatched collectives (allsum vs allgatherv) and
                # hang — decide on the summed run count, which is also
                # the actual gathered payload
                total_runs = int(dmat.allsum(
                    np.array([float(len(runs))]))[0])
                if total_runs > limit:
                    if not getattr(self, "_warned_auc_runs", False):
                        self._warned_auc_runs = True
                        print(f"[dist-auc] {total_runs} distinct-value "
                              f"runs across shards exceeds "
                              f"dist_auc_max_runs={limit}; falling "
                              "back to the reference's approximate "
                              "mean-of-shards AUC", file=sys.stderr)
                    partial = m.partial_fn(p, labels, weights, None)
                    val = m.finalize_fn(dmat.allsum(partial))
                else:
                    val = auc_exact_from_runs(dmat.allgatherv(runs))
            else:
                partial = m.partial_fn(p, labels, weights, None)
                val = m.finalize_fn(dmat.allsum(partial))
            parts.append(f"{name}-{m.metric_name}:{val:.6f}")

    def eval(self, data: DMatrix, name: str = "eval", iteration: int = 0) -> str:
        return self.eval_set([(data, name)], iteration)

    # ---------------------------------------------------------- model store
    def save_model(self, path: str, save_base64: bool = False):
        """Save the model; ``save_base64`` writes the text-safe encoding
        (the reference's ``bs64`` mode, learner-inl.hpp:240-252, which
        survives text-only channels).

        File writes are crash-safe: the payload (plus its CRC32
        integrity footer, reliability/integrity.py) goes through
        ``atomic_write``, so a watcher of ``path`` — the serving
        ModelRegistry, the checkpoint ring — can never observe a torn
        file.  ``stdout`` streams the bare payload (no footer: the
        reader of a pipe already owns the transport)."""
        assert self.gbtree is not None, "nothing to save"
        header = {
            "magic": _MAGIC,
            "param": _jsonable(self.param.to_dict()),
            "objective": self.param.objective,
            "booster": self.param.booster,
            "num_feature": self.num_feature,
            "attributes": self.attributes,
            "best_iteration": self.best_iteration,
        }
        state = self.gbtree.get_state()
        import io
        buf = io.BytesIO()
        np.savez(buf, header=np.frombuffer(
            json.dumps(header).encode(), dtype=np.uint8), **state)
        payload = buf.getvalue()
        if save_base64 or path == "stdout":
            # stdout is always base64, like the reference
            # (learner-inl.hpp:240-243)
            import base64
            payload = b"bs64\t" + base64.b64encode(payload) + b"\n"
            if path == "stdout":
                import sys
                sys.stdout.buffer.write(payload)
                sys.stdout.buffer.flush()
                return
        from xgboost_tpu.obs import span
        from xgboost_tpu.reliability.integrity import (add_footer,
                                                       atomic_write)
        with span("model.save", path=path, bytes=len(payload)):
            atomic_write(path, add_footer(payload))

    def load_model(self, path: str):
        from xgboost_tpu.obs import span
        from xgboost_tpu.reliability.integrity import (read_file,
                                                       verify_model_bytes)
        with span("model.load", path=path):
            raw = read_file(path)
            # strips + checks the CRC footer; raises ModelIntegrityError
            # on torn/bit-flipped files, warns once on footer-less
            # legacy files
            self.load_raw(verify_model_bytes(raw, name=path), name=path)

    def load_raw(self, raw: bytes, name: str = "<buffer>"):
        """Load a model from an in-memory buffer (reference
        XGBoosterLoadModelFromBuffer, wrapper/xgboost_wrapper.cpp:338-341).
        Sniffs the same formats as load_model: our npz, base64 text-safe
        (bs64), or the reference binary stream (binf / reference bs64)."""
        import io
        head = raw[:5]
        if head[:4] in (b"binf", b"bs64") and head != b"bs64\t":
            # reference binary format: delegate to the compat reader
            self._load_reference(raw)
            return
        if head == b"bs64\t":
            import base64
            try:
                dec = base64.b64decode(b"".join(raw[5:].split()),
                                       validate=True)
            except Exception as e:
                from xgboost_tpu.reliability.integrity import \
                    ModelIntegrityError
                raise ModelIntegrityError(
                    f"{name}: torn/invalid bs64 payload: {e}")
            if not dec.startswith(b"PK"):  # not our npz: reference stream
                self._load_reference(dec)
                return
            raw = dec
        self._load_np(io.BytesIO(raw), name)

    def _load_np(self, src, path):
        from xgboost_tpu.reliability.integrity import ModelIntegrityError
        try:
            z = np.load(src, allow_pickle=False)
        except Exception as e:
            # unparseable npz: for a footer-less file this is the only
            # torn-write signal there is — type it so recovery paths
            # (checkpoint-ring fallback, registry poisoning) can react
            from xgboost_tpu.profiling import reliability_metrics
            reliability_metrics().integrity_failures.inc()
            raise ModelIntegrityError(
                f"{path} is not an xgboost_tpu model file: {e}")
        with z:
            header = json.loads(bytes(z["header"]).decode())
            assert header.get("magic") == _MAGIC, "not an xgboost_tpu model"
            self.param = TrainParam.from_dict(header["param"])
            self.num_feature = header["num_feature"]
            self.attributes = header.get("attributes", {})
            self.best_iteration = header.get("best_iteration", -1)
            state = {k: z[k] for k in z.files if k != "header"}
        self._init_obj()
        if self.param.booster == "gblinear":
            from xgboost_tpu.models.gblinear import GBLinear
            self.gbtree = GBLinear.from_state(self.param, state)
        else:
            from xgboost_tpu.models.gbtree import GBTree
            self.gbtree = GBTree.from_state(self.param, state)
        self._cache.clear()
        self._model_gen += 1

    def _load_reference(self, src):
        """Adopt the state of a reference-format model (path or bytes)."""
        from xgboost_tpu.compat import load_reference_model
        other = load_reference_model(src)
        self.param = other.param
        self.obj = other.obj
        self.gbtree = other.gbtree
        self.num_feature = other.num_feature
        self._cache.clear()
        self._model_gen += 1

    def save_raw(self) -> bytes:
        import io
        buf = io.BytesIO()
        header = {"magic": _MAGIC, "param": _jsonable(self.param.to_dict()),
                  "num_feature": self.num_feature,
                  "attributes": self.attributes,
                  "best_iteration": self.best_iteration}
        np.savez(buf, header=np.frombuffer(
            json.dumps(header).encode(), dtype=np.uint8),
            **self.gbtree.get_state())
        return buf.getvalue()

    # --------------------------------------------------------------- dumps
    def get_dump(self, fmap: str = "", with_stats: bool = False) -> List[str]:
        from xgboost_tpu.dump import dump_trees
        return dump_trees(self, fmap, with_stats)

    def dump_model(self, fout: str, fmap: str = "", with_stats: bool = False):
        dumps = self.get_dump(fmap, with_stats)
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(fout, "".join(
            f"booster[{i}]:\n{s}" for i, s in enumerate(dumps)).encode())

    def get_fscore(self, fmap: str = "") -> Dict[str, int]:
        """Split-count feature importance (wrapper/xgboost.py:512-530)."""
        from xgboost_tpu.dump import feature_importance
        return feature_importance(self, fmap)


def _pad_info(info: MetaInfo, n: int, pad: int, k: int = 1) -> MetaInfo:
    """Row-pad metadata with zero-weight rows so padded rows produce zero
    gradients (group_ptr is left untouched: rows past gptr[-1] are
    group-less and get no ranking pairs)."""
    if pad == 0:
        # still a fresh MetaInfo (sharing the arrays): the caller
        # populates _dev_cache with mesh-sharded device arrays, which
        # must not leak into the user's DMatrix
        out = MetaInfo()
        for f in ("label", "weight", "base_margin", "root_index",
                  "fold_index", "group_ptr"):
            setattr(out, f, getattr(info, f))
        return out
    out = MetaInfo()
    if info.label is not None:
        out.label = np.concatenate(
            [info.label, np.zeros(pad, np.float32)])
    out.weight = np.concatenate(
        [info.get_weight(n), np.zeros(pad, np.float32)])
    if info.base_margin is not None:
        # base_margin may arrive flat (n,), raveled (n*k,) or (n, k):
        # pad along ROWS so a later reshape(n_pad, k) stays valid
        bm = np.asarray(info.base_margin, np.float32).reshape(n, k)
        out.base_margin = np.concatenate(
            [bm, np.zeros((pad, k), np.float32)])
    if info.group_ptr is None:
        # one explicit group over the real rows, so ranking objectives never
        # pair padding rows
        out.group_ptr = np.array([0, n], dtype=np.int64)
    else:
        out.group_ptr = info.group_ptr
    return out


def _jsonable(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, tuple):
            v = list(v)
        out[k] = v
    return out


_MAXIMIZE_METRICS = ("auc", "ams", "ndcg", "map", "pre")


def train(params: dict, dtrain: DMatrix, num_boost_round: int = 10,
          evals: Sequence[Tuple[DMatrix, str]] = (), obj=None, feval=None,
          maximize: Optional[bool] = None,
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[dict] = None, verbose_eval: bool = True,
          xgb_model=None, init_model=None) -> Booster:
    """Train a booster (reference wrapper/xgboost.py:533-632, including the
    early-stopping protocol: best_score/best_iteration attributes, stop
    after `early_stopping_rounds` non-improving rounds on the LAST metric
    of the LAST eval set).

    ``init_model``/``xgb_model`` (aliases; a Booster or a model path)
    warm-start continuation: the new rounds APPEND to the existing
    ensemble, and their iteration indices continue the existing round
    numbering — so per-iteration seeding (``fold_in(seed, iteration)``,
    subsample draws) matches what one uninterrupted run of
    ``existing + num_boost_round`` rounds would have used, and the
    continued model is bit-identical to it (the continuous-training
    pipeline's resume contract, PIPELINE.md)."""
    if init_model is not None and xgb_model is not None:
        raise ValueError("pass init_model or xgb_model, not both "
                         "(they are aliases)")
    xgb_model = xgb_model if xgb_model is not None else init_model
    start_round = 0
    if xgb_model is not None:
        bst = xgb_model if isinstance(xgb_model, Booster) else Booster(
            params, model_file=xgb_model)
        bst.set_param(params or {})
        # continuation rounds keep counting where the loaded ensemble
        # stopped (ntree accounting): round i of this call is global
        # iteration start_round + i
        if bst.gbtree is not None:
            start_round = bst.gbtree.num_boosted_rounds
    else:
        bst = Booster(params, cache=[dtrain] + [d for d, _ in evals])

    best_score = None
    best_iter = 0
    best_msg = ""

    if not evals and early_stopping_rounds is None:
        # nothing runs on the host between rounds: fuse the whole round
        # loop into one device launch where eligible (update_many falls
        # back to per-round updates otherwise)
        bst.update_many(dtrain, start_round, num_boost_round, fobj=obj)
        rounds = ()
    else:
        rounds = range(num_boost_round)

    for i in rounds:
        bst.update(dtrain, start_round + i, fobj=obj)
        if not evals:
            continue
        from contextlib import nullcontext
        prof = bst.profiler
        with prof.phase("eval") if prof else nullcontext():
            msg = bst.eval_set(evals, i, feval)  # folds into ended round
        # bool => on/off; int N > 1 => print every N rounds (and the
        # last), the newer reference wrappers' print-period idiom
        if verbose_eval and (
                verbose_eval is True or int(verbose_eval) <= 1
                or i % int(verbose_eval) == 0
                or i == num_boost_round - 1):
            print(msg)
        scores = _parse_eval(msg)
        if evals_result is not None:
            for k, v in scores.items():
                evals_result.setdefault(k, []).append(v)
        if early_stopping_rounds is not None:
            last_key = list(scores)[-1]
            score = scores[last_key]
            mx = maximize
            if mx is None:
                metric = last_key.split("-", 1)[1]
                mx = any(metric.startswith(m) for m in _MAXIMIZE_METRICS)
            improved = (best_score is None or
                        (score > best_score if mx else score < best_score))
            if improved:
                best_score, best_iter, best_msg = score, i, msg
            elif i - best_iter >= early_stopping_rounds:
                if verbose_eval:
                    print(f"Stopping. Best iteration:\n{best_msg}")
                break
    if early_stopping_rounds is not None and best_score is not None:
        bst.best_score = best_score
        bst.best_iteration = best_iter
    if getattr(bst, "_profiler", None) is not None:
        bst._profiler.print_summary()
        bst._profiler.stop()
    return bst


def _parse_eval(msg: str) -> Dict[str, float]:
    out = {}
    for part in msg.split("\t")[1:]:
        k, _, v = part.rpartition(":")
        out[k] = float(v)
    return out


class CVPack:
    """One fold's (train, test, booster) bundle (wrapper/xgboost.py:635-650)."""

    def __init__(self, dtrain: DMatrix, dtest: DMatrix, params: dict):
        self.dtrain, self.dtest = dtrain, dtest
        self.bst = Booster(params, cache=[dtrain, dtest])
        self.watchlist = [(dtrain, "train"), (dtest, "test")]

    def update(self, i, fobj):
        self.bst.update(self.dtrain, i, fobj)

    def eval(self, i, feval):
        return self.bst.eval_set(self.watchlist, i, feval)


def mknfold(dall: DMatrix, nfold: int, params: dict, seed: int,
            evals=(), fpreproc=None) -> List[CVPack]:
    """Random nfold partition (reference wrapper/xgboost.py:652-674)."""
    from xgboost_tpu.config import params_to_dict
    rng = np.random.RandomState(seed)
    idx = rng.permutation(dall.num_row)
    folds = np.array_split(idx, nfold)
    packs = []
    for k in range(nfold):
        test_idx = folds[k]
        train_idx = np.concatenate([folds[j] for j in range(nfold) if j != k])
        dtrain = dall.slice(np.sort(train_idx))
        dtest = dall.slice(np.sort(test_idx))
        p = params_to_dict(params)
        if fpreproc is not None:
            dtrain, dtest, p = fpreproc(dtrain, dtest, p)
        packs.append(CVPack(dtrain, dtest, p))
    return packs


def aggcv(rlist: List[str], show_stdv: bool = True) -> str:
    """Aggregate per-fold eval lines into cv mean+std (wrapper
    xgboost.py:676-695)."""
    cvmap: Dict[str, List[float]] = {}
    ret = rlist[0].split("\t")[0]
    for line in rlist:
        for part in line.split("\t")[1:]:
            k, _, v = part.rpartition(":")
            cvmap.setdefault(k, []).append(float(v))
    for k, vals in cvmap.items():
        v = np.asarray(vals)
        if show_stdv:
            ret += f"\tcv-{k}:{v.mean():.6f}+{v.std():.6f}"
        else:
            ret += f"\tcv-{k}:{v.mean():.6f}"
    return ret


def cv(params: dict, dtrain: DMatrix, num_boost_round: int = 10,
       nfold: int = 3, metrics=(), obj=None, feval=None, fpreproc=None,
       show_stdv: bool = True, seed: int = 0,
       verbose_eval: bool = True) -> List[str]:
    """k-fold cross validation (reference wrapper/xgboost.py:697-740)."""
    from xgboost_tpu.config import params_to_dict
    params = params_to_dict(params)
    if metrics:
        params["eval_metric"] = list(metrics)
    packs = mknfold(dtrain, nfold, params, seed, fpreproc=fpreproc)
    results = []
    for i in range(num_boost_round):
        for p in packs:
            p.update(i, obj)
        line = aggcv([p.eval(i, feval) for p in packs], show_stdv)
        if verbose_eval:
            print(line)
        results.append(line)
    return results

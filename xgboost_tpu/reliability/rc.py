"""Process exit-code registry (RELIABILITY.md; lint rule XGT016).

Every deliberate non-zero exit code in the tree is defined HERE, once,
and referenced symbolically everywhere else.  The elastic-recovery
machinery (parallel/launch.py, parallel/gang.py) keys restart-vs-fence
decisions off worker return codes, and the chaos drivers grep logs for
them — a magic ``143`` living in three files is exactly the kind of
protocol constant that drifts silently.  xgtpu-lint XGT016 enforces the
discipline: ``*_RC`` constants defined outside this module, int
literals matching a registered code in exit/returncode contexts, and
``sys.exit``/``os._exit`` with bare literals are all findings, and the
registry is committed as the ``exit_codes`` section of
ANALYSIS_CONTRACTS.json so a new code lands as a reviewed diff.

The 142-145 band is chosen above the shell's 128+signal range for
common signals and below 255; 41/43 predate the band (chaos-kill
codes baked into CHAOS cell log scanners) and are kept stable.
"""

from __future__ import annotations

#: a chaos-dispatch worker died on an unexpected exception (cli.py
#: wraps the dispatch and converts any crash into this code so the
#: coordinator's restart accounting sees one value, not a traceback).
WORKER_CRASH_RC = 41

#: a serving replica was chaos-killed via the fleet ``replica_kill``
#: fault (fleet/membership.py ``on_kill``; reliability/faults.py).
REPLICA_KILL_RC = 43

#: the coordinator declared a heartbeat stall and tore the gang down
#: (parallel/launch.py watchdog).
STALL_RC = 142

#: a worker fenced itself: it saw a coordinator generation newer than
#: its own and died before touching shared state (parallel/gang.py).
FENCE_RC = 143

#: a worker's host (or its heartbeat lease) was declared lost —
#: permanent, not restartable in place (parallel/gang.py).
HOST_LOSS_RC = 144

#: a standby coordinator fenced the incumbent: the incumbent exits
#: with this code without touching the workers (parallel/launch.py).
COORD_FENCED_RC = 145


def registry() -> dict:
    """``{name: value}`` for every registered code, sorted by value —
    the committed ``exit_codes`` inventory section is exactly this."""
    out = {name: value for name, value in globals().items()
           if name.endswith("_RC") and isinstance(value, int)}
    return dict(sorted(out.items(), key=lambda kv: kv[1]))

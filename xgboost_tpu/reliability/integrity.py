"""Crash-safe, integrity-checked file I/O for persisted models.

Two guarantees (RELIABILITY.md):

1. **No torn destination files.**  :func:`atomic_write` stages into a
   same-directory temp file, flushes + fsyncs it, ``os.replace``-s over
   the destination, and fsyncs the directory — a crash at ANY point
   leaves either the complete old file or the complete new file, never
   a prefix.
2. **No silent corruption.**  Every model file written through
   :func:`add_footer` carries a fixed-length ASCII CRC32 footer::

       \\nXGTPUCRC1 <crc32:08x> <payload_len:016d>\\n

   :func:`verify_model_bytes` strips and checks it, raising the typed
   :class:`ModelIntegrityError` on torn or bit-flipped content.  The
   footer is ASCII so the text-safe ``bs64`` model encoding stays
   text-safe, and it is appended AFTER the payload so readers strip it
   before parsing.  Files without a footer (pre-reliability saves,
   reference-format models) load with a one-time warning — backward
   compatible, just unverified.

Both functions route through :mod:`~xgboost_tpu.reliability.faults`
seams, so chaos tests corrupt/starve the REAL write and read paths.
"""

from __future__ import annotations

import contextlib
import os
import re
import sys
import tempfile
import zlib
from typing import Union

from xgboost_tpu.reliability import faults

FOOTER_MAGIC = b"XGTPUCRC1"
# \n + magic(9) + sp + crc(8 hex) + sp + len(16 dec) + \n
FOOTER_LEN = 1 + 9 + 1 + 8 + 1 + 16 + 1
_FOOTER_RE = re.compile(rb"\nXGTPUCRC1 ([0-9a-f]{8}) (\d{16})\n\Z")


class ModelIntegrityError(ValueError):
    """A persisted model failed verification (torn, truncated, or
    bit-flipped).  Subclasses ``ValueError`` so pre-reliability callers
    that caught generic parse errors keep working."""


def make_footer(payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"\n%s %08x %016d\n" % (FOOTER_MAGIC, crc, len(payload))


def add_footer(payload: bytes) -> bytes:
    """Payload + CRC32 footer (what every model writer persists)."""
    return payload + make_footer(payload)


def has_footer(raw: bytes) -> bool:
    return _FOOTER_RE.search(raw) is not None


_warned_unverified = set()


def verify_model_bytes(raw: bytes, name: str = "<buffer>",
                       warn: bool = True) -> bytes:
    """Verify + strip the CRC footer, returning the payload.

    Raises :class:`ModelIntegrityError` when the footer is present but
    wrong (bit flip), truncated mid-footer (torn write), or the length
    disagrees.  Footer-less files return unchanged with a one-time
    warning per name — pre-reliability and reference-format models stay
    loadable, just unverified."""
    m = _FOOTER_RE.search(raw)
    if m is None:
        # a torn write can cut INSIDE the footer: payload bytes intact
        # but the verification record mangled — that is corruption, not
        # a legacy file.  Two tells: the full magic somewhere in the
        # tail (cut after the magic), or the file ENDING with a proper
        # prefix of the footer (cut inside the magic itself)
        head = b"\n" + FOOTER_MAGIC + b" "
        torn_prefix = any(raw.endswith(head[:k])
                          for k in range(2, len(head)))
        if torn_prefix or FOOTER_MAGIC in raw[-(FOOTER_LEN + 8):]:
            _count_integrity_failure(name, "truncated footer (torn write)")
            raise ModelIntegrityError(
                f"{name}: truncated integrity footer (torn write)")
        if warn and name not in _warned_unverified:
            _warned_unverified.add(name)
            print(f"[integrity] {name}: no integrity footer "
                  "(pre-reliability or reference file); loading "
                  "unverified", file=sys.stderr)
        return raw
    payload = raw[:-FOOTER_LEN]
    want_crc, want_len = int(m.group(1), 16), int(m.group(2))
    if len(payload) != want_len:
        _count_integrity_failure(name, "length mismatch (torn write)")
        raise ModelIntegrityError(
            f"{name}: payload is {len(payload)} bytes, footer says "
            f"{want_len} (torn write)")
    got_crc = zlib.crc32(payload) & 0xFFFFFFFF
    if got_crc != want_crc:
        _count_integrity_failure(name, "CRC32 mismatch")
        raise ModelIntegrityError(
            f"{name}: CRC32 mismatch (footer {want_crc:08x}, content "
            f"{got_crc:08x}) — bit flip or partial overwrite")
    return payload


def _count_integrity_failure(name: str = "<buffer>",
                             reason: str = "") -> None:
    from xgboost_tpu.obs import event
    from xgboost_tpu.profiling import reliability_metrics
    reliability_metrics().integrity_failures.inc()
    event("integrity.failure", file=name, reason=reason)


@contextlib.contextmanager
def atomic_writer(path: Union[str, os.PathLike], durable: bool = True):
    """Context manager yielding a binary file object staged in the
    destination directory; a clean exit flushes, fsyncs, ``os.replace``-s
    it over ``path`` and fsyncs the directory — :func:`atomic_write`
    for writers that STREAM (an npz archive bigger than RAM headroom
    must not be staged in memory first).  An exception unlinks the
    temp file and leaves the destination untouched.

    Streamed bytes bypass the ``faults.mutate_write`` chaos seam (it
    needs the whole payload); whole-payload writers should use
    :func:`atomic_write`."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    # mkstemp creates 0600; a plain open(path, "wb") would have given
    # 0666&~umask (and overwriting keeps the old mode) — preserve that
    # contract so a reader under another uid/gid doesn't lose access
    try:
        mode = os.stat(path).st_mode & 0o777
    except OSError:
        mask = os.umask(0)
        os.umask(mask)
        mode = 0o666 & ~mask
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            yield f
            f.flush()
            os.fchmod(f.fileno(), mode)
            if durable:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)


def atomic_write(path: Union[str, os.PathLike], data: bytes,
                 durable: bool = True) -> None:
    """Crash-safe whole-file write: tmp file in the destination
    directory -> flush -> fsync -> ``os.replace`` -> directory fsync.
    ``durable=False`` skips the fsyncs (scratch files, tests)."""
    path = os.fspath(path)
    data = faults.mutate_write(path, data)
    with atomic_writer(path, durable=durable) as f:
        f.write(data)


def read_file(path: Union[str, os.PathLike]) -> bytes:
    """Whole-file read through the fault seam (slow_read/read_flip)."""
    path = os.fspath(path)
    with open(path, "rb") as f:
        raw = f.read()
    return faults.mutate_read(path, raw)


def quarantine(path: Union[str, os.PathLike]) -> str:
    """Move a corrupt file aside as ``<path>.corrupt`` (numbered when
    that exists) so retry loops stop re-reading it and a post-mortem
    can inspect the bytes.  Returns the quarantine path."""
    path = os.fspath(path)
    dest = path + ".corrupt"
    i = 1
    while os.path.exists(dest):
        dest = f"{path}.corrupt{i}"
        i += 1
    os.replace(path, dest)
    # same dir-fsync discipline as atomic_write: the rename must be
    # durable before the next ring scan trusts it — a crash straight
    # after an unfsynced quarantine can resurrect the corrupt member
    # under its original name and send the scan into the same bytes
    dfd = os.open(os.path.dirname(os.path.abspath(dest)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    from xgboost_tpu.obs import event
    from xgboost_tpu.profiling import reliability_metrics
    reliability_metrics().quarantines.inc()
    event("integrity.quarantine", file=path, quarantined_as=dest)
    return dest

"""xgboost_tpu.reliability — crash-safe persistence + failure injection.

Three modules wired through the whole stack (design in RELIABILITY.md):

- :mod:`~xgboost_tpu.reliability.integrity` — ``atomic_write`` (tmp +
  fsync + rename + dir fsync) and a CRC32 footer scheme so every
  persisted model byte is verifiable; ``verify_model_bytes`` raises a
  typed :class:`ModelIntegrityError` on torn or bit-flipped files.
- :mod:`~xgboost_tpu.reliability.faults` — a process-wide fault
  registry generalizing the collective-seam injector
  (``parallel/mock.py``) to the I/O and serving seams: torn writes,
  bit flips, ENOSPC, slow reads, reload failures — selectable via the
  ``XGBTPU_FAULTS`` env var or the CLI ``faults=`` parameter, so chaos
  tests drive the REAL code paths.
- :mod:`~xgboost_tpu.reliability.deadline` — the stall half of the
  fault model: :class:`Deadline` budgets propagated end to end via
  ``X-Deadline-Ms`` (router admission, replica
  admission-by-service-time, batcher pre-dispatch drops), plus the
  shared :func:`jittered` / :func:`backoff_delay` timing helpers.

Consumers: ``Learner.save_model``/``load_model`` (atomic + checksummed
model files), the CLI checkpoint ring (fallback to the older replica +
quarantine on corruption), and the serving ``ModelRegistry`` (verify
before build, poisoned-fingerprint memory).
"""

from xgboost_tpu.reliability.deadline import (DEADLINE_HEADER, Deadline,
                                              DeadlineExceeded,
                                              backoff_delay, jittered)
from xgboost_tpu.reliability.faults import (InjectedFault, clear_faults,
                                            inject, install_spec)
from xgboost_tpu.reliability.integrity import (ModelIntegrityError,
                                               add_footer, atomic_write,
                                               has_footer, quarantine,
                                               read_file, verify_model_bytes)

__all__ = [
    "ModelIntegrityError",
    "atomic_write",
    "add_footer",
    "has_footer",
    "verify_model_bytes",
    "read_file",
    "quarantine",
    "InjectedFault",
    "inject",
    "clear_faults",
    "install_spec",
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "backoff_delay",
    "jittered",
]

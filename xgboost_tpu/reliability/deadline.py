"""Deadlines as a first-class value (RELIABILITY.md stall matrix).

Every failure mode the reliability layer handled before this module is
a *death* — SIGKILL, torn write, bit flip.  The reference's fault model
is wider: rabit recovers from workers that stop making *progress*, not
just workers that exit (``allreduce_robust`` timeout recovery), and at
serving scale the analog is a request whose caller has already given
up.  A caller-less request is pure waste: the router forwards it, the
replica batches it, the device executes it, and nobody reads the
answer.

:class:`Deadline` is the budget object that kills that waste.  It is
created once at the edge (the client's ``X-Deadline-Ms`` header, or the
router's ``fleet_deadline_ms`` default), and every hop *spends* from it
instead of arming a fresh timeout:

- the fleet router rejects an already-expired request before any
  dispatch, stamps the REMAINING budget onto the replica hop
  (:data:`DEADLINE_HEADER`), and bounds each forward attempt (and the
  retry-once backoff) by what is left;
- the replica rejects before any device work when the remaining budget
  cannot cover the bucket's observed service time (admission by
  deadline — a 504 up front beats a 200 that arrives after the caller
  hung up);
- the :class:`~xgboost_tpu.serving.batcher.MicroBatcher` drops expired
  entries pre-dispatch (the deadline twin of abandoned-request
  shedding).

Rejections count on ``xgbtpu_deadline_rejected_total``; batcher drops
on ``xgbtpu_deadline_dropped_total`` (both in the reliability metric
group).

All arithmetic uses ``time.monotonic()`` — a budget is a DURATION, and
an NTP step must not expire every request in flight (XGT006).

The module also hosts :func:`jittered`, the shared anti-lockstep
helper: periodic fleet loops (lease heartbeats, registry reload polls,
router health checks) multiply their period by ``uniform(1-f, 1+f)``
so a fleet restarted together does not heartbeat in phase forever.
"""

from __future__ import annotations

import random
import time
from typing import Optional

#: the one header name the router and replicas share — both sides
#: import THIS constant, so the propagation contract cannot drift
DEADLINE_HEADER = "X-Deadline-Ms"


class DeadlineExceeded(TimeoutError):
    """A request's deadline budget ran out before useful work started
    (batcher pre-dispatch drop, or an admission check).  Maps to HTTP
    504 at the serving front ends."""


class Deadline:
    """A monotonic spend-down budget for one request.

    Constructed from a millisecond budget; hops read the remaining
    budget (never the original) so queueing time anywhere in the chain
    is charged against the request, not forgiven."""

    __slots__ = ("_expires_at",)

    def __init__(self, budget_ms: float):
        self._expires_at = time.monotonic() + float(budget_ms) / 1e3

    # ------------------------------------------------------------ queries
    def remaining(self) -> float:
        """Seconds of budget left (never negative)."""
        return max(0.0, self._expires_at - time.monotonic())

    def remaining_ms(self) -> float:
        return self.remaining() * 1e3

    def expired(self) -> bool:
        return time.monotonic() >= self._expires_at

    # ------------------------------------------------------- propagation
    def header_value(self) -> str:
        """The remaining budget as the :data:`DEADLINE_HEADER` value —
        stamped fresh at every hop (propagating the ORIGINAL budget
        would hand downstream a lie)."""
        return str(int(self.remaining_ms()))

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["Deadline"]:
        """Parse a header value; None/garbage/negative -> no deadline
        (an unparseable budget must not fail a request that would have
        succeeded without one)."""
        if value is None:
            return None
        try:
            ms = float(value)
        except (TypeError, ValueError):
            return None
        if ms < 0:
            return None
        return cls(ms)

    @classmethod
    def from_headers(cls, headers) -> Optional["Deadline"]:
        """Parse from an ``email.message``-style headers mapping (the
        stdlib HTTP server's ``self.headers``)."""
        return cls.from_header(headers.get(DEADLINE_HEADER))

    def describe_ms(self) -> float:
        return round(self.remaining_ms(), 1)


def jittered(seconds: float, frac: float = 0.2) -> float:
    """``seconds`` scaled by ``uniform(1 - frac, 1 + frac)`` — the
    anti-lockstep discipline for periodic fleet loops.  A fleet of
    replicas restarted together would otherwise heartbeat (and poll,
    and health-check) in phase forever, turning every period into a
    synchronized thundering herd at the router."""
    return max(0.0, seconds) * random.uniform(1.0 - frac, 1.0 + frac)


def backoff_delay(attempt: int, base: float = 0.05,
                  cap: float = 2.0,
                  deadline: Optional[Deadline] = None) -> float:
    """Jittered exponential backoff for retry ``attempt`` (1-based),
    bounded so a deadline-carrying request never sleeps its remaining
    budget away: at most a quarter of what is left."""
    d = min(cap, base * (2 ** max(0, attempt - 1))) * random.uniform(0.5, 1.0)
    if deadline is not None:
        d = min(d, deadline.remaining() * 0.25)
    return max(0.0, d)

"""Process-wide fault registry for the I/O and serving seams.

The reference proves recovery by *injecting* failures at exact
coordinates (``AllreduceMock``, ``subtree/rabit/src/allreduce_mock.h``);
``parallel/mock.py`` carries that injector for the collective seam.
This module generalizes the idea to every other failure surface the
system persists or serves through:

============== =============================== =========================
kind            effect                          seam
============== =============================== =========================
torn_write      truncate written bytes at N     ``integrity.atomic_write``
bit_flip        flip one bit at byte N on write ``integrity.atomic_write``
enospc          raise ``OSError(ENOSPC)``       ``integrity.atomic_write``
slow_read       sleep N seconds before read     ``integrity.read_file``
read_flip       flip one bit at byte N on read  ``integrity.read_file``
reload          raise at the registry reload    ``ModelRegistry`` rebuild
heartbeat_loss  drop a lease renewal            fleet ``LeaseClient``
replica_kill    sudden replica death (no drain) fleet ``LeaseClient``
slow_replica    sleep N sec per predict         replica predict path
partition       coordinator<->worker drop N sec gang round boundary
host_loss       permanent host death, no respawn gang round boundary
============== =============================== =========================

The three fleet kinds (``@path`` matches the replica id the lease
client registered) prove the router's failure paths: ``heartbeat_loss``
lets a lease decay so the membership sweep drops the replica from
rotation; ``replica_kill`` fires the lease client's ``on_kill`` —
``os._exit(43)`` in a real replica process — without drain or
deregistration, exactly the crash the health checker + retry-once
dispatch must absorb; ``slow_replica`` wedges the predict path (arg =
seconds of added latency per request, lease + health still fine) —
the stall twin of ``replica_kill``, which the router's latency-aware
ejection (fleet/membership.py) must route around.

The two GANG kinds fire at the worker's round boundary
(``parallel/gang.py`` calls :func:`gang_fault` from
``parallel/mock.py``'s ``begin_round``), where ``@path`` matches the
coordinate string ``t<trial>.r<rank>.v<version>.`` (note the trailing
dots — ``@v2.`` targets round 2 exactly).  ``partition`` (arg =
seconds, default 5) opens a both-directions message-drop window: the
worker stops touching its heartbeat beacon and treats the
coordinator's beacon as unreadable, so after ``gang_partition_sec`` it
self-fences (RECOVERY.md degraded-mode matrix).  ``host_loss``
simulates a permanently dead host: the worker writes a tombstone and
dies with ``HOST_LOSS_RC``; because the env spec re-arms in every
respawn, the host stays dead until the launcher re-plans the gang
WITHOUT it (degraded attempts export ``XGBTPU_GANG_DEGRADED`` and skip
the host_loss check — the lost host is no longer scheduled).

Faults are armed with :func:`inject` (tests), the CLI ``faults=``
parameter, or the ``XGBTPU_FAULTS`` env var (subprocess chaos drivers,
parsed once at import).  Spec grammar, semicolon-separated::

    kind[=arg][@path_substring][*times]

(``#times`` also works, but not inside CLI config files, where ``#``
starts a comment), e.g.
``XGBTPU_FAULTS="torn_write=128@ckpt-000003;slow_read=0.05*3"``
truncates the write of the third checkpoint at byte 128 (once) and
delays the next three reads by 50 ms.  Each armed fault fires
``times`` times (default 1) and then disarms — the restarted run sails
past it, exactly the reference mock's ``ntrial`` semantics.

A spec that does not parse raises the typed :class:`FaultSpecError` at
ARM time (after emitting a ``faults.invalid_spec`` obs event), and a
bad entry arms NOTHING from the whole spec: a chaos driver with a
typo'd spec must die loudly at startup, not report a clean pass its
faults never tested.

Because the seams are the REAL production code paths (the injector
only mutates bytes or raises at them), a passing chaos suite certifies
the actual recovery logic, not a test double.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Tuple

_WRITE_KINDS = ("torn_write", "bit_flip", "enospc")
_READ_KINDS = ("slow_read", "read_flip")
_POINT_KINDS = ("reload", "heartbeat_loss", "replica_kill",
                "slow_replica")
#: gang-seam kinds (parallel/gang.py round-boundary check): the
#: @path coordinate is "t<trial>.r<rank>.v<version>."
_GANG_KINDS = ("partition", "host_loss")
_KINDS = _WRITE_KINDS + _READ_KINDS + _POINT_KINDS + _GANG_KINDS


class InjectedFault(OSError):
    """An injected (not organic) failure; carries the fault kind."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"[fault] injected {kind}"
                         + (f": {detail}" if detail else ""))
        self.kind = kind


class FaultSpecError(ValueError):
    """An ``XGBTPU_FAULTS``/``faults=`` spec failed to parse or names an
    unknown kind.  Raised at ARM time (import for the env var, ``run()``
    for the CLI param, :func:`inject` for tests) so a typo'd chaos spec
    kills the run loudly instead of silently arming nothing.
    Subclasses ``ValueError`` so pre-existing broad handlers keep
    working."""


class _Fault:
    __slots__ = ("kind", "arg", "path_sub", "remaining")

    def __init__(self, kind: str, arg: Optional[float],
                 path_sub: Optional[str], times: int):
        if kind not in _KINDS:
            raise FaultSpecError(f"unknown fault kind {kind!r}; "
                                 f"known: {', '.join(_KINDS)}")
        self.kind = kind
        self.arg = arg
        self.path_sub = path_sub
        self.remaining = int(times)

    def matches(self, path: Optional[str]) -> bool:
        if self.remaining <= 0:
            return False
        if self.path_sub is None:
            return True
        return path is not None and self.path_sub in str(path)


_registry: List[_Fault] = []
_lock = threading.Lock()
_fired: dict = {}


def inject(kind: str, arg: Optional[float] = None,
           path_sub: Optional[str] = None, times: int = 1) -> None:
    """Arm one fault (see module docstring for kinds/args)."""
    with _lock:
        _registry.append(_Fault(kind, arg, path_sub, times))


def clear_faults() -> None:
    """Disarm everything (test teardown)."""
    with _lock:
        _registry.clear()


def active() -> bool:
    with _lock:
        return any(f.remaining > 0 for f in _registry)


def fired(kind: Optional[str] = None) -> int:
    """How many faults have fired (optionally of one kind)."""
    with _lock:
        if kind is None:
            return sum(_fired.values())
        return _fired.get(kind, 0)


def _spec_error(spec: str, part: str, why: str) -> FaultSpecError:
    """Build the typed arm-time error and log it to the obs timeline
    first, so a chaos post-mortem sees WHY the run died at startup."""
    try:
        from xgboost_tpu.obs import event
        event("faults.invalid_spec", spec=spec, part=part, error=why)
    except Exception as e:  # the report must not mask the parse error
        from xgboost_tpu.obs.metrics import swallowed_error
        swallowed_error("faults.invalid_spec_event", e, emit_event=False)
    return FaultSpecError(
        f"fault spec entry {part!r}: {why} (full spec {spec!r})")


def install_spec(spec: str) -> None:
    """Parse and arm a ``kind[=arg][@path][*times];...`` spec string.
    ``#times`` is accepted as an alias everywhere EXCEPT CLI config
    files, whose parser strips ``#`` comments — use ``*times`` there.

    Fails LOUD: any unparseable entry (or a spec that reduces to zero
    entries) raises :class:`FaultSpecError` after emitting a
    ``faults.invalid_spec`` obs event, and arms NOTHING — the whole
    spec is validated before the first fault is armed, so a trailing
    typo cannot leave a half-armed chaos run."""
    parsed = []
    for raw in spec.split(";"):
        part = raw.strip()
        if not part:
            continue
        times = 1
        for sep in ("*", "#"):
            if sep in part:
                part, _, t = part.rpartition(sep)
                try:
                    times = int(t)
                except ValueError:
                    raise _spec_error(spec, raw.strip(),
                                      f"repeat count {t!r} is not an "
                                      "integer") from None
                break
        if times < 1:
            raise _spec_error(spec, raw.strip(),
                              f"repeat count {times} arms a fault that "
                              "can never fire (must be >= 1)")
        path_sub = None
        if "@" in part:
            part, _, path_sub = part.partition("@")
        arg: Optional[float] = None
        if "=" in part:
            part, _, a = part.partition("=")
            try:
                arg = float(a)
            except ValueError:
                raise _spec_error(spec, raw.strip(),
                                  f"arg {a!r} is not a number") from None
        kind = part.strip()
        if not kind:
            raise _spec_error(spec, raw.strip(), "missing fault kind")
        if kind not in _KINDS:
            raise _spec_error(spec, raw.strip(),
                              f"unknown fault kind {kind!r} (known: "
                              f"{', '.join(_KINDS)})")
        parsed.append((kind, arg, path_sub or None, times))
    if not parsed:
        raise _spec_error(spec, spec, "spec arms nothing")
    for kind, arg, path_sub, times in parsed:
        inject(kind, arg, path_sub, times)


def _take(kinds, path: Optional[str], seam: str = "") -> List[_Fault]:
    """Pop (decrement) every armed fault of the given kinds matching
    ``path``, in arm order."""
    out = []
    with _lock:
        for f in _registry:
            if f.kind in kinds and f.matches(path):
                f.remaining -= 1
                _fired[f.kind] = _fired.get(f.kind, 0) + 1
                out.append(f)
    if out:
        from xgboost_tpu.obs import event
        from xgboost_tpu.profiling import reliability_metrics
        reliability_metrics().faults_injected.inc(len(out))
        for f in out:
            # each fired fault lands in the event-log timeline (fault
            # name, seam, path; the current boosting round attaches
            # automatically) so a CHAOS.json run correlates its deaths
            # and corruptions with the rounds they hit (post-mortems
            # read the rendered tools/obs_report.py view)
            event("fault.injected", kind=f.kind,
                  seam=seam or f.kind, path=str(path) if path else None)
    return out


def _flip_bit(data: bytes, at: int) -> bytes:
    if not data:
        return data  # nothing to corrupt in an empty payload
    at = min(max(int(at), 0), len(data) - 1)
    b = bytearray(data)
    b[at] ^= 0x40
    return bytes(b)


# ------------------------------------------------------------------ seams
def mutate_write(path: str, data: bytes) -> bytes:
    """Write seam: called by ``integrity.atomic_write`` with the bytes
    about to be persisted.  May truncate (torn_write), corrupt
    (bit_flip), or raise ``OSError(ENOSPC)``."""
    for f in _take(_WRITE_KINDS, path, seam="write"):
        if f.kind == "enospc":
            import errno
            raise OSError(errno.ENOSPC,
                          f"[fault] injected ENOSPC writing {path}")
        if f.kind == "torn_write":
            n = int(f.arg if f.arg is not None else len(data) // 2)
            data = data[:n]
        elif f.kind == "bit_flip":
            data = _flip_bit(data, f.arg if f.arg is not None
                             else len(data) // 2)
    return data


def mutate_read(path: str, data: bytes) -> bytes:
    """Read seam: called by ``integrity.read_file`` with the bytes just
    read.  May delay (slow_read) or corrupt (read_flip)."""
    for f in _take(_READ_KINDS, path, seam="read"):
        if f.kind == "slow_read":
            time.sleep(float(f.arg if f.arg is not None else 0.05))
        elif f.kind == "read_flip":
            data = _flip_bit(data, f.arg if f.arg is not None
                             else len(data) // 2)
    return data


def check(point: str, path: Optional[str] = None) -> None:
    """Named-point seam (currently ``reload``: the registry's engine
    rebuild).  Raises :class:`InjectedFault` when armed."""
    if _take((point,), path, seam=point):
        raise InjectedFault(point, str(path) if path else "")


def delay_for(point: str, path: Optional[str] = None) -> float:
    """Delay seam (``slow_replica``): seconds the calling hot path
    should sleep, summed over every armed matching fault (0.0 = none).
    Unlike :func:`check` this never raises — a wedged-but-alive
    component keeps answering, just late, which is exactly the failure
    the latency-ejection machinery exists for."""
    return sum(float(f.arg if f.arg is not None else 0.25)
               for f in _take((point,), path, seam=point))


def gang_fault(path: str) -> List[Tuple[str, Optional[float]]]:
    """Gang seam (``parallel/gang.py``): fire every armed gang fault
    matching the round coordinate ``t<trial>.r<rank>.v<version>.`` and
    return ``(kind, arg)`` pairs — ``("partition", seconds)`` opens a
    message-drop window, ``("host_loss", _)`` is a permanent host
    death.  The caller owns the effects; this just pops coordinates
    (and logs ``fault.injected``, like every other seam)."""
    return [(f.kind, f.arg)
            for f in _take(_GANG_KINDS, path, seam="gang")]


# subprocess chaos drivers arm faults via the environment; parse once at
# import so any seam hit afterwards sees them
if os.environ.get("XGBTPU_FAULTS"):
    install_spec(os.environ["XGBTPU_FAULTS"])

"""Fresh-data seam for the continuous-training pipeline (PIPELINE.md).

A :class:`DataSource` answers one question per cycle: *what does the
trainer append trees on, and what does the gate judge on?*  The seam is
deliberately tiny — ``next_cycle(cycle) -> (dtrain, dholdout) | None``
— so production feeds (a directory a log-shipper drops files into, a
feature-store export, a queue consumer) plug in without touching the
trainer.

Determinism contract: for a given ``cycle`` index the source must hand
back the SAME data on every call — a cycle killed mid-train resumes
from the checkpoint ring and re-reads its data, and the resumed run
must be bit-identical to an uninterrupted one (the chaos harness
asserts exactly this).  :class:`FileDataSource` satisfies it by
re-reading the same files; :class:`SyntheticDataSource` by seeding its
generator with ``fold(seed, cycle)``.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple


class DataSource:
    """Pluggable fresh-data feed.  ``next_cycle`` returns the cycle's
    ``(train DMatrix, holdout DMatrix)`` pair, or ``None`` when no
    fresh data is available yet (the trainer idles and retries)."""

    def next_cycle(self, cycle: int):
        raise NotImplementedError

    def holdout_for(self, cycle: int):
        """The holdout window ALONE, or None when unavailable.  The
        crash-recovery re-gate needs no fresh train data — a producer
        that rotated the cycle's train file away between the kill and
        the restart must not wedge the re-gate forever.  Default:
        the pair's second element."""
        data = self.next_cycle(cycle)
        return None if data is None else data[1]


class FileDataSource(DataSource):
    """Per-cycle file feed: ``train_path`` may carry a ``{cycle}``
    placeholder (``fresh-{cycle}.libsvm``) that substitutes the cycle
    index — the producer-drops-a-file-per-window idiom; without the
    placeholder the same path is re-read every cycle (the producer
    rewrites it in place, atomically).  ``holdout_path`` is the fixed
    held-out eval window; it is re-loaded only when its (mtime, size)
    changes, so a long-running pipeline does not re-parse an unchanged
    holdout every cycle."""

    def __init__(self, train_path: str, holdout_path: str,
                 silent: bool = True):
        self.train_path = train_path
        self.holdout_path = holdout_path
        self.silent = silent
        self._holdout = None
        self._holdout_stat = None

    def _resolve(self, cycle: int) -> str:
        return self.train_path.replace("{cycle}", str(cycle))

    def _load_holdout(self):
        st = os.stat(self.holdout_path)
        stat = (st.st_mtime_ns, st.st_size)
        if self._holdout is None or stat != self._holdout_stat:
            from xgboost_tpu.data import DMatrix
            self._holdout = DMatrix(self.holdout_path, silent=self.silent)
            self._holdout_stat = stat
        return self._holdout

    def next_cycle(self, cycle: int):
        path = self._resolve(cycle)
        if not os.path.exists(path) or not os.path.exists(
                self.holdout_path):
            return None
        from xgboost_tpu.data import DMatrix
        return (DMatrix(path, silent=self.silent), self._load_holdout())

    def holdout_for(self, cycle: int):
        # independent of the cycle's train file: a re-gate after the
        # producer rotated it away still has its holdout
        if not os.path.exists(self.holdout_path):
            return None
        return self._load_holdout()


class SyntheticDataSource(DataSource):
    """Deterministic synthetic stream (bench + chaos + tests): cycle
    ``k`` draws ``n_rows`` fresh rows from a generator seeded with
    ``seed + k + 1`` against a fixed target function, and the holdout
    is one fixed draw at ``seed``.  Same cycle index, same bytes —
    the determinism contract the resume path needs, with zero files."""

    def __init__(self, n_rows: int = 512, n_features: int = 8,
                 seed: int = 0):
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self.seed = int(seed)
        self._holdout = None

    def _draw(self, seed: int, n: int):
        import numpy as np

        from xgboost_tpu.data import DMatrix
        rng = np.random.RandomState(seed)
        X = rng.rand(n, self.n_features).astype(np.float32)
        y = ((X[:, 0] + 0.25 * X[:, 1]) > 0.6).astype(np.float32)
        return DMatrix(X, label=y)

    def next_cycle(self, cycle: int):
        if self._holdout is None:
            self._holdout = self._draw(self.seed, max(self.n_rows, 256))
        return (self._draw(self.seed + cycle + 1, self.n_rows),
                self._holdout)


class CallableDataSource(DataSource):
    """Wrap a plain ``cycle -> (dtrain, dholdout) | None`` function
    (tests, notebooks)."""

    def __init__(self, fn: Callable[[int], Optional[Tuple]]):
        self.fn = fn

    def next_cycle(self, cycle: int):
        return self.fn(cycle)

"""ContinuousTrainer: the train → gate → publish cycle loop.

One cycle (PIPELINE.md has the full state machine and failure matrix):

1. **warm-start** — load the incumbent from the publish path through
   the CRC-verified load path (``Booster.load_model``); cold start
   trains from scratch when nothing is published yet.
2. **train** — append ``rounds_per_cycle`` boosting rounds on the
   cycle's fresh data (the :class:`~.datasource.DataSource` seam)
   through the segmented fused driver (``Booster.update_many``:
   ``rounds_per_dispatch`` rounds per device dispatch), checkpointing
   at every segment boundary into the same two-member checkpoint ring
   the CLI uses — a SIGKILL mid-train (even mid-SEGMENT) resumes from
   the ring and, because the data source is deterministic per cycle
   and seeding is per-iteration, finishes bit-identical to an
   uninterrupted cycle.
3. **gate** — verify the candidate file's CRC, then score candidate vs
   incumbent on the held-out window (:class:`~.gate.EvalGate`).  A
   failing (or corrupt) candidate is quarantined and the incumbent
   keeps serving untouched.
4. **publish** — append the candidate's hash to the ``gated.log``
   ledger (fsync'd BEFORE any byte reaches the publish path — the
   chaos harness proves "no unverified/ungated model is ever served"
   against this ledger), then hand the candidate to the
   :class:`~.publisher.Publisher` (direct atomic swap, or the fleet
   canary lane).

Crash discipline: every persisted artifact is atomic (state file,
candidate, publish) or append-only (ledger), and the recorded phase is
re-entered conservatively on restart — a process that died anywhere
past training **re-gates** the candidate from its bytes rather than
trusting a pre-crash verdict.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import time
from typing import Optional

from xgboost_tpu.obs import event, span
from xgboost_tpu.obs.metrics import pipeline_metrics
from xgboost_tpu.pipeline.datasource import DataSource
from xgboost_tpu.pipeline.gate import EvalGate
from xgboost_tpu.pipeline.publisher import Publisher, PublishRejected

_STATE_FILE = "state.json"
_CANDIDATE = "candidate.model"
_GATED_LOG = "gated.log"


class ContinuousTrainer:
    """Owns one publish path: warm-starts from it, appends trees on
    fresh data, and republishes through the gate."""

    def __init__(self, publish_path: str, source: DataSource,
                 workdir: str, rounds_per_cycle: int = 5,
                 params: Optional[dict] = None,
                 gate: Optional[EvalGate] = None,
                 publisher: Optional[Publisher] = None,
                 quiet: bool = False, lane: str = ""):
        self.publish_path = publish_path
        self.source = source
        self.workdir = workdir
        self.rounds_per_cycle = int(rounds_per_cycle)
        self.params = dict(params or {})
        # tenant lane name: tags every pipeline event/log line so N
        # concurrent per-model lanes stay attributable in one stream
        self.lane = lane
        self.gate = gate if gate is not None else EvalGate()
        self.publisher = (publisher if publisher is not None
                          else Publisher(publish_path))
        self.quiet = quiet
        self.ckpt_dir = os.path.join(workdir, "ckpt")
        self.candidate_path = os.path.join(workdir, _CANDIDATE)
        self.quarantine_dir = os.path.join(workdir, "quarantine")
        self.state_path = os.path.join(workdir, _STATE_FILE)
        self.gated_log = os.path.join(workdir, _GATED_LOG)
        # verified copy of the last published bytes: the incumbent's
        # ring replica (bit rot on the publish path restores from here)
        self.backup_path = os.path.join(workdir, "incumbent.model")
        self.metrics = pipeline_metrics()
        os.makedirs(workdir, exist_ok=True)

    # --------------------------------------------------------------- state
    def _read_state(self) -> dict:
        """The persisted cycle cursor.  Unreadable/missing state resets
        to a fresh cycle-0 train — the artifacts themselves (candidate
        CRC, ring verification, ledger) carry the safety, the state
        file only carries the cursor."""
        try:
            with open(self.state_path, encoding="utf-8") as f:
                st = json.load(f)
            return st if isinstance(st, dict) else {}
        except (OSError, ValueError):
            return {}

    def _write_state(self, st: dict) -> None:
        from xgboost_tpu.reliability.integrity import atomic_write
        atomic_write(self.state_path,
                     (json.dumps(st, sort_keys=True) + "\n").encode())

    def _event(self, name: str, **kw) -> None:
        if self.lane:
            kw.setdefault("lane", self.lane)
        event(name, **kw)

    def _say(self, msg: str) -> None:
        if not self.quiet:
            tag = f"pipeline:{self.lane}" if self.lane else "pipeline"
            print(f"[{tag}] {msg}", file=sys.stderr)

    def _data(self, cycle: int):
        """Memoized per-cycle (dtrain, dholdout): the gate runs in the
        same process right after training, and re-parsing the cycle's
        files for it would double the ingest cost."""
        memo = getattr(self, "_data_memo", None)
        if memo is not None and memo[0] == cycle:
            return memo[1]
        data = self.source.next_cycle(cycle)
        self._data_memo = (cycle, data) if data is not None else None
        return data

    # ---------------------------------------------------------------- ring
    def _clear_ring(self) -> None:
        if not os.path.isdir(self.ckpt_dir):
            return
        for name in os.listdir(self.ckpt_dir):
            if re.fullmatch(r"ckpt-\d{6}\.model(\.corrupt\d*)?", name):
                try:
                    os.remove(os.path.join(self.ckpt_dir, name))
                except OSError:
                    pass  # xgtpu: disable=XGT004 — best-effort cleanup

    # --------------------------------------------------------------- train
    def _load_incumbent(self):
        """The currently-published model, or None on cold start.

        A publish-path file that fails its CRC (bit rot, external
        tamper — never a torn publish, those are atomic) is healed from
        the incumbent ring replica (``incumbent.model``, the verified
        bytes of our last publish): the corrupt file is quarantined and
        the backup atomically restored, so pollers and replica restarts
        come back on a gated model.  With no restorable backup the
        cycle ABORTS (never silently train from scratch and publish
        OVER a lineage we merely failed to read)."""
        if not os.path.exists(self.publish_path):
            return None
        from xgboost_tpu.learner import Booster
        from xgboost_tpu.reliability.integrity import ModelIntegrityError
        bst = Booster(dict(self.params))
        try:
            bst.load_model(self.publish_path)  # CRC-verified
        except ModelIntegrityError as e:
            self._restore_incumbent(e)
            bst = Booster(dict(self.params))
            bst.load_model(self.publish_path)
        bst.set_param(dict(self.params))
        return bst

    def _restore_incumbent(self, cause: Exception) -> None:
        """Quarantine the corrupt publish-path file and restore the
        last published (verified, gated) bytes from the backup."""
        from xgboost_tpu.reliability.integrity import (atomic_write,
                                                       quarantine,
                                                       read_file,
                                                       verify_model_bytes)
        raw = read_file(self.backup_path)  # OSError -> cycle aborts
        verify_model_bytes(raw, name=self.backup_path)
        try:
            qpath = quarantine(self.publish_path)
        except OSError:
            qpath = None  # xgtpu: disable=XGT004 — restore still heals
        atomic_write(self.publish_path, raw)
        self._event("pipeline.incumbent_restored", path=self.publish_path,
              quarantined_as=qpath, cause=str(cause))
        self._say(f"publish path failed verification ({cause}); "
                  "restored the last published model from the backup")

    def _prepare_booster(self, bst, cycle: int) -> None:
        """Per-cycle booster hook, run after any ring resume and before
        the first boosted round.  The base trainer does nothing; the
        stream trainer reapplies drift state (cut rebinds, EMA-FS
        feature screens) that model bytes alone do not carry."""

    def _boost_rounds(self, bst, dtrain, it0: int, n_rounds: int,
                      segment_callback) -> None:
        """The cycle's boosting call — the one seam the gang-batched
        lane driver (pipeline/lanes.py) overrides to route rounds
        through a shared multi-tenant dispatch instead of this
        booster's own ``update_many``.  Everything around it (resume,
        gate, publish, ledger) stays per-tenant and host-side."""
        bst.update_many(dtrain, it0, n_rounds,
                        segment_callback=segment_callback)

    def _train(self, cycle: int, st: dict) -> Optional[str]:
        """Train the cycle's candidate; returns its path, or None when
        the source has no fresh data yet."""
        data = self._data(cycle)
        if data is None:
            return None
        dtrain, _ = data
        resuming = (st.get("phase") == "train"
                    and st.get("cycle") == cycle
                    and os.path.isdir(self.ckpt_dir))
        if not resuming:
            self._clear_ring()
            self._write_state({"cycle": cycle, "phase": "train"})
        from xgboost_tpu.cli import _load_checkpoint, _save_checkpoint
        from xgboost_tpu.learner import Booster
        bst = self._load_incumbent()
        if bst is None:
            bst = Booster(dict(self.params))
        appended = 0
        if resuming:
            # mid-train SIGKILL: the ring holds the incumbent + the
            # rounds appended so far; a corrupt newest member falls
            # back to the older replica (cli._load_checkpoint)
            bst, appended = _load_checkpoint(self.ckpt_dir, bst,
                                             dict(self.params))
            if appended:
                self.metrics.resumes.inc()
                self._event("pipeline.resume", cycle=cycle, phase="train",
                      appended_rounds=appended)
                self._say(f"cycle {cycle}: resumed mid-train at "
                          f"appended round {appended}")
        # after the ring resume: ring bytes already carry any refreshed
        # cuts, but per-cycle state that is NOT serialized in model
        # bytes (e.g. the stream trainer's feature screen) must be
        # re-applied here, on fresh runs and resumes alike
        self._prepare_booster(bst, cycle)
        with span("pipeline.train", cycle=cycle, resumed=appended):
            if appended < self.rounds_per_cycle:
                # iteration index continues the incumbent's numbering,
                # so per-iteration seeding (fold_in) matches what one
                # long uninterrupted training run would have used
                it0 = (bst.gbtree.num_boosted_rounds
                       if bst.gbtree is not None else 0)
                base = it0 - appended  # the incumbent's own rounds

                def seg_cb(last_i: int) -> None:
                    # ring checkpoint at every fused segment boundary
                    # (per round when fusion is ineligible): a SIGKILL
                    # inside a segment resumes from the last boundary
                    # member and — deterministic per-iteration seeding —
                    # retrains the lost tail bit-identically
                    _save_checkpoint(self.ckpt_dir, bst,
                                     last_i + 1 - base)

                self._boost_rounds(bst, dtrain, it0,
                                   self.rounds_per_cycle - appended,
                                   seg_cb)
            bst.save_model(self.candidate_path)  # atomic + CRC
        self._write_state({"cycle": cycle, "phase": "gate"})
        return self.candidate_path

    # ---------------------------------------------------------------- gate
    def _judge(self, cycle: int) -> dict:
        """Verify + score the candidate file against the incumbent.
        Returns the verdict dict (``passed`` False for corrupt or
        gate-failing candidates)."""
        from xgboost_tpu.learner import Booster
        from xgboost_tpu.reliability.integrity import (read_file,
                                                       verify_model_bytes)
        # the gate needs ONLY the holdout: a crash-recovery re-gate
        # must not wedge because the producer rotated the cycle's
        # train file away between the kill and the restart
        memo = getattr(self, "_data_memo", None)
        if memo is not None and memo[0] == cycle:
            holdout = memo[1][1]
        else:
            holdout = self.source.holdout_for(cycle)
        if holdout is None:
            raise RuntimeError(
                f"cycle {cycle}: holdout unavailable for the gate")
        with span("pipeline.gate", cycle=cycle):
            try:
                raw = read_file(self.candidate_path)
                cand = Booster()
                cand.load_raw(verify_model_bytes(raw,
                                                 name=self.candidate_path),
                              name=self.candidate_path)
            except (OSError, ValueError) as e:
                # ValueError covers ModelIntegrityError: a candidate
                # corrupted between save and gate never publishes
                return {"passed": False, "verified": False,
                        "reason": f"candidate failed verification: {e}"}
            verdict = self._judge_vs_incumbent(cand, holdout, cycle)
            verdict["verified"] = True
            verdict["model_hash"] = hashlib.sha256(raw).hexdigest()
        self._event("pipeline.gate", cycle=cycle, passed=verdict["passed"],
              metric=verdict.get("metric"),
              candidate=verdict.get("candidate"),
              incumbent=verdict.get("incumbent"),
              reason=verdict.get("reason"))
        return verdict

    def _publish_hash(self) -> Optional[str]:
        try:
            with open(self.publish_path, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def _judge_vs_incumbent(self, cand, holdout, cycle: int) -> dict:
        """Run the gate, reusing the cached incumbent holdout score
        when the published bytes, the holdout object, and the gate are
        all unchanged — the incumbent's score can only move when a
        publish (or a bit-rot restore) lands, so steady-state cycles
        skip one full model load + one full holdout evaluation."""
        inc_hash = self._publish_hash()
        cache = getattr(self, "_incumbent_cache", None)
        gate_key = (id(self.gate), self.gate.metric)
        if (inc_hash is not None and cache is not None
                and cache["hash"] == inc_hash
                and cache["holdout_id"] == id(holdout)
                and cache["gate_key"] == gate_key):
            verdict = self.gate.judge(cand, None, holdout, cycle,
                                      incumbent_score=cache["score"])
            inc_trees = cache["num_trees"]
        else:
            incumbent = (self._load_incumbent()
                         if inc_hash is not None else None)
            verdict = self.gate.judge(cand, incumbent, holdout, cycle)
            inc_trees = (incumbent.gbtree.num_trees
                         if incumbent is not None
                         and incumbent.gbtree is not None else 0)
            if incumbent is not None and verdict.get(
                    "incumbent") is not None:
                # re-hash AFTER the load: _load_incumbent may have
                # healed a corrupt publish path from the backup
                self._incumbent_cache = {
                    "hash": self._publish_hash(),
                    "holdout_id": id(holdout), "gate_key": gate_key,
                    "score": verdict["incumbent"],
                    "num_trees": inc_trees}
        verdict["new_trees"] = cand.gbtree.num_trees - inc_trees
        return verdict

    def _quarantine(self, cycle: int, verdict: dict) -> None:
        """Move the rejected candidate aside (numbered, never clobbers
        an earlier cycle's evidence) so the publish path can never pick
        it up and a post-mortem can inspect it."""
        if not os.path.exists(self.candidate_path):
            return
        os.makedirs(self.quarantine_dir, exist_ok=True)
        dest = os.path.join(self.quarantine_dir,
                            f"cycle-{cycle:04d}.model")
        i = 1
        while os.path.exists(dest):
            dest = os.path.join(self.quarantine_dir,
                                f"cycle-{cycle:04d}.model.{i}")
            i += 1
        os.replace(self.candidate_path, dest)
        self.metrics.quarantines.inc()
        self._event("pipeline.quarantine", cycle=cycle, quarantined_as=dest,
              reason=verdict.get("reason"))
        self._say(f"cycle {cycle}: candidate quarantined as {dest} "
                  f"({verdict.get('reason')})")

    def _record_gated(self, cycle: int, model_hash: str) -> None:
        """Append the approved hash to the gated ledger, durably,
        BEFORE any publish byte moves: every hash that can ever appear
        at the publish path is in this file first (the chaos harness'
        zero-ungated-models contract reads it).  Append-only by design
        — a crash tears at most the final line."""
        with open(self.gated_log, "ab") as f:
            f.write(f"{cycle} {model_hash}\n".encode())
            f.flush()
            os.fsync(f.fileno())

    # ------------------------------------------------------------- publish
    def _refresh_backup(self) -> None:
        """Incumbent ring replica: the just-published candidate bytes,
        kept in the workdir so later publish-path bit rot is
        recoverable.  Best-effort — the publish itself already
        succeeded; a failed backup only costs future healing."""
        from xgboost_tpu.reliability.integrity import (atomic_write,
                                                       read_file)
        try:
            atomic_write(self.backup_path,
                         read_file(self.candidate_path))
        except OSError as e:
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("pipeline.backup", e)

    def _publish(self, cycle: int, verdict: dict) -> dict:
        pm = self.metrics
        t0 = time.perf_counter()
        try:
            pub = self.publisher.publish(self.candidate_path)
        except PublishRejected:
            pm.publish_failures.inc()
            raise
        except (OSError, ValueError):
            # I/O failure (ENOSPC, fault injection) or bytes that no
            # longer verify: the publish path still holds the complete
            # incumbent (atomic_write); the phase stays "publish" and
            # the next attempt re-gates + retries
            pm.publish_failures.inc()
            raise
        pm.publishes.inc()
        pm.publish_seconds.inc(time.perf_counter() - t0)
        pm.trees_published.inc(max(0, int(verdict.get("new_trees", 0))))
        pm.note_publish()
        self._refresh_backup()
        return pub

    def _already_published(self) -> Optional[str]:
        """The candidate's verified bytes already sit at the publish
        path → its hash (the publish completed; only the epilogue was
        lost); else None.  Membership in the gated ledger is implied —
        publishing is unreachable before :meth:`_record_gated`."""
        from xgboost_tpu.reliability.integrity import (ModelIntegrityError,
                                                       verify_model_bytes)
        try:
            with open(self.candidate_path, "rb") as f:
                cand = f.read()
            with open(self.publish_path, "rb") as f:
                pub = f.read()
        except OSError:
            return None
        if cand != pub:
            return None
        try:
            verify_model_bytes(cand, name=self.candidate_path)
        except ModelIntegrityError:
            return None  # let the re-gate quarantine it
        return hashlib.sha256(cand).hexdigest()

    def _finalize_published(self, cycle: int, model_hash: str) -> None:
        """Lost epilogue of a completed publish: refresh the incumbent
        ring replica (the crash may also have landed between the
        publish and the backup write, which would leave a later
        bit-rot heal restoring a one-generation-stale model) and
        re-stamp the metrics the dead process took with it."""
        self._refresh_backup()
        self.metrics.note_publish()
        self._event("pipeline.publish", path=self.publish_path,
              model_hash=model_hash, resumed=True)

    # --------------------------------------------------------------- cycle
    def run_cycle(self) -> dict:
        """One full cycle from whatever phase the persisted state is in
        (a fresh train, or crash recovery: mid-train ring resume /
        re-gate of an already-trained candidate).  Returns an outcome
        dict with ``status`` in ``published | gate_failed |
        publish_rejected | idle``."""
        pm = self.metrics
        st = self._read_state()
        cycle = int(st.get("cycle", 0))
        phase = st.get("phase", "train")
        t0 = time.perf_counter()
        try:
            with span("pipeline.cycle", cycle=cycle, start_phase=phase):
                if phase == "train" or not os.path.exists(
                        self.candidate_path):
                    if self._train(cycle, st) is None:
                        return {"cycle": cycle, "status": "idle"}
                else:
                    # died past training: RE-GATE the candidate from its
                    # bytes — a pre-crash verdict is not trusted
                    pm.resumes.inc()
                    self._event("pipeline.resume", cycle=cycle, phase=phase)
                    done_hash = self._already_published()
                    if done_hash is not None:
                        # the crash landed BETWEEN a completed publish
                        # and the cursor advance: the candidate IS the
                        # incumbent now.  Finalize instead of re-gating
                        # it against itself — with min_delta > 0 the
                        # zero self-improvement would quarantine the
                        # live, already-serving model
                        self._finalize_published(cycle, done_hash)
                        self._advance(cycle)
                        self._say(f"cycle {cycle}: publish had already "
                                  "completed before the crash; finalized")
                        return {"cycle": cycle, "status": "published",
                                "resumed": True,
                                "publish": {"mode": "resumed",
                                            "path": self.publish_path,
                                            "model_hash": done_hash}}
                    self._say(f"cycle {cycle}: resumed at phase "
                              f"{phase!r}; re-gating candidate")
                verdict = self._judge(cycle)
                if not verdict["passed"]:
                    pm.gate_fail.inc()
                    self._quarantine(cycle, verdict)
                    self._advance(cycle)
                    return {"cycle": cycle, "status": "gate_failed",
                            "gate": verdict}
                pm.gate_pass.inc()
                self._record_gated(cycle, verdict["model_hash"])
                self._write_state({"cycle": cycle, "phase": "publish"})
                try:
                    pub = self._publish(cycle, verdict)
                except PublishRejected as e:
                    # the fleet's canary lane vetoed it: quarantine like
                    # a local gate failure (the router already rolled
                    # the canaries back)
                    self._quarantine(cycle, {
                        "reason": f"rollout rejected: "
                                  f"{e.report.get('reason', e.report.get('error'))}"})
                    self._advance(cycle)
                    return {"cycle": cycle, "status": "publish_rejected",
                            "gate": verdict, "report": e.report}
                self._advance(cycle)
                self._say(f"cycle {cycle}: published "
                          f"{verdict['new_trees']} new trees "
                          f"({verdict.get('metric')} "
                          f"{verdict.get('candidate')})")
                return {"cycle": cycle, "status": "published",
                        "gate": verdict, "publish": pub}
        finally:
            pm.cycles.inc()
            pm.cycle_seconds.observe(time.perf_counter() - t0)

    def _advance(self, cycle: int) -> None:
        """Cycle epilogue: drop the ring (its members belong to the
        finished cycle) and move the cursor."""
        self._clear_ring()
        try:
            if os.path.exists(self.candidate_path):
                os.remove(self.candidate_path)
        except OSError:
            pass  # xgtpu: disable=XGT004 — best-effort cleanup
        self._write_state({"cycle": cycle + 1, "phase": "train"})

    # ----------------------------------------------------------------- run
    def run(self, cycles: int = 0, sleep_sec: float = 0.0) -> dict:
        """Drive ``cycles`` cycles (0 = forever).  Per-cycle exceptions
        are contained: the error is logged + counted and the loop
        continues — the persisted phase means the next attempt resumes
        (or re-gates) instead of redoing finished work."""
        summary = {"cycles": 0, "published": 0, "gate_failed": 0,
                   "publish_rejected": 0, "idle": 0, "errors": 0}
        while cycles <= 0 or summary["cycles"] < cycles:
            summary["cycles"] += 1
            try:
                out = self.run_cycle()
            except Exception as e:
                summary["errors"] += 1
                self._event("pipeline.cycle_error",
                      error=f"{type(e).__name__}: {e}")
                self._say(f"cycle error ({type(e).__name__}: {e}); "
                          "will retry from the persisted phase")
                out = {"status": "error"}
            else:
                summary[out["status"]] = summary.get(out["status"], 0) + 1
            if out.get("status") in ("idle", "error"):
                time.sleep(max(sleep_sec, 0.05))
            elif sleep_sec > 0:
                time.sleep(sleep_sec)
        return summary

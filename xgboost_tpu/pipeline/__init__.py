"""xgboost_tpu.pipeline — continuous training next to a live fleet.

The composition layer that turns five standalone subsystems into one
story (PIPELINE.md): warm-start continuation (learner), the checkpoint
ring + CRC'd atomic persistence (reliability), the hot-reload registry
the serving tier polls (serving), the canary rollout lane (fleet), and
per-phase spans/metrics (obs).  A :class:`ContinuousTrainer` loads the
currently-published model, appends trees on fresh data from a
pluggable :class:`DataSource`, judges the candidate against the
incumbent on a held-out window (:class:`EvalGate`), and atomically
publishes gated models to the path the serving fleet watches
(:class:`Publisher` / :class:`RolloutPublisher`) — surviving
kill/corrupt at every boundary.

Quickstart::

    python -m xgboost_tpu task=pipeline \\
        pipeline_publish_path=serving/model.bin \\
        pipeline_data=fresh-{cycle}.libsvm pipeline_holdout=holdout.libsvm \\
        pipeline_rounds_per_cycle=5 pipeline_cycles=0 \\
        objective=binary:logistic max_depth=4
"""

from typing import Optional

from xgboost_tpu.pipeline.datasource import (CallableDataSource,  # noqa: F401
                                             DataSource, FileDataSource,
                                             SyntheticDataSource)
from xgboost_tpu.pipeline.gate import EvalGate  # noqa: F401
from xgboost_tpu.pipeline.publisher import (Publisher,  # noqa: F401
                                            PublishRejected,
                                            RolloutPublisher)
from xgboost_tpu.pipeline.trainer import ContinuousTrainer  # noqa: F401


def run_pipeline(publish_path: str, workdir: str = "./pipeline",
                 data: str = "", holdout: str = "",
                 rounds_per_cycle: int = 5, cycles: int = 1,
                 metric: str = "", min_delta: float = 0.0,
                 max_regression: float = 0.0, router_url: str = "",
                 publish_timeout_sec: float = 600.0,
                 sleep_sec: float = 0.0,
                 params: Optional[dict] = None,
                 source: Optional[DataSource] = None,
                 quiet: bool = False, lane: str = "",
                 trainer_cls=None) -> dict:
    """Assemble the default pipeline from flat knob values (the CLI
    ``task=pipeline`` surface — every ``PIPELINE_PARAMS`` key maps to
    one argument) and run it.  ``source`` overrides the file seam for
    embedders.  ``lane`` names the catalog tenant this pipeline trains:
    its events are lane-tagged, a router publish is scoped to that
    model's hosting replicas (per-tenant rollout), and — unless params
    pin one — the booster seed derives from the lane NAME, so a
    tenant's model bytes never depend on which neighbors it shares a
    process (or a gang-batched stack) with.  ``trainer_cls`` swaps the
    trainer implementation (the gang-batched lane driver passes a
    :class:`~xgboost_tpu.pipeline.lanes.GangTrainer` factory)."""
    if not publish_path:
        raise ValueError("pipeline_publish_path is required")
    if source is None:
        if not data or not holdout:
            raise ValueError(
                "pipeline_data and pipeline_holdout are required "
                "(or pass a custom DataSource)")
        source = FileDataSource(data, holdout)
    if lane and "seed" not in (params or {}):
        import zlib
        params = dict(params or {})
        params["seed"] = zlib.crc32(lane.encode("utf-8")) & 0x7FFFFFFF
    gate = EvalGate(metric=metric, min_delta=min_delta,
                    max_regression=max_regression)
    publisher = (RolloutPublisher(publish_path, router_url,
                                  timeout=publish_timeout_sec,
                                  model=lane)
                 if router_url else Publisher(publish_path))
    trainer = (trainer_cls or ContinuousTrainer)(
        publish_path, source, workdir,
        rounds_per_cycle=rounds_per_cycle, params=params, gate=gate,
        publisher=publisher, quiet=quiet, lane=lane)
    return trainer.run(cycles=cycles, sleep_sec=sleep_sec)


def run_tenant_lanes(lanes: dict, quiet: bool = False,
                     max_workers: Optional[int] = None,
                     stacked: Optional[bool] = None,
                     window_sec: float = 0.2) -> dict:
    """Run one training lane per catalog tenant, concurrently.

    ``lanes`` maps a tenant/model name to a :func:`run_pipeline` kwargs
    dict (each lane needs its OWN ``publish_path``/``workdir``; the
    lane name is injected as ``lane=`` unless the kwargs override it).
    Every lane keeps the full single-pipeline crash discipline — its
    own fsync'd ``gated.log`` ledger, quarantine dir, and checkpoint
    ring live under its own workdir, so the zero-ungated-models
    contract holds PER TENANT.  Lanes are isolated: one lane raising
    (or gate-failing forever) never stalls or poisons its neighbors —
    the error is contained in that lane's summary entry.

    Two execution modes, byte-identical per tenant:

    - **stacked** (default): same-shape lanes gang-batch their boosting
      rounds into ONE vmapped device dispatch per round segment
      (:mod:`xgboost_tpu.pipeline.lanes`); gate/publish/ledger fan-out
      stays host-side per lane.  ``XGBTPU_LANE_STACK=0`` (or
      ``stacked=False``) forces the host loop — the A/B baseline.
    - **host loop**: each lane is a fully independent pipeline run,
      bounded to ``max_workers`` concurrent lanes (default
      ``min(len(lanes), 8)``).
    """
    import os
    import threading

    from xgboost_tpu.obs import event

    if stacked is None:
        stacked = os.environ.get("XGBTPU_LANE_STACK", "1") not in ("0",)
    if stacked:
        from xgboost_tpu.pipeline.lanes import run_tenant_lanes_stacked
        return run_tenant_lanes_stacked(lanes, quiet=quiet,
                                        window_sec=window_sec,
                                        max_workers=max_workers)

    results: dict = {}
    rlock = threading.Lock()
    if max_workers is None:
        max_workers = min(len(lanes), 8)
    max_workers = max(1, min(int(max_workers), len(lanes))) if lanes else 0

    def _one(name: str, kw: dict) -> None:
        kw = dict(kw)
        kw.setdefault("lane", name)
        kw.setdefault("quiet", quiet)
        try:
            summary = run_pipeline(**kw)
            with rlock:
                results[name] = {"status": "ok", "summary": summary}
        except Exception as e:  # lane isolation: never kill siblings
            with rlock:
                results[name] = {"status": "error",
                                 "error": f"{type(e).__name__}: {e}"}
            event("pipeline.lane_error", lane=name,
                  error=f"{type(e).__name__}: {e}")

    pending = list(lanes)
    plock = threading.Lock()

    def _worker() -> None:
        while True:
            with plock:
                if not pending:
                    return
                name = pending.pop(0)
            _one(name, lanes[name])

    threads = [threading.Thread(target=_worker, name=f"lane-worker-{i}",
                                daemon=True)
               for i in range(max_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


__all__ = [
    "ContinuousTrainer", "DataSource", "FileDataSource",
    "SyntheticDataSource", "CallableDataSource", "EvalGate",
    "Publisher", "RolloutPublisher", "PublishRejected", "run_pipeline",
    "run_tenant_lanes",
]

"""Gang-batched tenant lanes — N same-shape boosters, ONE dispatch.

The catalog trains thousands of small per-tenant models; the host-loop
``run_tenant_lanes`` gives each its own thread and its own device
dispatch stream, so lane count costs dispatches (ROADMAP: "the biggest
single lever for millions-of-users economics").  This module is the
training twin of the serving engine's power-of-two shape buckets: a
packer groups tenant lanes by their fused-scan compilation shape, pads
each bucket to a power-of-two stack width, and vmaps the whole bucket
through one ``_scan_rounds_lanes`` dispatch
(:func:`xgboost_tpu.models.gbtree._scan_rounds_lanes_impl`) — K rounds
for L tenants in a single device launch.

Contracts:

- **Bit-identity.**  A stacked lane's model bytes equal its solo run's,
  byte for byte (tests/test_lanes.py pins N ∈ {2, 8, 64}).  Each lane
  keeps its OWN ``PRNGKey(seed)`` (seeds derive from the lane NAME, not
  the stack index — ``run_pipeline``'s per-lane seed rule), its own
  dynamic ``first_iteration``, and its own label/margin slots; row pads
  ride at ``row_valid=False`` / ``pos = -1`` (the histogram kernel's
  inactive-row convention) and therefore never touch a neighbor's sums.
  A tenant joining or leaving a bucket changes ONLY the stack width.
- **Pad-lane semantics.**  A bucket of L real lanes pads to the next
  power of two with inactive lanes (lane 0's bins, all-False
  ``row_valid``, zero gradients): they grow degenerate zero trees the
  host discards.  Padding bounds compile count — tenants churn, the
  compiled program does not.
- **Per-tenant isolation.**  Only the boosting rounds stack; gate,
  publish, ledger, quarantine and checkpoints stay host-side per lane
  (zero-ungated-served holds PER TENANT).  A lane whose unpack or
  checkpoint callback raises keeps its error to itself; a failure of
  the stacked dispatch itself drops every affected lane back to the
  solo path — loudly (``xgbtpu_lane_solo_total`` + ``lanes.solo``
  events).
- **When the host loop still wins.**  Heterogeneous shapes (every lane
  its own bucket), ``subsample < 1`` with unequal row counts (N-shaped
  RNG draws forbid row padding), or one huge tenant dominating the
  stack: set ``XGBTPU_LANE_STACK=0`` for the A/B baseline.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from xgboost_tpu.obs import event, lane_metrics, span
from xgboost_tpu.pipeline.trainer import ContinuousTrainer

__all__ = ["LaneGang", "GangTrainer", "run_tenant_lanes_stacked"]


def _pow2_at_least(n: int, floor: int = 1) -> int:
    p = max(1, int(floor))
    while p < n:
        p *= 2
    return p


def _bucket_of(spec):
    """Shape-bucket key: everything that shapes the stacked scan's
    compiled program.  Static identities (cfg, split finder, gradient
    fn, pred_chunk) come straight from the LaneSpec — they are the jit
    static args of the scan itself, so key-equal lanes by construction
    compile (and cache) ONE program.  Rows pad to a power of two only
    when ``subsample == 1.0``: the subsample Bernoulli draw is N-shaped,
    so padded rows would shift a solo run's draws (bit-identity is the
    contract; exact-N buckets still stack equal-sized tenants)."""
    if spec.subsample >= 1.0:
        n_key = _pow2_at_least(spec.n_rows, 64)
    else:
        n_key = spec.n_rows
    w_key = _pow2_at_least(int(spec.cut_values.shape[1]), 8)
    return (n_key, spec.n_features, w_key, str(spec.binned.dtype),
            spec.K, spec.npar, spec.n_rounds, spec.seg_k, spec.cfg,
            spec.split_finder, spec.grad_fn, spec.pred_chunk)


def _pad_rows(x, n_pad: int, fill=0):
    """End-pad axis 0 to ``n_pad`` rows (identity when already there)."""
    n = x.shape[0]
    if n == n_pad:
        return x
    widths = [(0, n_pad - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=fill)


class _Arrival:
    """One lane's pending boost request at the gang rendezvous."""

    def __init__(self, name: str, spec, segment_callback):
        self.name = name
        self.spec = spec
        self.segment_callback = segment_callback
        self.done = False
        self.fallback = False   # stacked dispatch failed: run solo
        self.exc: Optional[BaseException] = None


class LaneGang:
    """Rendezvous + dispatcher for gang-batched lane training.

    Lanes call :meth:`boost` once per training cycle.  Arrivals collect
    until every registered lane is present or ``window_sec`` has passed
    since the first arrival, then ONE lane thread becomes the
    dispatcher: it groups arrivals into shape buckets, pads each bucket
    to a power-of-two stack width, and advances every bucket segment by
    segment through the lane-stacked scan.  Late lanes simply form the
    next batch — batch composition never changes any lane's bytes (see
    the module contract), only how much dispatch cost is shared.

    Lanes that finish (or error out) call :meth:`resign` so the
    rendezvous stops waiting for them; a lane whose spec is ineligible
    for stacking resigns implicitly and keeps its solo dispatch stream.
    """

    def __init__(self, expected: int, window_sec: float = 0.2):
        self._cv = threading.Condition()
        self._expected = int(expected)
        self._window = float(window_sec)
        self._arrivals: Dict[str, _Arrival] = {}
        self._t0: Optional[float] = None
        self._dispatching = False
        # steady-bucket carry: bucket key -> (identity tokens, strong
        # refs pinning those identities, stacked device columns, carried
        # margin stack).  When the same lanes re-arrive with the same
        # operand OBJECTS (static data, cached base key, the margin
        # views we handed back last dispatch), re-stacking is skipped
        # entirely and the scan consumes its own previous margin output
        # — the host cost of a steady cycle is one int stack plus the
        # dispatch itself.  Any identity change rebuilds the bucket
        # (counted by xgbtpu_lane_restack_total).
        self._carry: Dict[tuple, tuple] = {}

    # ------------------------------------------------------------ members
    def resign(self, name: str) -> None:
        """This lane will not arrive again (finished, errored, or
        permanently ineligible) — stop holding the rendezvous for it."""
        with self._cv:
            self._expected = max(0, self._expected - 1)
            self._cv.notify_all()

    # -------------------------------------------------------------- boost
    def boost(self, name: str, bst, dtrain, it0: int, n_rounds: int,
              segment_callback) -> None:
        """Advance one lane ``n_rounds`` rounds — stacked with whatever
        bucket peers rendezvous with it, or solo (loudly) when
        ineligible.  Blocks until the lane's rounds are fully absorbed
        (same contract as ``Booster.update_many``)."""
        spec, why = bst.fused_lane_spec(dtrain, it0, n_rounds)
        if spec is None:
            lane_metrics().solo.inc(why)
            event("lanes.solo", lane=name, reason=why)
            self.resign(name)  # permanent: eligibility is config-shaped
            bst.update_many(dtrain, it0, n_rounds,
                            segment_callback=segment_callback)
            return
        arr = _Arrival(name, spec, segment_callback)
        batch = None
        with self._cv:
            self._arrivals[name] = arr
            if self._t0 is None:
                self._t0 = time.monotonic()
            self._cv.notify_all()
            while not arr.done:
                full = len(self._arrivals) >= self._expected
                waited = (time.monotonic() - self._t0
                          if self._t0 is not None else 0.0)
                if ((full or waited >= self._window)
                        and not self._dispatching and not arr.done):
                    self._dispatching = True
                    batch = list(self._arrivals.values())
                    self._arrivals.clear()
                    self._t0 = None
                    break
                self._cv.wait(timeout=max(0.01, self._window / 4.0))
        if batch is not None:
            try:
                self._dispatch(batch)
            finally:
                with self._cv:
                    self._dispatching = False
                    for a in batch:
                        a.done = True
                    self._cv.notify_all()
        if arr.fallback:
            lane_metrics().solo.inc("stack_error")
            bst.update_many(dtrain, it0, n_rounds,
                            segment_callback=segment_callback)
            return
        if arr.exc is not None:
            raise arr.exc

    # ----------------------------------------------------------- dispatch
    def _dispatch(self, batch: List[_Arrival]) -> None:
        buckets: Dict[tuple, List[_Arrival]] = {}
        for arr in batch:
            buckets.setdefault(_bucket_of(arr.spec), []).append(arr)
        lane_metrics().buckets.set(float(len(buckets)))
        for key, arrs in buckets.items():
            # deterministic lane order inside the stack (order cannot
            # change bytes — this only keeps dispatch logs stable)
            arrs.sort(key=lambda a: a.name)
            try:
                self._dispatch_bucket(key, arrs)
            except Exception as e:  # whole-bucket failure: solo, loudly
                event("lanes.stack_error", lanes=[a.name for a in arrs],
                      error=f"{type(e).__name__}: {e}")
                for arr in arrs:
                    arr.fallback = True

    def _dispatch_bucket(self, key, arrs: List[_Arrival]) -> None:
        from xgboost_tpu.models.gbtree import (_scan_rounds_lanes,
                                               _scan_rounds_lanes_donated,
                                               _unstack_lane_flats)
        n_pad, n_feat, w_pad = key[0], key[1], key[2]
        specs = [a.spec for a in arrs]
        s0 = specs[0]
        L_real = len(specs)
        L = _pow2_at_least(L_real)
        lm = lane_metrics()

        # steady-bucket carry: identical lane OBJECTS re-arriving means
        # the stacked columns are already on device and the carried
        # margin stack IS last dispatch's output (the views we handed
        # each lane are slices of its host copy).  Identity (not value)
        # comparison keeps this exact; the refs stored below pin every
        # tokenized object so a recycled id can never alias.
        tokens = tuple(
            (a.name, id(s.binned), id(s.label), id(s.weight),
             id(s.base_key), id(s.cut_values), id(s.n_cuts),
             None if s.row_valid is None else id(s.row_valid),
             id(s.margin))
            for a, s in zip(arrs, specs))
        carry = self._carry.get(key)
        if carry is not None and carry[0] == tokens:
            (binned_s, label_s, weight_s, key_s, cut_s, ncut_s,
             rv_s) = carry[2]
            margin_s = carry[3]
        else:
            lm.restacks.inc()

            def rows(x, fill=0):
                a = np.asarray(x)
                if a.shape[0] == n_pad:
                    return a
                w = [(0, n_pad - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
                return np.pad(a, w, constant_values=fill)

            def cuts(s):
                c = np.asarray(s.cut_values)
                if c.shape[1] < w_pad:
                    # +inf pad columns are inert: thresholds only read
                    # cut_values[f, i] at i < n_cuts[f] <= real width
                    c = np.pad(c, ((0, 0), (0, w_pad - c.shape[1])),
                               constant_values=np.inf)
                return c

            def valid(s):
                if s.row_valid is None:
                    return rows(np.ones(s.n_rows, np.bool_), fill=False)
                return rows(s.row_valid, fill=False)

            # stack host-side in numpy: ONE device put per column
            # instead of ~9 pad/stack dispatches per lane
            bcol = [rows(s.binned) for s in specs]
            mcol = [rows(s.margin) for s in specs]
            lcol = [rows(s.label) for s in specs]
            wcol = [rows(s.weight) for s in specs]
            kcol = [s.base_key for s in specs]
            ccol = [cuts(s) for s in specs]
            ncol = [np.asarray(s.n_cuts) for s in specs]
            rcol = [valid(s) for s in specs]
            if L > L_real:
                # inactive pad lanes: lane 0's bins/cuts (valid values,
                # zero cost after the stack copies either way), all rows
                # masked out — they grow degenerate zero trees the host
                # discards
                pads = L - L_real
                bcol += [bcol[0]] * pads
                mcol += [np.zeros_like(mcol[0])] * pads
                lcol += [np.zeros_like(lcol[0])] * pads
                wcol += [np.zeros_like(wcol[0])] * pads
                kcol += [jax.random.PRNGKey(0)] * pads
                ccol += [ccol[0]] * pads
                ncol += [ncol[0]] * pads
                rcol += [np.zeros(n_pad, np.bool_)] * pads
            binned_s = jnp.asarray(np.stack(bcol))
            margin_s = jnp.asarray(np.stack(mcol))
            label_s = jnp.asarray(np.stack(lcol))
            weight_s = jnp.asarray(np.stack(wcol))
            key_s = jnp.stack(kcol)  # keys may be typed: stack on device
            cut_s = jnp.asarray(np.stack(ccol))
            ncut_s = jnp.asarray(np.stack(ncol))
            rv_s = jnp.asarray(np.stack(rcol))

        first_s = jnp.asarray(np.asarray(
            [s.first_iteration for s in specs] + [0] * (L - L_real),
            np.int32))
        env = os.environ.get("XGBTPU_FUSED_DONATE")
        donate = (env == "1" if env not in (None, "")
                  else jax.default_backend() != "cpu")
        scan = _scan_rounds_lanes_donated if donate else _scan_rounds_lanes
        n_rounds, seg_k = s0.n_rounds, s0.seg_k
        done = 0
        views: List[Optional[np.ndarray]] = [None] * L_real
        while done < n_rounds:
            seg = min(seg_k, n_rounds - done)
            with span("lanes.dispatch", lanes=L_real, width=L,
                      n_rounds=seg, bucket_rows=n_pad):
                t0 = time.perf_counter()
                margin_s, stacks = scan(
                    binned_s, margin_s, label_s, weight_s, key_s,
                    first_s + done, cut_s, ncut_s, rv_s,
                    n_rounds=seg, K=s0.K, npar=s0.npar, cfg=s0.cfg,
                    split_finder=s0.split_finder, grad_fn=s0.grad_fn,
                    pred_chunk=s0.pred_chunk)
                # block at the segment boundary: per-lane checkpoint
                # callbacks pull model bytes from this dispatch next,
                # and the histogram must record device wall time
                jax.block_until_ready(margin_s)
                dt = time.perf_counter() - t0
            lm.dispatches.inc()
            lm.dispatch_seconds.observe(dt)
            lm.stack_width.set(float(L))
            lm.stacked.inc(float(L_real))
            lm.padded.inc(float(L - L_real))
            # slice the lane axis in ONE launch, then per-tenant absorb;
            # margins fan out as views of ONE host copy (per-lane device
            # slicing would be a dispatch per lane per segment)
            lane_stacks = _unstack_lane_flats(stacks, L)
            margin_np = np.asarray(margin_s)  # xgtpu: disable=XGT002 — ONE batched pull per segment for ALL lanes
            for i, arr in enumerate(arrs):
                if arr.exc is not None:
                    continue  # this lane failed an earlier segment
                try:
                    spec = arr.spec
                    views[i] = margin_np[i, :spec.n_rows]
                    spec.booster.absorb_lane_segment(
                        spec, lane_stacks[i], views[i], seg)
                    arr.segment_callback(
                        spec.first_iteration + done + seg - 1)
                except Exception as e:  # isolation: keep it in-lane
                    arr.exc = e
            done += seg
        if all(a.exc is None for a in arrs):
            tokens_next = tuple(
                (a.name, id(s.binned), id(s.label), id(s.weight),
                 id(s.base_key), id(s.cut_values), id(s.n_cuts),
                 None if s.row_valid is None else id(s.row_valid),
                 id(views[i]))
                for i, (a, s) in enumerate(zip(arrs, specs)))
            self._carry[key] = (
                tokens_next,
                (specs, views),  # pin tokenized objects (id-reuse guard)
                (binned_s, label_s, weight_s, key_s, cut_s, ncut_s,
                 rv_s),
                margin_s)
        else:
            self._carry.pop(key, None)


class GangTrainer(ContinuousTrainer):
    """A :class:`ContinuousTrainer` whose boosting rounds route through
    a shared :class:`LaneGang` — everything else (resume, gate, publish,
    ledger) is the per-tenant base behavior, untouched."""

    def __init__(self, *args, gang: Optional[LaneGang] = None, **kw):
        super().__init__(*args, **kw)
        self._gang = gang

    def _boost_rounds(self, bst, dtrain, it0: int, n_rounds: int,
                      segment_callback) -> None:
        if self._gang is None:
            super()._boost_rounds(bst, dtrain, it0, n_rounds,
                                  segment_callback)
            return
        self._gang.boost(self.lane or self.publish_path, bst, dtrain,
                         it0, n_rounds, segment_callback)


def run_tenant_lanes_stacked(lanes: dict, quiet: bool = False,
                             window_sec: float = 0.2,
                             max_workers: Optional[int] = None) -> dict:
    """Stacked execution mode of
    :func:`xgboost_tpu.pipeline.run_tenant_lanes`: one thread per lane
    for the host-side phases (threads idle at the gang rendezvous while
    the device works), boosting rounds gang-batched through a shared
    :class:`LaneGang`.  Same call/return shape as the host loop."""
    import functools

    from xgboost_tpu.pipeline import run_pipeline

    gang = LaneGang(expected=len(lanes), window_sec=window_sec)
    results: dict = {}
    rlock = threading.Lock()
    names = list(lanes)
    if max_workers is None:
        max_workers = len(lanes)
    max_workers = max(1, min(int(max_workers), len(lanes))) if lanes else 0

    def _one(name: str, kw: dict) -> None:
        kw = dict(kw)
        kw.setdefault("lane", name)
        kw.setdefault("quiet", quiet)
        try:
            summary = run_pipeline(
                trainer_cls=functools.partial(GangTrainer, gang=gang),
                **kw)
            with rlock:
                results[name] = {"status": "ok", "summary": summary}
        except Exception as e:  # lane isolation: never kill siblings
            with rlock:
                results[name] = {"status": "error",
                                 "error": f"{type(e).__name__}: {e}"}
            event("pipeline.lane_error", lane=name,
                  error=f"{type(e).__name__}: {e}")
        finally:
            gang.resign(name)

    pending = list(names)
    plock = threading.Lock()

    def _worker() -> None:
        while True:
            with plock:
                if not pending:
                    return
                name = pending.pop(0)
            _one(name, lanes[name])

    threads = [threading.Thread(target=_worker, name=f"lane-worker-{i}",
                                daemon=True)
               for i in range(max_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results

"""Eval gate: candidate-vs-incumbent scoring on a held-out window.

The publish decision of the continuous-training pipeline (PIPELINE.md):
both models score the SAME holdout DMatrix through the learner's own
eval path (``Booster.eval_set`` — the gate sees exactly what a
training eval line would print, transform quirks included), and the
candidate publishes only when its improvement clears the threshold.

Threshold semantics (one number, two knobs):

- ``min_delta > 0`` demands strict improvement: the candidate must beat
  the incumbent by at least ``min_delta`` (``max_regression`` is moot).
- otherwise ``max_regression`` is the tolerated worsening: fresh-data
  drift can make an honest candidate score slightly worse on a fixed
  holdout, and a pipeline that never publishes is as broken as one
  that publishes garbage.  Defaults (0, 0) mean "no worse than the
  incumbent".

A missing incumbent (cold start — nothing at the publish path yet)
passes unconditionally: there is nothing to regress against.
"""

from __future__ import annotations

from typing import Optional

from xgboost_tpu.learner import _MAXIMIZE_METRICS, _parse_eval


class EvalGate:
    """Judge a candidate model against the incumbent on a holdout."""

    def __init__(self, metric: str = "", min_delta: float = 0.0,
                 max_regression: float = 0.0):
        self.metric = metric
        self.min_delta = float(min_delta)
        self.max_regression = float(max_regression)

    def _score(self, bst, holdout, cycle: int) -> tuple:
        """-> (metric_name, value) via the learner's eval path."""
        if self.metric:
            bst.param.eval_metric = (self.metric,)
        scores = _parse_eval(bst.eval_set([(holdout, "gate")], cycle))
        key = list(scores)[-1]
        return key.split("-", 1)[1], scores[key]

    def judge(self, candidate, incumbent: Optional[object], holdout,
              cycle: int = 0,
              incumbent_score: Optional[float] = None) -> dict:
        """-> verdict dict: ``passed``, ``metric``, ``candidate``,
        ``incumbent``, ``improvement`` (signed so positive = better),
        ``threshold``, ``reason``.

        ``incumbent_score`` (when not None) is a precomputed incumbent
        value on THIS holdout under THIS gate config — the trainer's
        per-hash cache; the incumbent model then never loads or
        scores."""
        name, c = self._score(candidate, holdout, cycle)
        if incumbent is None and incumbent_score is None:
            return {"passed": True, "metric": name, "candidate": c,
                    "incumbent": None, "improvement": None,
                    "reason": "no incumbent (cold start)"}
        i = (incumbent_score if incumbent_score is not None
             else self._score(incumbent, holdout, cycle)[1])
        maximize = any(name.startswith(m) for m in _MAXIMIZE_METRICS)
        improvement = (c - i) if maximize else (i - c)
        threshold = (self.min_delta if self.min_delta > 0.0
                     else -self.max_regression)
        passed = improvement >= threshold
        verdict = {"passed": passed, "metric": name,
                   "candidate": c, "incumbent": i,
                   "improvement": improvement, "threshold": threshold}
        if not passed:
            verdict["reason"] = (
                f"{name} improvement {improvement:.6f} < "
                f"threshold {threshold:.6f} "
                f"(candidate {c:.6f} vs incumbent {i:.6f})")
        return verdict

"""Atomic model publication to the path the serving tier polls.

Two lanes (PIPELINE.md):

- **direct** — the gated candidate's bytes (already CRC-footered by
  ``save_model``) are re-verified and ``atomic_write``-n over the
  publish path.  Atomicity is the whole torn-publish story: a poller
  (``ModelRegistry.check_reload``, a fleet replica) sees either the
  complete old file or the complete new file, never a prefix — a
  SIGKILL mid-publish is invisible by construction.
- **rollout** — the candidate is staged to the publish path the same
  way, then handed to the fleet router's canary lane (``POST
  /fleet/rollout``): verify → canary push → soak → gate on the
  canaries' own metrics → fleet push, or instant rollback
  (fleet/rollout.py).  A rolled-back rollout surfaces as
  :class:`PublishRejected` so the trainer quarantines the candidate
  instead of pretending it shipped.

Both lanes refuse unverified bytes: ``verify_model_bytes`` runs on the
exact buffer about to be written, so a candidate corrupted on disk
between gate and publish is caught here too.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional
from urllib.parse import urlparse

from xgboost_tpu.obs import event, span
from xgboost_tpu.reliability.integrity import (atomic_write, read_file,
                                               verify_model_bytes)


class PublishRejected(RuntimeError):
    """The fleet's canary lane rejected (rolled back) the candidate.
    Carries the router's full rollout report."""

    def __init__(self, report: dict):
        super().__init__(f"rollout {report.get('status')}: "
                         f"{report.get('reason', report.get('error'))}")
        self.report = report


class Publisher:
    """Direct atomic publish to ``publish_path``."""

    def __init__(self, publish_path: str):
        self.publish_path = publish_path

    def publish(self, candidate_path: str) -> dict:
        raw = read_file(candidate_path)
        # never publish bytes that do not verify — the candidate file
        # is CRC-footered, and this is the exact buffer written out
        verify_model_bytes(raw, name=candidate_path)
        digest = hashlib.sha256(raw).hexdigest()
        with span("pipeline.publish", path=self.publish_path,
                  model_hash=digest, bytes=len(raw)):
            atomic_write(self.publish_path, raw)
        event("pipeline.publish", path=self.publish_path,
              model_hash=digest)
        return {"mode": "direct", "path": self.publish_path,
                "model_hash": digest}


class RolloutPublisher(Publisher):
    """Publish through the fleet router's staged canary rollout.

    The candidate is staged to a SEPARATE ``<publish_path>.staging``
    file for the router's rollout controller to read and push from —
    never to ``publish_path`` itself, which replicas may be polling
    directly (a shared-model fleet): writing ungated bytes there would
    hot-reload the whole fleet BEFORE the canary soak/gate ran.  Then
    ``POST /fleet/rollout`` runs the canary → soak → gate →
    fleet-push protocol, and only a SUCCESSFUL rollout records the
    bytes at ``publish_path`` (the next cycle's warm-start incumbent).
    ``None`` rollout knobs defer to the router's configured
    defaults."""

    def __init__(self, publish_path: str, router_url: str,
                 canaries: Optional[int] = None,
                 soak_sec: Optional[float] = None,
                 timeout: float = 600.0, model: str = ""):
        # timeout must outlive the router's soak window (the POST
        # blocks through canary push + soak + gate + fleet push); a
        # timeout mid-soak would count a succeeding rollout as a
        # publish failure and re-POST into the router's rollout lock
        super().__init__(publish_path)
        self.router_url = router_url.rstrip("/")
        self.canaries = canaries
        self.soak_sec = soak_sec
        self.timeout = timeout
        # catalog tenant: the router scopes the rollout to replicas
        # hosting this model and pushes to THEIR per-model paths
        self.model = model

    def _rollout_call(self, payload: dict) -> dict:
        import http.client
        p = urlparse(self.router_url)
        conn = http.client.HTTPConnection(p.hostname, p.port,
                                          timeout=self.timeout)
        try:
            body = json.dumps(payload).encode()
            conn.request("POST", "/fleet/rollout", body=body,
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            out = r.read()
        finally:
            conn.close()
        try:
            report = json.loads(out)
        except ValueError:
            report = {"status": "error",
                      "error": out[:200].decode("utf-8", "replace")}
        report.setdefault("status", "error")
        report["http_status"] = r.status
        return report

    def publish(self, candidate_path: str) -> dict:
        import os
        raw = read_file(candidate_path)
        verify_model_bytes(raw, name=candidate_path)
        digest = hashlib.sha256(raw).hexdigest()
        stage = self.publish_path + ".staging"
        with span("pipeline.publish", path=self.publish_path,
                  model_hash=digest, lane="rollout"):
            atomic_write(stage, raw)  # router-visible, poller-invisible
            payload: dict = {"model_path": stage}
            if self.model:
                payload["model"] = self.model
            if self.canaries is not None:
                payload["canaries"] = int(self.canaries)
            if self.soak_sec is not None:
                payload["soak_sec"] = float(self.soak_sec)
            try:
                report = self._rollout_call(payload)
            finally:
                try:
                    os.unlink(stage)
                except OSError:
                    pass  # xgtpu: disable=XGT004 — best-effort cleanup
            if report.get("status") != "ok":
                event("pipeline.publish_rejected", model_hash=digest,
                      status=report.get("status"),
                      reason=report.get("reason", report.get("error")))
                raise PublishRejected(report)
            # the fleet runs it: record the bytes as the warm-start
            # incumbent only AFTER the canary gate passed
            atomic_write(self.publish_path, raw)
        event("pipeline.publish", path=self.publish_path,
              model_hash=digest, lane="rollout")
        return {"mode": "rollout", "path": self.publish_path,
                "model_hash": digest, "report": report}

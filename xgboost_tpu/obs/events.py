"""Crash-safe structured event log: append-only JSONL.

The durable half of the observability layer (OBSERVABILITY.md): spans
(obs/trace.py, obs/profiler.py) and discrete events (reload, drain,
integrity failure, fault injection) append one JSON object per line to
a log file, so a run that dies leaves a replayable timeline —
``tools/obs_report.py`` renders it into a per-round / per-request text
view.

Crash-safety discipline:

- every line is ``write()`` + ``flush()`` — a process crash loses at
  most the line being formatted (the kernel holds flushed bytes);
- ``fsync`` is throttled (default at most once per second) so a
  per-request serving span cannot turn into a per-request disk sync;
- rotation reuses :func:`reliability.integrity.atomic_write`'s fsync
  discipline: fsync the live file, ``os.replace`` it to ``<path>.1``,
  fsync the directory, reopen — a crash mid-rotation leaves either the
  old live file or the rotated file, never a torn rename.

Configuration: :func:`configure_log` (CLI ``obs_log=`` / serving
embedders) or the ``XGBTPU_OBS_LOG`` env var (read lazily on first
use, so subprocess chaos/mp workers inherit it).  Unconfigured, every
emit is a cheap no-op.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class EventLog:
    """One append-only JSONL sink with throttled fsync and size-based
    rotation."""

    def __init__(self, path: str, rotate_bytes: int = 64 << 20,
                 fsync_interval_s: float = 1.0):
        self.path = os.fspath(path)
        self.rotate_bytes = int(rotate_bytes)
        self.fsync_interval_s = float(fsync_interval_s)
        self._lock = threading.Lock()
        self._last_fsync = 0.0
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "ab")

    # -------------------------------------------------------------- emit
    def emit(self, record: dict) -> None:
        """Append one record (a dict; non-JSON values fall back to
        ``str``).  Never raises into the instrumented code path: a full
        disk degrades observability, not training."""
        try:
            line = json.dumps(record, separators=(",", ":"),
                              default=str).encode() + b"\n"
        except Exception as e:
            # even the str() fallback failed (a repr that raises): the
            # record is lost, but the loss is COUNTED — emit_event=False
            # because we ARE the event log (recursion guard)
            from xgboost_tpu.obs.metrics import swallowed_error
            swallowed_error("obs.events.format", e, emit_event=False)
            return
        with self._lock:
            try:
                self._f.write(line)
                self._f.flush()
                now = time.monotonic()
                if now - self._last_fsync >= self.fsync_interval_s:
                    os.fsync(self._f.fileno())
                    self._last_fsync = now
                if self._f.tell() >= self.rotate_bytes:
                    self._rotate_locked()
            except (OSError, ValueError) as e:
                # full disk / closed file degrades observability, not
                # training — but the dropped line is counted
                from xgboost_tpu.obs.metrics import swallowed_error
                swallowed_error("obs.events.write", e, emit_event=False)

    def _rotate_locked(self) -> None:
        """Rotate ``path`` -> ``path.1`` (one generation kept) with the
        atomic_write fsync discipline."""
        os.fsync(self._f.fileno())
        self._f.close()
        os.replace(self.path, self.path + ".1")
        d = os.path.dirname(os.path.abspath(self.path))
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._f = open(self.path, "ab")
        self._last_fsync = time.monotonic()

    # ------------------------------------------------------------- close
    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            try:
                self._f.close()
            except (OSError, ValueError):
                pass


_UNSET = object()
_log = _UNSET  # _UNSET -> consult env once; None -> explicitly off
_log_lock = threading.Lock()


def configure_log(path: Optional[str], rotate_bytes: int = 64 << 20,
                  fsync_interval_s: float = 1.0) -> Optional[EventLog]:
    """Install (or with ``path=None`` remove) the process-wide event
    log.  Returns the installed :class:`EventLog` (or None)."""
    global _log
    with _log_lock:
        if _log not in (_UNSET, None):
            _log.close()
        _log = (EventLog(path, rotate_bytes, fsync_interval_s)
                if path else None)
        return _log


def get_log() -> Optional[EventLog]:
    """The process-wide event log, or None when logging is off.  First
    call consults ``XGBTPU_OBS_LOG`` so subprocesses armed via the
    environment log without any code change."""
    global _log
    if _log is _UNSET:
        with _log_lock:
            if _log is _UNSET:
                env = os.environ.get("XGBTPU_OBS_LOG")
                _log = EventLog(env) if env else None
    return _log


def emit(record: dict) -> None:
    """Append one record to the process-wide log (no-op when off)."""
    log = get_log()
    if log is not None:
        log.emit(record)

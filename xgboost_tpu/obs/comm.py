"""Per-worker collective statistics — the ``report_stats`` analog.

The reference's mock allreduce accounts per-version allreduce time and
checkpoint cost (``subtree/rabit/src/allreduce_mock.h:52-56,87-95``);
"GPU-acceleration for Large-scale Tree Boosting" (PAPERS.md) shows the
communication volume is the number that decides sharding strategy.
This module is where that accounting lives for the TPU port: every
host-side collective entry records ``(op, count, bytes, seconds)``
both cumulatively (Prometheus counters, group ``"comm"`` in the
registry) and per boosting round (consumed by the round profiler's
timeline events and the multi-worker tests).

Instrumented seams:

- ``parallel/mock.py collective()`` — one ``allreduce`` count (+payload
  estimate) per tree-growth launch, so ``xgbtpu_comm_allreduce_total``
  matches the mock seam's seqno count by construction;
- the per-round growth launches (``models/gbtree.py do_boost``) add
  wall seconds via :func:`timed`/:func:`record` with ``count=0`` —
  host-side launch time; the device-side collective is inside XLA and
  visible only to ``profile=2`` traces;
- the MESH-FUSED scan (``do_boost_fused`` under a data mesh) counts
  its real in-scan reductions as ``psum``: ``max_depth`` histogram
  psums per tree-growth step with the whole-tree payload estimate in
  ``xgbtpu_comm_psum_bytes_total``.  Its ``seconds`` counter stays 0
  by design — the psums execute inside ONE fused device program, so
  per-collective wall time is not observable host-side (the measured
  per-round psum cost lives in MULTICHIP_r06.json, fitted by
  ``tools/fit_round_model.py``'s mesh cell); the dispatch wall goes to
  ``xgbtpu_train_dispatch_seconds``, never to a collective family;
- ``parallel/sharded.py`` eval collectives (``allsum``/``allgatherv``)
  and ``parallel/colsplit.py`` per-level split gathers record as
  ``allgather`` with real payload bytes.

Bytes for in-XLA reductions are ESTIMATES of the logical payload (what
the reference would have shipped over rabit), not wire bytes — ICI
topology and XLA fusion make wire truth unknowable host-side.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

OPS = ("allreduce", "allgather", "psum")

_lock = threading.Lock()
_metrics = None
_round: Optional[int] = None
# per-round tallies: round -> op -> {"count","bytes","seconds"}
_per_round: Dict[int, Dict[str, Dict[str, float]]] = {}
_MAX_ROUND_HISTORY = 4096


class CommMetrics:
    """Cumulative per-op counters, registered as registry group
    ``"comm"``."""

    def __init__(self, prefix: str = "xgbtpu_comm"):
        from xgboost_tpu.obs.metrics import Counter, registry
        self.count: Dict[str, object] = {}
        self.bytes: Dict[str, object] = {}
        self.seconds: Dict[str, object] = {}
        for op in OPS:
            self.count[op] = Counter(
                f"{prefix}_{op}_total",
                f"host-side {op} collective launches")
            self.bytes[op] = Counter(
                f"{prefix}_{op}_bytes_total",
                f"logical payload bytes moved by {op} collectives "
                "(estimate for in-XLA reductions)")
            self.seconds[op] = Counter(
                f"{prefix}_{op}_seconds_total",
                f"host-side wall seconds in {op} collective launches")
        registry().register("comm", self.render)

    def render(self) -> str:
        parts = []
        for op in OPS:
            parts += [self.count[op].render(), self.bytes[op].render(),
                      self.seconds[op].render()]
        return "".join(parts)


def metrics() -> CommMetrics:
    """The process-wide CommMetrics singleton."""
    global _metrics
    if _metrics is None:
        with _lock:
            if _metrics is None:
                _metrics = CommMetrics()
    return _metrics


# ----------------------------------------------------------------- record
def begin_round(version: int) -> None:
    """Open the per-round tally for ``version`` (called from the mock
    seam's ``begin_round``, i.e. once per boosting round)."""
    global _round
    with _lock:
        _round = int(version)
        _per_round.setdefault(_round, {})
        if len(_per_round) > _MAX_ROUND_HISTORY:
            for k in sorted(_per_round)[:len(_per_round) // 2]:
                del _per_round[k]


def record(op: str, nbytes: float = 0.0, seconds: float = 0.0,
           count: int = 1) -> None:
    """Record one (or ``count``) collective launches of ``op`` with a
    payload estimate and host wall seconds.  ``count=0`` adds
    bytes/seconds to an already-counted launch (the timing wrapper
    around a launch whose count the mock seam already took)."""
    m = metrics()
    if count:
        m.count[op].inc(count)
    if nbytes:
        m.bytes[op].inc(float(nbytes))
    if seconds:
        m.seconds[op].inc(float(seconds))
    with _lock:
        if _round is None:
            return
        tally = _per_round[_round].setdefault(
            op, {"count": 0.0, "bytes": 0.0, "seconds": 0.0})
        tally["count"] += count
        tally["bytes"] += float(nbytes)
        tally["seconds"] += float(seconds)


@contextmanager
def timed(op: str, nbytes: float = 0.0, count: int = 1):
    """Time a block as one collective launch (``count=0`` when the mock
    seam already counted it)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(op, nbytes=nbytes, seconds=time.perf_counter() - t0,
               count=count)


# ---------------------------------------------------------------- queries
def round_stats(version: Optional[int] = None
                ) -> Dict[str, Dict[str, float]]:
    """Per-op tallies of one round (default: the current round); empty
    dict when nothing was recorded."""
    with _lock:
        v = _round if version is None else int(version)
        if v is None or v not in _per_round:
            return {}
        return {op: dict(t) for op, t in _per_round[v].items()}


def all_round_stats() -> Dict[int, Dict[str, Dict[str, float]]]:
    with _lock:
        return {r: {op: dict(t) for op, t in per_op.items()}
                for r, per_op in _per_round.items()}


def totals() -> Dict[str, Dict[str, float]]:
    """Cumulative per-op totals for THIS worker."""
    m = metrics()
    return {op: {"count": m.count[op].value,
                 "bytes": m.bytes[op].value,
                 "seconds": m.seconds[op].value} for op in OPS}


def aggregate_across_workers() -> Dict[str, Dict[str, float]]:
    """Sum per-worker totals across all processes using the existing mesh
    collective (``ShardedDMatrix.allsum`` — a multihost allgather+sum);
    in single-process mode this is just :func:`totals`."""
    import numpy as np
    from xgboost_tpu.parallel.sharded import ShardedDMatrix
    mine = totals()
    vec = np.asarray([mine[op][k] for op in OPS
                      for k in ("count", "bytes", "seconds")], np.float64)
    summed = ShardedDMatrix.allsum(vec)
    out: Dict[str, Dict[str, float]] = {}
    i = 0
    for op in OPS:
        out[op] = {}
        for k in ("count", "bytes", "seconds"):
            out[op][k] = float(summed[i])
            i += 1
    return out


def reset_for_tests() -> None:
    """Drop per-round history (cumulative counters stay — tests read
    deltas, like the reliability counters)."""
    global _round
    with _lock:
        _per_round.clear()
        _round = None

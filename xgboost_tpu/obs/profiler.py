"""Per-round phase timing: stderr lines, tracing spans, and training
metrics from ONE instrument.

This is ``profiling.RoundProfiler`` moved into the observability layer
and taught to feed it (the compat import path keeps working).  Three
consumers, all driven by the same phase boundaries:

- ``level>=1`` — the classic ``[prof]`` stderr lines per round plus the
  end-of-run summary (``profile=1``); ``level>=2`` additionally
  captures a ``jax.profiler`` trace (``profile=2``);
- event log — every phase and every round emit ``kind="span"`` records
  (name ``train.phase``/``train.round``) when ``obs_log=`` is
  configured, the round record carrying the phase breakdown and the
  round's collective tallies (obs/comm.py) so a dead run leaves a
  replayable timeline;
- metrics — rounds completed, per-phase seconds, round wall time and
  device memory land on :class:`~xgboost_tpu.obs.metrics.TrainingMetrics`
  for the ``metrics_port=`` scrape.

Phases force a true device barrier at their boundaries (``.block``) so
async dispatch doesn't smear costs across phases — which is also why
the learner only instruments rounds when profiling or observability is
explicitly enabled (a barrier costs a full round-trip on
remote-attached backends; see PROFILE.md).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Optional

from xgboost_tpu.obs import comm, trace
from xgboost_tpu.obs.metrics import training_metrics


class RoundProfiler:
    """Collects per-phase wall time per boosting round.

    ``level=0`` keeps the spans/metrics but prints nothing — the shape
    an ``obs_log=``-only run uses; ``level>=1`` adds the ``[prof]``
    stderr lines; ``level>=2`` adds the jax.profiler trace."""

    def __init__(self, level: int = 1, trace_dir: Optional[str] = None,
                 out=None):
        import sys
        self.level = level
        self.trace_dir = trace_dir or "./xgtpu_profile"
        self.out = out if out is not None else sys.stderr
        self.rounds = []
        self._current = None
        self._tracing = False
        self._round_t0: Optional[float] = None
        self._round_trace: Optional[str] = None

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self.level >= 2 and not self._tracing:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True

    def stop(self):
        if self._tracing:
            import jax
            jax.profiler.stop_trace()
            self._tracing = False
            print(f"[prof] jax.profiler trace written to {self.trace_dir}",
                  file=self.out)

    # ---------------------------------------------------------- round phases
    def begin_round(self, iteration: int):
        self._current = {"round": iteration, "phases": {}, "t0": None}
        self._round_t0 = time.perf_counter()
        self._round_trace = trace.new_id()
        trace.set_round(iteration)

    def phase(self, name: str):
        """Context manager timing one phase of the current round.  Call
        ``.block(x)`` inside (or rely on the caller's own sync) to pin
        async device work to this phase."""
        return _Phase(self, name)

    def end_round(self):
        if self._current is None:
            return
        c = self._current
        total = sum(c["phases"].values())
        dur = (time.perf_counter() - self._round_t0
               if self._round_t0 is not None else total)
        tm = training_metrics()
        tm.rounds.inc()
        tm.round.set(c["round"])
        tm.round_seconds.observe(dur)
        from xgboost_tpu.obs import events
        if events.get_log() is not None:
            rec = {"ts": round(time.time(), 6), "kind": "span",
                   "name": "train.round", "trace": self._round_trace,
                   "span": trace.new_id(), "round": c["round"],
                   "dur_ms": round(dur * 1e3, 3),
                   "attrs": {"phases_ms": {
                       k: round(v * 1e3, 3)
                       for k, v in c["phases"].items()}}}
            cs = comm.round_stats(c["round"])
            if cs:
                rec["attrs"]["comm"] = cs
            events.emit(rec)
        if self.level >= 1:
            parts = " ".join(f"{k}={v * 1e3:.1f}ms"
                             for k, v in c["phases"].items())
            print(f"[prof] round {c['round']}: total={total * 1e3:.1f}ms "
                  f"{parts}", file=self.out)
        self.rounds.append(c)
        self._current = None
        trace.set_round(None)

    # ------------------------------------------------------------- summary
    def summary(self) -> str:
        if not self.rounds:
            return "[prof] no rounds recorded"
        agg = defaultdict(float)
        for r in self.rounds:
            for k, v in r["phases"].items():
                agg[k] += v
        total = sum(agg.values())
        n = len(self.rounds)
        lines = [f"[prof] {n} rounds, {total:.3f}s total, "
                 f"{total / n * 1e3:.1f}ms/round"]
        if not agg:
            # rounds recorded but no phases inside them (e.g. every
            # phase elided): nothing to break down, and no total to
            # divide by
            lines.append("[prof]   (no phases recorded)")
            return "\n".join(lines)
        for k, v in sorted(agg.items(), key=lambda kv: -kv[1]):
            # all-zero phase durations (clock granularity, empty
            # rounds) must yield a line, not a ZeroDivisionError
            pct = (v / total * 100) if total > 0 else 0.0
            lines.append(f"[prof]   {k:<10s} {v:8.3f}s  "
                         f"{pct:5.1f}%  {v / n * 1e3:8.1f}ms/round")
        return "\n".join(lines)

    def print_summary(self):
        if self.level >= 1:
            print(self.summary(), file=self.out)


class _Phase:
    def __init__(self, prof: RoundProfiler, name: str):
        self.prof = prof
        self.name = name
        self._blocked = None

    def block(self, x):
        """Record device arrays whose completion closes this phase."""
        self._blocked = x
        return x

    def __enter__(self):
        self.t0 = time.perf_counter()
        self.ts = time.time()
        return self

    def __exit__(self, *exc):
        if self._blocked is not None and exc[0] is None:
            import jax
            jax.block_until_ready(self._blocked)
            # block_until_ready is advisory on some remote-attached
            # backends (axon tunnel); one single-element host pull is a
            # true barrier on the in-order stream (last leaf suffices)
            leaves = [x for x in jax.tree.leaves(self._blocked)
                      if hasattr(x, "ravel")
                      and getattr(x, "is_fully_addressable", True)]
            if leaves:
                jax.device_get(leaves[-1].ravel()[:1])
        dur = time.perf_counter() - self.t0
        cur = self.prof._current
        if cur is None and self.prof.rounds:
            # outside begin/end (e.g. eval after end_round): fold into
            # the most recent round
            cur = self.prof.rounds[-1]
        if cur is not None:
            cur["phases"][self.name] = (
                cur["phases"].get(self.name, 0.0) + dur)
        training_metrics().phase_seconds.inc(self.name, dur)
        from xgboost_tpu.obs import events
        if events.get_log() is not None:
            rnd = cur["round"] if cur is not None else None
            events.emit({
                "ts": round(self.ts, 6), "kind": "span",
                "name": "train.phase", "trace": self.prof._round_trace,
                "span": trace.new_id(), "round": rnd,
                "dur_ms": round(dur * 1e3, 3),
                "attrs": {"phase": self.name}})
        return False

"""Training-side metrics endpoint: ``/metrics`` + ``/healthz`` from a
daemon thread.

The serving stack has always been scrapeable; a multi-hour TRAINING run
was dark.  ``metrics_port=`` (CLI) or :func:`start_metrics_server`
starts a stdlib HTTP server on a daemon thread that renders the
process-wide :func:`~xgboost_tpu.obs.metrics.registry` — training
progress, per-phase seconds, collective stats, reliability counters,
and any in-process serving metrics — in the Prometheus text exposition
format.  ``port=0`` binds an ephemeral port (printed, and on
``server.port``); under the multi-host launcher each rank serves its
own process's registry (rank r binds ``metrics_port + r``), which is
how per-rank collective stats are exported.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # header/body go out as two writes: without TCP_NODELAY the body
    # stalls ~40 ms behind the delayed ACK (same fix as serving/http.py)
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # metrics scrapes stay quiet
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from xgboost_tpu.obs.metrics import registry, training_metrics
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._send(200, registry().render().encode(),
                       PROM_CONTENT_TYPE)
            return
        if path == "/healthz":
            tm = training_metrics()
            body = json.dumps({
                "status": "ok",
                "uptime_seconds": round(
                    time.perf_counter() - self.server.obs_t0, 3),
                "rounds_completed": int(tm.rounds.value),
                "round": int(tm.round.value),
                "rank": self.server.obs_rank,
            }).encode()
            self._send(200, body, "application/json")
            return
        self._send(404, json.dumps(
            {"error": f"no route {path}"}).encode(), "application/json")

    def do_POST(self):
        # the training metrics endpoint is read-only; an unknown POST
        # gets the same JSON 404 body every handler in the tree sends
        # (the stdlib default would be a 501 HTML page) — the route
        # sweep's consistency contract, pinned by
        # tests/test_analysis_contracts.py.  The body is drained
        # (bounded) so a keep-alive client's next request line is not
        # parsed out of the unread payload.
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = 0
        if length > 0:
            self.rfile.read(min(length, 1 << 20))
        self.close_connection = True
        path = self.path.split("?", 1)[0]
        self._send(404, json.dumps(
            {"error": f"no route {path}"}).encode(), "application/json")


class MetricsServer:
    """Bind + serve the registry from a daemon thread (``stop()`` to
    close; the thread dies with the process otherwise)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 rank: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs_t0 = time.perf_counter()  # uptime = duration
        self._httpd.obs_rank = rank
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="xgbtpu-obs-metrics")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(5.0)


_server: Optional[MetricsServer] = None
_lock = threading.Lock()


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         rank: int = 0) -> MetricsServer:
    """Start (or return the already-running) process-wide metrics
    server.  Eagerly creates the training + comm metric groups so a
    scrape that lands before the first round still sees the families."""
    global _server
    with _lock:
        if _server is None:
            from xgboost_tpu.obs import comm
            from xgboost_tpu.obs.metrics import (reliability_metrics,
                                                 training_metrics)
            training_metrics()
            reliability_metrics()
            comm.metrics()
            _server = MetricsServer(port=port, host=host, rank=rank)
        return _server


def get_metrics_server() -> Optional[MetricsServer]:
    return _server


def stop_metrics_server() -> None:
    global _server
    with _lock:
        if _server is not None:
            _server.stop()
            _server = None

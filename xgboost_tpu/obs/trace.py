"""Lightweight tracing spans with thread-local parent linkage.

The span model (OBSERVABILITY.md): a :func:`span` is a context manager
that times a named unit of work and, when the event log is configured
(obs/events.py), appends one ``kind="span"`` record at exit carrying

- ``trace`` — the request/round correlation id.  The serving front end
  seeds it from the ``X-Request-Id`` header (and echoes it back); the
  round profiler seeds one per boosting round; a span opened with no
  ambient trace id starts a fresh one;
- ``span``/``parent`` — random 64-bit ids linked through a
  thread-local stack, so nested spans reconstruct into a tree;
- ``dur_ms`` and the caller's attributes.

Spans are cheap when logging is off: the thread-local bookkeeping runs
(so an inner span still sees its parent if an outer one enabled
logging mid-flight) but nothing is formatted or written.

:func:`event` appends a discrete (non-timed) record the same way —
fault injections, reloads, drains, integrity failures.  Both attach
the current boosting round (:func:`set_round`) when one is active, so
a chaos fault lands next to the round it hit in the timeline.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from xgboost_tpu.obs import events

_tls = threading.local()
_round_lock = threading.Lock()
_current_round: Optional[int] = None


def new_id() -> str:
    """Random 64-bit hex id (span/trace ids)."""
    return os.urandom(8).hex()


def current_trace_id() -> Optional[str]:
    return getattr(_tls, "trace", None)


def current_span_id() -> Optional[str]:
    stack = getattr(_tls, "spans", None)
    return stack[-1] if stack else None


@contextmanager
def trace_context(trace_id: Optional[str] = None):
    """Set the ambient trace id for this thread (e.g. from an incoming
    ``X-Request-Id``); restores the previous one on exit.  ``None``
    generates a fresh id."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace_id or new_id()
    try:
        yield _tls.trace
    finally:
        _tls.trace = prev


def set_round(version: Optional[int]) -> None:
    """Record the boosting round in progress (profiler/mock seam), so
    discrete events correlate with the round that produced them."""
    global _current_round
    with _round_lock:
        _current_round = version


def current_round() -> Optional[int]:
    return _current_round


class SpanHandle:
    """Yielded by :func:`span`; ``set(k, v)`` adds attributes after the
    span opened (row counts, status codes, ...)."""

    __slots__ = ("name", "attrs", "trace", "span_id", "parent")

    def __init__(self, name, attrs, trace, span_id, parent):
        self.name = name
        self.attrs = attrs
        self.trace = trace
        self.span_id = span_id
        self.parent = parent

    def set(self, key: str, value) -> None:
        self.attrs[key] = value


@contextmanager
def span(name: str, **attrs):
    """Time one named unit of work; emit a span record at exit when the
    event log is configured.  Exceptions propagate (recorded as
    ``status="error"``).

    Truly cheap when logging is off: no ids are generated and nothing
    is timed or formatted — only a ``None`` sentinel keeps the
    thread-local nesting depth consistent (a log enabled mid-span emits
    from the NEXT span on; the in-flight one is dropped, which is the
    right trade for a hot serving path)."""
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    if events.get_log() is None:
        stack.append(None)
        try:
            yield SpanHandle(name, attrs, getattr(_tls, "trace", None),
                             None, None)
        finally:
            stack.pop()
        return
    parent = stack[-1] if stack else None
    trace = getattr(_tls, "trace", None)
    own_trace = trace is None
    if own_trace:
        trace = new_id()
        _tls.trace = trace
    sid = new_id()
    stack.append(sid)
    handle = SpanHandle(name, attrs, trace, sid, parent)
    t0 = time.perf_counter()
    ts = time.time()
    err: Optional[BaseException] = None
    try:
        yield handle
    except BaseException as e:
        err = e
        raise
    finally:
        stack.pop()
        if own_trace:
            _tls.trace = None
        if events.get_log() is not None:
            rec = {"ts": round(ts, 6), "kind": "span", "name": name,
                   "trace": trace, "span": sid,
                   "dur_ms": round((time.perf_counter() - t0) * 1e3, 3)}
            if parent is not None:
                rec["parent"] = parent
            rnd = current_round()
            if rnd is not None:
                rec["round"] = rnd
            if err is not None:
                rec["status"] = "error"
                rec["error"] = f"{type(err).__name__}: {err}"
            if handle.attrs:
                rec["attrs"] = handle.attrs
            events.emit(rec)


def event(name: str, **fields) -> None:
    """Append one discrete (non-timed) event record (no-op when the log
    is off)."""
    if events.get_log() is None:
        return
    rec = {"ts": round(time.time(), 6), "kind": "event", "name": name}
    trace = current_trace_id()
    if trace is not None:
        rec["trace"] = trace
    rnd = current_round()
    if rnd is not None:
        rec["round"] = rnd
    if fields:
        rec["attrs"] = fields
    events.emit(rec)

"""Prometheus-style metric primitives and the process-wide registry.

This is the metrics half of the observability layer (OBSERVABILITY.md):
the :class:`Counter`/:class:`Gauge`/:class:`Histogram` primitives that
``xgboost_tpu.serving`` introduced, plus labeled families, plus ONE
process-wide :class:`MetricsRegistry` that every metric group —
:class:`ServingMetrics`, :class:`ReliabilityMetrics`, the training-side
:class:`TrainingMetrics`, and the collective-seam counters
(:mod:`xgboost_tpu.obs.comm`) — registers into, so a single
``render()`` covers the whole process regardless of which subsystems
are active.  The reference's analog is ``report_stats``
(``subtree/rabit/src/allreduce_mock.h:52-56,87-95``): one place that
accounts for allreduce time and checkpoint cost per version.

``xgboost_tpu.profiling`` re-exports everything here for backward
compatibility.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# latency buckets in seconds: 0.5ms .. 5s, roughly x2 per step
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
# batch-size buckets in rows: powers of two
_ROWS_BUCKETS = tuple(float(1 << i) for i in range(15))
# per-round wall-time buckets in seconds: 1ms .. 60s
_ROUND_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5, 5.0, 15.0, 60.0)


def _fmt(v: float) -> str:
    return f"{int(v)}" if float(v).is_integer() else repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    def __init__(self, name: str, help_text: str = ""):
        self.name, self.help = name, help_text
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {_fmt(self._v)}\n")


class Gauge:
    """Settable value (Prometheus ``gauge``)."""

    def __init__(self, name: str, help_text: str = ""):
        self.name, self.help = name, help_text
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v

    def render(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} gauge\n"
                f"{self.name} {_fmt(self._v)}\n")


class LabeledCounter:
    """One counter FAMILY with a single label dimension — e.g.
    ``xgbtpu_training_phase_seconds_total{phase="grow"}``.  The family
    renders one HELP/TYPE header and one sample per observed label
    value, which is what scrapers (and the exposition lint test)
    expect of labeled families."""

    def __init__(self, name: str, label: str, help_text: str = ""):
        self.name, self.label, self.help = name, label, help_text
        self._v: Dict[str, float] = {}
        self._lock = threading.Lock()

    def inc(self, label_value: str, v: float = 1.0) -> None:
        with self._lock:
            self._v[label_value] = self._v.get(label_value, 0.0) + v

    def value(self, label_value: str) -> float:
        return self._v.get(label_value, 0.0)

    def values(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._v)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._v.items())
        for lv, v in items:
            lines.append(f'{self.name}{{{self.label}="{_escape_label(lv)}"}}'
                         f' {_fmt(v)}')
        return "\n".join(lines) + "\n"


class LabeledGauge:
    """Gauge family with one label dimension (e.g. eval scores keyed by
    ``set-metric``)."""

    def __init__(self, name: str, label: str, help_text: str = ""):
        self.name, self.label, self.help = name, label, help_text
        self._v: Dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, label_value: str, v: float) -> None:
        with self._lock:
            self._v[label_value] = float(v)

    def value(self, label_value: str) -> float:
        return self._v.get(label_value, 0.0)

    def values(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._v)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._v.items())
        for lv, v in items:
            lines.append(f'{self.name}{{{self.label}="{_escape_label(lv)}"}}'
                         f' {_fmt(v)}')
        return "\n".join(lines) + "\n"


class Histogram:
    """Fixed-bucket histogram (Prometheus ``histogram``) with quantile
    estimation by linear interpolation within the winning bucket —
    enough resolution for p50/p99 gauges on the metrics page."""

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = _LATENCY_BUCKETS):
        self.name, self.help = name, help_text
        self.bounds = tuple(sorted(buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, x: float) -> None:
        i = bisect.bisect_left(self.bounds, x)
        with self._lock:
            self._counts[i] += 1
            self._sum += x
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate q-quantile from the bucket counts.  Edge cases
        are exact: no observations -> 0.0; ``q<=0`` -> the lower edge of
        the first non-empty bucket; ``q>=1`` -> the upper edge of the
        last non-empty finite bucket (the top finite bound when the
        overflow bucket holds observations)."""
        with self._lock:
            n = self._n
            counts = list(self._counts)
        if n == 0:
            return 0.0
        if q <= 0.0:
            # lower edge of the first non-empty bucket (0.0 below the
            # first bound) — previously this returned bounds[0] even
            # when the first buckets were empty
            for i, c in enumerate(counts):
                if c > 0:
                    return self.bounds[i - 1] if i > 0 else 0.0
            return 0.0
        target = min(q, 1.0) * n
        cum = 0.0
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else lo
                if hi <= lo:
                    return hi
                return lo + (hi - lo) * (target - prev) / c
        return self.bounds[-1]

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        with self._lock:
            counts = list(self._counts)
            total, s = self._n, self._sum
        for bound, c in zip(self.bounds, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {_fmt(s)}")
        lines.append(f"{self.name}_count {total}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- registry
class MetricsRegistry:
    """Process-wide registry of named metric GROUPS.

    Groups (not individual metrics) register a render callable under a
    stable name; re-registering a name replaces the previous group (a
    test that builds several ``ServingMetrics`` keeps exactly one
    registered).  :meth:`render` concatenates every group — the body of
    the training ``/metrics`` endpoint, and the tail of the serving
    one."""

    def __init__(self):
        self._groups: Dict[str, Callable[[], str]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, render_fn: Callable[[], str]) -> None:
        with self._lock:
            self._groups[name] = render_fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._groups)

    def render(self, exclude: Sequence[str] = ()) -> str:
        with self._lock:
            groups = [(n, fn) for n, fn in self._groups.items()
                      if n not in exclude]
        return "".join(fn() for _, fn in groups)


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    """The process-wide MetricsRegistry singleton."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


# ------------------------------------------------------------------ errors
_SWALLOWED: Optional[LabeledCounter] = None
_SWALLOWED_LOCK = threading.Lock()
_SWALLOW_EVENT_INTERVAL_S = 60.0
_swallow_last_event: Dict[str, float] = {}
_swallow_tls = threading.local()


def swallowed_errors() -> LabeledCounter:
    """The process-wide ``xgbtpu_swallowed_errors_total{site}`` family:
    every deliberately swallowed exception in the tree is counted here
    (the XGT004 lint rule enforces it), so "errors that vanish" become
    a scrapeable number instead of silence."""
    global _SWALLOWED
    if _SWALLOWED is None:
        with _SWALLOWED_LOCK:
            if _SWALLOWED is None:
                c = LabeledCounter(
                    "xgbtpu_swallowed_errors_total", "site",
                    "exceptions deliberately swallowed, by site")
                registry().register("errors", c.render)
                _SWALLOWED = c
    return _SWALLOWED


def swallowed_error(site: str, exc: Optional[BaseException] = None,
                    emit_event: bool = True) -> None:
    """Account a deliberately swallowed exception — the XGT004 fix
    recipe (ANALYSIS.md): increments
    ``xgbtpu_swallowed_errors_total{site=...}`` and, at most once per
    site per minute, emits a throttled ``error.swallowed`` obs event.

    NEVER raises: this runs inside ``except`` blocks on paths (the
    event log's own write failure, ``__del__`` at interpreter shutdown)
    where a second failure must not escape.  ``emit_event=False`` keeps
    callers that sit UNDER the event log (obs/events.py itself) from
    recursing into it; a thread-local guard backstops the same."""
    try:
        swallowed_errors().inc(site)
        if not emit_event or getattr(_swallow_tls, "active", False):
            return
        now = time.monotonic()
        with _SWALLOWED_LOCK:
            last = _swallow_last_event.get(site)
            if last is not None and now - last < _SWALLOW_EVENT_INTERVAL_S:
                return
            _swallow_last_event[site] = now
        _swallow_tls.active = True
        try:
            from xgboost_tpu.obs.trace import event
            event("error.swallowed", site=site,
                  error=f"{type(exc).__name__}: {exc}" if exc else "")
        finally:
            _swallow_tls.active = False
    except Exception:  # xgtpu: disable=XGT004 — accounting must not raise
        pass


# ------------------------------------------------------------- reliability
class ReliabilityMetrics:
    """Process-wide failure-path accounting (RELIABILITY.md): how often
    the crash-safety machinery actually engaged.  One instance per
    process (:func:`reliability_metrics`), shared by the learner's
    model I/O, the CLI checkpoint ring, and the serving stack; rendered
    into every ``/metrics`` body via the registry."""

    def __init__(self, prefix: str = "xgbtpu_reliability"):
        p = prefix
        self.integrity_failures = Counter(
            f"{p}_integrity_failures_total",
            "persisted files that failed CRC/footer verification")
        self.ring_fallbacks = Counter(
            f"{p}_ckpt_ring_fallbacks_total",
            "checkpoint loads that fell back past a corrupt ring member")
        self.quarantines = Counter(
            f"{p}_quarantined_files_total",
            "corrupt files moved aside as *.corrupt")
        self.poisoned_reloads = Counter(
            f"{p}_poisoned_reload_skips_total",
            "reload polls skipped because the file content is known-bad")
        self.shed_requests = Counter(
            f"{p}_shed_requests_total",
            "abandoned (caller timed out) requests shed before dispatch")
        self.faults_injected = Counter(
            f"{p}_faults_injected_total",
            "chaos faults fired by the injection registry")
        self.drain_seconds = Gauge(
            f"{p}_drain_seconds",
            "duration of the last HTTP drain (SIGTERM to stopped)")
        # deadline discipline (reliability/deadline.py): requests turned
        # away BEFORE work because their budget was spent, and expired
        # batch entries dropped before device dispatch
        self.deadline_rejected = Counter(
            "xgbtpu_deadline_rejected_total",
            "requests rejected before device work because the deadline "
            "budget was spent or cannot cover observed service time")
        self.deadline_dropped = Counter(
            "xgbtpu_deadline_dropped_total",
            "expired requests dropped by the micro-batcher pre-dispatch")
        # gang-launcher stall/death accounting (parallel/launch.py):
        # RECOVERY.md recovery-cost bookkeeping, scrapeable like
        # everything else instead of stderr-only
        self.launch_worker_deaths = Counter(
            "xgbtpu_launch_worker_deaths_total",
            "worker processes observed dead nonzero by the gang "
            "launcher")
        self.launch_restarts = LabeledCounter(
            "xgbtpu_launch_restarts_total", "reason",
            "whole-gang restarts by the launcher, by reason "
            "(death = nonzero worker exit, stall = watchdog kill, "
            "fence = worker self-fenced, host_loss = permanent host "
            "death, growback = re-expansion to full size)")
        # elastic degraded-mesh recovery (RECOVERY.md degraded-mode
        # matrix): gang size re-planning, partition fencing, grow-back
        self.launch_mesh_size = Gauge(
            "xgbtpu_launch_mesh_size",
            "devices the launcher's current gang plan schedules "
            "(workers x local devices); drops on degrade, restores on "
            "grow-back")
        self.launch_degraded = Gauge(
            "xgbtpu_launch_degraded",
            "1 while the gang runs below its full planned size")
        self.launch_fences = Counter(
            "xgbtpu_launch_fence_total",
            "workers that self-fenced after the coordinator was "
            "unreachable past gang_partition_sec")
        self.launch_growbacks = Counter(
            "xgbtpu_launch_growbacks_total",
            "degraded gangs re-expanded to full size after a "
            "replacement worker registered")
        self._all = (self.integrity_failures, self.ring_fallbacks,
                     self.quarantines, self.poisoned_reloads,
                     self.shed_requests, self.faults_injected,
                     self.drain_seconds, self.deadline_rejected,
                     self.deadline_dropped, self.launch_worker_deaths,
                     self.launch_restarts, self.launch_mesh_size,
                     self.launch_degraded, self.launch_fences,
                     self.launch_growbacks)
        registry().register("reliability", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_RELIABILITY: Optional[ReliabilityMetrics] = None
_RELIABILITY_LOCK = threading.Lock()


def reliability_metrics() -> ReliabilityMetrics:
    """The process-wide ReliabilityMetrics singleton.  Counters are
    cumulative for the process lifetime; tests read deltas."""
    global _RELIABILITY
    if _RELIABILITY is None:
        with _RELIABILITY_LOCK:
            if _RELIABILITY is None:
                _RELIABILITY = ReliabilityMetrics()
    return _RELIABILITY


# ---------------------------------------------------------------- training
class TrainingMetrics:
    """Training-side metric group (``xgbtpu_training_*``): live progress
    of a long run, scrapeable mid-run via the ``metrics_port=`` daemon
    (obs/server.py).  One instance per process
    (:func:`training_metrics`), fed by the round profiler
    (obs/profiler.py), the eval path, and the CLI checkpoint loop."""

    def __init__(self, prefix: str = "xgbtpu_training"):
        p = prefix
        self.rounds = Counter(
            f"{p}_rounds_total", "boosting rounds completed")
        self.round = Gauge(
            f"{p}_round", "most recently completed boosting round index")
        self.round_seconds = Histogram(
            f"{p}_round_seconds", "wall time per boosting round",
            _ROUND_BUCKETS)
        self.phase_seconds = LabeledCounter(
            f"{p}_phase_seconds_total", "phase",
            "cumulative wall seconds per round phase "
            "(predict/gradient/grow/eval)")
        self.eval_score = LabeledGauge(
            f"{p}_eval_score", "key",
            "latest eval metric values, keyed set-metric")
        self.checkpoints = Counter(
            f"{p}_checkpoints_total", "model checkpoints written")
        self.checkpoint_seconds = Counter(
            f"{p}_checkpoint_seconds_total",
            "cumulative wall seconds spent writing checkpoints "
            "(the reference report_stats' checkpoint cost)")
        self.device_memory = Gauge(
            f"{p}_device_memory_bytes",
            "bytes in use on local device 0 (0 when the backend does "
            "not report memory stats)")
        # segmented round fusion (learner.update_many): one fused
        # dispatch covers a SEGMENT of rounds, so round_seconds goes
        # quiet on the fused path — these two carry the progress signal
        # instead (note the xgbtpu_train_ family, not xgbtpu_training_:
        # the dispatch is a device-launch unit, not a logical round)
        self.dispatch_seconds = Histogram(
            "xgbtpu_train_dispatch_seconds",
            "wall time per fused training dispatch (one scan over a "
            "segment of boosting rounds, device-blocked at the "
            "segment boundary)", _ROUND_BUCKETS)
        self.rounds_per_dispatch = Gauge(
            "xgbtpu_train_rounds_per_dispatch",
            "rounds covered by the most recent fused training dispatch "
            "(segment size; stays 0 on the per-round path)")
        # loud fallback accounting: a multi-round train request that
        # took the per-round path instead of segmented fusion, by the
        # first failing eligibility reason (update_many's gate).  A
        # chaos or bench run that MEANT to measure the fused path
        # asserts this stays 0 (paired with the train.fused_fallback
        # obs event carrying the full reason list).
        self.fused_fallback = LabeledCounter(
            "xgbtpu_train_fused_fallback_total", "reason",
            "multi-round training runs that fell back from segmented "
            "round fusion to per-round dispatch, by first failing "
            "eligibility reason")
        self._all = (self.rounds, self.round, self.round_seconds,
                     self.phase_seconds, self.eval_score,
                     self.checkpoints, self.checkpoint_seconds,
                     self.device_memory, self.dispatch_seconds,
                     self.rounds_per_dispatch, self.fused_fallback)
        registry().register("training", self.render)

    def observe_eval(self, scores: Dict[str, float]) -> None:
        """Record parsed eval-line scores (``{'train-error': 0.02}``)
        as gauges."""
        for k, v in scores.items():
            try:
                self.eval_score.set(k, float(v))
            except (TypeError, ValueError):
                pass

    def refresh_device_memory(self) -> None:
        """Best-effort device-memory gauge via
        ``jax.local_devices()[0].memory_stats()`` (TPU/GPU report it;
        CPU returns None — the gauge stays 0 there)."""
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            if stats:
                self.device_memory.set(float(stats.get("bytes_in_use", 0)))
        except Exception as e:
            # CPU backends report no memory stats; the gauge stays 0 —
            # but the miss is counted, not invisible
            swallowed_error("obs.metrics.device_memory", e,
                            emit_event=False)

    def render(self) -> str:
        self.refresh_device_memory()
        return "".join(m.render() for m in self._all)


_TRAINING: Optional[TrainingMetrics] = None
_TRAINING_LOCK = threading.Lock()


def training_metrics() -> TrainingMetrics:
    """The process-wide TrainingMetrics singleton."""
    global _TRAINING
    if _TRAINING is None:
        with _TRAINING_LOCK:
            if _TRAINING is None:
                _TRAINING = TrainingMetrics()
    return _TRAINING


# ---------------------------------------------------------------- predict
class PredictMetrics:
    """Prediction-path metric group (``xgbtpu_predict_*``): attributes
    the chunked tree-parallel traversal (models/tree.py) in /metrics.
    One instance per process (:func:`predict_metrics`), fed by
    ``Learner.predict`` and the serving ``PredictEngine``; rendered into
    every scrape via the registry."""

    def __init__(self, prefix: str = "xgbtpu_predict"):
        p = prefix
        self.rows = Counter(
            f"{p}_rows_total",
            "rows predicted through the gbtree traversal "
            "(Learner.predict + serving engine)")
        self.chunk_seconds = Histogram(
            f"{p}_chunk_seconds",
            "device traversal wall seconds per tree chunk "
            "(margin launch time / chunk count)", _LATENCY_BUCKETS)
        self.transfer_seconds = Histogram(
            f"{p}_transfer_seconds",
            "host→device feature upload wall seconds per transfer "
            "(prediction paths: learner blocks, engine batches, "
            "feature-store puts)", _LATENCY_BUCKETS)
        self.transfer_bytes = Counter(
            f"{p}_transfer_bytes_total",
            "host→device feature bytes uploaded on prediction paths "
            "(flat while the feature store serves resident entities)")
        self._all = (self.rows, self.chunk_seconds,
                     self.transfer_seconds, self.transfer_bytes)
        registry().register("predict", self.render)

    def observe_transfer(self, nbytes: int, seconds: float) -> None:
        """Account one host→device feature upload (the transfer-wall
        counters, round 7): every prediction-path upload feeds these, so
        'zero upload' claims (feature-store steady state) are assertable
        from /metrics instead of taken on faith."""
        self.transfer_bytes.inc(nbytes)
        self.transfer_seconds.observe(seconds)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_PREDICT: Optional[PredictMetrics] = None
_PREDICT_LOCK = threading.Lock()


def predict_metrics() -> PredictMetrics:
    """The process-wide PredictMetrics singleton."""
    global _PREDICT
    if _PREDICT is None:
        with _PREDICT_LOCK:
            if _PREDICT is None:
                _PREDICT = PredictMetrics()
    return _PREDICT


def timed_device_put(arr, observe=None):
    """THE prediction-upload sequence: ``device_put`` + block + optional
    transfer accounting, in one place (learner blocks, the sparse
    host-binned path, engine batches, the prefetch pipeline's worker).
    ``observe`` is an ``(nbytes, seconds)`` callback — usually
    ``predict_metrics().observe_transfer``; ``None`` uploads without
    observing (engine warmup traffic).  The feature store times its own
    slab scatter separately (the write is upload + in-place update)."""
    import time

    import jax
    t0 = time.perf_counter()
    dev = jax.device_put(arr)
    jax.block_until_ready(dev)
    if observe is not None:
        observe(getattr(arr, "nbytes", 0), time.perf_counter() - t0)
    return dev


# ------------------------------------------------------------ feature store
class FeatureStoreMetrics:
    """Device-resident feature-store accounting (``xgbtpu_featurestore_*``,
    SERVING.md): the hit/miss economics of the predict-by-id fast path
    and the LRU's byte pressure.  One instance per process
    (:func:`featurestore_metrics`); rendered into every /metrics body via
    the registry."""

    def __init__(self, prefix: str = "xgbtpu_featurestore"):
        p = prefix
        self.hits = Counter(
            f"{p}_hits_total",
            "entity rows served from the device-resident store")
        self.misses = Counter(
            f"{p}_misses_total",
            "entity lookups that were not resident")
        self.evictions = Counter(
            f"{p}_evictions_total",
            "entity rows evicted by LRU byte-budget pressure")
        self.resident_bytes = Gauge(
            f"{p}_resident_bytes",
            "feature bytes currently resident on device")
        self._all = (self.hits, self.misses, self.evictions,
                     self.resident_bytes)
        registry().register("featurestore", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_FEATURESTORE: Optional[FeatureStoreMetrics] = None
_FEATURESTORE_LOCK = threading.Lock()


def featurestore_metrics() -> FeatureStoreMetrics:
    """The process-wide FeatureStoreMetrics singleton."""
    global _FEATURESTORE
    if _FEATURESTORE is None:
        with _FEATURESTORE_LOCK:
            if _FEATURESTORE is None:
                _FEATURESTORE = FeatureStoreMetrics()
    return _FEATURESTORE


# ---------------------------------------------------------------- pipeline
class PipelineMetrics:
    """Continuous-training pipeline accounting (``xgbtpu_pipeline_*``,
    PIPELINE.md): the train→gate→publish cycle loop's health at a
    glance — cycles completed, gate verdicts, publish cost, trees
    shipped, and how stale the incumbent the fleet serves is.  One
    instance per process (:func:`pipeline_metrics`); rendered into
    every /metrics body via the registry."""

    def __init__(self, prefix: str = "xgbtpu_pipeline"):
        p = prefix
        self.cycles = Counter(
            f"{p}_cycles_total", "train→gate→publish cycles completed "
            "(any outcome: published, gate-failed, or idle)")
        self.cycle_seconds = Histogram(
            f"{p}_cycle_seconds", "wall time per pipeline cycle",
            _ROUND_BUCKETS)
        self.gate_pass = Counter(
            f"{p}_gate_pass_total", "candidates that passed the eval gate")
        self.gate_fail = Counter(
            f"{p}_gate_fail_total",
            "candidates rejected by the eval gate (incl. corrupt "
            "candidates failing CRC verification)")
        self.publishes = Counter(
            f"{p}_publishes_total",
            "gated models published to the serving path")
        self.publish_failures = Counter(
            f"{p}_publish_failures_total",
            "publish attempts that failed (I/O error or a rejected "
            "fleet canary rollout)")
        self.publish_seconds = Counter(
            f"{p}_publish_seconds_total",
            "cumulative wall seconds spent publishing gated models")
        self.trees_published = Counter(
            f"{p}_trees_published_total",
            "trees appended to the incumbent and published")
        self.quarantines = Counter(
            f"{p}_quarantines_total",
            "candidates quarantined (failed gate or failed verification)")
        self.resumes = Counter(
            f"{p}_resumes_total",
            "cycles resumed after a crash (checkpoint-ring mid-train "
            "resume or a re-gate of an already-trained candidate)")
        self.incumbent_age = Gauge(
            f"{p}_incumbent_age_seconds",
            "seconds since this pipeline last published (0 until the "
            "first publish)")
        self._published_at: Optional[float] = None
        self._all = (self.cycles, self.cycle_seconds, self.gate_pass,
                     self.gate_fail, self.publishes,
                     self.publish_failures, self.publish_seconds,
                     self.trees_published, self.quarantines,
                     self.resumes, self.incumbent_age)
        registry().register("pipeline", self.render)

    def note_publish(self) -> None:
        """Stamp the incumbent-age clock (monotonic — the gauge is a
        DURATION, XGT006)."""
        self._published_at = time.perf_counter()

    def render(self) -> str:
        if self._published_at is not None:
            self.incumbent_age.set(time.perf_counter()
                                   - self._published_at)
        return "".join(m.render() for m in self._all)


_PIPELINE: Optional[PipelineMetrics] = None
_PIPELINE_LOCK = threading.Lock()


def pipeline_metrics() -> PipelineMetrics:
    """The process-wide PipelineMetrics singleton."""
    global _PIPELINE
    if _PIPELINE is None:
        with _PIPELINE_LOCK:
            if _PIPELINE is None:
                _PIPELINE = PipelineMetrics()
    return _PIPELINE


class LaneMetrics:
    """Gang-batched tenant-lane accounting (``xgbtpu_lane_*``,
    PIPELINE.md "Gang-batched lanes"): how many tenants each stacked
    dispatch carried, how much of the stack was padding, how often a
    lane fell back to its own solo dispatch stream and why, and the
    shape-bucket population.  One instance per process
    (:func:`lane_metrics`); rendered into every /metrics body via the
    registry."""

    def __init__(self, prefix: str = "xgbtpu_lane"):
        p = prefix
        self.dispatches = Counter(
            f"{p}_dispatches_total",
            "stacked multi-tenant segment dispatches (one device launch "
            "each, regardless of how many lanes it carried)")
        self.stacked = Counter(
            f"{p}_stacked_total",
            "real tenant lane-segments advanced by stacked dispatches")
        self.padded = Counter(
            f"{p}_padded_total",
            "inactive pad lane-segments dispatched to round a bucket up "
            "to its power-of-two stack width")
        self.solo = LabeledCounter(
            f"{p}_solo_total", "reason",
            "lane cycles that ran the solo host-loop path instead of "
            "stacking, by first blocking reason")
        self.stack_width = Gauge(
            f"{p}_stack_width",
            "lane count (incl. padding) of the most recent stacked "
            "dispatch")
        self.buckets = Gauge(
            f"{p}_buckets",
            "distinct shape buckets in the most recent gang window")
        self.dispatch_seconds = Histogram(
            f"{p}_dispatch_seconds",
            "wall time per stacked segment dispatch (all lanes in the "
            "bucket advance together)", _ROUND_BUCKETS)
        self.restacks = Counter(
            f"{p}_restack_total",
            "bucket re-stacks: dispatches that rebuilt the stacked "
            "device columns instead of reusing the steady-bucket carry "
            "(lane churn, fresh data, or a first arrival)")
        self._all = (self.dispatches, self.stacked, self.padded,
                     self.solo, self.stack_width, self.buckets,
                     self.dispatch_seconds, self.restacks)
        registry().register("lanes", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_LANES: Optional[LaneMetrics] = None
_LANES_LOCK = threading.Lock()


def lane_metrics() -> LaneMetrics:
    """The process-wide LaneMetrics singleton."""
    global _LANES
    if _LANES is None:
        with _LANES_LOCK:
            if _LANES is None:
                _LANES = LaneMetrics()
    return _LANES


class StreamMetrics:
    """Streaming continuous-learning accounting (``xgbtpu_stream_*``,
    PIPELINE.md streaming section): batch ingest, micro-cycle
    composition, the idle/collecting/ready/catch-up state machine,
    backpressure, and the drift→cut-refresh loop.  One instance per
    process (:func:`stream_metrics`); rendered into every /metrics
    body via the registry."""

    def __init__(self, prefix: str = "xgbtpu_stream"):
        p = prefix
        self.batches = Counter(
            f"{p}_batches_total",
            "spooled row batches claimed into micro-cycle manifests")
        self.rows = Counter(
            f"{p}_rows_total", "rows consumed across all micro-cycles")
        self.cycles = Counter(
            f"{p}_cycles_total",
            "micro-cycle manifests composed (each commits its batch "
            "set before any data is returned)")
        self.backlog = Gauge(
            f"{p}_backlog",
            "unclaimed spooled batches ahead of the consumer")
        self.backpressure = Counter(
            f"{p}_backpressure_total",
            "producer pushes refused because the unclaimed backlog hit "
            "max_backlog (StreamBacklogFull)")
        self.state = Gauge(
            f"{p}_state",
            "stream source state: 0=idle 1=collecting 2=ready "
            "3=catch_up")
        self.drift_score = Gauge(
            f"{p}_drift_score",
            "max per-feature PSI of the sliding window vs the "
            "reference distribution, as of the last cycle")
        self.drift_events = Counter(
            f"{p}_drift_events_total",
            "drift FIRE edges (a score crossing the threshold while "
            "not already fired; hysteresis suppresses repeats)")
        self.cut_refreshes = Counter(
            f"{p}_cut_refreshes_total",
            "online quantile-cut rebuilds (sketch proposal unioned "
            "with live thresholds, incumbent rebound exactly)")
        self.refresh_seconds = Histogram(
            f"{p}_refresh_seconds",
            "wall time per online cut refresh (propose + union + "
            "persist)", _ROUND_BUCKETS)
        self.kept_features = Gauge(
            f"{p}_kept_features",
            "features surviving the EMA-gain screen for the current "
            "cycle (the histogram working set's F; full width when "
            "screening is off)")
        self._all = (self.batches, self.rows, self.cycles, self.backlog,
                     self.backpressure, self.state, self.drift_score,
                     self.drift_events, self.cut_refreshes,
                     self.refresh_seconds, self.kept_features)
        registry().register("stream", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_STREAM: Optional[StreamMetrics] = None
_STREAM_LOCK = threading.Lock()


def stream_metrics() -> StreamMetrics:
    """The process-wide StreamMetrics singleton."""
    global _STREAM
    if _STREAM is None:
        with _STREAM_LOCK:
            if _STREAM is None:
                _STREAM = StreamMetrics()
    return _STREAM


_TENANT_DEQUEUES: Optional[LabeledCounter] = None
_TENANT_DEQUEUES_LOCK = threading.Lock()


def tenant_dequeues() -> LabeledCounter:
    """The process-wide
    ``xgbtpu_batcher_tenant_dequeues_total{model}`` family: requests
    dequeued from the micro-batcher's accept queue per tenant — the
    observable side of weighted round-robin fairness (a heavy tenant's
    share of dequeues tracks its weight, not its queue depth)."""
    global _TENANT_DEQUEUES
    if _TENANT_DEQUEUES is None:
        with _TENANT_DEQUEUES_LOCK:
            if _TENANT_DEQUEUES is None:
                c = LabeledCounter(
                    "xgbtpu_batcher_tenant_dequeues_total", "model",
                    "micro-batcher dequeues per tenant (WRR fairness)")
                registry().register("batcher", c.render)
                _TENANT_DEQUEUES = c
    return _TENANT_DEQUEUES


# ------------------------------------------------------------------- fleet
class FleetMetrics:
    """Router-side fleet accounting (``xgbtpu_fleet_*``, SERVING.md
    fleet section): per-replica request/error attribution, the global
    admission budget's shed count, retry and breaker activity, and the
    membership gauge pair (registered vs in-rotation — their gap is the
    fleet's sick-replica count).  One instance per process
    (:func:`fleet_metrics`); rendered into every /metrics body via the
    registry."""

    def __init__(self, prefix: str = "xgbtpu_fleet"):
        p = prefix
        self.requests = LabeledCounter(
            f"{p}_requests_total", "replica",
            "requests dispatched by the router, by replica")
        self.errors = LabeledCounter(
            f"{p}_errors_total", "replica",
            "dispatches that failed (connect/5xx), by replica")
        self.latency = Histogram(
            f"{p}_latency_seconds",
            "router-side request latency, dispatch to response "
            "(includes the replica hop and any retry)")
        self.shed = Counter(
            f"{p}_shed_total",
            "requests shed with 503 by the router's in-flight budget")
        self.retries = Counter(
            f"{p}_retries_total",
            "requests retried on a second replica after a failure")
        self.breaker_trips = Counter(
            f"{p}_breaker_trips_total",
            "circuit breakers tripped open (consecutive failures)")
        self.breaker_open = LabeledGauge(
            f"{p}_breaker_open", "replica",
            "1 while a replica's circuit breaker is open/half-open")
        self.members = Gauge(
            f"{p}_members",
            "replicas currently in rotation (lease live + healthy + "
            "serving)")
        self.members_registered = Gauge(
            f"{p}_members_registered",
            "replicas currently registered (any state)")
        self.inflight = Gauge(
            f"{p}_inflight", "requests in flight through the router")
        self.rollouts = Counter(
            f"{p}_rollouts_total", "canary rollouts completed fleet-wide")
        self.rollbacks = Counter(
            f"{p}_rollbacks_total",
            "rollouts rolled back (gate failure or operator command)")
        # latency-aware ejection (fleet/membership.py): a slow-but-alive
        # replica sails under the failure-count breaker while wrecking
        # fleet p99 — these make the ejection state machine scrapeable
        self.slow_ejections = Counter(
            f"{p}_slow_ejections_total",
            "replicas ejected from least-loaded dispatch for latency "
            "(EWMA above k x the peers' median)")
        self.ejected = LabeledGauge(
            f"{p}_ejected", "replica",
            "1 while a replica is latency-ejected (awaiting its "
            "readmission probe)")
        self.replica_latency = LabeledGauge(
            f"{p}_replica_latency_ewma_seconds", "replica",
            "per-replica EWMA of router-observed dispatch latency")
        # heartbeat payload drift fix: every advertisement change the
        # membership table absorbs mid-lease (catalog delta, eviction)
        # is counted, so "how stale could the routing map have been"
        # is answerable from a scrape
        self.advert_updates = Counter(
            f"{p}_advert_updates_total",
            "heartbeats whose model/device advertisement differed "
            "from the membership table (map updated in place)")
        self._all = (self.requests, self.errors, self.latency, self.shed,
                     self.retries, self.breaker_trips, self.breaker_open,
                     self.members, self.members_registered, self.inflight,
                     self.rollouts, self.rollbacks, self.slow_ejections,
                     self.ejected, self.replica_latency,
                     self.advert_updates)
        registry().register("fleet", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_FLEET: Optional[FleetMetrics] = None
_FLEET_LOCK = threading.Lock()


def fleet_metrics() -> FleetMetrics:
    """The process-wide FleetMetrics singleton."""
    global _FLEET
    if _FLEET is None:
        with _FLEET_LOCK:
            if _FLEET is None:
                _FLEET = FleetMetrics()
    return _FLEET


# ----------------------------------------------------------------- catalog
class CatalogMetrics:
    """Replica-side model-catalog accounting (``xgbtpu_catalog_*``,
    SERVING.md catalog section): how many models are configured vs
    actually resident, where the shared device budget stands, and the
    admission/eviction churn of the cold tail.  One instance per
    process (:func:`catalog_metrics`); rendered into every /metrics
    body via the registry."""

    def __init__(self, prefix: str = "xgbtpu_catalog"):
        p = prefix
        self.models_configured = Gauge(
            f"{p}_models_configured",
            "models named in this replica's catalog manifest")
        self.models_resident = Gauge(
            f"{p}_models_resident",
            "models with a live engine on device right now")
        self.bytes_used = Gauge(
            f"{p}_bytes_used",
            "estimated device bytes held by resident model engines")
        self.bytes_budget = Gauge(
            f"{p}_bytes_budget",
            "serve_catalog_mb budget in bytes (0 = unlimited)")
        self.admissions = Counter(
            f"{p}_admissions_total",
            "evicted models re-built and re-warmed on demand")
        self.evictions = Counter(
            f"{p}_evictions_total",
            "cold models' engines LRU-evicted to fit the budget")
        self.requests = LabeledCounter(
            f"{p}_requests_total", "model",
            "catalog resolves served, by model name")
        self.unknown_model = Counter(
            f"{p}_unknown_model_total",
            "requests naming a model the catalog does not hold (404)")
        self._all = (self.models_configured, self.models_resident,
                     self.bytes_used, self.bytes_budget, self.admissions,
                     self.evictions, self.requests, self.unknown_model)
        registry().register("catalog", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_CATALOG: Optional[CatalogMetrics] = None
_CATALOG_LOCK = threading.Lock()


def catalog_metrics() -> CatalogMetrics:
    """The process-wide CatalogMetrics singleton."""
    global _CATALOG
    if _CATALOG is None:
        with _CATALOG_LOCK:
            if _CATALOG is None:
                _CATALOG = CatalogMetrics()
    return _CATALOG


# ------------------------------------------------------------------ tenant
class TenantMetrics:
    """Router-side per-tenant accounting (``xgbtpu_tenant_*``,
    SERVING.md catalog section): request/shed/latency per model name at
    the front door, so one tenant's overload is attributable — and
    provably isolated — at a glance.  Latency is a labeled
    milliseconds-sum counter; pair with ``requests_total`` for the
    per-tenant mean (per-tenant quantiles live in the bench/chaos
    reports, which sample client-side).  One instance per process
    (:func:`tenant_metrics`)."""

    def __init__(self, prefix: str = "xgbtpu_tenant"):
        p = prefix
        self.requests = LabeledCounter(
            f"{p}_requests_total", "model",
            "requests entering the router, by model name")
        self.shed = LabeledCounter(
            f"{p}_shed_total", "model",
            "requests shed by that tenant's quota (429 rate / "
            "503 in-flight)")
        self.latency_ms = LabeledCounter(
            f"{p}_latency_ms_total", "model",
            "cumulative router-side request milliseconds, by model")
        self.inflight = LabeledGauge(
            f"{p}_inflight", "model",
            "requests currently in flight through the router, by model")
        self._all = (self.requests, self.shed, self.latency_ms,
                     self.inflight)
        registry().register("tenant", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_TENANT: Optional[TenantMetrics] = None
_TENANT_LOCK = threading.Lock()


def tenant_metrics() -> TenantMetrics:
    """The process-wide TenantMetrics singleton."""
    global _TENANT
    if _TENANT is None:
        with _TENANT_LOCK:
            if _TENANT is None:
                _TENANT = TenantMetrics()
    return _TENANT


# ------------------------------------------------------------------ placer
class PlacerMetrics:
    """Control-plane accounting for the autonomous placer
    (``xgbtpu_placer_*``, SERVING.md "Autonomous placement"): plan
    churn, manifest-delta pushes, convergence state, and the elastic
    supervisor's band/resize activity.  One instance per process
    (:func:`placer_metrics`); rendered into every /metrics body via
    the registry."""

    def __init__(self, prefix: str = "xgbtpu_placer"):
        p = prefix
        self.ticks = Counter(
            f"{p}_ticks_total",
            "placement control-loop iterations (lease held)")
        self.standby_ticks = Counter(
            f"{p}_standby_ticks_total",
            "iterations skipped because another placer holds the lease")
        self.plans = Counter(
            f"{p}_plans_total",
            "target assignments computed that differ from the last")
        self.moves = LabeledCounter(
            f"{p}_moves_total", "kind",
            "tenant placement deltas decided, kind=attach|detach")
        self.pushes = Counter(
            f"{p}_pushes_total",
            "manifest-delta pushes sent to replica admin surfaces")
        self.push_errors = Counter(
            f"{p}_push_errors_total",
            "manifest-delta pushes that failed (replica unreachable "
            "or rejected)")
        self.tenants = Gauge(
            f"{p}_tenants",
            "tenant models under placer management")
        self.tenants_placed = Gauge(
            f"{p}_tenants_placed",
            "managed tenants with >=1 in-rotation host advertising "
            "them")
        self.converged = Gauge(
            f"{p}_converged",
            "1 while the fleet's advertised hosting matches the "
            "target assignment")
        self.fleet_util = Gauge(
            f"{p}_fleet_utilization",
            "EWMA of fleet in-flight / (replica_slots * replicas), "
            "the elastic band signal")
        self.replicas_target = Gauge(
            f"{p}_replicas_target",
            "replica count the elastic supervisor is converging to")
        self.resizes = LabeledCounter(
            f"{p}_resizes_total", "direction",
            "elastic resizes executed, direction=up|down")
        self.resize_holds = Counter(
            f"{p}_resize_holds_total",
            "resizes deferred because a rollout/canary soak was in "
            "flight (path-group pinning)")
        self._all = (self.ticks, self.standby_ticks, self.plans,
                     self.moves, self.pushes, self.push_errors,
                     self.tenants, self.tenants_placed, self.converged,
                     self.fleet_util, self.replicas_target, self.resizes,
                     self.resize_holds)
        registry().register("placer", self.render)

    def render(self) -> str:
        return "".join(m.render() for m in self._all)


_PLACER: Optional[PlacerMetrics] = None
_PLACER_LOCK = threading.Lock()


def placer_metrics() -> PlacerMetrics:
    """The process-wide PlacerMetrics singleton."""
    global _PLACER
    if _PLACER is None:
        with _PLACER_LOCK:
            if _PLACER is None:
                _PLACER = PlacerMetrics()
    return _PLACER


# ----------------------------------------------------------------- serving
class ServingMetrics:
    """Metric registry for the serving subsystem (see SERVING.md for the
    full schema).  One instance is shared by engine + batcher + registry
    + HTTP front end; :meth:`render` produces the ``GET /metrics`` body.
    The instance registers into the process-wide registry as group
    ``"serving"`` (latest instance wins), and its own render appends
    every OTHER registered group, so one scrape covers steady-state,
    failure-path, and training-side behavior at once."""

    def __init__(self, prefix: str = "xgbtpu_serving"):
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()  # uptime is a DURATION (XGT006)
        p = prefix
        self.requests = self.counter(
            f"{p}_requests_total", "prediction requests received")
        self.rows = self.counter(
            f"{p}_rows_total", "real (caller-supplied) rows predicted")
        self.padded_rows = self.counter(
            f"{p}_padded_rows_total",
            "padding rows added to reach the shape bucket")
        self.rejected = self.counter(
            f"{p}_rejected_total", "requests rejected with QueueFull (503)")
        self.errors = self.counter(
            f"{p}_errors_total", "requests that raised during prediction")
        self.batches = self.counter(
            f"{p}_batches_total", "coalesced device batches executed")
        self.compiles = self.counter(
            f"{p}_compiles_total", "predict executables compiled")
        self.reloads = self.counter(
            f"{p}_reloads_total", "successful model hot-reloads")
        self.reload_errors = self.counter(
            f"{p}_reload_errors_total", "failed model reload attempts")
        self.queue_rows = self.gauge(
            f"{p}_queue_rows", "rows currently waiting in the batch queue")
        self.model_version = self.gauge(
            f"{p}_model_version", "monotonic version of the served model")
        self.batch_rows = self.histogram(
            f"{p}_batch_rows", "rows per coalesced device batch",
            _ROWS_BUCKETS)
        self.latency = self.histogram(
            f"{p}_latency_seconds",
            "request latency, submit to result (includes queueing)")
        # p50/p99 latency as plain gauges (scrapers that don't do
        # histogram_quantile still get the headline numbers); refreshed
        # from the histogram at render time
        self.latency_p50 = self.gauge(
            f"{p}_latency_p50_seconds", "p50 request latency")
        self.latency_p99 = self.gauge(
            f"{p}_latency_p99_seconds", "p99 request latency")
        registry().register("serving", self._render_own)

    # ------------------------------------------------------- constructors
    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(Counter(name, help_text))

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(Gauge(name, help_text))

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = _LATENCY_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_text, buckets))

    def _register(self, m):
        with self._lock:
            if m.name in self._metrics:
                return self._metrics[m.name]
            self._metrics[m.name] = m
            return m

    # ------------------------------------------------------------- render
    @property
    def uptime_seconds(self) -> float:
        return time.perf_counter() - self._t0

    def quantiles(self, qs: Tuple[float, ...] = (0.5, 0.99)
                  ) -> Dict[float, float]:
        return {q: self.latency.quantile(q) for q in qs}

    def _render_own(self) -> str:
        self.latency_p50.set(self.latency.quantile(0.5))
        self.latency_p99.set(self.latency.quantile(0.99))
        with self._lock:
            metrics = list(self._metrics.values())
        return "".join(m.render() for m in metrics)

    def render(self) -> str:
        # every other registered group rides along (reliability has
        # always been here; training/comm join when active) so one
        # scrape covers the whole process
        reliability_metrics()  # ensure the classic tail exists
        return self._render_own() + registry().render(exclude=("serving",))

"""xgboost_tpu.obs — the unified observability layer (OBSERVABILITY.md).

Four pieces, one package:

- **tracing spans** (:mod:`~xgboost_tpu.obs.trace`): ``span(name,
  **attrs)`` with thread-local parent linkage and per-request /
  per-round trace ids, wired through the learner's round phases, the
  serving path (``X-Request-Id`` in -> batcher -> engine -> response
  header out) and checkpoint save/load;
- **structured event log** (:mod:`~xgboost_tpu.obs.events`): spans and
  discrete events (reload, drain, integrity failure, fault injection)
  append to a crash-safe JSONL file (``obs_log=`` / ``XGBTPU_OBS_LOG``)
  that ``tools/obs_report.py`` renders into a timeline;
- **metrics** (:mod:`~xgboost_tpu.obs.metrics`): one process-wide
  :class:`MetricsRegistry` that :class:`ServingMetrics`,
  :class:`ReliabilityMetrics`, :class:`TrainingMetrics` and the
  collective stats all register into, with one ``render()``;
- **training scrapeability + collective stats**
  (:mod:`~xgboost_tpu.obs.server`, :mod:`~xgboost_tpu.obs.comm`):
  ``metrics_port=`` serves ``/metrics`` + ``/healthz`` from a daemon
  thread during training, and the ``parallel/`` collective seam
  accounts each allreduce/allgather per round and per rank — the
  reference's ``report_stats`` (``allreduce_mock.h:52-56,87-95``).

``xgboost_tpu.profiling`` remains as a compatibility shim re-exporting
the metric primitives and :class:`RoundProfiler` from here.
"""

from xgboost_tpu.obs import comm  # noqa: F401
from xgboost_tpu.obs.events import (EventLog, configure_log,  # noqa: F401
                                    get_log)
from xgboost_tpu.obs.metrics import (Counter, Gauge,  # noqa: F401
                                     Histogram, LabeledCounter,
                                     LabeledGauge, LaneMetrics,
                                     MetricsRegistry,
                                     PipelineMetrics, PredictMetrics,
                                     ReliabilityMetrics, ServingMetrics,
                                     TrainingMetrics, lane_metrics,
                                     pipeline_metrics,
                                     predict_metrics, registry,
                                     reliability_metrics,
                                     training_metrics)
from xgboost_tpu.obs.profiler import RoundProfiler  # noqa: F401
from xgboost_tpu.obs.server import (get_metrics_server,  # noqa: F401
                                    start_metrics_server,
                                    stop_metrics_server)
from xgboost_tpu.obs.trace import (current_trace_id, event,  # noqa: F401
                                   span, trace_context)


def phases_enabled() -> bool:
    """True when round-phase instrumentation should run even without
    ``profile>=1``: the event log is configured, the metrics server is
    up, or ``XGBTPU_OBS=1``.  Phase timing forces device barriers at
    phase boundaries (and keeps the round loop on the host), so it is
    opt-in — the same cost contract as ``profile=1`` (PROFILE.md).

    ``XGBTPU_OBS_PHASES=0`` keeps a configured event log / metrics
    server WITHOUT the phase barriers: discrete events and dispatch
    spans still land in the JSONL log, but the fused multi-round
    dispatch stays eligible.  The chaos suite's fallback-free
    verification rides this — it needs ``train.fused_fallback`` events
    observable without the observer forcing the fallback."""
    import os
    if os.environ.get("XGBTPU_OBS_PHASES", "") == "0":
        return False
    if get_log() is not None or get_metrics_server() is not None:
        return True
    return os.environ.get("XGBTPU_OBS", "") not in ("", "0")


__all__ = [
    "comm", "span", "event", "trace_context", "current_trace_id",
    "EventLog", "configure_log", "get_log",
    "Counter", "Gauge", "Histogram", "LabeledCounter", "LabeledGauge",
    "MetricsRegistry", "registry",
    "ServingMetrics", "ReliabilityMetrics", "TrainingMetrics",
    "PredictMetrics", "predict_metrics",
    "PipelineMetrics", "pipeline_metrics",
    "LaneMetrics", "lane_metrics",
    "reliability_metrics", "training_metrics",
    "RoundProfiler",
    "start_metrics_server", "get_metrics_server", "stop_metrics_server",
    "phases_enabled",
]

"""Benchmark: gbtree training throughput on one TPU chip, 3 workloads.

Primary metric reproduces the shape of the reference's headline
benchmark (``demo/kaggle-higgs/speedtest.py``: depth 6, eta 0.1, binary
logistic — the config behind the "20x faster than sklearn" README
claim): trains ``BENCH_ROUNDS`` boosted trees of depth 6 on a synthetic
1M x 28 Higgs-like dataset and reports training-row throughput per chip
plus the achieved AUC on a held-out split.

The SAME json line also carries the other two workload families the
reference benchmarks (VERDICT r3 item 4 — a regression in either is now
driver-visible in BENCH_r*.json):

  - ``multiclass_ms_per_round``: 6-class softmax on 200k x 28
    (``demo/multiclass_classification`` shape) — exercises the vmapped
    K-tree ensemble growth path.
  - ``rank_rounds_per_sec``: rank:ndcg on 1M rows in 10k groups
    (``demo/rank`` shape) — exercises the fused device LambdaRank
    gradient.

Baseline for ``vs_baseline``: the reference CLI's MEASURED Higgs-1M
single-thread training rate from ``PARITY.json`` (produced by
``tools/parity.py`` — reference binary built from /root/reference and
timed on this host).  vs_baseline = our rows/s/chip divided by the
reference rows/s/thread; with 16 chips per v5e-16 pod and 16 threads
per CPU socket the factors cancel, so this single-chip ratio equals the
pod-vs-socket wall-clock ratio under (generous) linear CPU scaling —
the BASELINE.md target is >= 10.  Fallback when PARITY.json is absent:
the pre-measurement estimate 8e4 rows/s.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"multiclass_ms_per_round", "rank_rounds_per_sec", ...}.
``BENCH_WORKLOADS`` (comma list of binary,multiclass,rank) trims it.
"""

import json
import os
import time

import numpy as np


def make_higgs_like(n, f=28, seed=42):
    """Deterministic Higgs-like binary task: kinematic-ish features with a
    nonlinear decision surface and ~30% bayes noise."""
    rng = np.random.RandomState(seed)
    X = np.empty((n, f), dtype=np.float32)
    # mix of exponential (pT-like), gaussian (eta-like) and uniform features
    X[:, : f // 3] = rng.exponential(1.0, (n, f // 3))
    X[:, f // 3: 2 * f // 3] = rng.randn(n, f - 2 * (f // 3) + f // 3)[:, : f // 3]
    X[:, 2 * (f // 3):] = rng.rand(n, f - 2 * (f // 3))
    score = (np.sin(3 * X[:, 0]) + X[:, 1] * X[:, 2] - 0.5 * X[:, 3] ** 2
             + 2.0 * (X[:, 4] > 1.0) + 0.8 * rng.randn(n))
    y = (score > np.median(score)).astype(np.float32)
    return X, y


def _barrier_entry(bst, d):
    """True device barrier: block_until_ready is advisory on
    remote-attached backends (see PROFILE.md); a one-element host pull
    drains the in-order stream."""
    import jax
    m = bst._cache[id(d)].margin
    jax.block_until_ready(m)
    jax.device_get(m.ravel()[:1])


def _time_training(xgb, params, d, rounds):
    """Shared timing harness: one warm-up booster pays all jit
    compilation (round-0 single launch + the fused (rounds-1)-round
    scan); then best-of-BENCH_REPS fresh boosters hitting the shared
    jit caches (the tunnel-attached chip shows run-to-run interference
    of +-25%).  Returns (best seconds for rounds-1 rounds, last bst)."""
    warm = xgb.Booster(params, cache=[d])
    warm.update(d, 0)
    warm.update_many(d, 1, rounds - 1)
    _barrier_entry(warm, d)
    del warm
    dt = float("inf")
    for _ in range(int(os.environ.get("BENCH_REPS", 3))):
        bst = xgb.Booster(params, cache=[d])
        bst.update(d, 0)
        _barrier_entry(bst, d)
        t0 = time.perf_counter()
        bst.update_many(d, 1, rounds - 1)
        _barrier_entry(bst, d)
        dt = min(dt, time.perf_counter() - t0)
    return dt, bst


def bench_multiclass():
    """6-class softmax, 200k x 28, depth 6 (demo/multiclass_classification
    shape scaled up; exercises the vmapped ensemble growth).  Returns
    (ms_per_round, merror)."""
    import xgboost_tpu as xgb

    n, rounds = 200_000, 60
    rng = np.random.RandomState(7)
    X = rng.randn(n + 20_000, 28).astype(np.float32)
    centers = rng.randn(6, 28).astype(np.float32) * 1.2
    logits = X @ centers.T + 0.8 * rng.randn(n + 20_000, 6)
    y = logits.argmax(axis=1).astype(np.float32)
    d = xgb.DMatrix(X[:n], label=y[:n])
    dte = xgb.DMatrix(X[n:], label=y[n:])
    params = {"objective": "multi:softmax", "num_class": 6,
              "max_depth": 6, "eta": 0.3, "max_bin": 64}
    dt, bst = _time_training(xgb, params, d, rounds)
    pred = bst.predict(dte)
    merror = float((pred != y[n:]).mean())
    return dt / (rounds - 1) * 1e3, merror


def bench_rank():
    """rank:ndcg, 1M rows in 10k groups of 100, depth 6 (demo/rank
    shape scaled up; exercises the fused on-device LambdaRank).
    Returns (rounds_per_sec, ndcg)."""
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics as M

    n, gsize, rounds = 1_000_000, 100, 50
    rng = np.random.RandomState(11)
    X = rng.randn(n, 28).astype(np.float32)
    rel = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
           + 0.5 * rng.randn(n))
    y = np.clip((rel > 0.5) + (rel > 1.5), 0, 2).astype(np.float32)
    group = np.full(n // gsize, gsize, np.uint32)
    d = xgb.DMatrix(X, label=y)
    d.set_group(group)
    params = {"objective": "rank:ndcg", "max_depth": 6, "eta": 0.1,
              "max_bin": 64}
    dt, bst = _time_training(xgb, params, d, rounds)
    ndcg = M.ndcg(np.asarray(bst.predict(d)), np.asarray(d.info.label),
                  None, group_ptr=d.info.group_ptr)
    return (rounds - 1) / dt, float(ndcg)


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    workloads = [w.strip() for w in os.environ.get(
        "BENCH_WORKLOADS", "binary,multiclass,rank").split(",")]
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics

    out = {}
    if "binary" in workloads:
        X, y = make_higgs_like(n_rows + 100_000)
        Xtr, ytr = X[:n_rows], y[:n_rows]
        Xte, yte = X[n_rows:], y[n_rows:]
        dtrain = xgb.DMatrix(Xtr, label=ytr)
        dtest = xgb.DMatrix(Xte, label=yte)

        # max_bin=64: AUC-equal to the sketch's eps-driven 67 bins on
        # this task (measured 0.9455 at both, 100 rounds) and
        # MXU-aligned — the histogram dot's cost scales with
        # ceil(n_bin/8) sublane chunks
        params = {"objective": "binary:logistic", "max_depth": 6,
                  "eta": 0.1, "max_bin": 64, "eval_metric": "auc"}
        dt, bst = _time_training(xgb, params, dtrain, n_rounds)

        rounds_per_sec = (n_rounds - 1) / dt
        rows_per_sec = rounds_per_sec * n_rows
        auc = metrics.auc(bst.predict(dtest), yte, np.ones_like(yte))

        baseline_rows_per_sec = 8e4  # pre-measurement fallback (docstring)
        parity = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "PARITY.json")
        if os.path.exists(parity):
            with open(parity) as f:
                measured = json.load(f).get("baseline_1m", {})
            baseline_rows_per_sec = measured.get("rows_per_sec_1thread",
                                                 baseline_rows_per_sec)
        out = {
            "metric": "higgs1m_train_rows_per_sec_per_chip",
            "value": round(rows_per_sec, 1),
            "unit": f"rows/s (depth6 x {n_rounds} rounds, 1 chip; "
                    f"auc={auc:.4f}, rounds/s={rounds_per_sec:.2f})",
            "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 2),
        }
    if "multiclass" in workloads:
        mc_ms, mc_err = bench_multiclass()
        out["multiclass_ms_per_round"] = round(mc_ms, 2)
        out["multiclass_merror"] = round(mc_err, 4)
    if "rank" in workloads:
        rk_rps, rk_ndcg = bench_rank()
        out["rank_rounds_per_sec"] = round(rk_rps, 2)
        out["rank_ndcg"] = round(rk_ndcg, 4)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

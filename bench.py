"""Benchmark: gbtree training throughput on one TPU chip, 3 workloads.

Primary metric reproduces the shape of the reference's headline
benchmark (``demo/kaggle-higgs/speedtest.py``: depth 6, eta 0.1, binary
logistic — the config behind the "20x faster than sklearn" README
claim): trains ``BENCH_ROUNDS`` boosted trees of depth 6 on a synthetic
1M x 28 Higgs-like dataset and reports training-row throughput per chip
plus the achieved AUC on a held-out split.

The SAME json line also carries the other two workload families the
reference benchmarks (VERDICT r3 item 4 — a regression in either is now
driver-visible in BENCH_r*.json):

  - ``multiclass_ms_per_round``: 6-class softmax on 200k x 28
    (``demo/multiclass_classification`` shape) — exercises the vmapped
    K-tree ensemble growth path.
  - ``rank_rounds_per_sec``: rank:ndcg on 1M rows in 10k groups
    (``demo/rank`` shape) — exercises the fused device LambdaRank
    gradient.

Baseline for ``vs_baseline``: the reference CLI's MEASURED Higgs-1M
single-thread training rate from ``PARITY.json`` (produced by
``tools/parity.py`` — reference binary built from /root/reference and
timed on this host).  vs_baseline = our rows/s/chip divided by the
reference rows/s/thread; with 16 chips per v5e-16 pod and 16 threads
per CPU socket the factors cancel, so this single-chip ratio equals the
pod-vs-socket wall-clock ratio under (generous) linear CPU scaling —
the BASELINE.md target is >= 10.  Fallback when PARITY.json is absent:
the pre-measurement estimate 8e4 rows/s.

Round 5 widens the driver-visible surface (VERDICT r4 items 4-6):
``predict_rows_per_sec`` fields pin the prediction fast paths (round 6
splits them: ``predict_binned_rows_per_sec`` is the traversal-only
rate on the cached pre-binned matrix, so quantize/upload cost and the
chunked tree-parallel traversal cost are pinned separately); the
``otto`` (200k x 93, 9-class softprob — f_tile < F kernel tiling) and
``yearpred`` (500k x 90 regression) workloads time previously-untimed
kernel paths; ``extmem`` forces the over-budget STREAMING
external-memory path and reports rounds/s + staged MB/s.

Round 8 adds the ``fusion`` workload: segmented round fusion A/B —
per-round dispatch (``rounds_per_dispatch=0``, the same switch
``XGBTPU_ROUNDS_PER_DISPATCH=0`` flips) vs fused segments
K ∈ {1, 4, 16, 64} WITH a configured watchlist (the exact shape the
CLI gate used to force onto the per-round path), plus the eval-free
fused rate at K=16 so the device-resident eval's cost is
driver-visible (``fusion_watchlist_vs_noeval_k16``).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"multiclass_ms_per_round", "rank_rounds_per_sec", ...}.
``BENCH_WORKLOADS`` (comma list of binary,multiclass,rank,otto,
yearpred,extmem,fusion) trims it.
"""

import json
import os
import time

import numpy as np


def make_higgs_like(n, f=28, seed=42):
    """Deterministic Higgs-like binary task: kinematic-ish features with a
    nonlinear decision surface and ~30% bayes noise."""
    rng = np.random.RandomState(seed)
    X = np.empty((n, f), dtype=np.float32)
    # mix of exponential (pT-like), gaussian (eta-like) and uniform features
    X[:, : f // 3] = rng.exponential(1.0, (n, f // 3))
    X[:, f // 3: 2 * f // 3] = rng.randn(n, f - 2 * (f // 3) + f // 3)[:, : f // 3]
    X[:, 2 * (f // 3):] = rng.rand(n, f - 2 * (f // 3))
    score = (np.sin(3 * X[:, 0]) + X[:, 1] * X[:, 2] - 0.5 * X[:, 3] ** 2
             + 2.0 * (X[:, 4] > 1.0) + 0.8 * rng.randn(n))
    y = (score > np.median(score)).astype(np.float32)
    return X, y


def _barrier_entry(bst, d):
    """True device barrier: block_until_ready is advisory on
    remote-attached backends (see PROFILE.md); a one-element host pull
    drains the in-order stream."""
    import jax
    m = bst._cache[id(d)].margin
    jax.block_until_ready(m)
    jax.device_get(m.ravel()[:1])


def _time_training(xgb, params, d, rounds):
    """Shared timing harness: one warm-up booster pays all jit
    compilation (round-0 single launch + the fused (rounds-1)-round
    scan); then best-of-BENCH_REPS fresh boosters hitting the shared
    jit caches (the tunnel-attached chip shows run-to-run interference
    of +-25%).  Returns (best seconds for rounds-1 rounds, last bst)."""
    warm = xgb.Booster(params, cache=[d])
    warm.update(d, 0)
    warm.update_many(d, 1, rounds - 1)
    _barrier_entry(warm, d)
    del warm
    dt = float("inf")
    for _ in range(int(os.environ.get("BENCH_REPS", 3))):
        bst = xgb.Booster(params, cache=[d])
        bst.update(d, 0)
        _barrier_entry(bst, d)
        t0 = time.perf_counter()
        bst.update_many(d, 1, rounds - 1)
        _barrier_entry(bst, d)
        dt = min(dt, time.perf_counter() - t0)
    return dt, bst


def _time_predict(bst, make_input, n_rows):
    """Best-of-reps one-off prediction timing (predict returns a host
    numpy array, so the pull is the barrier).  A FRESH input per rep
    exercises the uncached path — round 7: raw f32 ndarray inputs ride
    the direct-buffer + fused quantize+traverse pipeline (upload
    overlapped block-wise, binned matrix never materialized), the
    serving-realistic shape of one-off scoring.  Also returns the
    measured host→device transfer rate from the round-7 counters
    (``predict_transfer_mb_per_sec``) so the transfer wall is pinned
    separately from end-to-end rows/s."""
    from xgboost_tpu.obs.metrics import predict_metrics
    bst.predict(make_input())                    # warm the jit caches
    pm = predict_metrics()
    dt = float("inf")
    b0, s0 = pm.transfer_bytes.value, pm.transfer_seconds.sum
    for _ in range(int(os.environ.get("BENCH_REPS", 3))):
        d = make_input()
        t0 = time.perf_counter()
        p = bst.predict(d)
        dt = min(dt, time.perf_counter() - t0)
        assert p.shape[0] == n_rows
    db = pm.transfer_bytes.value - b0
    ds = pm.transfer_seconds.sum - s0
    mbps = (db / 1e6 / ds) if ds > 0 else 0.0
    return n_rows / dt, mbps


def _time_predict_binned(bst, binned, n_rows):
    """Traversal-only rows/s on a PRE-BINNED device matrix: isolates
    the chunked tree-parallel ensemble traversal (models/tree.py
    ``predict_tree_chunk``) from quantize + upload.  ``_time_predict``
    keeps the combined uncached number, so BENCH json pins the two
    costs separately — a transfer regression and a traversal
    regression are no longer the same field."""
    import jax
    import jax.numpy as jnp
    base = jnp.zeros((), jnp.float32)

    def run():
        m = bst.gbtree.predict_margin(binned, base)
        jax.block_until_ready(m)
        jax.device_get(m.ravel()[:1])            # true tunnel barrier

    run()                                        # warm the jit caches
    dt = float("inf")
    for _ in range(int(os.environ.get("BENCH_REPS", 3))):
        t0 = time.perf_counter()
        run()
        dt = min(dt, time.perf_counter() - t0)
    return n_rows / dt


def bench_multiclass():
    """6-class softmax, 200k x 28, depth 6 (demo/multiclass_classification
    shape scaled up; exercises the vmapped ensemble growth).  Returns
    (ms_per_round, merror)."""
    import xgboost_tpu as xgb

    n, rounds = 200_000, 60
    rng = np.random.RandomState(7)
    X = rng.randn(n + 20_000, 28).astype(np.float32)
    centers = rng.randn(6, 28).astype(np.float32) * 1.2
    logits = X @ centers.T + 0.8 * rng.randn(n + 20_000, 6)
    y = logits.argmax(axis=1).astype(np.float32)
    d = xgb.DMatrix(X[:n], label=y[:n])
    dte = xgb.DMatrix(X[n:], label=y[n:])
    params = {"objective": "multi:softmax", "num_class": 6,
              "max_depth": 6, "eta": 0.3, "max_bin": 64}
    dt, bst = _time_training(xgb, params, d, rounds)
    pred = bst.predict(dte)
    merror = float((pred != y[n:]).mean())
    pred_rps, _ = _time_predict(
        bst, lambda: np.ascontiguousarray(X[:n]), n)
    pred_binned_rps = _time_predict_binned(
        bst, bst._cache[id(d)].binned, n)
    return dt / (rounds - 1) * 1e3, merror, pred_rps, pred_binned_rps


def bench_otto():
    """9-class softprob, 200k x 93, depth 6 (demo/kaggle-otto shape:
    otto_train_pred.py trains softprob on 93 features / 9 classes).
    Exercises the f_tile < F feature-tiling path of the pallas
    histogram kernel (first taken at F > 64 with B = 64) and wide-K
    vmapped ensemble growth — both untimed by the main workloads
    (VERDICT r4 Weak #4).  Returns (ms_per_round, mlogloss)."""
    import xgboost_tpu as xgb

    n, f, k, rounds = 200_000, 93, 9, 60
    rng = np.random.RandomState(21)
    X = rng.rand(n + 20_000, f).astype(np.float32) ** 2   # otto counts skew
    centers = rng.randn(k, f).astype(np.float32)
    logits = X @ centers.T + 0.5 * rng.randn(n + 20_000, k)
    y = logits.argmax(axis=1).astype(np.float32)
    d = xgb.DMatrix(X[:n], label=y[:n])
    dte = xgb.DMatrix(X[n:], label=y[n:])
    params = {"objective": "multi:softprob", "num_class": k,
              "max_depth": 6, "eta": 0.3, "max_bin": 64}
    dt, bst = _time_training(xgb, params, d, rounds)
    p = np.asarray(bst.predict(dte)).reshape(-1, k)
    yi = y[n:].astype(np.int64)
    mll = float(-np.mean(np.log(np.clip(p[np.arange(len(yi)), yi],
                                        1e-15, 1.0))))
    return dt / (rounds - 1) * 1e3, mll


def bench_yearpred():
    """Squared-error regression, 500k x 90, depth 6 (demo/yearpredMSD
    shape: 90 audio features, year target).  Exercises the same wide-F
    kernel tiling single-output — the regression family is otherwise
    driver-invisible.  Returns (rounds_per_sec, rmse)."""
    import xgboost_tpu as xgb

    n, f, rounds = 500_000, 90, 60
    rng = np.random.RandomState(31)
    X = rng.randn(n + 50_000, f).astype(np.float32)
    yr = (1998.0 + 8.0 * np.tanh(X[:, 0] + 0.5 * X[:, 1] * X[:, 2])
          + 2.0 * rng.randn(n + 50_000)).astype(np.float32)
    d = xgb.DMatrix(X[:n], label=yr[:n])
    dte = xgb.DMatrix(X[n:], label=yr[n:])
    params = {"objective": "reg:linear", "max_depth": 6, "eta": 0.3,
              "max_bin": 64, "base_score": float(yr[:n].mean())}
    dt, bst = _time_training(xgb, params, d, rounds)
    pred = np.asarray(bst.predict(dte))
    rmse = float(np.sqrt(np.mean((pred - yr[n:]) ** 2)))
    return (rounds - 1) / dt, rmse


def bench_extmem():
    """STREAMING external-memory training: the bench config (1M x 28,
    depth 6) forced over-budget with a 16 MB device cache so every
    level streams binned batches host→device (the out-of-HBM path —
    in-budget matrices collapse to the in-memory fast path and never
    exercise it; VERDICT r4 Missing #4).  Background prefetch
    (external._prefetch_to_device) overlaps batch staging with device
    compute; the A/B against synchronous staging is in PROFILE.md.
    Returns (rounds_per_sec, staged_MB_per_sec, auc).  Reference
    counterpart: page_dmatrix-inl.hpp:20-60 prints ingest MB/s at
    runtime (:172-177)."""
    import shutil
    import tempfile
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics as M
    from xgboost_tpu.external import ExtMemDMatrix

    n, rounds = 1_000_000, 6
    X, y = make_higgs_like(n + 100_000)
    cache = os.path.join(tempfile.mkdtemp(prefix="xgbtpu_bench_ext_"), "m")

    def chunks():
        for s in range(0, n, 1 << 18):
            yield X[s:s + (1 << 18)], y[s:s + (1 << 18)]

    # 256k-row pages: the tunnel-attached chip pays ~100 ms RTT per
    # upload, so batches amortize it (7.3 MB each at 33 MB/s measured)
    d = ExtMemDMatrix(chunks(), cache=cache, page_rows=1 << 18)
    params = {"objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
              "max_bin": 64}
    old = os.environ.get("XGTPU_EXT_DEVICE_CACHE_MB")
    os.environ["XGTPU_EXT_DEVICE_CACHE_MB"] = "16"
    try:
        bst = xgb.Booster(params, cache=[d])
        bst.update(d, 0)                       # compile + first round
        _barrier_entry(bst, d)
        t0 = time.perf_counter()
        for i in range(1, rounds):
            bst.update(d, i)
        _barrier_entry(bst, d)
        dt = time.perf_counter() - t0
    finally:
        if old is None:
            os.environ.pop("XGTPU_EXT_DEVICE_CACHE_MB", None)
        else:
            os.environ["XGTPU_EXT_DEVICE_CACHE_MB"] = old
    rps = (rounds - 1) / dt
    # bytes staged per round: every non-terminal level re-streams the
    # whole binned matrix (+ the per-round delta/margin pass)
    staged_mb = (n * 28 * (6 + 1)) / 1e6
    auc = M.auc(bst.predict(xgb.DMatrix(X[n:], label=y[n:])), y[n:],
                np.ones(100_000, np.float32))
    del d, bst     # release the memmap before removing its backing dir
    shutil.rmtree(os.path.dirname(cache), ignore_errors=True)
    return rps, staged_mb * rps, float(auc)


def bench_fusion():
    """Segmented round fusion A/B (round 8): rounds/s of the per-round
    baseline (K=0) vs fused segments K ∈ {1, 4, 16, 64}, all WITH a
    watchlist (held-out eval set + train-as-eval, auc) — the workload
    shape that rode the per-round path before the segmented driver.
    ``noeval_k16`` times the eval-free fused path so the device-resident
    eval's cost is pinned: the round-8 gate is watchlist rounds/s at
    K=16 within 15% of it.  Returns a flat field dict."""
    import xgboost_tpu as xgb

    n = int(os.environ.get("BENCH_FUSION_ROWS",
                           os.environ.get("BENCH_ROWS", 1_000_000)))
    # rounds-1 timed rounds; 65 makes the K=64 cell one full segment
    rounds = int(os.environ.get("BENCH_FUSION_ROUNDS", 65))
    reps = int(os.environ.get("BENCH_REPS", 3))
    X, y = make_higgs_like(n + n // 10 + 1)
    d = xgb.DMatrix(X[:n], label=y[:n])
    dval = xgb.DMatrix(X[n:], label=y[n:])
    params = {"objective": "binary:logistic", "max_depth": 6,
              "eta": 0.1, "max_bin": 64, "eval_metric": "auc"}

    def time_cfg(k, with_eval):
        evals = [(dval, "eval"), (d, "train")] if with_eval else None
        dt = float("inf")
        for rep in range(reps + 1):               # rep 0 pays compilation
            bst = xgb.Booster(params, cache=[d, dval])
            bst.update(d, 0)
            _barrier_entry(bst, d)
            t0 = time.perf_counter()
            bst.update_many(d, 1, rounds - 1, evals=evals,
                            rounds_per_dispatch=k)
            _barrier_entry(bst, d)
            if rep:
                dt = min(dt, time.perf_counter() - t0)
        return (rounds - 1) / dt

    out = {}
    for k in (0, 1, 4, 16, 64):
        out[f"fusion_eval_rounds_per_sec_k{k}"] = round(
            time_cfg(k, True), 3)
    out["fusion_noeval_rounds_per_sec_k16"] = round(time_cfg(16, False), 3)
    out["fusion_watchlist_vs_noeval_k16"] = round(
        out["fusion_eval_rounds_per_sec_k16"]
        / out["fusion_noeval_rounds_per_sec_k16"], 4)
    out["fusion_speedup_k16_vs_per_round"] = round(
        out["fusion_eval_rounds_per_sec_k16"]
        / out["fusion_eval_rounds_per_sec_k0"], 4)
    out["fusion_rows"] = n
    return out


def bench_rank():
    """rank:ndcg, 1M rows in 10k groups of 100, depth 6 (demo/rank
    shape scaled up; exercises the fused on-device LambdaRank).
    Returns (rounds_per_sec, ndcg)."""
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics as M

    n, gsize, rounds = 1_000_000, 100, 50
    rng = np.random.RandomState(11)
    X = rng.randn(n, 28).astype(np.float32)
    rel = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
           + 0.5 * rng.randn(n))
    y = np.clip((rel > 0.5) + (rel > 1.5), 0, 2).astype(np.float32)
    group = np.full(n // gsize, gsize, np.uint32)
    d = xgb.DMatrix(X, label=y)
    d.set_group(group)
    params = {"objective": "rank:ndcg", "max_depth": 6, "eta": 0.1,
              "max_bin": 64}
    dt, bst = _time_training(xgb, params, d, rounds)
    ndcg = M.ndcg(np.asarray(bst.predict(d)), np.asarray(d.info.label),
                  None, group_ptr=d.info.group_ptr)
    return (rounds - 1) / dt, float(ndcg)


def main():
    if not os.environ.get("XGBTPU_NO_JITCACHE"):
        # repo-local persistent jit cache (same mechanism the CLI uses
        # for warm-cache recovery, cli.py:147-162): bench compiles are
        # ~60 s each through the tunnel and identical run to run —
        # notably the 8 per-level executables of the streamed extmem
        # workload — so later runs (the driver's) reload instead of
        # recompiling
        import jax
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jitcache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    workloads = [w.strip() for w in os.environ.get(
        "BENCH_WORKLOADS",
        "binary,multiclass,rank,otto,yearpred,extmem,fusion").split(",")]
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics

    out = {}
    if "binary" in workloads:
        X, y = make_higgs_like(n_rows + 100_000)
        Xtr, ytr = X[:n_rows], y[:n_rows]
        Xte, yte = X[n_rows:], y[n_rows:]
        dtrain = xgb.DMatrix(Xtr, label=ytr)
        dtest = xgb.DMatrix(Xte, label=yte)

        # max_bin=64: AUC-equal to the sketch's eps-driven 67 bins on
        # this task (measured 0.9455 at both, 100 rounds) and
        # MXU-aligned — the histogram dot's cost scales with
        # ceil(n_bin/8) sublane chunks
        params = {"objective": "binary:logistic", "max_depth": 6,
                  "eta": 0.1, "max_bin": 64, "eval_metric": "auc"}
        dt, bst = _time_training(xgb, params, dtrain, n_rounds)

        rounds_per_sec = (n_rounds - 1) / dt
        rows_per_sec = rounds_per_sec * n_rows
        auc = metrics.auc(bst.predict(dtest), yte, np.ones_like(yte))

        baseline_rows_per_sec = 8e4  # pre-measurement fallback (docstring)
        parity = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "PARITY.json")
        if os.path.exists(parity):
            with open(parity) as f:
                measured = json.load(f).get("baseline_1m", {})
            baseline_rows_per_sec = measured.get("rows_per_sec_1thread",
                                                 baseline_rows_per_sec)
        # one-off 100-tree prediction on the full training shape —
        # driver-visible so the prediction fast paths can't silently
        # regress.  predict_binned_rows_per_sec strips quantize + upload
        # (traversal only, cached binned matrix); the round-7 fields pin
        # the transfer wall itself: predict_transfer_mb_per_sec is the
        # measured upload rate from the xgbtpu_predict_transfer_*
        # counters and predict_gap_ratio = uncached/traversal-only
        # rows/s (1.0 = the transfer wall is gone; ROADMAP's success
        # metric for the round-7 work)
        pred_rps, transfer_mbps = _time_predict(
            bst, lambda: np.ascontiguousarray(Xtr), n_rows)
        pred_binned_rps = _time_predict_binned(
            bst, bst._cache[id(dtrain)].binned, n_rows)
        out = {
            "metric": "higgs1m_train_rows_per_sec_per_chip",
            "value": round(rows_per_sec, 1),
            "unit": f"rows/s (depth6 x {n_rounds} rounds, 1 chip; "
                    f"auc={auc:.4f}, rounds/s={rounds_per_sec:.2f})",
            "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 2),
            "predict_rows_per_sec": round(pred_rps, 1),
            "predict_binned_rows_per_sec": round(pred_binned_rps, 1),
            "predict_transfer_mb_per_sec": round(transfer_mbps, 1),
            "predict_gap_ratio": round(pred_rps / pred_binned_rps, 4),
        }
    if "multiclass" in workloads:
        mc_ms, mc_err, mc_prps, mc_bprps = bench_multiclass()
        out["multiclass_ms_per_round"] = round(mc_ms, 2)
        out["multiclass_merror"] = round(mc_err, 4)
        out["multiclass_predict_rows_per_sec"] = round(mc_prps, 1)
        out["multiclass_predict_binned_rows_per_sec"] = round(mc_bprps, 1)
        out["multiclass_predict_gap_ratio"] = round(mc_prps / mc_bprps, 4)
    if "rank" in workloads:
        rk_rps, rk_ndcg = bench_rank()
        out["rank_rounds_per_sec"] = round(rk_rps, 2)
        out["rank_ndcg"] = round(rk_ndcg, 4)
    if "otto" in workloads:
        ot_ms, ot_mll = bench_otto()
        out["otto_ms_per_round"] = round(ot_ms, 2)
        out["otto_mlogloss"] = round(ot_mll, 4)
    if "yearpred" in workloads:
        yp_rps, yp_rmse = bench_yearpred()
        out["yearpred_rounds_per_sec"] = round(yp_rps, 2)
        out["yearpred_rmse"] = round(yp_rmse, 4)
    if "extmem" in workloads:
        ex_rps, ex_mbs, ex_auc = bench_extmem()
        out["extmem_stream_rounds_per_sec"] = round(ex_rps, 3)
        out["extmem_staged_mb_per_sec"] = round(ex_mbs, 1)
        out["extmem_auc"] = round(ex_auc, 4)
    if "fusion" in workloads:
        out.update(bench_fusion())
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Benchmark: Higgs-scale gbtree training throughput on one TPU chip.

Reproduces the shape of the reference's headline benchmark
(``demo/kaggle-higgs/speedtest.py``: depth 6, eta 0.1, binary logistic —
the config behind the "20x faster than sklearn" README claim): trains
``BENCH_ROUNDS`` boosted trees of depth 6 on a synthetic 1M x 28
Higgs-like dataset and reports training-row throughput per chip plus the
achieved AUC on a held-out split.

Baseline for ``vs_baseline``: the reference CLI's MEASURED Higgs-1M
single-thread training rate from ``PARITY.json`` (produced by
``tools/parity.py`` — reference binary built from /root/reference and
timed on this host).  vs_baseline = our rows/s/chip divided by the
reference rows/s/thread; with 16 chips per v5e-16 pod and 16 threads
per CPU socket the factors cancel, so this single-chip ratio equals the
pod-vs-socket wall-clock ratio under (generous) linear CPU scaling —
the BASELINE.md target is >= 10.  Fallback when PARITY.json is absent:
the pre-measurement estimate 8e4 rows/s.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def make_higgs_like(n, f=28, seed=42):
    """Deterministic Higgs-like binary task: kinematic-ish features with a
    nonlinear decision surface and ~30% bayes noise."""
    rng = np.random.RandomState(seed)
    X = np.empty((n, f), dtype=np.float32)
    # mix of exponential (pT-like), gaussian (eta-like) and uniform features
    X[:, : f // 3] = rng.exponential(1.0, (n, f // 3))
    X[:, f // 3: 2 * f // 3] = rng.randn(n, f - 2 * (f // 3) + f // 3)[:, : f // 3]
    X[:, 2 * (f // 3):] = rng.rand(n, f - 2 * (f // 3))
    score = (np.sin(3 * X[:, 0]) + X[:, 1] * X[:, 2] - 0.5 * X[:, 3] ** 2
             + 2.0 * (X[:, 4] > 1.0) + 0.8 * rng.randn(n))
    y = (score > np.median(score)).astype(np.float32)
    return X, y


def main():
    n_rows = int(os.environ.get("BENCH_ROWS", 1_000_000))
    n_rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    import xgboost_tpu as xgb
    from xgboost_tpu import metrics

    X, y = make_higgs_like(n_rows + 100_000)
    Xtr, ytr = X[:n_rows], y[:n_rows]
    Xte, yte = X[n_rows:], y[n_rows:]
    dtrain = xgb.DMatrix(Xtr, label=ytr)
    dtest = xgb.DMatrix(Xte, label=yte)

    # max_bin=64: AUC-equal to the sketch's eps-driven 67 bins on this
    # task (measured 0.9455 at both, 100 rounds) and MXU-aligned — the
    # histogram dot's cost scales with ceil(n_bin/8) sublane chunks
    params = {"objective": "binary:logistic", "max_depth": 6, "eta": 0.1,
              "max_bin": 64, "eval_metric": "auc"}
    import jax

    def barrier(b):
        # block_until_ready is advisory on remote-attached backends
        # (see PROFILE.md); a one-element host pull is a true barrier
        # on the in-order stream
        m = b._cache[id(dtrain)].margin
        jax.block_until_ready(m)
        jax.device_get(m.ravel()[:1])

    # warm-up booster pays all jit compilation (round-0 single-round
    # launch + the fused (n_rounds-1)-round scan); the timed booster
    # then hits the shared jit caches
    warm = xgb.Booster(params, cache=[dtrain])
    warm.update(dtrain, 0)
    warm.update_many(dtrain, 1, n_rounds - 1)
    barrier(warm)
    del warm

    # the tunnel-attached chip shows run-to-run interference; report the
    # best of BENCH_REPS full runs (each: one fused launch of all
    # remaining rounds on a fresh booster hitting the shared jit cache)
    reps = int(os.environ.get("BENCH_REPS", 3))
    dt = float("inf")
    for _ in range(reps):
        bst = xgb.Booster(params, cache=[dtrain])
        bst.update(dtrain, 0)
        barrier(bst)
        t0 = time.perf_counter()
        bst.update_many(dtrain, 1, n_rounds - 1)
        barrier(bst)
        dt = min(dt, time.perf_counter() - t0)

    rounds_per_sec = (n_rounds - 1) / dt
    rows_per_sec = rounds_per_sec * n_rows
    auc = metrics.auc(bst.predict(dtest), yte, np.ones_like(yte))

    baseline_rows_per_sec = 8e4  # pre-measurement fallback (see docstring)
    parity = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "PARITY.json")
    if os.path.exists(parity):
        with open(parity) as f:
            measured = json.load(f).get("baseline_1m", {})
        baseline_rows_per_sec = measured.get("rows_per_sec_1thread",
                                             baseline_rows_per_sec)
    print(json.dumps({
        "metric": "higgs1m_train_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": f"rows/s (depth6 x {n_rounds} rounds, 1 chip; "
                f"auc={auc:.4f}, rounds/s={rounds_per_sec:.2f})",
        "vs_baseline": round(rows_per_sec / baseline_rows_per_sec, 2),
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Gang-batched tenant-lane micro-benchmark: stacked vs host loop.

Measures the TRAINING-STEP throughput the lane stacker optimizes
(PIPELINE.md "Gang-batched lanes"): N same-shape tenant boosters each
advancing ``rounds`` boosting rounds per cycle, either as N solo fused
dispatches (the ``XGBTPU_LANE_STACK=0`` host loop's boost path) or as
ONE ``_scan_rounds_lanes`` dispatch through the real ``LaneGang``
bucket dispatcher — rendezvous, carry cache, unpack and per-tenant
absorb included.  Gate/publish/ledger fan-out is identical host-side
work in both modes and is deliberately outside the timed region; the
catalog regime this targets is thousands of SMALL tenants, where
per-lane dispatch overhead — not device FLOPs — is the bill.

Writes ``BENCH_lanes.json``::

    JAX_PLATFORMS=cpu python tools/bench_lanes.py

Cells (per lane count N in ``--lanes``):

- ``solo``    — N sequential ``update_many`` calls per cycle (warm).
- ``stacked`` — one ``LaneGang`` bucket dispatch per cycle (warm).

Every cell pins BIT-identity: after the timed cycles, each stacked
booster's ``save_raw()`` bytes must equal its solo twin's, and the
stacked dispatch count per cycle must be 1 regardless of N (the
dispatch-independence acceptance claim).  The committed N=64 cell must
show ``speedup >= 3``; the driver re-checks this in the same container
the numbers were measured in.

Like BENCH_fleet.json, the host ``cpu`` block is recorded: this
container is CPU-only, so the stacked win measured here is the
dispatch-amortization floor — on a TPU the per-dispatch overhead the
stack removes is larger, not smaller.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

import numpy as np  # noqa: E402

N_ROWS, N_FEAT, DEPTH, ROUNDS = 64, 4, 2, 2
PARAMS = {"objective": "binary:logistic", "max_depth": DEPTH,
          "eta": 0.3, "silent": 1}


def make_boosters(n):
    import xgboost_tpu as xgb
    out = []
    for i in range(n):
        rng = np.random.RandomState(1000 + i)
        X = rng.rand(N_ROWS, N_FEAT).astype(np.float32)
        y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
        d = xgb.DMatrix(X, label=y)
        out.append((xgb.Booster(dict(PARAMS, seed=1000 + i), [d]), d))
    return out


def bench_solo(n, cycles, warmup):
    lanes = make_boosters(n)
    ts = []
    for c in range(warmup + cycles):
        t0 = time.perf_counter()
        for b, d in lanes:
            b.update_many(d, c * ROUNDS, ROUNDS)
        dt = time.perf_counter() - t0
        if c >= warmup:
            ts.append(dt)
    return lanes, ts


def bench_stacked(n, cycles, warmup):
    from xgboost_tpu.obs import lane_metrics
    from xgboost_tpu.pipeline.lanes import LaneGang, _Arrival, _bucket_of

    lanes = make_boosters(n)
    gang = LaneGang(expected=0)
    lm = lane_metrics()
    ts, dispatches = [], []
    for c in range(warmup + cycles):
        d0 = lm.dispatches.value
        t0 = time.perf_counter()
        arrs = []
        for i, (b, d) in enumerate(lanes):
            spec, why = b.fused_lane_spec(d, c * ROUNDS, ROUNDS)
            assert spec is not None, f"lane {i} declined stacking: {why}"
            arrs.append(_Arrival(f"lane{i:03d}", spec, lambda it: None))
        gang._dispatch_bucket(_bucket_of(arrs[0].spec), arrs)
        dt = time.perf_counter() - t0
        for a in arrs:
            assert a.exc is None, a.exc
        if c >= warmup:
            ts.append(dt)
            dispatches.append(lm.dispatches.value - d0)
    return lanes, ts, dispatches


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", default="8,64",
                    help="comma-separated lane counts (cells)")
    ap.add_argument("--cycles", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(_HERE), "BENCH_lanes.json"))
    args = ap.parse_args(argv)

    import jax
    try:
        affinity = len(os.sched_getaffinity(0))
    except AttributeError:
        affinity = None
    out = {
        "backend": jax.default_backend(),
        "rows": N_ROWS, "features": N_FEAT, "max_depth": DEPTH,
        "rounds_per_cycle": ROUNDS, "cycles": args.cycles,
        "warmup_cycles": args.warmup,
        "cpu": {"cpu_count": os.cpu_count(), "affinity": affinity},
        "cells": {},
    }
    for n in [int(x) for x in args.lanes.split(",") if x]:
        solo_lanes, solo_ts = bench_solo(n, args.cycles, args.warmup)
        stacked_lanes, st_ts, disp = bench_stacked(
            n, args.cycles, args.warmup)
        # bit-identity pin: every stacked tenant == its solo twin
        mismatched = [i for i, ((bs, _), (bh, _))
                      in enumerate(zip(stacked_lanes, solo_lanes))
                      if bs.save_raw() != bh.save_raw()]
        assert not mismatched, \
            f"N={n}: stacked bytes != solo bytes for lanes {mismatched}"
        # dispatch independence: one stacked launch per cycle, any N
        assert all(d == 1 for d in disp), \
            f"N={n}: expected 1 dispatch/cycle, saw {disp}"
        solo_med = float(np.median(solo_ts))
        st_med = float(np.median(st_ts))
        cell = {
            "solo_cycle_seconds": round(solo_med, 5),
            "stacked_cycle_seconds": round(st_med, 5),
            "solo_lanes_per_s": round(n / solo_med, 2),
            "stacked_lanes_per_s": round(n / st_med, 2),
            "speedup": round(solo_med / st_med, 2),
            "dispatches_per_cycle": 1,
            "bit_identical": True,
        }
        out["cells"][f"n{n}"] = cell
        print(f"N={n:4d}  solo {solo_med*1e3:8.2f} ms/cycle   "
              f"stacked {st_med*1e3:8.2f} ms/cycle   "
              f"speedup {cell['speedup']:.2f}x")
    n64 = out["cells"].get("n64")
    if n64 is not None and n64["speedup"] < 3.0:
        print(f"FAIL: N=64 speedup {n64['speedup']} < 3.0",
              file=sys.stderr)
        return 1
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""A/B: streaming external-memory training with vs without prefetch.

VERDICT r4 Missing #4: the over-budget streaming path (the actual
point of external.py) had no measured throughput and no evidence the
host→device batch staging overlaps compute.  This tool forces the
bench config over budget (XGBTPU_EXT_DEVICE_CACHE_MB=16) and times
rounds/s with the depth-2 background prefetcher
(external._prefetch_to_device — the reference's ThreadBuffer idea,
utils/thread_buffer.h, at the device boundary) against synchronous
staging (XGBTPU_EXT_PREFETCH=0).  A second, larger shape (2M x 100)
scales the streamed volume ~7x to confirm the staging-bound rate
holds at scale.

Run on the real chip: ``python tools/ext_stream_ab.py``.  Results are
recorded in PROFILE.md (round 5).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_case(n, f, rounds, seed, prefetch: bool):
    import xgboost_tpu as xgb
    from xgboost_tpu.external import ExtMemDMatrix
    import bench as B

    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0.65).astype(np.float32)
    cache = os.path.join(tempfile.mkdtemp(prefix="xgbtpu_ab_ext_"), "m")

    def chunks():
        for s in range(0, n, 1 << 18):
            yield X[s:s + (1 << 18)], y[s:s + (1 << 18)]

    d = ExtMemDMatrix(chunks(), cache=cache, page_rows=1 << 18)
    saved = {k: os.environ.get(k) for k in ("XGBTPU_EXT_DEVICE_CACHE_MB",
                                            "XGBTPU_EXT_PREFETCH")}
    os.environ["XGBTPU_EXT_DEVICE_CACHE_MB"] = "16"
    os.environ["XGBTPU_EXT_PREFETCH"] = "1" if prefetch else "0"
    try:
        bst = xgb.Booster({"objective": "binary:logistic", "max_depth": 6,
                           "eta": 0.1, "max_bin": 64}, cache=[d])
        bst.update(d, 0)
        B._barrier_entry(bst, d)
        t0 = time.perf_counter()
        for i in range(1, rounds):
            bst.update(d, i)
        B._barrier_entry(bst, d)
        dt = (time.perf_counter() - t0) / (rounds - 1)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        del d, bst
        import shutil
        shutil.rmtree(os.path.dirname(cache), ignore_errors=True)
    staged_mb = n * f * 7 / 1e6          # 6 levels + delta pass
    return {"rows": n, "feat": f, "s_per_round": dt,
            "rounds_per_sec": 1 / dt,
            "staged_mb_per_sec": staged_mb / dt,
            "prefetch": prefetch}


def main():
    out = []
    for n, f, rounds in ((1_000_000, 28, 4), (2_000_000, 100, 3)):
        for prefetch in (False, True):
            r = run_case(n, f, rounds, seed=3, prefetch=prefetch)
            print(f"{n:>9,} x {f:>3}  prefetch={int(prefetch)}  "
                  f"{r['s_per_round']*1e3:8.1f} ms/round  "
                  f"({r['staged_mb_per_sec']:7.1f} MB/s staged)",
                  file=sys.stderr)
            out.append(r)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

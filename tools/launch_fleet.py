#!/usr/bin/env python
"""Launch a local serving fleet: N replica processes + the router.

The ``rabit_demo.py`` analog for the serving tier (SERVING.md fleet
section): one command brings up the fleet router (in this process) and
N replica subprocesses (``python -m xgboost_tpu task=serve
serve_router_url=...``), each serving its OWN copy of the model file
(so canary rollouts stage per replica), with keepalive — a replica
that dies is restarted and re-registers under its old id (the tracker
``recover`` path).

Usage::

    JAX_PLATFORMS=cpu python tools/launch_fleet.py \
        --model m.bin --replicas 3 --port 8000

Ctrl-C drains: replicas get SIGTERM (their drain state machine
finishes in-flight requests and deregisters), then the router stops.

The :class:`FleetLauncher` class is importable — tools/bench_fleet.py
and tools/chaos_loop.py ``--fleet`` drive fleets through it.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.request
from typing import Dict, List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class RetryingPredictClient:
    """Keep-alive ``POST /predict`` client shared by the fleet drivers
    (tools/bench_fleet.py, tools/chaos_loop.py ``--fleet``).

    A reset/close on a REUSED keep-alive connection is the standard
    retry-safe race (RFC 7230 §6.3.1): every real HTTP client retries
    an idempotent request once on a fresh connection.  A second
    transport failure is a REAL failure.  Non-200 responses close the
    connection (the server does too) and reconnect lazily."""

    def __init__(self, base_url: str, timeout: float = 30.0,
                 path: str = "/predict"):
        import http.client
        from urllib.parse import urlparse
        p = urlparse(base_url)
        self._host, self._port = p.hostname, p.port
        self._timeout = timeout
        self._path = path  # e.g. "/predict?model=b" for catalog tenants
        self._http = http.client
        self._conn = self._connect()

    def _connect(self):
        return self._http.HTTPConnection(self._host, self._port,
                                         timeout=self._timeout)

    def post(self, body: bytes, headers=None):
        """-> (status, detail).  status None = transport failure after
        the one retry (detail = error string); non-200 statuses carry a
        response-body excerpt in detail; 200 -> (200, None)."""
        for attempt in range(2):
            try:
                self._conn.request("POST", self._path, body=body,
                                   headers=headers or {})
                r = self._conn.getresponse()
                out = r.read()
            except OSError as e:
                self._conn.close()
                self._conn = self._connect()
                if attempt:
                    return None, f"{type(e).__name__}: {e}"
                continue
            if r.status != 200:
                self._conn.close()
                self._conn = self._connect()
                return r.status, out[:120].decode("utf-8", "replace")
            return 200, None
        return None, "unreachable"

    def close(self) -> None:
        self._conn.close()


class FleetLauncher:
    """Owns one local fleet: an in-process router + replica
    subprocesses, with per-replica model-file copies and optional
    keepalive restarts."""

    def __init__(self, model_path: str, replicas: int = 3,
                 workdir: str = ".fleet", host: str = "127.0.0.1",
                 port: int = 0, featurestore_mb: float = 0.0,
                 serve_args: Optional[List[str]] = None,
                 router_kwargs: Optional[dict] = None,
                 quiet: bool = True, shared_model: bool = False,
                 replica_faults: Optional[Dict[int, str]] = None):
        self.model_path = model_path
        # shared_model: every replica polls the SAME file (the
        # continuous-training pipeline's publish path) instead of a
        # per-replica copy — the blind-swap reload lane, where one
        # atomic publish hot-reloads the whole fleet (PIPELINE.md);
        # per-replica copies remain the default (canary rollouts stage
        # per replica)
        self.shared_model = bool(shared_model)
        self.n = int(replicas)
        self.workdir = workdir
        self.host = host
        self.featurestore_mb = featurestore_mb
        self.serve_args = list(serve_args or [])
        self.router_kwargs = dict(router_kwargs or {})
        self.quiet = quiet
        # per-replica XGBTPU_FAULTS specs (reliability/faults.py):
        # chaos drivers arm e.g. slow_replica on ONE replica subprocess
        # while its siblings stay healthy
        self.replica_faults = dict(replica_faults or {})
        self.router = None
        self.procs: Dict[int, subprocess.Popen] = {}
        self.restarts = 0
        self._port = port

    # ----------------------------------------------------------- plumbing
    @property
    def url(self) -> str:
        return f"http://{self.router.host}:{self.router.port}"

    def replica_model(self, i: int) -> str:
        if self.shared_model:
            return self.model_path
        return os.path.join(self.workdir, f"replica-{i}", "model.bin")

    def _replica_cmd(self, i: int) -> List[str]:
        return [sys.executable, "-m", "xgboost_tpu", "task=serve",
                f"model_in={self.replica_model(i)}", "serve_port=0",
                f"serve_host={self.host}",
                f"serve_router_url={self.url}",
                f"serve_replica_id=r{i}",
                f"serve_featurestore_mb={self.featurestore_mb}",
                "silent=1"] + self.serve_args

    def spawn(self, i: int) -> subprocess.Popen:
        log = open(os.path.join(self.workdir, f"replica-{i}.log"), "ab")
        env = dict(os.environ)
        if i in self.replica_faults:
            env["XGBTPU_FAULTS"] = self.replica_faults[i]
        p = subprocess.Popen(self._replica_cmd(i), stdout=log, stderr=log,
                             env=env,
                             cwd=os.path.dirname(os.path.dirname(
                                 os.path.abspath(__file__))))
        log.close()  # the child holds its own fd
        self.procs[i] = p
        return p

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "FleetLauncher":
        from xgboost_tpu.fleet import run_router
        os.makedirs(self.workdir, exist_ok=True)
        for i in range(self.n):
            if self.shared_model:
                continue  # all replicas poll model_path itself
            os.makedirs(os.path.dirname(self.replica_model(i)),
                        exist_ok=True)
            shutil.copyfile(self.model_path, self.replica_model(i))
        self.router = run_router(host=self.host, port=self._port,
                                 quiet=self.quiet, block=False,
                                 **self.router_kwargs)
        for i in range(self.n):
            self.spawn(i)
        return self

    def members(self) -> dict:
        with urllib.request.urlopen(self.url + "/fleet/members",
                                    timeout=5) as r:
            return json.load(r)

    def wait_ready(self, n: Optional[int] = None,
                   timeout: float = 120.0) -> int:
        """Block until ``n`` replicas are in rotation (default: all)."""
        want = self.n if n is None else n
        deadline = time.perf_counter() + timeout
        got = 0
        while time.perf_counter() < deadline:
            try:
                got = self.members()["in_rotation"]
            except OSError:
                got = 0
            if got >= want:
                return got
            time.sleep(0.25)
        raise TimeoutError(
            f"fleet not ready: {got}/{want} replicas in rotation "
            f"after {timeout}s (see {self.workdir}/replica-*.log)")

    # ------------------------------------------------------------ elastic
    def live_indices(self) -> List[int]:
        return [i for i, p in self.procs.items() if p.poll() is None]

    def count(self) -> int:
        return len(self.live_indices())

    def spawn_next(self) -> int:
        """Scale-up: start one more replica (fresh index, own model
        copy).  It registers through the normal lease path and enters
        rotation when its first health check passes."""
        i = max(self.procs, default=-1) + 1
        if not self.shared_model:
            os.makedirs(os.path.dirname(self.replica_model(i)),
                        exist_ok=True)
            shutil.copyfile(self.model_path, self.replica_model(i))
        self.spawn(i)
        return i

    def drain_replica(self, i: Optional[int] = None) -> Optional[str]:
        """Scale-down: drain one replica (default: the newest).  The
        replica's SIGTERM drain path deregisters AT DRAIN START — it
        leaves rotation before finishing its in-flight requests, so no
        request is lost; the router-side deregister below is the
        belt-and-braces for a replica too wedged to announce itself.
        The process is dropped from the keepalive set so it is not
        resurrected.  Returns the drained replica id, or None."""
        live = self.live_indices()
        if not live:
            return None
        i = max(live) if i is None else i
        p = self.procs.pop(i, None)
        if p is None or p.poll() is not None:
            return None
        p.terminate()
        try:
            req = urllib.request.Request(
                self.url + "/fleet/deregister",
                data=json.dumps({"replica_id": f"r{i}"}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                r.read()
        except OSError:
            pass  # the replica's own drain deregister is the main path
        return f"r{i}"

    # ------------------------------------------------------------- chaos
    def kill_replica(self, i: int) -> Optional[int]:
        """SIGKILL replica ``i`` (no drain, no deregister — the crash
        case).  Returns the dead pid, or None if it was not running."""
        p = self.procs.get(i)
        if p is None or p.poll() is not None:
            return None
        p.kill()
        p.wait()
        return p.pid

    def reap_and_restart(self) -> int:
        """The keepalive pass: restart every dead replica (it re-uses
        its replica id — the recover path).  Returns restarts made."""
        n = 0
        for i, p in list(self.procs.items()):
            if p.poll() is not None:
                self.spawn(i)
                self.restarts += 1
                n += 1
        return n

    def stop(self, drain_timeout: float = 15.0) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()  # SIGTERM -> replica drain state machine
        deadline = time.perf_counter() + drain_timeout
        for p in self.procs.values():
            left = max(0.1, deadline - time.perf_counter())
            try:
                p.wait(left)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()
        if self.router is not None:
            self.router.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", required=True, help="model file to serve")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="router port (0 = ephemeral)")
    ap.add_argument("--workdir", default=".fleet",
                    help="per-replica model copies + logs land here")
    ap.add_argument("--featurestore-mb", type=float, default=0.0)
    ap.add_argument("--keepalive", type=int, default=1,
                    help="restart dead replicas (0 disables)")
    ap.add_argument("--serve-arg", action="append", default=[],
                    help="extra name=value passed to every replica "
                         "(repeatable)")
    # elastic supervision (xgboost_tpu.placer.elastic, SERVING.md
    # "Autonomous placement"): band defaults come from PLACER_PARAMS —
    # one knob table drives the CLI and this tool alike
    from xgboost_tpu.config import PLACER_PARAMS
    ap.add_argument("--supervise", action="store_true",
                    help="hold fleet utilization inside the "
                         "[--util-low, --util-high] band by "
                         "spawning/draining replicas")
    ap.add_argument("--min-replicas", type=int,
                    default=PLACER_PARAMS["placer_min_replicas"][0])
    ap.add_argument("--max-replicas", type=int,
                    default=PLACER_PARAMS["placer_max_replicas"][0])
    ap.add_argument("--util-low", type=float,
                    default=PLACER_PARAMS["placer_util_low"][0])
    ap.add_argument("--util-high", type=float,
                    default=PLACER_PARAMS["placer_util_high"][0])
    ap.add_argument("--util-alpha", type=float,
                    default=PLACER_PARAMS["placer_util_alpha"][0])
    ap.add_argument("--replica-slots", type=int,
                    default=PLACER_PARAMS["placer_replica_slots"][0])
    ap.add_argument("--cooldown-sec", type=float,
                    default=PLACER_PARAMS["placer_cooldown_sec"][0])
    args = ap.parse_args(argv)

    fl = FleetLauncher(args.model, replicas=args.replicas,
                       workdir=args.workdir, host=args.host,
                       port=args.port,
                       featurestore_mb=args.featurestore_mb,
                       serve_args=args.serve_arg, quiet=False)
    fl.start()
    print(f"[fleet] router {fl.url}; waiting for {args.replicas} "
          "replica(s) to register...", file=sys.stderr)
    fl.wait_ready()
    print(f"[fleet] up: {args.replicas} replicas in rotation "
          f"(logs in {args.workdir}/)", file=sys.stderr)

    supervisor = None
    if args.supervise:
        from xgboost_tpu.placer import ElasticSupervisor
        supervisor = ElasticSupervisor(
            fl.url, spawn_fn=fl.spawn_next, drain_fn=fl.drain_replica,
            count_fn=fl.count,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            util_low=args.util_low, util_high=args.util_high,
            util_alpha=args.util_alpha,
            replica_slots=args.replica_slots,
            cooldown_sec=args.cooldown_sec)
        print(f"[fleet] supervising: util band "
              f"[{args.util_low}, {args.util_high}], "
              f"{args.min_replicas}..{args.max_replicas} replicas",
              file=sys.stderr)

    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    try:
        while not stop:
            time.sleep(1.0)
            if args.keepalive:
                n = fl.reap_and_restart()
                if n:
                    print(f"[fleet] keepalive restarted {n} replica(s)",
                          file=sys.stderr)
            if supervisor is not None:
                st = supervisor.tick()
                if st["state"] not in ("steady",):
                    print(f"[fleet] supervisor: {st}", file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        print("[fleet] draining...", file=sys.stderr)
        fl.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

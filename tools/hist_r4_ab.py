"""Round-4 histogram-kernel A/B (VERDICT r3 item 7).

Variants at the bench shape (1M x 28, B=64, deep level M=64), all
timed amortized inside one lax.scan launch (the tunnel's fixed
~110 ms dispatch divides out):

  prod      — production kernel, bf16 mode (the 33 r/s bench path)
  dotfloor  — same dots, one-hot replaced by a constant bf16 tile
              (isolates the one-hot build: prod - dotfloor = VPU cost)
  u8bins    — bins stored uint8 in HBM, widened in-kernel (4x less
              kernel input bandwidth)
  i16hot    — one-hot built by int16-select of 0x3F80 + bitcast to
              bf16 (the "int8/int16 compare via bitcast" candidate:
              avoids the int->float convert on the select)
  rtile=K   — r_tile sweep around the production 2048

Prints per-variant ms/level-equivalent and the implied bench celling.
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402

N, F, B, M = 1_000_000, 28, 64, 64


def make_kernel(mode):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
               n_bin, m_pad, f_tile):
        r_tile = binned_ref.shape[1]
        m2 = 2 * m_pad
        m_base = pl.program_id(0) * m_pad

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        pos = pos_ref[:, 0]
        lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
        node_of_lane = m_base + jnp.where(lane < m_pad, lane,
                                          lane - m_pad)
        ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
        gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel,
                           0.0).astype(jnp.bfloat16)

        bins = binned_ref[:]
        if mode == "u8bins":
            bins = bins.astype(jnp.int32)
        bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
        for f in range(f_tile):
            if mode == "dotfloor":
                onehot = (bin_ids < 1).astype(jnp.bfloat16)
            elif mode == "i16hot":
                eq = bins[f:f + 1, :] == bin_ids
                onehot = jax.lax.bitcast_convert_type(
                    jnp.where(eq, jnp.int16(0x3F80), jnp.int16(0)),
                    jnp.bfloat16)
            else:
                onehot = (bins[f:f + 1, :] == bin_ids).astype(
                    jnp.bfloat16)
            acc = jax.lax.dot_general(
                onehot, gh_exp, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.float32)
            out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc

    return kernel


def build(mode, r_tile):
    bins_dtype = jnp.uint8 if mode == "u8bins" else jnp.int32

    @jax.jit
    def fn(binned_t, pos, gh):
        f_tile = F
        n_pad = binned_t.shape[1]
        kernel = functools.partial(make_kernel(mode), n_bin=B, m_pad=M,
                                   f_tile=f_tile)
        return pl.pallas_call(
            kernel,
            grid=(1, 1, n_pad // r_tile),
            in_specs=[
                pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
                pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
            ],
            out_specs=pl.BlockSpec((1, f_tile * B, 2 * M),
                                   lambda mi, fi, ri: (mi, fi, 0)),
            out_shape=jax.ShapeDtypeStruct((1, f_tile * B, 2 * M),
                                           jnp.float32),
        )(binned_t, pos, gh)

    return fn, bins_dtype


def timed(fn, binned_t, pos, gh, iters=30):
    @jax.jit
    def loop(b, p, g):
        def body(c, _):
            out = fn(b, p, g + c * 1e-20)
            return c + jnp.sum(out[0, :2, :2]) % 7.0 * 1e-20, None
        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=iters)
        return c

    r = loop(binned_t, pos, gh); jax.block_until_ready(r); float(r)
    t0 = time.perf_counter()
    float(loop(binned_t, pos, gh))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    r_tile0 = 2048
    n_pad = _round_up(N, 8192)
    binned = rng.randint(0, B, (F, n_pad)).astype(np.int32)
    pos = rng.randint(0, M, (n_pad, 1)).astype(np.int32)
    gh = rng.randn(n_pad, 2).astype(np.float32)

    results = {}
    for mode in ("prod", "dotfloor", "u8bins", "i16hot"):
        for r_tile in ((1024, 2048, 4096) if mode == "prod"
                       else (r_tile0,)):
            fn, bdt = build(mode, r_tile)
            bt = jnp.asarray(binned.astype(np.uint8) if mode == "u8bins"
                             else binned)
            try:
                ms = timed(fn, bt, jnp.asarray(pos), jnp.asarray(gh))
                tag = f"{mode}@r{r_tile}"
                results[tag] = ms
                print(f"{tag:18s} {ms:7.2f} ms/level "
                      f"(x6 = {ms*6:6.1f} ms/round-equiv)")
            except Exception as e:
                print(f"{mode}@r{r_tile}: FAILED {type(e).__name__}: "
                      f"{str(e)[:200]}")
    if "prod@r2048" in results and "dotfloor@r2048" in results:
        p, d = results["prod@r2048"], results["dotfloor@r2048"]
        print(f"\none-hot build cost: {p - d:.2f} ms/level "
              f"({(p - d) / p * 100:.0f}% of kernel); dot floor "
              f"{d:.2f} ms/level -> floor bench ceiling ~"
              f"{1000 / (d * 6 + 7):.0f} r/s (with ~7 ms non-hist round)")


if __name__ == "__main__":
    main()

"""Round-5 histogram-kernel A/B: the one-hot build is the bound.

hlo_stats of the fused round (tools/trace_round.py) shows the int8
kernel at ~1.84 ms/level FLAT in node count — the MXU floor is ~0.6 ms
and the rest is VPU one-hot construction (B x R compares + i8 convert
per feature).  Variants:

  prod      — production int8 kernel (bins widened to i32, i32 iota
              compare, select -> i8)
  u8cmp     — compare in the u8 domain (u8 bins vs u8 iota, no widen);
              tests whether Mosaic vectorizes sub-word compares
  b64       — n_bin=64 instead of 67: the i8 one-hot tile pads
              sublanes to 96 for B=67 but 64 for B=64 (~33% fewer
              physical VPU elements)
  shared6   — ONE one-hot per (feature, row tile) contracted against
              6 levels' gh_exp operands (the per-round floor IF levels
              could share the build; they can't today — sequential
              splits — this measures what a restructure would buy)
  gh32      — gh_exp kept i32, dot in i32?? (not supported; skipped)

All timed amortized in a lax.scan (tunnel dispatch divides out).
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402

N, F, M = 1_000_000, 28, 64
R_TILE = 2048


def make_kernel(mode, n_bin, n_levels=1):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref):
        r_tile = binned_ref.shape[1]
        m2 = 2 * M

        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        sub = jax.lax.broadcasted_iota(jnp.int32, (m2, r_tile), 0)
        node_of_sub = jnp.where(sub < M, sub, sub - M)
        ghsel = jnp.where(sub < M, gh_ref[0:1, :], gh_ref[1:2, :])
        pos = pos_ref[0:1, :]
        gh_exps = []
        for lv in range(n_levels):
            act = (pos + lv) % M == node_of_sub if n_levels > 1 else \
                pos == node_of_sub
            gh_exps.append(jnp.where(act, ghsel, 0).astype(jnp.int8))

        if mode == "u8cmp":
            bins = binned_ref[:]                      # stay u8
            # u8 iota is unsupported; build once from i32 (hoisted out
            # of the feature loop — the per-feature compares stay u8)
            bin_ids = jax.lax.broadcasted_iota(
                jnp.int32, (n_bin, r_tile), 0).astype(jnp.uint8)
        else:
            bins = binned_ref[:].astype(jnp.int32)
            bin_ids = jax.lax.broadcasted_iota(
                jnp.int32, (n_bin, r_tile), 0)
        for f in range(F):
            onehot = (bins[f:f + 1, :] == bin_ids).astype(jnp.int8)
            for lv, ghe in enumerate(gh_exps):
                acc = jax.lax.dot_general(
                    onehot, ghe, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out_ref[lv, f * n_bin:(f + 1) * n_bin, :] += acc

    return kernel


def build(mode, n_bin, n_levels=1):
    @jax.jit
    def fn(binned_t, pos, gh):
        n_pad = binned_t.shape[1]
        kernel = make_kernel(mode, n_bin, n_levels)
        return pl.pallas_call(
            kernel,
            grid=(n_pad // R_TILE,),
            in_specs=[
                pl.BlockSpec((F, R_TILE), lambda ri: (0, ri)),
                pl.BlockSpec((1, R_TILE), lambda ri: (0, ri)),
                pl.BlockSpec((2, R_TILE), lambda ri: (0, ri)),
            ],
            out_specs=pl.BlockSpec((n_levels, F * n_bin, 2 * M),
                                   lambda ri: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((n_levels, F * n_bin, 2 * M),
                                           jnp.int32),
        )(binned_t, pos, gh)

    return fn


def timed(fn, binned_t, pos, gh, iters=40):
    @jax.jit
    def loop(b, p, g):
        def body(c, _):
            out = fn(b, p, g + c)
            return c + out[0, 0, 0] % 3, None
        c, _ = jax.lax.scan(body, jnp.int32(0), None, length=iters)
        return c

    r = loop(binned_t, pos, gh); jax.block_until_ready(r); int(r)
    t0 = time.perf_counter()
    int(loop(binned_t, pos, gh))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    n_pad = _round_up(N, R_TILE)
    pos = jnp.asarray(np.pad(
        rng.randint(0, M, N).astype(np.int32), (0, n_pad - N),
        constant_values=-1))[None, :]
    gh = jnp.asarray(rng.randint(-127, 127, (2, n_pad)).astype(np.int32))

    # NOTE u8cmp fails Mosaic compilation twice over: u8 iota is "not
    # implemented" and so is cmpi on vector<8x128x4xi8> — though the
    # 4-per-lane vector type confirms a packed compare WOULD be 4x.
    # Negative result recorded; the i32-domain compare is the floor.
    for n_bin in (67, 64, 32):
        bt = jnp.asarray(rng.randint(0, n_bin, (F, n_pad)).astype(np.uint8))
        t = timed(build("prod", n_bin), bt, pos, gh)
        print(f"prod    B={n_bin}: {t:7.2f} ms/level")
    bt = jnp.asarray(rng.randint(0, 64, (F, n_pad)).astype(np.uint8))
    t6 = timed(build("prod", 64, n_levels=6), bt, pos, gh, iters=20)
    print(f"shared6 B=64: {t6:7.2f} ms for 6 levels "
          f"({t6 / 6:.2f} ms/level-equivalent)")


if __name__ == "__main__":
    main()

"""Microbenchmark of Pallas histogram kernel variants on the real chip.

Measures build_level_histogram_pallas-style kernels at the bench shape
(1M x 28, B=67, depth-6 level M=64) to guide kernel tuning.  Variants:

  base      — production kernel (f32 one-hot, selected precision)
  bf16hot   — one-hot built directly in bf16 (halves VMEM write traffic)
  i16cmp    — bin ids held as int16 in VMEM (halves compare read traffic)

Usage: python tools/hist_microbench.py [n_rows] [n_feat] [n_bin]
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")
from xgboost_tpu.ops.pallas_hist import (  # noqa: E402
    _round_up, build_level_histogram_pallas)


def _variant_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                    n_bin, m_pad, f_tile, precision_mode, hot_dtype):
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    m_base = pl.program_id(0) * m_pad

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = m_base + jnp.where(lane < m_pad, lane, lane - m_pad)
    g = gh_ref[:, 0:1]
    h = gh_ref[:, 1:2]
    ghsel = jnp.where(lane < m_pad, g, h)
    active = (pos[:, None] == node_of_lane)
    gh_exp = jnp.where(active, ghsel, 0.0).astype(hot_dtype)

    prec = (jax.lax.Precision.HIGHEST if precision_mode == "fp32"
            else jax.lax.Precision.DEFAULT)
    bins = binned_ref[:]
    bin_ids = jax.lax.broadcasted_iota(bins.dtype, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=prec, preferred_element_type=jnp.float32)
        out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "hot_dtype", "bin_dtype", "r_tile"))
def variant(binned, gh, pos, n_node, n_bin, precision="bf16",
            hot_dtype=jnp.float32, bin_dtype=jnp.int32, r_tile=1024):
    N, F = binned.shape
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    f_tile = max(1, min(F, (256 * 1024) // (max(n_bin, 1)
                                            * max(2 * m_pad, 128))))
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = _round_up(F, f_tile)
    binned_t = binned.astype(bin_dtype).T
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)
    kernel = functools.partial(_variant_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, precision_mode=precision,
                               hot_dtype=hot_dtype)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, f_tile * n_bin, 2 * m_pad),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * n_bin, 2 * m_pad),
                                       jnp.float32),
    )(binned_t, pos.reshape(-1, 1).astype(jnp.int32),
      gh.astype(jnp.float32))
    out = out.reshape(n_m_tiles, f_pad, n_bin, 2, m_pad)
    out = out.transpose(0, 4, 1, 2, 3).reshape(
        n_m_tiles * m_pad, f_pad, n_bin, 2)
    return out[:n_node, :F, :, :]


def barrier(x):
    # true device drain through the axon tunnel: one-element host pull
    np.asarray(jax.device_get(jax.numpy.sum(x)))


def timeit(fn, *args, reps=20, **kw):
    out = fn(*args, **kw)
    barrier(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    barrier(out)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 28
    b = int(sys.argv[3]) if len(sys.argv) > 3 else 67
    n_node = 64
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, b, size=(n, f)), jnp.int32)
    gh = jnp.asarray(rng.randn(n, 2), jnp.float32)
    pos = jnp.asarray(rng.randint(0, n_node, size=n), jnp.int32)

    ms = timeit(build_level_histogram_pallas, binned, gh, pos, n_node, b,
                precision="bf16")
    print(f"production bf16        : {ms:7.2f} ms")
    for name, kw in [
        ("base f32hot bf16mm", dict(precision="bf16",
                                    hot_dtype=jnp.float32)),
        ("bf16hot bf16mm", dict(precision="bf16", hot_dtype=jnp.bfloat16)),
        ("i16cmp f32hot", dict(precision="bf16", hot_dtype=jnp.float32,
                               bin_dtype=jnp.int16)),
        ("i16cmp bf16hot", dict(precision="bf16", hot_dtype=jnp.bfloat16,
                                bin_dtype=jnp.int16)),
        ("bf16hot r2048", dict(precision="bf16", hot_dtype=jnp.bfloat16,
                               r_tile=2048)),
        ("f32 HIGHEST (exact)", dict(precision="fp32",
                                     hot_dtype=jnp.float32)),
    ]:
        try:
            ms = timeit(variant, binned, gh, pos, n_node, b, **kw)
            print(f"{name:22s} : {ms:7.2f} ms")
        except Exception as e:
            print(f"{name:22s} : FAILED {type(e).__name__}: {str(e)[:90]}")


if __name__ == "__main__":
    main()

"""Prototype: feature-grouped one-hot matmul for the histogram kernel.

Instead of one (B, R) @ (R, 2M) matmul per feature (which fills only
B=67 of the MXU's 128 output sublanes), concatenate ``fg`` features'
one-hots — each padded to Bp = roundup(B, 8) sublanes — into one
(fg*Bp, R) operand and run one matmul per group.  MXU row-blocks per
step drop from fg*ceil(B/128) to ceil(fg*Bp/128).
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")
from tools.hist_microbench import timeit  # noqa: E402
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402


def _grouped_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                    n_bin, b_pad, m_pad, f_tile, fg, hot_dtype):
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    m_base = pl.program_id(0) * m_pad

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    pos = pos_ref[:, 0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
    node_of_lane = m_base + jnp.where(lane < m_pad, lane, lane - m_pad)
    ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
    gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel, 0.0)
    gh_exp = gh_exp.astype(hot_dtype)

    bins = binned_ref[:]
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (b_pad, r_tile), 0)
    n_group = f_tile // fg
    for g in range(n_group):
        hots = []
        for j in range(fg):
            f = g * fg + j
            # bin_ids rows >= n_bin never match (bins < n_bin)
            hots.append((bins[f:f + 1, :] == bin_ids).astype(hot_dtype))
        onehot = jnp.concatenate(hots, axis=0)          # (fg*b_pad, R)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)          # (fg*b_pad, 2M)
        out_ref[0, g * fg * b_pad:(g + 1) * fg * b_pad, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "fg", "r_tile", "hot_dtype"))
def grouped(binned, gh, pos, n_node, n_bin, fg=4, r_tile=2048,
            hot_dtype=jnp.bfloat16):
    N, F = binned.shape
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    b_pad = _round_up(n_bin, 8)
    f_tile = _round_up(F, fg)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = f_tile

    binned_t = binned.astype(jnp.int32).T
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, (0, n_pad - N), constant_values=-1)

    kernel = functools.partial(_grouped_kernel, n_bin=n_bin, b_pad=b_pad,
                               m_pad=m_pad, f_tile=f_tile, fg=fg,
                               hot_dtype=hot_dtype)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, 1, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, f_pad * b_pad, 2 * m_pad),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * b_pad, 2 * m_pad),
                                       jnp.float32),
    )(binned_t, pos.reshape(-1, 1).astype(jnp.int32),
      gh.astype(jnp.float32))

    # (m_tiles, f_pad*Bp, 2M) -> (m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, f_pad, b_pad, 2, m_pad)
    out = out.transpose(0, 4, 1, 2, 3).reshape(
        n_m_tiles * m_pad, f_pad, b_pad, 2)
    return out[:n_node, :F, :n_bin, :]


def main():
    from xgboost_tpu.ops.pallas_hist import build_level_histogram_pallas
    n, f, b, n_node = 1_000_000, 28, 67, 64
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, b, size=(n, f)), jnp.int32)
    gh = jnp.asarray(rng.randn(n, 2), jnp.float32)
    pos = jnp.asarray(rng.randint(0, n_node, size=n), jnp.int32)

    ref = np.asarray(build_level_histogram_pallas(
        binned, gh, pos, n_node, b, precision="fp32"))
    got = np.asarray(grouped(binned[:4096], gh[:4096], pos[:4096],
                             n_node, b, fg=4))
    ref4 = np.asarray(build_level_histogram_pallas(
        binned[:4096], gh[:4096], pos[:4096], n_node, b, precision="fp32"))
    err = np.abs(got - ref4).max()
    print("small parity max err (bf16 vs f32):", err)

    ms = timeit(build_level_histogram_pallas, binned, gh, pos, n_node, b,
                precision="bf16")
    print(f"production bf16   : {ms:7.2f} ms")
    for fg in (2, 4, 7, 14):
        for r in (1024, 2048, 4096):
            try:
                ms = timeit(grouped, binned, gh, pos, n_node, b,
                            fg=fg, r_tile=r)
                print(f"grouped fg={fg:2d} r={r:5d}: {ms:7.2f} ms")
            except Exception as e:
                print(f"grouped fg={fg:2d} r={r:5d}: FAILED {str(e)[:70]}")


if __name__ == "__main__":
    main()

"""Fit the per-round compute model from a single-chip row sweep.

The multi-chip projection (``parallel/commcost.project_round_time``)
models per-chip compute as ``fixed_round_s + per_row_s * rows_per_chip``.
Round 4 ASSUMED ``fixed_round_s = 0.004`` — 79% of the projected 8-chip
round — with no measurement behind it (VERDICT r4, Missing #2).  This
tool replaces the assumption with a measurement: it times the bench's
binary workload (depth 6, max_bin 64, F=28 — the exact config the
projection speaks about) at 1M, 1M/2, 1M/4 and 1M/8 rows on the real
chip, least-squares fits the affine model, and writes ``ROUND_MODEL.json``
at the repo root, which ``project_round_time`` then loads as its
calibrated defaults.

The row sweep measures exactly the quantity the projection needs:
per-chip round time at N/k rows is the single-chip round time at that
row count (the level structure — launches, split finding, routing — is
identical; only the row-proportional kernels shrink), plus the psum
term, which is modeled separately and test-pinned byte-for-byte
(tests/test_distributed.py).

Run on the real chip (default env): ``python tools/fit_round_model.py``.
Reference counterpart: the network boundary being modeled is
``updater_histmaker-inl.hpp:343-346`` (per-level histogram allreduce);
the reference validated its distributed mode with real multi-node runs
(``multi-node/col-split/mushroom-col-rabit.sh``), which this image's
single chip cannot — the fit makes the projection as anchored as the
hardware allows.

MESH CELL (``FIT_MESH=1``): measures — rather than projects — the
mesh-fused scan (round 6).  Trains the bench workload through the
shard_map'd segmented scan (``dsplit=row``, ``hist_precision=fixed``)
on every visible device and again on ONE device at the sharded
per-device row count; the delta is the measured per-round psum +
shard_map overhead the ring model only estimated.  Writes
``MULTICHIP_r06.json`` (measured rounds/s, per-round psum seconds,
measured-vs-projected error against a host-local affine fit) and does
NOT touch ``ROUND_MODEL.json`` — the committed fit there is from the
real chip and a CPU bench host must never clobber it.
``FIT_MESH_DEVICES=N`` forces N in-process virtual CPU devices (the
live multi-device target on hosts whose backend cannot run
multi-process programs); ``FIT_MESH_ROWS``/``FIT_MESH_ROUNDS`` size
the workload.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _sweep(B, xgb, params, X, y, rows_list, rounds, tag):
    points = []
    for n in rows_list:
        d = xgb.DMatrix(X[:n], label=y[:n])
        t0 = time.perf_counter()
        dt, _ = B._time_training(xgb, params, d, rounds)
        s_round = dt / (rounds - 1)
        points.append({"rows": n, "s_per_round": s_round})
        print(f"[{tag}] rows={n:>9,}  {s_round*1e3:7.3f} ms/round  "
              f"({1/s_round:6.1f} r/s; wall {time.perf_counter()-t0:.0f}s)",
              file=sys.stderr)
    rows = np.array([p["rows"] for p in points], np.float64)
    t = np.array([p["s_per_round"] for p in points], np.float64)
    A = np.stack([np.ones_like(rows), rows], axis=1)
    (fixed, slope), res, *_ = np.linalg.lstsq(A, t, rcond=None)
    pred = A @ np.array([fixed, slope])
    rel_err = np.abs(pred - t) / t
    return float(fixed), float(slope), points, float(rel_err.max())


def mesh_cell():
    """The round-6 measurement: multi-device mesh-fused rounds/s and
    per-round psum seconds (delta method), written to
    MULTICHIP_r06.json beside the r05 projection (see module
    docstring)."""
    import bench as B
    import jax

    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import training_metrics
    from xgboost_tpu.parallel import commcost
    from xgboost_tpu.parallel import mesh as pmesh

    D = len(jax.devices())
    rows = int(os.environ.get("FIT_MESH_ROWS", 262144))
    rounds = int(os.environ.get("FIT_MESH_ROUNDS", 20))
    rows -= rows % D  # mesh-divisible, so no padding skews the delta
    params = {"objective": "binary:logistic", "max_depth": 6,
              "eta": 0.1, "max_bin": 64, "dsplit": "row",
              "hist_precision": "fixed"}
    X, y = B.make_higgs_like(rows)
    fb0 = dict(training_metrics().fused_fallback.values())

    def timed(n_dev, n_rows, tag):
        pmesh.set_mesh(pmesh.data_parallel_mesh(n_dev))
        try:
            d = xgb.DMatrix(X[:n_rows], label=y[:n_rows])
            dt, _ = B._time_training(xgb, params, d, rounds)
        finally:
            pmesh.set_mesh(None)
        s = dt / (rounds - 1)
        print(f"[mesh] {tag}: devices={n_dev} rows={n_rows:>9,}  "
              f"{s*1e3:7.3f} ms/round ({1/s:6.1f} r/s)", file=sys.stderr)
        return s

    # single-device anchors: the per-device compute at the sharded row
    # count (what each mesh device grinds per round), plus two more
    # points for the host-local affine fit
    s_shard = timed(1, rows // D, "1dev@rows/D")
    s_half = timed(1, rows // 2, "1dev@rows/2")
    s_full = timed(1, rows, "1dev@rows")
    # the measurement the projection only modeled
    s_mesh = timed(D, rows, f"{D}dev fused")

    fb1 = dict(training_metrics().fused_fallback.values())
    fallbacks = sum(fb1.values()) - sum(fb0.values())

    # host-local affine fit from the three single-device points — NOT
    # the committed ROUND_MODEL.json, which is chip-fitted
    pts_r = np.array([rows // D, rows // 2, rows], np.float64)
    pts_t = np.array([s_shard, s_half, s_full], np.float64)
    A = np.stack([np.ones_like(pts_r), pts_r], axis=1)
    (fixed, slope), *_ = np.linalg.lstsq(A, pts_t, rcond=None)
    fixed, slope = float(fixed), float(slope)
    proj = commcost.project_round_time(
        rows=rows, max_depth=6, n_feat=28, n_bin=64, n_chips=D,
        single_chip_round_s=s_full, single_chip_rows=rows,
        fixed_round_s=fixed, per_row_s=slope)
    psum_measured = s_mesh - s_shard
    rel_err = (s_mesh - proj["round_s"]) / proj["round_s"]

    r05 = None
    r05_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTICHIP_r05.json")
    if os.path.exists(r05_path):
        with open(r05_path) as f:
            r05 = json.load(f).get("tail", "").strip()

    report = {
        "mode": "mesh_fused_measurement",
        "n_devices": D,
        "rows": rows,
        "rounds": rounds,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "config": {k: v for k, v in params.items()},
        "single_device_round_s_at_shard_rows": s_shard,
        "single_device_round_s_at_half_rows": s_half,
        "single_device_round_s_at_full_rows": s_full,
        "mesh_round_s": s_mesh,
        "measured_rounds_per_sec": 1.0 / s_mesh,
        "measured_psum_s_per_round": psum_measured,
        "host_fit": {"fixed_round_s": fixed, "per_row_s": slope},
        "projected": proj,
        "measured_vs_projected_rel_err": rel_err,
        "scaling_efficiency_vs_full": s_full / (D * s_mesh),
        "fused_fallbacks": fallbacks,
        "r05_projection": r05,
        "note": ("virtual CPU devices share the host's physical cores, "
                 "so the delta (mesh_round_s - "
                 "single_device_round_s_at_shard_rows) bundles real "
                 "psum/shard_map overhead WITH core contention — an "
                 "upper bound on the collective cost.  On a real "
                 "multi-chip mesh each device has its own silicon and "
                 "the delta isolates the interconnect term the ring "
                 "model projects."),
        "fitted_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MULTICHIP_r06.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(f"[mesh] {D}-device fused: {1/s_mesh:.1f} rounds/s measured "
          f"(projected {proj['rounds_per_sec']:.1f}; rel err "
          f"{rel_err:+.1%}); per-round psum+overhead "
          f"{psum_measured*1e3:.3f} ms (ring model projected "
          f"{proj['psum_s']*1e3:.3f} ms); {fallbacks} fused "
          f"fallbacks -> {out}", file=sys.stderr)
    print(json.dumps(report))
    if fallbacks:
        raise SystemExit("mesh cell fell back to per-round dispatch — "
                         "the measurement above is NOT the fused path")


def main():
    if os.environ.get("FIT_MESH", "") not in ("", "0"):
        nd = os.environ.get("FIT_MESH_DEVICES")
        if nd:
            # must precede the first jax import (bench imports jax)
            flags = os.environ.get("XLA_FLAGS", "")
            if "host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count"
                    f"={nd}").strip()
            os.environ["JAX_PLATFORMS"] = "cpu"
        mesh_cell()
        return

    import bench as B
    import xgboost_tpu as xgb
    import jax

    rounds = int(os.environ.get("FIT_ROUNDS", 50))
    rows_list = [int(r) for r in os.environ.get(
        "FIT_ROWS", "125000,250000,500000,1000000").split(",")]
    params = {"objective": "binary:logistic", "max_depth": 6,
              "eta": 0.1, "max_bin": 64}

    X, y = B.make_higgs_like(max(rows_list))
    fixed, slope, points, max_rel = _sweep(
        B, xgb, params, X, y, rows_list, rounds, "fused")

    # round 8: the primary sweep rides update_many's segmented fusion
    # (auto-K, or XGBTPU_ROUNDS_PER_DISPATCH in the env); a second
    # sweep at K=0 measures the per-round dispatch floor the fusion
    # removes, so the json carries the A/B the PROFILE quotes.
    # FIT_PER_ROUND_BASELINE=0 skips it.
    baseline = None
    if os.environ.get("FIT_PER_ROUND_BASELINE", "1") != "0":
        old = os.environ.get("XGBTPU_ROUNDS_PER_DISPATCH")
        os.environ["XGBTPU_ROUNDS_PER_DISPATCH"] = "0"
        try:
            bfixed, bslope, bpoints, bmax_rel = _sweep(
                B, xgb, params, X, y, rows_list, rounds, "per-round")
        finally:
            if old is None:
                os.environ.pop("XGBTPU_ROUNDS_PER_DISPATCH", None)
            else:
                os.environ["XGBTPU_ROUNDS_PER_DISPATCH"] = old
        baseline = {"fixed_round_s": bfixed, "per_row_s": bslope,
                    "points": bpoints, "fit_max_rel_err": bmax_rel,
                    "fixed_drop_vs_fused": (bfixed / fixed)
                    if fixed > 0 else None}

    model = {
        "fixed_round_s": fixed,
        "per_row_s": slope,
        "config": {"max_depth": 6, "n_feat": 28, "n_bin": 64,
                   "max_bin": 64, "eta": 0.1,
                   "objective": "binary:logistic", "rounds": rounds,
                   "rounds_per_dispatch": os.environ.get(
                       "XGBTPU_ROUNDS_PER_DISPATCH", "auto")},
        "points": points,
        "fit_max_rel_err": max_rel,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "fitted_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if baseline is not None:
        model["per_round_baseline"] = baseline
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ROUND_MODEL.json")
    with open(out, "w") as f:
        json.dump(model, f, indent=1)
    print(json.dumps(model))


if __name__ == "__main__":
    main()

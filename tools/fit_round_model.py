"""Fit the per-round compute model from a single-chip row sweep.

The multi-chip projection (``parallel/commcost.project_round_time``)
models per-chip compute as ``fixed_round_s + per_row_s * rows_per_chip``.
Round 4 ASSUMED ``fixed_round_s = 0.004`` — 79% of the projected 8-chip
round — with no measurement behind it (VERDICT r4, Missing #2).  This
tool replaces the assumption with a measurement: it times the bench's
binary workload (depth 6, max_bin 64, F=28 — the exact config the
projection speaks about) at 1M, 1M/2, 1M/4 and 1M/8 rows on the real
chip, least-squares fits the affine model, and writes ``ROUND_MODEL.json``
at the repo root, which ``project_round_time`` then loads as its
calibrated defaults.

The row sweep measures exactly the quantity the projection needs:
per-chip round time at N/k rows is the single-chip round time at that
row count (the level structure — launches, split finding, routing — is
identical; only the row-proportional kernels shrink), plus the psum
term, which is modeled separately and test-pinned byte-for-byte
(tests/test_distributed.py).

Run on the real chip (default env): ``python tools/fit_round_model.py``.
Reference counterpart: the network boundary being modeled is
``updater_histmaker-inl.hpp:343-346`` (per-level histogram allreduce);
the reference validated its distributed mode with real multi-node runs
(``multi-node/col-split/mushroom-col-rabit.sh``), which this image's
single chip cannot — the fit makes the projection as anchored as the
hardware allows.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _sweep(B, xgb, params, X, y, rows_list, rounds, tag):
    points = []
    for n in rows_list:
        d = xgb.DMatrix(X[:n], label=y[:n])
        t0 = time.perf_counter()
        dt, _ = B._time_training(xgb, params, d, rounds)
        s_round = dt / (rounds - 1)
        points.append({"rows": n, "s_per_round": s_round})
        print(f"[{tag}] rows={n:>9,}  {s_round*1e3:7.3f} ms/round  "
              f"({1/s_round:6.1f} r/s; wall {time.perf_counter()-t0:.0f}s)",
              file=sys.stderr)
    rows = np.array([p["rows"] for p in points], np.float64)
    t = np.array([p["s_per_round"] for p in points], np.float64)
    A = np.stack([np.ones_like(rows), rows], axis=1)
    (fixed, slope), res, *_ = np.linalg.lstsq(A, t, rcond=None)
    pred = A @ np.array([fixed, slope])
    rel_err = np.abs(pred - t) / t
    return float(fixed), float(slope), points, float(rel_err.max())


def main():
    import bench as B
    import xgboost_tpu as xgb
    import jax

    rounds = int(os.environ.get("FIT_ROUNDS", 50))
    rows_list = [int(r) for r in os.environ.get(
        "FIT_ROWS", "125000,250000,500000,1000000").split(",")]
    params = {"objective": "binary:logistic", "max_depth": 6,
              "eta": 0.1, "max_bin": 64}

    X, y = B.make_higgs_like(max(rows_list))
    fixed, slope, points, max_rel = _sweep(
        B, xgb, params, X, y, rows_list, rounds, "fused")

    # round 8: the primary sweep rides update_many's segmented fusion
    # (auto-K, or XGBTPU_ROUNDS_PER_DISPATCH in the env); a second
    # sweep at K=0 measures the per-round dispatch floor the fusion
    # removes, so the json carries the A/B the PROFILE quotes.
    # FIT_PER_ROUND_BASELINE=0 skips it.
    baseline = None
    if os.environ.get("FIT_PER_ROUND_BASELINE", "1") != "0":
        old = os.environ.get("XGBTPU_ROUNDS_PER_DISPATCH")
        os.environ["XGBTPU_ROUNDS_PER_DISPATCH"] = "0"
        try:
            bfixed, bslope, bpoints, bmax_rel = _sweep(
                B, xgb, params, X, y, rows_list, rounds, "per-round")
        finally:
            if old is None:
                os.environ.pop("XGBTPU_ROUNDS_PER_DISPATCH", None)
            else:
                os.environ["XGBTPU_ROUNDS_PER_DISPATCH"] = old
        baseline = {"fixed_round_s": bfixed, "per_row_s": bslope,
                    "points": bpoints, "fit_max_rel_err": bmax_rel,
                    "fixed_drop_vs_fused": (bfixed / fixed)
                    if fixed > 0 else None}

    model = {
        "fixed_round_s": fixed,
        "per_row_s": slope,
        "config": {"max_depth": 6, "n_feat": 28, "n_bin": 64,
                   "max_bin": 64, "eta": 0.1,
                   "objective": "binary:logistic", "rounds": rounds,
                   "rounds_per_dispatch": os.environ.get(
                       "XGBTPU_ROUNDS_PER_DISPATCH", "auto")},
        "points": points,
        "fit_max_rel_err": max_rel,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "fitted_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if baseline is not None:
        model["per_round_baseline"] = baseline
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ROUND_MODEL.json")
    with open(out, "w") as f:
        json.dump(model, f, indent=1)
    print(json.dumps(model))


if __name__ == "__main__":
    main()

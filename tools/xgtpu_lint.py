#!/usr/bin/env python
"""xgtpu-lint CLI — thin wrapper over ``python -m xgboost_tpu.analysis``.

Usage:
    tools/xgtpu_lint.py [paths...] [--json | --sarif]
                        [--rules XGT003,XGT011]
                        [--baseline PATH | --no-baseline]
                        [--write-baseline] [--list-rules] [-v]
                        [--changed [REF]] [--write-contracts]
                        [--no-contracts]

``--changed [REF]`` (default HEAD) is the fast pre-commit loop: only
findings anchored in files changed vs. REF are reported (cross-file
contract rules XGT008-XGT012/XGT016/XGT017 still collect facts
repo-wide).  ``--write-contracts`` regenerates the committed
ANALYSIS_CONTRACTS.json inventory (routes, metric families, knobs,
lock edges, exit codes, event names).  ``--sarif`` emits SARIF 2.1.0
(one run per rule code) for editor/CI ingestion.

Exit codes: 0 clean, 1 findings, 2 usage/internal error.  Rule catalog
and fix recipes: ANALYSIS.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from xgboost_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

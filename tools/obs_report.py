"""Render an ``obs_log=`` JSONL event log into a human timeline.

Usage::

    python tools/obs_report.py RUN.jsonl            # full timeline
    python tools/obs_report.py RUN.jsonl --rounds   # per-round view only
    python tools/obs_report.py RUN.jsonl --requests # serving view only
    python tools/obs_report.py --selftest           # synthesize + verify

Three sections (any subset may be present in a log):

- **training rounds** — one line per ``train.round`` span with the
  phase breakdown (predict/gradient/grow/eval) and the round's
  collective tallies (allreduce count / bytes / seconds — the
  report_stats view);
- **serving requests** — one line per ``serve.request`` span (request
  id, rows, status, duration) plus ``serve.batch`` coalescing spans;
- **events** — every discrete event (fault injections, reloads,
  drains, integrity failures, checkpoint ring fallbacks) in time
  order, tagged with the round it hit when one was active.

A truncated final line (the process died mid-append) is tolerated and
reported, not fatal — that is exactly the crash this log exists for.
"""

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple


def load(path: str) -> Tuple[List[dict], int]:
    """Parse a JSONL log; returns (records, n_bad_lines)."""
    records, bad = [], 0
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1  # torn tail from a dead run
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records, bad


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


def render_rounds(records: List[dict]) -> List[str]:
    out = []
    rounds = [r for r in records
              if r.get("kind") == "span" and r.get("name") == "train.round"]
    if not rounds:
        return out
    out.append(f"== training: {len(rounds)} rounds ==")
    for r in rounds:
        attrs = r.get("attrs", {})
        phases = attrs.get("phases_ms", {})
        parts = " ".join(f"{k}={v:.1f}ms" for k, v in phases.items())
        line = (f"  round {r.get('round', '?'):>4}  "
                f"total={r.get('dur_ms', 0.0):8.1f}ms  {parts}")
        comm = attrs.get("comm", {})
        for op, t in sorted(comm.items()):
            line += (f"  [{op} n={int(t.get('count', 0))}"
                     f" {_fmt_bytes(t.get('bytes', 0.0))}"
                     f" {t.get('seconds', 0.0) * 1e3:.1f}ms]")
        out.append(line)
    return out


def render_requests(records: List[dict]) -> List[str]:
    out = []
    reqs = [r for r in records
            if r.get("kind") == "span" and r.get("name") == "serve.request"]
    batches = [r for r in records
               if r.get("kind") == "span" and r.get("name") == "serve.batch"]
    if not reqs and not batches:
        return out
    out.append(f"== serving: {len(reqs)} requests, "
               f"{len(batches)} device batches ==")
    for r in reqs:
        a = r.get("attrs", {})
        out.append(f"  req {a.get('request_id', r.get('trace', '?'))}  "
                   f"rows={a.get('rows', '?')} "
                   f"status={a.get('status', '?')} "
                   f"v{a.get('model_version', '?')}  "
                   f"{r.get('dur_ms', 0.0):.2f}ms")
    for b in batches:
        a = b.get("attrs", {})
        out.append(f"  batch rows={a.get('rows', '?')} "
                   f"requests={a.get('requests', '?')}  "
                   f"{b.get('dur_ms', 0.0):.2f}ms")
    return out


def render_events(records: List[dict]) -> List[str]:
    out = []
    events = [r for r in records if r.get("kind") == "event"]
    if not events:
        return out
    out.append(f"== events: {len(events)} ==")
    t0 = records[0].get("ts", 0.0) if records else 0.0
    for e in events:
        a = e.get("attrs", {})
        detail = " ".join(f"{k}={v}" for k, v in a.items()
                          if v is not None)
        rnd = f" (round {e['round']})" if "round" in e else ""
        out.append(f"  +{e.get('ts', 0.0) - t0:9.3f}s  "
                   f"{e.get('name', '?')}{rnd}  {detail}")
    return out


def render(path: str, rounds_only: bool = False,
           requests_only: bool = False) -> str:
    records, bad = load(path)
    lines = [f"# obs timeline: {path} ({len(records)} records)"]
    if bad:
        lines.append(f"# WARNING: {bad} unparseable line(s) — "
                     "torn tail from a dead run")
    if not requests_only:
        lines += render_rounds(records)
    if not rounds_only:
        lines += render_requests(records)
    if not rounds_only and not requests_only:
        lines += render_events(records)
    return "\n".join(lines)


# ------------------------------------------------------------- selftest
def selftest() -> int:
    """Generate a synthetic log through the REAL obs APIs and assert
    the rendered timeline shows every section — run as a fast test
    (tests/test_obs.py) and usable standalone as a smoke check."""
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from xgboost_tpu import obs
    from xgboost_tpu.obs import comm, trace

    d = tempfile.mkdtemp(prefix="obs_selftest_")
    path = os.path.join(d, "obs.jsonl")
    obs.configure_log(path)
    try:
        # three synthetic training rounds with phases + comm tallies
        prof = obs.RoundProfiler(level=0)
        for i in range(3):
            from xgboost_tpu.parallel import mock
            mock.begin_round(i)
            prof.begin_round(i)
            with prof.phase("predict"):
                pass
            with prof.phase("grow"):
                comm.record("allreduce", nbytes=1024, seconds=0.001)
            prof.end_round()
        # one serving request span + a discrete fault event
        with trace.trace_context("req-selftest-1"):
            with obs.span("serve.request", request_id="req-selftest-1",
                          rows=4) as sp:
                sp.set("status", 200)
        trace.event("fault.injected", kind="torn_write", seam="write",
                    path="ckpt-000001.model")
        # a torn tail: the report must tolerate it
        with open(path, "ab") as f:
            f.write(b'{"ts": 1, "kind": "ev')
    finally:
        obs.configure_log(None)

    text = render(path)
    for needle in ("3 rounds", "round    0", "grow=", "[allreduce n=1",
                   "req-selftest-1", "status=200", "fault.injected",
                   "kind=torn_write", "unparseable"):
        assert needle in text, f"selftest: {needle!r} missing from:\n{text}"
    print(text)
    print("obs_report selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", nargs="?", help="obs JSONL log path")
    ap.add_argument("--rounds", action="store_true",
                    help="training rounds only")
    ap.add_argument("--requests", action="store_true",
                    help="serving requests only")
    ap.add_argument("--selftest", action="store_true",
                    help="generate a synthetic log and verify rendering")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.log:
        ap.error("log path required (or --selftest)")
    print(render(args.log, rounds_only=args.rounds,
                 requests_only=args.requests))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""A/B: exact-mode per-LEVEL sort vs per-TREE sort + partition apply.

VERDICT r4 Weak #3 / next-round #7: the segment-sorted exact grower
spends ~14 of ~21 ms/level on the packed-key bitonic sort
(models/colmaker.py).  Row positions refine monotonically within a
level order, so one sort per TREE suffices mathematically: after the
level-d sort, each node segment splits stably into left/right child
blocks, i.e. the level-(d+1) order is a PERMUTATION computable from
routing bits without comparing values again.

The catch is applying that permutation: the sorted layout carries 3
operands (packed key, g, h) that all must move, and on TPU a
row-granular (F, N) take_along_axis / scatter is the known-serializing
dynamic lane gather (PROFILE.md round 3: 16 ms/level at 1M x 28 for
ONE operand, vs the whole 3-operand sort at 14 ms).  This tool
measures the actual alternatives at the exact-bench shape:

  A. lax.sort of (packed int32 key, g, h), num_keys=1 — the shipped
     per-level path;
  B. destination-index computation + 3x take_along_axis — the
     per-tree-sort inner step (destination math itself is cheap
     segmented-cumsum work, also timed);
  C. destination-index + 3x scatter (.at[dest].set) — the same
     permutation, scatter-form.

If B or C beats A by >=1.5x, per-tree sort pays and the grower should
adopt it; otherwise this file is the committed negative result (like
pack2/in-kernel routing in earlier rounds).  Measured verdict in
PROFILE.md round 5.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    F, N = 28, 250_000
    rng = np.random.RandomState(0)
    key_np = rng.randint(0, 1 << 22, (F, N)).astype(np.int32)
    g_np = rng.randn(F, N).astype(np.float32)
    h_np = rng.rand(F, N).astype(np.float32)
    perm_np = np.stack([rng.permutation(N) for _ in range(F)]).astype(
        np.int32)

    key_d = jnp.asarray(key_np)
    g_d = jnp.asarray(g_np)
    h_d = jnp.asarray(h_np)
    perm_d = jnp.asarray(perm_np)

    @jax.jit
    def sort3(k, g, h):
        return jax.lax.sort((k, g, h), dimension=1, num_keys=1,
                            is_stable=False)

    @jax.jit
    def gather3(perm, k, g, h):
        return (jnp.take_along_axis(k, perm, axis=1),
                jnp.take_along_axis(g, perm, axis=1),
                jnp.take_along_axis(h, perm, axis=1))

    @jax.jit
    def scatter3(perm, k, g, h):
        z = jnp.zeros_like
        return (z(k).at[jnp.arange(F)[:, None], perm].set(k),
                z(g).at[jnp.arange(F)[:, None], perm].set(g),
                z(h).at[jnp.arange(F)[:, None], perm].set(h))

    @jax.jit
    def dest_math(go_left, seg_lo, key):
        # the per-tree-sort bookkeeping: destination = child segment
        # base + stable within-child rank, via two segmented cumsums
        # (approximated here by their global-cumsum cost shape)
        gl = go_left.astype(jnp.int32)
        c_left = jnp.cumsum(gl, axis=1)
        c_right = jnp.cumsum(1 - gl, axis=1)
        return jnp.where(go_left, c_left, c_right) + seg_lo

    go_left = jnp.asarray(rng.rand(F, N) < 0.5)
    seg_lo = jnp.zeros((F, N), jnp.int32)

    def bench(fn, *args):
        r = fn(*args)
        jax.block_until_ready(r)
        jax.device_get(np.asarray(jax.tree.leaves(r)[0].ravel()[:1]))
        t0 = time.perf_counter()
        for _ in range(10):
            r = fn(*args)
        jax.block_until_ready(r)
        jax.device_get(np.asarray(jax.tree.leaves(r)[0].ravel()[:1]))
        return (time.perf_counter() - t0) / 10 * 1e3

    t_sort = bench(sort3, key_d, g_d, h_d)
    t_gather = bench(gather3, perm_d, key_d, g_d, h_d)
    t_scatter = bench(scatter3, perm_d, key_d, g_d, h_d)
    t_dest = bench(dest_math, go_left, seg_lo, key_d)
    print(f"A per-level sort3          : {t_sort:7.2f} ms")
    print(f"B permutation via gather3  : {t_gather:7.2f} ms (+ dest "
          f"{t_dest:.2f} ms)")
    print(f"C permutation via scatter3 : {t_scatter:7.2f} ms (+ dest "
          f"{t_dest:.2f} ms)")
    best_alt = min(t_gather, t_scatter) + t_dest
    print(f"verdict: per-tree sort {'PAYS' if best_alt * 1.5 <= t_sort else 'does NOT pay'} "
          f"(best alternative {best_alt:.2f} vs sort {t_sort:.2f} ms; "
          f"adoption bar 1.5x)")


if __name__ == "__main__":
    main()

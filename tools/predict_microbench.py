"""Prediction-traversal microbenchmark: sequential scan-over-trees vs
chunked tree-parallel vmap (models/tree.py ``tree_chunk``) at several
(T, N, depth) shapes, on whatever backend is active.

Synthetic random ensembles (uniform features/cuts, leaf values at the
bottom level) traverse identically to trained ones — the kernel cost
is shape-driven.  Every A/B cell first asserts the chunked margins are
BIT-identical to the scan's, then reports best-of-reps wall ms and the
speedup.  JSON output like ``tools/bench_serving.py``::

    python tools/predict_microbench.py [PREDICT_MICROBENCH.json]

Round 7 adds END-TO-END cells (``e2e_cells``): raw f32 row blocks
upload through ``external._prefetch_to_device`` and predict, A/B-ing
upload depth (0 = synchronous, 1, 2 = double-buffered) × fused
quantize+traverse vs the two-step quantize-then-traverse — the
transfer-wall knobs of PROFILE.md round 7, with a per-cell bitwise
assert that fused margins equal two-step margins.

Env knobs: ``PRED_MB_SHAPES`` ("T,N,depth;..." cells),
``PRED_MB_CHUNKS`` (comma list), ``PRED_MB_REPS`` (default 5),
``PRED_MB_E2E_SHAPES`` (e2e "T,N,depth;..." cells),
``PRED_MB_E2E_DEPTHS`` (upload depths, default "1,2").
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from xgboost_tpu.models.tree import (  # noqa: E402
    TreeArrays, predict_margin_binned, predict_margin_fused,
    tree_capacity)

N_FEAT = 28
N_BIN = 64
DEFAULT_SHAPES = "100,1000000,6;100,100000,6;20,100000,6;100,100000,10"
DEFAULT_CHUNKS = "8,32"
DEFAULT_E2E_SHAPES = "100,200000,6;100,1000000,6"
DEFAULT_E2E_DEPTHS = "1,2"
E2E_BLOCKS = 4  # raw f32 row blocks per end-to-end prediction


def synth_ensemble(T, depth, n_feat, n_bin, seed=0):
    """(stack, group) of T random depth-``depth`` trees: every node
    above the bottom level splits, the bottom level is all leaves —
    the worst-case (deepest) traversal for the layout."""
    rng = np.random.RandomState(seed)
    n_nodes = tree_capacity(depth)
    bottom = (1 << depth) - 1
    feature = rng.randint(0, n_feat, size=(T, n_nodes)).astype(np.int32)
    feature[:, bottom:] = -1
    is_leaf = np.zeros((T, n_nodes), bool)
    is_leaf[:, bottom:] = True
    stack = TreeArrays(
        feature=jnp.asarray(feature),
        cut_index=jnp.asarray(
            rng.randint(0, n_bin - 2, size=(T, n_nodes)), jnp.int32),
        threshold=jnp.zeros((T, n_nodes), jnp.float32),
        default_left=jnp.asarray(rng.rand(T, n_nodes) < 0.5),
        is_leaf=jnp.asarray(is_leaf),
        leaf_value=jnp.asarray(
            rng.randn(T, n_nodes).astype(np.float32) * 0.1),
        gain=jnp.zeros((T, n_nodes), jnp.float32),
        sum_hess=jnp.ones((T, n_nodes), jnp.float32),
    )
    return stack, jnp.zeros(T, jnp.int32)


def barrier(x):
    # true device drain (tunnel-safe): one-element host pull
    np.asarray(jax.device_get(jnp.sum(x)))


def timeit(fn, reps):
    out = fn()
    barrier(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        barrier(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3, out


def synth_raw(N, n_feat, n_bin, seed=3):
    """Raw f32 rows (with some NaN missing) + a sorted finite cut
    matrix: the end-to-end cells quantize these on device, so the
    two-step and fused paths start from identical host bytes."""
    rng = np.random.RandomState(seed)
    X = rng.rand(N, n_feat).astype(np.float32)
    X[:: 13, 0] = np.nan
    cuts = np.sort(rng.rand(n_feat, n_bin - 2).astype(np.float32),
                   axis=1)
    return X, cuts


def run_e2e(X, cuts, stack, group, depth, tree_chunk, upload_depth,
            fused):
    """One end-to-end prediction: raw f32 blocks → prefetch upload →
    (quantize →) traverse → concatenated margins.  This is the
    learner's one-off pipeline with the learner stripped away."""
    from xgboost_tpu.binning import bin_dense_device
    from xgboost_tpu.external import _prefetch_to_device
    N = X.shape[0]
    block = -(-N // E2E_BLOCKS)
    base = jnp.zeros((), jnp.float32)
    cuts_dev = jnp.asarray(cuts)

    def blocks():
        for s in range(0, N, block):
            yield s, X[s:s + block]

    parts = []
    for _, xd in _prefetch_to_device(blocks(), depth=upload_depth):
        if fused:
            parts.append(predict_margin_fused(
                stack, group, xd, cuts_dev, base, depth, 1,
                tree_chunk=tree_chunk))
        else:
            parts.append(predict_margin_binned(
                stack, group, bin_dense_device(xd, cuts_dev), base,
                depth, 1, tree_chunk=tree_chunk))
    return jnp.concatenate(parts, axis=0)


def e2e_main(reps, chunk):
    """End-to-end (upload+quantize+traverse) A/B grid: upload depth ×
    fused-vs-two-step, per shape.  Margins are bit-asserted equal
    across every variant of a cell."""
    shapes = [tuple(int(v) for v in cell.split(","))
              for cell in os.environ.get(
                  "PRED_MB_E2E_SHAPES", DEFAULT_E2E_SHAPES).split(";")
              if cell]
    depths = [int(d) for d in os.environ.get(
        "PRED_MB_E2E_DEPTHS", DEFAULT_E2E_DEPTHS).split(",")]
    cells = []
    for T, N, depth in shapes:
        X, cuts = synth_raw(N, N_FEAT, N_BIN)
        stack, group = synth_ensemble(T, depth, N_FEAT, N_BIN)
        cell = {"T": T, "N": N, "depth": depth, "blocks": E2E_BLOCKS,
                "tree_chunk": chunk}
        ref = None
        for fused in (False, True):
            for d in depths:
                ms, m = timeit(lambda: run_e2e(
                    X, cuts, stack, group, depth, chunk, d, fused),
                    reps)
                key = f"{'fused' if fused else 'twostep'}_depth{d}"
                cell[f"{key}_ms"] = round(ms, 2)
                cell[f"{key}_rows_per_sec"] = round(N / (ms / 1e3), 1)
                if ref is None:
                    ref = np.asarray(m)
                else:
                    bit = bool(np.array_equal(ref, np.asarray(m)))
                    cell[f"{key}_bit_identical"] = bit
                    assert bit, f"e2e margins diverged at {key} T={T}"
        cells.append(cell)
        print(json.dumps(cell))
    return cells


def main():
    shapes = [tuple(int(v) for v in cell.split(","))
              for cell in os.environ.get(
                  "PRED_MB_SHAPES", DEFAULT_SHAPES).split(";") if cell]
    chunks = [int(c) for c in os.environ.get(
        "PRED_MB_CHUNKS", DEFAULT_CHUNKS).split(",")]
    reps = int(os.environ.get("PRED_MB_REPS", "5"))
    base = jnp.zeros((), jnp.float32)
    cells = []
    for T, N, depth in shapes:
        rng = np.random.RandomState(1)
        binned = jnp.asarray(
            rng.randint(0, N_BIN, size=(N, N_FEAT)), jnp.uint8)
        stack, group = synth_ensemble(T, depth, N_FEAT, N_BIN)
        ms_scan, m_scan = timeit(
            lambda: predict_margin_binned(stack, group, binned, base,
                                          depth, 1, tree_chunk=0), reps)
        cell = {"T": T, "N": N, "depth": depth,
                "scan_ms": round(ms_scan, 2),
                "scan_rows_per_sec": round(N / (ms_scan / 1e3), 1)}
        for c in chunks:
            ms, m = timeit(
                lambda: predict_margin_binned(stack, group, binned, base,
                                              depth, 1, tree_chunk=c),
                reps)
            bit = bool(np.array_equal(np.asarray(m_scan), np.asarray(m)))
            cell[f"chunk{c}_ms"] = round(ms, 2)
            cell[f"chunk{c}_speedup"] = round(ms_scan / ms, 2)
            cell[f"chunk{c}_bit_identical"] = bit
            assert bit, f"chunked margins diverged at T={T} chunk={c}"
        cells.append(cell)
        print(json.dumps(cell))
    # e2e cells traverse at the auto-gate chunk (32 on TPU, scan on
    # CPU — gbtree.pred_chunk's own resolution), so the committed
    # numbers reflect what Learner.predict actually runs per backend
    e2e = e2e_main(reps, 32 if jax.default_backend() == "tpu" else 0)
    out = {"metric": "predict_traversal_scan_vs_chunked_ms",
           "backend": jax.default_backend(),
           "reps_best_of": reps, "n_feat": N_FEAT, "n_bin": N_BIN,
           "cells": cells, "e2e_cells": e2e}
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Serving micro-benchmark: requests/s and latency quantiles through
the full engine + micro-batcher stack at fixed row counts.

CPU-only (``JAX_PLATFORMS=cpu``), same output shape as the
``BENCH_r*.json`` files::

    python tools/bench_serving.py            # writes BENCH_serving.json

The headline metric is single-row requests/s after warmup (the
latency-bound serving shape); per-size throughput and p50/p99 ride
along, plus a concurrent-clients run that exercises coalescing.
"""

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import xgboost_tpu as xgb  # noqa: E402
from xgboost_tpu.profiling import ServingMetrics  # noqa: E402
from xgboost_tpu.serving import MicroBatcher, PredictEngine  # noqa: E402

ROWS_PER_REQ = (1, 8, 64, 512)
REQS_PER_SIZE = int(os.environ.get("BENCH_SERVING_REQS", "300"))
N_TRAIN, N_FEAT, ROUNDS = 20_000, 28, 20
CONCURRENT_CLIENTS = 8


def _train_model():
    rng = np.random.RandomState(0)
    X = rng.rand(N_TRAIN, N_FEAT).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2]
         + 0.1 * rng.randn(N_TRAIN) > 0.65).astype(np.float32)
    return xgb.train({"objective": "binary:logistic", "max_depth": 6,
                      "eta": 0.3, "silent": 1},
                     xgb.DMatrix(X, label=y), ROUNDS)


def bench_direct(engine, rng):
    """Engine-only path: one request at a time, per-size stats.  The
    p50/p99 come from the unified metric registry's latency histogram
    (one fresh ``ServingMetrics`` per size), not an ad-hoc sorted-list
    recompute — the bench reports exactly what a scrape would."""
    per_size = {}
    for n in ROWS_PER_REQ:
        metrics = ServingMetrics()
        Xs = [rng.rand(n, N_FEAT).astype(np.float32) for _ in range(32)]
        engine.predict(Xs[0])  # bucket already warm; prime np caches
        t0 = time.perf_counter()
        for i in range(REQS_PER_SIZE):
            s = time.perf_counter()
            engine.predict(Xs[i % len(Xs)])
            metrics.latency.observe(time.perf_counter() - s)
        wall = time.perf_counter() - t0
        q = metrics.quantiles((0.5, 0.99))
        per_size[n] = {
            "requests_per_sec": round(REQS_PER_SIZE / wall, 1),
            "rows_per_sec": round(REQS_PER_SIZE * n / wall, 1),
            "p50_ms": round(q[0.5] * 1e3, 3),
            "p99_ms": round(q[0.99] * 1e3, 3),
        }
    return per_size


def bench_concurrent(engine, rng):
    """Batched path: N client threads hammering one MicroBatcher with
    single-row requests (the coalescing win over bench_direct[1])."""
    metrics = ServingMetrics()
    batcher = MicroBatcher(engine.predict, max_batch_rows=1024,
                           max_wait_ms=1.0, max_queue_rows=1 << 20,
                           metrics=metrics)
    reqs_per_client = REQS_PER_SIZE // 2
    Xs = [rng.rand(1, N_FEAT).astype(np.float32) for _ in range(64)]
    barrier = threading.Barrier(CONCURRENT_CLIENTS + 1)

    def client():
        barrier.wait()
        for i in range(reqs_per_client):
            # the batcher observes each request's latency into
            # metrics.latency; quantiles below read the same histogram
            # the /metrics endpoint renders
            batcher.submit(Xs[i % len(Xs)])

    ts = [threading.Thread(target=client)
          for _ in range(CONCURRENT_CLIENTS)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    total = reqs_per_client * CONCURRENT_CLIENTS
    batcher.close()
    q = metrics.quantiles((0.5, 0.99))
    return {
        "clients": CONCURRENT_CLIENTS,
        "requests_per_sec": round(total / wall, 1),
        "p50_ms": round(q[0.5] * 1e3, 3),
        "p99_ms": round(q[0.99] * 1e3, 3),
        "batches": int(metrics.batches.value),
        "mean_batch_rows": round(total / max(metrics.batches.value, 1), 2),
    }


def main():
    bst = _train_model()
    engine = PredictEngine(bst, min_bucket=8, max_bucket=1024)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    rng = np.random.RandomState(1)

    # traversal-only latency comes from the unified registry's
    # per-chunk histogram (engine times the margin launch into it) —
    # snapshot the count so the bench reports only its own traffic
    from xgboost_tpu.obs.metrics import predict_metrics
    pm = predict_metrics()
    chunk_n0 = pm.chunk_seconds.count

    c0 = engine.compile_count
    per_size = bench_direct(engine, rng)
    concurrent = bench_concurrent(engine, rng)
    assert engine.compile_count == c0, "steady state recompiled!"

    desc = engine.describe()
    out = {
        "metric": "serving_1row_requests_per_sec",
        "value": per_size[1]["requests_per_sec"],
        "unit": (f"req/s (1-row requests, depth6 x {ROUNDS} trees, "
                 f"{N_FEAT} feats, CPU; p99="
                 f"{per_size[1]['p99_ms']}ms)"),
        "warmup_sec": round(warmup_s, 2),
        "buckets": engine.buckets,
        "compile_count": engine.compile_count,
        "steady_state_compiles": engine.compile_count - c0,
        "per_request_rows": {str(k): v for k, v in per_size.items()},
        "concurrent": concurrent,
        # device traversal time per tree chunk (xgbtpu_predict_chunk
        # _seconds), separated from the request latency above — the
        # queueing/transform/HTTP share is the difference
        "traversal": {
            "tree_chunk": desc["tree_chunk"],
            "tree_chunks": desc["tree_chunks"],
            "chunk_p50_ms": round(
                pm.chunk_seconds.quantile(0.5) * 1e3, 3),
            "chunk_p99_ms": round(
                pm.chunk_seconds.quantile(0.99) * 1e3, 3),
            "launches": pm.chunk_seconds.count - chunk_n0,
        },
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_serving.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Chaos loop: repeated kill-at-random-fault-point train/resume driver.

Each run arms a RANDOM failure combination against the real training
CLI — a worker death at a random (version, seqno) collective coordinate
(``mock=`` / parallel/mock.py) plus, half the time, a torn-write or
bit-flip fault on a random checkpoint-ring member at a random byte
offset (``reliability/faults.py``) — then lets the keepalive restart
recover through the checkpoint ring and asserts the finished model is
BIT-identical to an uninterrupted reference run.

Emits ``CHAOS.json``::

    {"runs": N, "recoveries": n, "bit_identical": n, "mismatches": 0,
     "deaths": total_kills, "corruptions_armed": n,
     "ring_fallbacks": n, "quarantines": n, "integrity_failures": n}

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_loop.py --runs 10 --seed 0

``--fleet`` switches to the SERVING-tier chaos mode (SERVING.md fleet
section): a local fleet (tools/launch_fleet.py — router + N replica
subprocesses) serves live traffic while a killer SIGKILLs a random
replica every few seconds and keepalive restarts it.  The assertion is
the fleet contract: ZERO failed non-shed requests — every client
request either succeeds (the router's retry-once path absorbs replica
deaths) or is an explicit 503 shed.  Emits ``CHAOS_fleet.json``.

``--pipeline`` switches to the CONTINUOUS-TRAINING chaos mode
(PIPELINE.md): a shared-model fleet (every replica polls the pipeline's
publish path) serves live traffic while ``task=pipeline`` subprocesses
train→gate→publish fresh cycles — and the driver SIGKILLs the pipeline
process at random moments and randomly arms bit-flip/torn-write faults
on the candidate, the checkpoint ring, and the publish path.  A hash
watcher scrapes every replica's ``/healthz`` ``model_hash``
continuously; the contract asserted is **zero unverified or ungated
models ever observed by a serving replica**: every hash a replica
serves must be the initial seed model or a hash recorded in the
pipeline's fsync'd ``gated.log`` ledger BEFORE its publish began.
Emits ``PIPELINE_CHAOS.json``.

``--catalog`` switches to the MULTI-TENANT catalog chaos mode
(SERVING.md catalog section): two width-divergent tenant models share a
catalog fleet (``task=serve catalog=a=...,b=...``) behind a router
subprocess running with ``fleet_state_path``; per-tenant ``task=
pipeline`` lanes train→gate→publish against each tenant's publish
path while per-tenant clients drive ``/predict?model=...`` and the
killer SIGKILLs lane trainers at random — and the ROUTER itself, whose
replacement must restore membership from the CRC-footered snapshot
with zero non-shed client failures.  Per-tenant hash watchers scrape
``/healthz`` ``models`` rows straight off every replica; the contract
is the pipeline mode's zero-ungated-models invariant enforced PER
TENANT (each tenant against its OWN ``gated.log``), plus isolation:
killing one tenant's trainer never stalls the other's lane.  Emits
``CATALOG_CHAOS.json``.

``--stream`` switches to the STREAMING chaos mode (PIPELINE.md
streaming section): a producer thread spools row batches into a
``StreamDataSource`` directory (shifting the feature distribution
halfway through, so drift fires and an online cut refresh lands
mid-chaos) while ``task=stream`` subprocesses consume micro-cycles —
and the driver SIGKILLs the stream trainer at random moments
(mid-compose, mid-train, mid-gate, mid-publish).  SIGKILL-only: the
stream contract under test is replay determinism, not media faults.
A watcher hashes the publish path continuously; asserted are (a) the
zero-ungated invariant — every observed publish-path hash is the seed
or in ``gated.log`` — and (b) bit-identical replay: a FRESH workdir
consuming the SAME spool re-publishes the identical per-cycle hash
sequence and identical final model bytes.  Emits
``STREAM_CHAOS.json``.

``--placer`` switches to the AUTONOMOUS-PLACEMENT chaos mode
(SERVING.md "Autonomous placement"): a router + N default-only catalog
replicas + a ``task=placer`` subprocess managing a 4-tenant manifest
(``placer_replication=2``).  Once the placer has attached every tenant,
per-tenant clients drive ``/predict?model=...`` through the router
while the killer (a) SIGKILLs a replica mid-rebalance (keepalive
restarts it under a FRESH identity, so the placer must re-home, not
wait), (b) SIGKILLs the placer itself mid-push and restarts it on the
same ``placer_plan_path``, and (c) repeats the placer kill in a quiet
window to pin plan-resume determinism.  A watcher samples
``/fleet/members`` continuously; the contract is (1) zero non-shed
client failures, (2) no tenant ever orphaned — every sample shows ≥1
in-rotation replica advertising each tenant — and (3) the resumed
placer reports the SAME target assignment it snapshotted before the
kill.  Emits ``PLACER_CHAOS.json``.

``--train`` switches to the STALL-failure training mode (RELIABILITY.md
stall matrix): each run arms a ``stall`` mock coordinate (the hang twin
of worker death, parallel/mock.py) — and, half the time, a death
coordinate on the NEXT trial — against the real CLI supervised by the
gang launcher's heartbeat watchdog (``--watchdog-stall-sec``).  The
wedged worker stops touching its per-rank heartbeat file, the watchdog
kills and restarts the gang, the restarted trial sails past the
coordinate (ntrial semantics) and resumes from the checkpoint ring; the
assertion is the same bit-identical-final-model contract as the death
suite.  Two cells run per invocation: ``baseline`` (single-device
segmented fused dispatch) and ``fused_mesh`` (``dsplit=row`` +
``hist_precision=fixed`` over ``--local-devices`` in-process devices —
the mesh-fused scan), both verified fallback-free via the obs event
log (``train.fused_fallback`` must never appear).  Emits
``TRAIN_CHAOS.json``.

``--train --degrade`` additionally runs the ELASTIC DEGRADED-MESH
cells (RECOVERY.md degraded-mode matrix) against the real CLI under
the gang launcher:

- ``host_loss_growback`` — a permanent host death mid-run
  (``host_loss`` gang fault) forces an immediate re-plan at half the
  device count; once degraded, the driver touches the ``grow`` signal
  (a replacement registered) and the launcher re-expands to full size
  at the next segment boundary.  Asserted: the finished model is
  BIT-identical to an uninterrupted run (PR 12 mesh-size invariance is
  the oracle) and the ``gang.host_loss`` / ``launch.degrade`` /
  ``launch.growback`` events all fired.
- ``coord_sigkill_adopt`` — SIGKILL the COORDINATOR mid-restart (right
  after a worker death triggered a gang restart); a replacement
  launcher started on the same ``--state-path`` re-ADOPTS the live
  workers (``launch.adopt``) instead of orphaning or re-spawning them,
  and the job finishes bit-identical with no leaked pids.
- ``partition_fence`` — a ``partition`` window straddling the ring
  writes: the worker self-fences (``gang.fence``, rc 143) once the
  coordinator beacon is stale past ``--gang-partition-sec``, the gang
  restarts and resumes from the ring.  A watcher thread samples every
  checkpoint-ring member THROUGHOUT; the split-brain assertion is that
  every observed member CRC-verifies (atomic_write: no torn reads) and
  every version slot ever observed holds exactly ONE payload hash
  across all attempts — one attempt lineage, no second writer.

Cell results merge into the same ``TRAIN_CHAOS.json`` under
``degrade``.  ``--runs 0`` skips the stall cells (degrade cells only).

``--selftest`` runs the fast, subprocess-free logic checks (partition
clock, degrade ladder, coordinator-state roundtrip, fail-loud fault
parsing, ring-lineage scanner) and prints ``selftest: OK`` — wired as
a tier-1 test (tests/test_chaos_selftest.py).

``--fleet --slow`` arms ``slow_replica`` (a wedged-but-alive replica:
every predict sleeps, lease and /healthz stay green) instead of kills:
the router's latency-aware ejection must take the replica out of
rotation and traffic must keep flowing with ZERO non-shed failures.
Emits ``CHAOS_fleet_slow.json``.

Also runs as a slow-marked test
(tests/test_reliability.py::test_chaos_loop_driver).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _write_libsvm(path: str, n: int = 300, f: int = 5, seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] > 0.5).astype(int)
    with open(path, "w") as fh:
        for i in range(n):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(f))
            fh.write(f"{y[i]} {feats}\n")


def _state(path: str):
    import xgboost_tpu as xgb
    return xgb.Booster(model_file=path).gbtree.get_state()


def _states_equal(a, b) -> bool:
    if set(a) != set(b):
        return False
    return all(np.array_equal(a[k], b[k]) for k in a)


def _scan_obs_events(prefix: str, name: str) -> int:
    """Count ``name`` events across the obs JSONL file(s) a run wrote
    (``prefix`` plus per-rank suffixes).  Append-only across gang
    restarts, so a fallback from ANY trial stays visible."""
    import glob
    hits = 0
    for path in glob.glob(prefix + "*"):
        try:
            with open(path) as f:
                for line in f:
                    if f'"name": "{name}"' in line or \
                            f'"name":"{name}"' in line:
                        hits += 1
        except OSError:
            pass
    return hits


def train_stall_mode(args) -> int:
    """Stall-failure training chaos: wedge the worker at a random
    collective coordinate, let the watchdog kill+restart the gang, and
    assert bit-identical resume — composed with a death on the restart
    trial half the time (see module docstring).

    Runs TWO cells per seed: ``baseline`` (single-device, segmented
    fused dispatch) and ``fused_mesh`` (``dsplit=row`` over
    ``--local-devices`` in-process devices with
    ``hist_precision=fixed``, the mesh-fused scan).  Both ride the
    fused driver — coordinates replay at segment boundaries — and both
    assert ZERO silent per-round fallbacks by scanning the run's obs
    event log for ``train.fused_fallback`` (counter-backed: the same
    events increment ``xgbtpu_train_fused_fallback_total``)."""
    import subprocess

    from xgboost_tpu.cli import main as cli_main

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaostrain_")
    os.makedirs(work, exist_ok=True)
    data = os.path.join(work, "train.libsvm")
    _write_libsvm(data, seed=args.seed)
    # rounds_per_dispatch=2: several segments per run, so the stall /
    # death coordinates land BETWEEN ring checkpoints and the restart
    # genuinely resumes mid-training (auto-K would fuse this tiny
    # workload into one segment and every restart would retrain from 0)
    common = [f"data={data}", "task=train", f"num_round={args.rounds}",
              "silent=2", "objective=binary:logistic", "max_depth=3",
              "eta=0.5", "max_bin=16", "rounds_per_dispatch=2"]
    cells = [
        ("baseline", [], []),
        ("fused_mesh", ["dsplit=row", "hist_precision=fixed"],
         ["--local-devices", str(args.local_devices)]),
    ]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = {"mode": "train_stall", "runs_per_cell": args.runs,
              "local_devices": args.local_devices,
              "stalls_armed": 0, "deaths_armed": 0,
              "watchdog_kills": 0, "restarts": 0,
              "bit_identical": 0, "mismatches": 0,
              "fused_fallbacks": 0, "run_log": []}
    if args.runs == 0:
        cells = []  # --runs 0: degrade cells only (see --degrade)
    for cell, extra, launch_extra in cells:
        # uninterrupted reference per cell (checkpointing ON: identical
        # code path; the mesh cell's params change the model)
        ref_model = os.path.join(work, f"ref_{cell}.model")
        rc = cli_main(common + extra + [
            f"model_out={ref_model}",
            f"checkpoint_dir={os.path.join(work, f'ck_ref_{cell}')}"])
        if rc != 0:
            print(f"[chaos-train] {cell} reference run failed (rc={rc})",
                  file=sys.stderr)
            return 1
        ref = _state(ref_model)

        rng = np.random.RandomState(args.seed)
        for run in range(args.runs):
            out = os.path.join(work, f"m_{cell}_{run:03d}.model")
            obs_log = os.path.join(work, f"obs_{cell}_{run:03d}.jsonl")
            vs = int(rng.randint(1, args.rounds))  # stall round (trial 0)
            mock = f"stall:{vs},0,0"
            report["stalls_armed"] += 1
            entry = {"cell": cell, "run": run, "mock": mock}
            if run % 2 == 1 or rng.rand() < 0.5:
                # compose stall with DEATH on (at least) every odd run:
                # the restarted trial (1) dies at a later coordinate,
                # exercising watchdog-kill followed by plain keepalive
                # restart in one recovery chain
                vd = int(rng.randint(1, args.rounds))
                mock += f";die:{vd},0,1"
                entry["mock"] = mock
                report["deaths_armed"] += 1
            cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "1",
                   "--standalone", "--keepalive", *launch_extra,
                   "--watchdog-stall-sec", str(args.stall_window),
                   "--restart-backoff-sec", "0.2", "--",
                   sys.executable, "-m", "xgboost_tpu", *common, *extra,
                   f"model_out={out}",
                   f"checkpoint_dir={os.path.join(work, f'ck_{cell}_{run:03d}')}",
                   f"mock={mock}"]
            # XGBTPU_OBS_PHASES=0: the event log must witness the run
            # WITHOUT forcing per-round phases (which would itself
            # block fusion — the fallback we are asserting against)
            r = subprocess.run(cmd, cwd=repo, capture_output=True,
                               text=True, timeout=600,
                               env=dict(os.environ, JAX_PLATFORMS="cpu",
                                        XGBTPU_OBS_LOG=obs_log,
                                        XGBTPU_OBS_PHASES="0"))
            entry["rc"] = r.returncode
            entry["watchdog_kills"] = r.stderr.count("[launch] STALL")
            entry["restarts"] = r.stderr.count("[launch] restarting")
            # the LOUD-fallback contract: every trial of every run must
            # have taken the fused driver (per-round fallback emits a
            # train.fused_fallback event + counter)
            entry["fused_fallbacks"] = _scan_obs_events(
                obs_log, "train.fused_fallback")
            report["watchdog_kills"] += entry["watchdog_kills"]
            report["restarts"] += entry["restarts"]
            report["fused_fallbacks"] += entry["fused_fallbacks"]
            if (r.returncode == 0 and _states_equal(ref, _state(out))
                    and entry["fused_fallbacks"] == 0):
                report["bit_identical"] += 1
                entry["result"] = "bit_identical"
            else:
                report["mismatches"] += 1
                entry["result"] = (
                    f"rc={r.returncode}" if r.returncode
                    else "FUSED_FALLBACK" if entry["fused_fallbacks"]
                    else "MISMATCH")
                entry["stderr_tail"] = r.stderr[-1500:]
            report["run_log"].append(entry)
            print(f"[chaos-train] {cell} run {run}: mock={mock} -> "
                  f"{entry['result']} ({entry['watchdog_kills']} "
                  f"watchdog kill(s), {entry['restarts']} restart(s), "
                  f"{entry['fused_fallbacks']} fused fallback(s))",
                  file=sys.stderr)
    degrade_ok = True
    if args.degrade:
        degrade_ok = degrade_cells(args, work, repo, report)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    total = args.runs * len(cells)
    print(f"[chaos-train] {report['bit_identical']}/{total} "
          f"bit-identical across {report['watchdog_kills']} watchdog "
          f"kills / {report['restarts']} restarts "
          f"({report['fused_fallbacks']} fused fallbacks) -> {args.out}",
          file=sys.stderr)
    ok = (report["mismatches"] == 0 and report["fused_fallbacks"] == 0
          and (args.runs == 0
               or (report["watchdog_kills"] >= 1
                   and report["restarts"] >= report["watchdog_kills"])))
    return 0 if (ok and degrade_ok) else 1


def _ckpt_lineage_violations(lineage) -> list:
    """Ring slots observed with MORE than one distinct payload hash —
    the split-brain witness: a resumed attempt rewriting a version slot
    must reproduce the identical bytes (deterministic recovery), so a
    second hash means a second, diverged writer touched the ring."""
    return sorted(name for name, hashes in lineage.items()
                  if len(hashes) > 1)


def degrade_cells(args, work, repo, report) -> bool:
    """The elastic degraded-mesh chaos cells (see module docstring,
    ``--train --degrade``): host-loss degrade + grow-back, coordinator
    SIGKILL + re-adoption, and a partition self-fence with the ring
    split-brain assertion.  Results land in ``report['degrade']``."""
    import hashlib
    import re
    import signal
    import subprocess
    import threading

    from xgboost_tpu.cli import main as cli_main
    from xgboost_tpu.reliability.integrity import (read_file,
                                                   verify_model_bytes)

    data = os.path.join(work, "train.libsvm")
    mesh = ["dsplit=row", "hist_precision=fixed"]

    def common(rounds):
        return [f"data={data}", "task=train", f"num_round={rounds}",
                "silent=2", "objective=binary:logistic", "max_depth=3",
                "eta=0.5", "max_bin=16", "rounds_per_dispatch=2"]

    def reference(tag, rounds, extra):
        # uninterrupted single-device reference: PR 12 mesh-size
        # invariance (dsplit=row + hist_precision=fixed) makes it the
        # oracle for EVERY size the elastic gang passes through
        ref_model = os.path.join(work, f"ref_{tag}.model")
        rc = cli_main(common(rounds) + extra + [
            f"model_out={ref_model}",
            f"checkpoint_dir={os.path.join(work, f'ck_ref_{tag}')}"])
        if rc != 0:
            raise RuntimeError(f"degrade reference {tag} failed rc={rc}")
        return _state(ref_model)

    def launch(tag, rounds, extra, launch_extra, env_extra,
               watch=None, timeout=420.0):
        """Run one launcher attempt; ``watch(proc, paths)`` is polled
        every 100ms for driver-side chaos (grow signals, SIGKILLs)."""
        out = os.path.join(work, f"{tag}.model")
        obs_log = os.path.join(work, f"obs_{tag}.jsonl")
        gang_dir = os.path.join(work, f"gang_{tag}")
        os.makedirs(gang_dir, exist_ok=True)
        ck = os.path.join(work, f"ck_{tag}")
        worker = [sys.executable, "-m", "xgboost_tpu", *common(rounds),
                  *extra, f"model_out={out}", f"checkpoint_dir={ck}"]
        cmd = [sys.executable, "-m", "xgboost_tpu.launch", "-n", "1",
               "--standalone", "--keepalive",
               "--restart-backoff-sec", "0.2",
               "--gang-dir", gang_dir, *launch_extra, "--", *worker]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XGBTPU_OBS_LOG=obs_log, XGBTPU_OBS_PHASES="0",
                   **env_extra)
        log = open(os.path.join(work, f"{tag}.log"), "ab")
        paths = {"out": out, "obs": obs_log, "gang_dir": gang_dir,
                 "ck": ck, "log": log.name,
                 "state": os.path.join(gang_dir, "coord-state.json")}
        p = subprocess.Popen(cmd, cwd=repo, env=env,
                             stdout=log, stderr=log)
        deadline = time.perf_counter() + timeout
        try:
            while p.poll() is None and time.perf_counter() < deadline:
                if watch is not None:
                    stop = watch(p, paths)
                    if stop:
                        break
                time.sleep(0.1)
            if p.poll() is None and (watch is None
                                     or time.perf_counter() >= deadline):
                p.kill()
        finally:
            p.wait()
            log.close()
        return p.returncode, paths

    results = {}
    ok = True

    # ---- cell 1: permanent host loss mid-run -> immediate degrade to
    # half the devices, then a grow-back once the driver (standing in
    # for a replacement host registering) touches the grow signal
    ref = reference("degrade", args.rounds, mesh)
    state_seen = {"grown": False}

    def grow_when_degraded(p, paths):
        if not state_seen["grown"]:
            try:
                with open(paths["state"], errors="replace") as f:
                    if '"degraded": true' in f.read():
                        open(os.path.join(paths["gang_dir"], "grow"),
                             "w").close()
                        state_seen["grown"] = True
                        print("[chaos-degrade] degraded snapshot seen; "
                              "touched grow signal", file=sys.stderr)
            except OSError:
                pass
        return False

    rc, paths = launch(
        "d1", args.rounds, mesh,
        ["--local-devices", "2", "--degrade-after", "3"],
        {"XGBTPU_FAULTS": "host_loss@t0.r0.v2."},
        watch=grow_when_degraded)
    cell = {"rc": rc,
            "grow_signal_sent": state_seen["grown"],
            "host_loss_events": _scan_obs_events(paths["obs"],
                                                 "gang.host_loss"),
            "degrades": _scan_obs_events(paths["obs"], "launch.degrade"),
            "growbacks": _scan_obs_events(paths["obs"],
                                          "launch.growback"),
            "bit_identical": (rc == 0
                              and _states_equal(ref,
                                                _state(paths["out"])))}
    cell["pass"] = bool(rc == 0 and cell["bit_identical"]
                        and cell["host_loss_events"] >= 1
                        and cell["degrades"] >= 1
                        and cell["growbacks"] >= 1)
    results["host_loss_growback"] = cell
    ok &= cell["pass"]
    print(f"[chaos-degrade] host_loss_growback: {cell}", file=sys.stderr)

    # ---- cell 2: coordinator SIGKILL mid-restart; the replacement
    # launcher on the same --state-path re-adopts the live gang
    ref2 = reference("adopt", args.rounds, [])
    killed = {"at": None}

    def kill_mid_restart(p, paths):
        # wait for the worker-death restart (the mock die fires at v3),
        # give trial 1 a second to be live mid-compile, then SIGKILL
        # the coordinator — the gang must survive it
        if killed["at"] is None and \
                _scan_obs_events(paths["obs"], "launch.restart") >= 1:
            killed["at"] = time.perf_counter() + 1.0
        if killed["at"] is not None \
                and time.perf_counter() >= killed["at"]:
            p.send_signal(signal.SIGKILL)
            print("[chaos-degrade] SIGKILLed coordinator mid-restart",
                  file=sys.stderr)
            return True
        return False

    state_path = os.path.join(work, "d2-coord-state.json")
    rc, paths = launch("d2", args.rounds, ["mock=die:3,0,0"],
                       ["--state-path", state_path], {},
                       watch=kill_mid_restart)
    orphans = []
    try:
        with open(state_path, errors="replace") as f:
            orphans = [int(m) for m in
                       re.findall(r'"pid": (\d+)', f.read())]
    except OSError:
        pass
    # the replacement coordinator: same state path, same command
    rc2, paths2 = launch("d2", args.rounds, ["mock=die:3,0,0"],
                         ["--state-path", state_path], {})
    time.sleep(1.0)  # adopted workers exit right after their done mark
    leaked = [pid for pid in orphans
              if os.path.exists(f"/proc/{pid}")]
    cell = {"coordinator_sigkilled": rc != 0 or killed["at"] is not None,
            "worker_pids_at_kill": orphans, "relaunch_rc": rc2,
            "adoptions": _scan_obs_events(paths2["obs"], "launch.adopt"),
            "leaked_pids": leaked,
            "bit_identical": (rc2 == 0
                              and _states_equal(ref2,
                                                _state(paths2["out"])))}
    cell["pass"] = bool(rc2 == 0 and cell["bit_identical"]
                        and cell["adoptions"] >= 1
                        and cell["coordinator_sigkilled"]
                        and not leaked)
    results["coord_sigkill_adopt"] = cell
    ok &= cell["pass"]
    print(f"[chaos-degrade] coord_sigkill_adopt: {cell}", file=sys.stderr)

    # ---- cell 3: partition window straddling the ring writes -> the
    # worker self-fences, the gang restarts and resumes from the ring;
    # a watcher samples every ring member throughout for the
    # split-brain assertion (CRC + one-lineage-per-slot)
    fence_rounds = 400  # ~8ms/segment: the window must outlast beacons
    ref3 = reference("fence", fence_rounds, [])
    lineage = {}
    crc_failures = []
    stop_watch = threading.Event()
    ck3 = os.path.join(work, "ck_d3")

    def ring_watcher():
        while not stop_watch.is_set():
            try:
                names = [n for n in os.listdir(ck3)
                         if re.fullmatch(r"ckpt-\d{6}\.model", n)]
            except OSError:
                names = []
            for n in names:
                try:
                    payload = verify_model_bytes(
                        read_file(os.path.join(ck3, n)), name=n)
                except OSError:
                    continue  # rotated away mid-read: not an observation
                except ValueError:
                    crc_failures.append(n)
                    continue
                lineage.setdefault(n, set()).add(
                    hashlib.sha256(payload).hexdigest())
            time.sleep(0.01)

    wt = threading.Thread(target=ring_watcher)
    wt.start()
    try:
        rc, paths = launch(
            "d3", fence_rounds, [],
            ["--gang-partition-sec", "0.5"],
            {"XGBTPU_FAULTS": "partition=20.0@t0.r0.v6."})
    finally:
        stop_watch.set()
        wt.join(10.0)
    cell = {"rc": rc,
            "fences": _scan_obs_events(paths["obs"], "gang.fence"),
            "partition_windows": _scan_obs_events(paths["obs"],
                                                  "gang.partition"),
            "restarts": _scan_obs_events(paths["obs"], "launch.restart"),
            "ring_slots_observed": len(lineage),
            "ring_crc_failures": sorted(set(crc_failures)),
            "ring_lineage_violations":
                _ckpt_lineage_violations(lineage),
            "bit_identical": (rc == 0
                              and _states_equal(ref3,
                                                _state(paths["out"])))}
    cell["pass"] = bool(rc == 0 and cell["bit_identical"]
                        and cell["fences"] >= 1
                        and cell["restarts"] >= 1
                        and cell["ring_slots_observed"] >= 2
                        and not cell["ring_crc_failures"]
                        and not cell["ring_lineage_violations"])
    results["partition_fence"] = cell
    ok &= cell["pass"]
    print(f"[chaos-degrade] partition_fence: {cell}", file=sys.stderr)

    report["degrade"] = results
    report["degrade_pass"] = bool(ok)
    return bool(ok)


def selftest() -> int:
    """Fast, subprocess-free logic checks for the elastic-gang pieces
    (wired as a tier-1 test; the heavyweight cells above are the real
    chaos proof).  Prints ``selftest: OK`` on success."""
    from xgboost_tpu.parallel.gang import PartitionClock
    from xgboost_tpu.parallel.launch import (_read_state, _write_state,
                                             plan_degrade)
    from xgboost_tpu.reliability import faults

    # -- partition clock: fence past threshold, heal on fresh beacon
    now = [0.0]
    clk = PartitionClock(partition_sec=0.5, monotonic=lambda: now[0])
    assert clk.observe(1.0) == "ok"          # grace starts
    now[0] = 0.1
    assert clk.observe(2.0) == "ok"          # beacon advanced
    clk.open_window(5.0)
    now[0] = 0.3
    assert clk.observe(3.0) == "partitioned"  # read dropped
    now[0] = 0.7
    assert clk.observe(4.0) == "fence"       # stale past 0.5s
    # heal path: window expired, a fresh beacon mtime lands
    now[0] = 6.0
    assert clk.observe(5.0) == "ok"
    # no spurious fence: boundaries every 50ms, beacon only every 200ms
    clk2 = PartitionClock(partition_sec=0.5, monotonic=lambda: now[0])
    mtime = 0.0
    for i in range(40):
        now[0] = 10.0 + i * 0.05
        if i % 4 == 0:
            mtime += 1.0
        assert clk2.observe(mtime) == "ok", f"spurious fence at {i}"
    # fencing disabled: stale forever still never fences
    clk3 = PartitionClock(partition_sec=0.0, monotonic=lambda: now[0])
    clk3.observe(1.0)
    now[0] += 1000.0
    assert clk3.observe(1.0) == "ok"

    # -- degrade ladder: devices halve first, then workers shed, and
    # min_workers floors the ladder
    assert plan_degrade(4, 4) == (4, 2)
    assert plan_degrade(4, 2) == (4, 1)
    assert plan_degrade(4, 1) == (3, 1)
    assert plan_degrade(2, None) == (1, None)
    assert plan_degrade(1, None) is None
    assert plan_degrade(2, None, min_workers=2) is None

    # -- coordinator-state snapshot: roundtrip + corrupt rejection
    with tempfile.TemporaryDirectory() as d:
        sp = os.path.join(d, "state.json")
        st = {"full_n": 2, "cur_n": 1, "degraded": True, "trial": 3,
              "workers": [{"rank": 0, "pid": 123}]}
        _write_state(sp, st, "pid42")
        got = _read_state(sp)
        assert got is not None and got["holder"] == "pid42"
        assert got["cur_n"] == 1 and got["degraded"] is True
        with open(sp, "r+b") as f:   # flip a byte: CRC must reject it
            f.seek(5)
            b = f.read(1)
            f.seek(5)
            f.write(bytes([b[0] ^ 0xFF]))
        assert _read_state(sp) is None

    # -- fail-loud fault specs: arm-time typed errors, nothing armed
    for bad in ("bogus_kind@ckpt", "torn_write=abc@ckpt",
                "torn_write=128@ckpt*0", "bit_flip@ckpt*zz", "=3@x"):
        try:
            faults.install_spec(bad)
        except faults.FaultSpecError:
            pass
        else:
            raise AssertionError(f"spec {bad!r} did not fail loud")
        finally:
            faults.clear_faults()
    # a trailing typo arms NOTHING (two-phase parse)
    try:
        faults.install_spec("torn_write=128@ckpt;bogus@x")
    except faults.FaultSpecError:
        pass
    assert not faults.gang_fault("t0.r0.v0.")
    faults.install_spec("host_loss@t0.r0.v2.;partition=3.5@t0.r0.v4.")
    assert faults.gang_fault("t0.r0.v2.") == [("host_loss", None)]
    assert faults.gang_fault("t0.r0.v4.") == [("partition", 3.5)]
    assert not faults.gang_fault("t1.r0.v2.")  # trial-scoped
    faults.clear_faults()

    # -- ring-lineage scanner: one hash per slot is clean, two is a
    # split brain
    clean = {"ckpt-000002.model": {"aa"}, "ckpt-000004.model": {"bb"}}
    split = {"ckpt-000002.model": {"aa", "cc"}}
    assert _ckpt_lineage_violations(clean) == []
    assert _ckpt_lineage_violations(split) == ["ckpt-000002.model"]

    print("selftest: OK")
    return 0


def fleet_mode(args) -> int:
    """Replica-kill chaos against a live local fleet: random SIGKILLs
    mid-traffic + keepalive restarts; asserts zero non-shed request
    failures (the router retry contract).  With ``--slow``, the chaos
    is a ``slow_replica`` wedge instead of kills: one replica stays
    alive and healthy-looking but answers every predict late, and the
    router's latency-aware ejection must route around it — same
    zero-non-shed-failures contract, plus at least one ejection."""
    import threading

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_fleet import FleetLauncher, RetryingPredictClient

    import xgboost_tpu as xgb

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaosfleet_")
    os.makedirs(work, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    X = rng.rand(400, 6).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.4, "silent": 1},
                    xgb.DMatrix(X, label=y), 4)
    model = os.path.join(work, "model.bin")
    bst.save_model(model)

    wedged = args.fleet_replicas - 1  # highest-numbered replica
    replica_faults = None
    if args.slow:
        # arm the wedge in the replica subprocess's env: every predict
        # on r<wedged> sleeps, while lease + /healthz stay green —
        # invisible to the breaker, fatal to the fleet p99
        replica_faults = {wedged: f"slow_replica={args.slow_delay}"
                                  f"@r{wedged}*1000000"}
    fl = FleetLauncher(
        model, replicas=args.fleet_replicas,
        workdir=os.path.join(work, "fleet"),
        serve_args=["serve_min_bucket=8", "serve_max_bucket=32",
                    "serve_max_wait_ms=1.0"],
        # short lease + fast health checks: a killed replica leaves
        # rotation quickly even before its breaker trips
        router_kwargs={"lease_sec": 3.0, "hc_sec": 0.5},
        replica_faults=replica_faults,
        quiet=True)
    fl.start()
    try:
        print(f"[chaos-fleet] waiting for {args.fleet_replicas} "
              "replicas...", file=sys.stderr)
        fl.wait_ready()
    except BaseException:
        # a failed bring-up must not orphan the router thread + N
        # replica subprocesses
        fl.stop()
        raise

    body = ",".join(f"{v:.6f}" for v in X[0]).encode()
    counts = {"ok": 0, "shed": 0, "fail": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        # retry-once keep-alive client (launch_fleet): a second
        # transport failure counts as a REAL failure — the router is
        # up throughout, only replicas get killed
        conn = RetryingPredictClient(fl.url)
        mine = {"ok": 0, "shed": 0, "fail": 0}
        while not stop.is_set():
            status, _detail = conn.post(body)
            if status == 200:
                mine["ok"] += 1
            elif status == 503:
                mine["shed"] += 1
            else:
                mine["fail"] += 1
        conn.close()
        with lock:
            for k in counts:
                counts[k] += mine[k]

    clients = [threading.Thread(target=client) for _ in range(4)]
    for t in clients:
        t.start()

    kills = 0
    t_end = time.perf_counter() + args.fleet_secs
    next_kill = time.perf_counter() + args.kill_every
    try:
        while time.perf_counter() < t_end:
            time.sleep(0.25)
            fl.reap_and_restart()  # keepalive
            if args.slow:
                continue  # the wedge IS the chaos; no kills
            if time.perf_counter() >= next_kill:
                # victims come from the IN-ROTATION set (the router's
                # view — an alive-but-still-warming restart is not a
                # serving replica), and only while at least two are in
                # rotation: the contract under test is "replica deaths
                # cost nothing" — killing the LAST serving replica
                # (restarts take seconds) is a whole-fleet outage,
                # where 5xx is the only honest answer
                try:
                    rotation = [m["replica_id"]
                                for m in fl.members()["replicas"]
                                if m["in_rotation"]]
                except OSError:
                    rotation = []
                if len(rotation) >= 2:
                    victim = int(
                        rotation[rng.randint(len(rotation))][1:])
                    if fl.kill_replica(victim) is not None:
                        kills += 1
                        print(f"[chaos-fleet] killed replica r{victim}",
                              file=sys.stderr)
                next_kill = time.perf_counter() + args.kill_every
    finally:
        stop.set()
        for t in clients:
            t.join(30.0)
        restarts = fl.restarts
        ejections = 0.0
        wedged_desc = {}
        if args.slow:
            # the ejection evidence, read from the router's own state
            # + metrics before teardown
            try:
                import urllib.request

                import xgboost_tpu.fleet as fleet_pkg
                mtext = urllib.request.urlopen(
                    fl.url + "/metrics", timeout=5).read().decode()
                ejections = fleet_pkg.scrape_samples(mtext).get(
                    "xgbtpu_fleet_slow_ejections_total", 0.0)
                wedged_desc = [m for m in fl.members()["replicas"]
                               if m["replica_id"] == f"r{wedged}"][0]
            except (OSError, ValueError, IndexError) as e:
                print(f"[chaos-fleet] metric scrape failed: {e}",
                      file=sys.stderr)
        fl.stop()

    report = {"mode": "fleet_slow" if args.slow else "fleet",
              "replicas": args.fleet_replicas,
              "duration_sec": args.fleet_secs, "kills": kills,
              "keepalive_restarts": restarts, **counts,
              "non_shed_failures": counts["fail"]}
    if args.slow:
        report.update({
            "wedged_replica": f"r{wedged}",
            "slow_delay_sec": args.slow_delay,
            "slow_ejections": ejections,
            "wedged_final": {k: wedged_desc.get(k)
                             for k in ("ejected", "latency_ewma_ms",
                                       "breaker", "in_rotation")},
        })
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    if args.slow:
        print(f"[chaos-fleet] SLOW mode: {counts['ok']} ok, "
              f"{counts['shed']} shed, {counts['fail']} FAILED; "
              f"{ejections:.0f} ejection(s), wedged final "
              f"{report['wedged_final']} -> {args.out}", file=sys.stderr)
        if counts["fail"] or ejections < 1 or not counts["ok"]:
            return 1
        return 0
    print(f"[chaos-fleet] {counts['ok']} ok, {counts['shed']} shed, "
          f"{counts['fail']} FAILED across {kills} kills / "
          f"{restarts} restarts -> {args.out}", file=sys.stderr)
    if counts["fail"] or kills == 0 or not counts["ok"]:
        return 1
    return 0


def pipeline_mode(args) -> int:
    """Continuous-training chaos: SIGKILL/corrupt the train→gate→
    publish→reload boundary under live fleet traffic (see module
    docstring).  Contract: zero unverified or ungated models ever
    observed by a serving replica."""
    import hashlib
    import subprocess
    import threading
    import urllib.request

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_fleet import FleetLauncher, RetryingPredictClient

    import xgboost_tpu as xgb

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaospipe_")
    os.makedirs(work, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    cycles = args.pipe_cycles

    # fresh data per cycle + the fixed holdout window
    holdout = os.path.join(work, "holdout.libsvm")
    _write_libsvm(holdout, n=400, f=6, seed=999)
    for c in range(cycles):
        _write_libsvm(os.path.join(work, f"fresh-{c}.libsvm"),
                      n=400, f=6, seed=100 + c)

    # seed incumbent, published before the fleet boots
    publish = os.path.join(work, "published.model")
    X0 = np.random.RandomState(7).rand(400, 6).astype(np.float32)
    y0 = (X0[:, 0] > 0.5).astype(np.float32)
    xgb.train({"objective": "binary:logistic", "max_depth": 3,
               "eta": 0.4, "silent": 1},
              xgb.DMatrix(X0, label=y0), 3).save_model(publish)
    with open(publish, "rb") as f:
        initial_hash = hashlib.sha256(f.read()).hexdigest()
    wd = os.path.join(work, "wd")

    fl = FleetLauncher(
        publish, replicas=args.fleet_replicas, shared_model=True,
        workdir=os.path.join(work, "fleet"),
        serve_args=["serve_min_bucket=8", "serve_max_bucket=32",
                    "serve_max_wait_ms=1.0", "serve_poll_sec=0.25"],
        router_kwargs={"lease_sec": 3.0, "hc_sec": 0.5}, quiet=True)
    fl.start()
    try:
        print(f"[chaos-pipe] waiting for {args.fleet_replicas} "
              "replicas...", file=sys.stderr)
        fl.wait_ready()
        replica_urls = [m["url"] for m in fl.members()["replicas"]]
    except BaseException:
        fl.stop()
        raise

    observed = set()
    counts = {"ok": 0, "shed": 0, "fail": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def watcher():
        # the contract's witness: what hash is each replica SERVING,
        # sampled continuously across every reload boundary
        while not stop.is_set():
            for u in replica_urls:
                try:
                    with urllib.request.urlopen(u + "/healthz",
                                                timeout=2) as r:
                        h = json.load(r).get("model_hash")
                except (OSError, ValueError):
                    continue
                if h:
                    with lock:
                        observed.add(h)
            time.sleep(0.05)

    body = ",".join(f"{v:.6f}" for v in X0[0]).encode()

    def client():
        conn = RetryingPredictClient(fl.url)
        mine = {"ok": 0, "shed": 0, "fail": 0}
        while not stop.is_set():
            status, _ = conn.post(body)
            key = ("ok" if status == 200
                   else "shed" if status == 503 else "fail")
            mine[key] += 1
        conn.close()
        with lock:
            for k in counts:
                counts[k] += mine[k]

    threads = [threading.Thread(target=watcher)] + [
        threading.Thread(target=client) for _ in range(2)]
    for t in threads:
        t.start()

    def cursor() -> int:
        try:
            with open(os.path.join(wd, "state.json")) as f:
                return int(json.load(f).get("cycle", 0))
        except (OSError, ValueError):
            return 0

    # the chaos menu: faults armed (via env) on a random subset of the
    # train→gate→publish boundary's write/read seams
    fault_menu = [None, None,  # half the attempts run fault-free
                  "bit_flip=256@candidate.model",
                  "torn_write=128@candidate.model",
                  "bit_flip=300@published.model",
                  "torn_write=200@ckpt-",
                  "read_flip=64@published.model"]
    pipe_cmd_base = [
        sys.executable, "-m", "xgboost_tpu", "task=pipeline",
        f"pipeline_publish_path={publish}", f"pipeline_dir={wd}",
        f"pipeline_data={os.path.join(work, 'fresh-{cycle}.libsvm')}",
        f"pipeline_holdout={holdout}", "pipeline_rounds_per_cycle=3",
        "pipeline_max_regression=0.2", "objective=binary:logistic",
        "max_depth=3", "eta=0.4", "silent=1"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    kills = faults_armed = attempts = 0
    log = open(os.path.join(work, "pipeline.log"), "ab")
    try:
        while cursor() < cycles and attempts < cycles * 5:
            attempts += 1
            remaining = cycles - cursor()
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            fault = fault_menu[rng.randint(len(fault_menu))]
            if fault:
                env["XGBTPU_FAULTS"] = fault
                faults_armed += 1
            p = subprocess.Popen(
                pipe_cmd_base + [f"pipeline_cycles={remaining}"],
                stdout=log, stderr=log, cwd=repo, env=env)
            # SIGKILL at a random moment inside the attempt — startup,
            # mid-train, mid-gate, mid-publish, mid-reload all get hit
            # across runs
            deadline = time.perf_counter() + float(rng.uniform(4.0, 25.0))
            while time.perf_counter() < deadline and p.poll() is None:
                time.sleep(0.25)
            if p.poll() is None:
                p.kill()
                p.wait()
                kills += 1
                print(f"[chaos-pipe] SIGKILL attempt {attempts} "
                      f"(fault={fault}, cursor={cursor()})",
                      file=sys.stderr)
            else:
                print(f"[chaos-pipe] attempt {attempts} exited "
                      f"rc={p.returncode} (fault={fault}, "
                      f"cursor={cursor()})", file=sys.stderr)
        # let the pollers observe the final publish before teardown
        time.sleep(1.5)
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        fl.stop()
        log.close()

    gated = set()
    try:
        with open(os.path.join(wd, "gated.log")) as f:
            # a SIGKILL can tear the final ledger line (the append-only
            # contract); a one-token tail is expected, not a crash
            gated = {parts[1] for parts in
                     (line.split() for line in f) if len(parts) >= 2}
    except OSError:
        pass
    allowed = gated | {initial_hash}
    violations = sorted(observed - allowed)
    report = {
        "mode": "pipeline", "cycles": cycles,
        "cycles_completed": cursor(), "attempts": attempts,
        "kills": kills, "faults_armed": faults_armed,
        "replicas": args.fleet_replicas,
        "gated_hashes": len(gated),
        "observed_hashes": len(observed),
        "published_observed": len(observed & gated),
        "ungated_or_unverified_observed": len(violations),
        "violations": violations, **counts,
        "non_shed_failures": counts["fail"],
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[chaos-pipe] {report['cycles_completed']}/{cycles} cycles, "
          f"{kills} kills, {faults_armed} faults, "
          f"{len(observed)} hashes observed "
          f"({len(violations)} VIOLATIONS), {counts['ok']} ok / "
          f"{counts['fail']} failed requests -> {args.out}",
          file=sys.stderr)
    ok = (not violations and counts["fail"] == 0
          and report["cycles_completed"] >= cycles
          and report["published_observed"] >= 1 and kills >= 1)
    return 0 if ok else 1


def stream_mode(args) -> int:
    """Streaming chaos: SIGKILL ``task=stream`` trainers mid-micro-
    cycle while a producer keeps the spool moving and the feature
    distribution shifts mid-run (see module docstring).  SIGKILL-only
    — the stream contract under test is replay determinism.
    Contracts: zero ungated publish-path hashes, and a fresh-workdir
    replay over the same spool is bit-identical."""
    import hashlib
    import subprocess
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import xgboost_tpu as xgb
    from xgboost_tpu.stream import StreamBacklogFull, StreamDataSource

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaosstream_")
    os.makedirs(work, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    cycles = args.stream_cycles
    stream_dir = os.path.join(work, "stream-in")
    # bit-identity across the chaos run and the fresh-workdir replay
    # requires IDENTICAL command strings: the CLI cascades every param
    # into the learner (reference xgboost_main.cpp behavior) and the
    # model header serializes the param dict, so a differing
    # stream_workdir= path would differ the published bytes.  Each run
    # therefore gets its own cwd holding relative wd/ + published.model
    # and a symlink to the one shared spool.
    run_chaos = os.path.join(work, "run-chaos")
    run_replay = os.path.join(work, "run-replay")
    os.makedirs(stream_dir, exist_ok=True)
    for d in (run_chaos, run_replay):
        os.makedirs(d, exist_ok=True)
        link = os.path.join(d, "stream-in")
        if not os.path.lexists(link):
            os.symlink(os.path.join("..", "stream-in"), link)
    wd = os.path.join(run_chaos, "wd")
    publish = os.path.join(run_chaos, "published.model")

    # seed incumbent at the publish path — the warm-start lineage the
    # replay later reproduces from the same bytes
    X0 = np.random.RandomState(7).rand(400, 6).astype(np.float32)
    y0 = (X0[:, 0] + 0.25 * X0[:, 1] > 0.6).astype(np.float32)
    xgb.train({"objective": "binary:logistic", "max_depth": 3,
               "eta": 0.4, "silent": 1},
              xgb.DMatrix(X0, label=y0), 3).save_model(publish)
    with open(publish, "rb") as f:
        seed_bytes = f.read()
    initial_hash = hashlib.sha256(seed_bytes).hexdigest()

    stop = threading.Event()
    pushed = [0]

    def producer():
        # batch CONTENT is deterministic (seeded by the producer's own
        # counter); batch→cycle composition is timing-dependent, which
        # is the point — the manifests pin it for replay.  The
        # distribution shifts a third of the way in so drift fires and
        # a cut refresh lands under chaos.
        src = StreamDataSource(stream_dir)
        i = 0
        while not stop.is_set() and i < 400:
            r = np.random.RandomState(1000 + i)
            shift = 0.35 if i >= 6 else 0.0
            X = (r.rand(160, 6) + shift).astype(np.float32)
            y = (X[:, 0] + 0.25 * X[:, 1]
                 > 0.6 + 1.25 * shift).astype(np.float32)
            try:
                src.push(X, y)
            except StreamBacklogFull:
                time.sleep(0.5)
                continue
            i += 1
            pushed[0] = i
            time.sleep(0.15)

    observed = set()

    def watcher():
        # the contract's witness: every complete byte-state the publish
        # path ever holds (atomic_write => never a torn file)
        while not stop.is_set():
            try:
                with open(publish, "rb") as f:
                    observed.add(hashlib.sha256(f.read()).hexdigest())
            except OSError:
                pass
            time.sleep(0.05)

    threads = [threading.Thread(target=producer),
               threading.Thread(target=watcher)]
    for t in threads:
        t.start()

    def cursor(d=None) -> int:
        try:
            with open(os.path.join(d or wd, "state.json")) as f:
                return int(json.load(f).get("cycle", 0))
        except (OSError, ValueError):
            return 0

    def cmd():
        # relative paths, and the SAME string every attempt (chaos and
        # replay): the CLI cascades every param into the learner and
        # the model header records the param dict, so a per-attempt
        # stream_cycles=remaining would make otherwise-identical
        # models hash differently.  The driver, not the arg, decides
        # when a run is done — it SIGKILLs the trainer once the cycle
        # cursor reaches the target.
        return [
            sys.executable, "-m", "xgboost_tpu", "task=stream",
            "stream_publish_path=published.model", "stream_workdir=wd",
            "stream_dir=stream-in", f"stream_cycles={cycles}",
            "stream_rounds_per_cycle=3", "stream_min_batches=1",
            "stream_max_batches=2", "stream_max_regression=0.5",
            "stream_sleep_sec=0.1", "objective=binary:logistic",
            "max_depth=3", "eta=0.4", "ema_fs=0.9", "silent=1"]

    def ledger(workdir):
        """(all gated hashes, cycle -> LAST gated hash).  A killed-
        then-resumed cycle re-gates, so the raw ledger may hold
        several lines per cycle; the last one is the publish."""
        all_hashes, last = set(), {}
        try:
            with open(os.path.join(workdir, "gated.log")) as f:
                # a SIGKILL can tear the final line; skip short tails
                for parts in (line.split() for line in f):
                    if len(parts) >= 2:
                        try:
                            last[int(parts[0])] = parts[1]
                        except ValueError:
                            continue
                        all_hashes.add(parts[1])
        except OSError:
            pass
        return all_hashes, last

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    kills = attempts = 0
    log = open(os.path.join(work, "stream.log"), "ab")
    target = cycles  # extended until the kill quota is met
    try:
        while (cursor() < target or kills < 3) and attempts < 30:
            if cursor() >= target:
                target += 2
                print(f"[chaos-stream] kill quota unmet, extending "
                      f"target to {target} cycles", file=sys.stderr)
            attempts += 1
            p = subprocess.Popen(cmd(), stdout=log, stderr=log,
                                 cwd=run_chaos, env=env)
            # short deadlines until the kill quota is met (startup +
            # the first cycle run longer than this, so SIGKILLs land
            # inside live micro-cycle work), generous afterwards
            lo, hi = (5.0, 12.0) if kills < 3 else (8.0, 25.0)
            deadline = time.perf_counter() + float(rng.uniform(lo, hi))
            reached = False
            while time.perf_counter() < deadline and p.poll() is None:
                if cursor() >= target:
                    reached = True
                    break
                time.sleep(0.25)
            if p.poll() is None:
                p.kill()
                p.wait()
                if reached:
                    print(f"[chaos-stream] attempt {attempts} reached "
                          f"target {target}, stopped", file=sys.stderr)
                else:
                    kills += 1
                    print(f"[chaos-stream] SIGKILL attempt {attempts} "
                          f"(cursor={cursor()}, pushed={pushed[0]})",
                          file=sys.stderr)
            else:
                print(f"[chaos-stream] attempt {attempts} exited "
                      f"rc={p.returncode} (cursor={cursor()})",
                      file=sys.stderr)
        time.sleep(0.5)  # let the watcher observe the final publish
        stop.set()
        for t in threads:
            t.join(30.0)
        completed = cursor()
        gated, chaos_last = ledger(wd)

        # bit-identical replay: a FRESH run dir + publish path seeded
        # with the same incumbent bytes, consuming the SAME spool with
        # the IDENTICAL command string
        wd2 = os.path.join(run_replay, "wd")
        pub2 = os.path.join(run_replay, "published.model")
        with open(pub2, "wb") as f:
            f.write(seed_bytes)
        replay_rc = None
        if completed > 0:
            print(f"[chaos-stream] replaying {completed} cycles in a "
                  "fresh workdir...", file=sys.stderr)
            guard = 0
            while cursor(wd2) < completed and guard < 10:
                guard += 1
                p = subprocess.Popen(cmd(), stdout=log, stderr=log,
                                     cwd=run_replay, env=env)
                t0 = time.perf_counter()
                while (p.poll() is None
                       and time.perf_counter() - t0 < 300.0):
                    if cursor(wd2) >= completed:
                        break
                    time.sleep(0.25)
                if p.poll() is None:
                    p.kill()
                p.wait()
                replay_rc = p.returncode
    finally:
        stop.set()
        for t in threads:
            t.join(30.0)
        log.close()

    # per-cycle published-candidate hashes, both runs restricted to the
    # cycles the chaos run completed (either side may have started one
    # cycle past its stop point — that tail is not part of the
    # contract)
    _, replay_last = ledger(wd2)
    chaos_map = {c: h for c, h in chaos_last.items() if c < completed}
    replay_map = {c: h for c, h in replay_last.items() if c < completed}
    seq_identical = bool(chaos_map) and replay_map == chaos_map
    last_cycle = max(chaos_map) if chaos_map else None
    final_identical = (last_cycle is not None
                       and replay_map.get(last_cycle)
                       == chaos_map[last_cycle])

    drift_fires = refreshes = 0
    plans_dir = os.path.join(wd, "plans")
    if os.path.isdir(plans_dir):
        for fn in sorted(os.listdir(plans_dir)):
            if fn.startswith("plan-") and fn.endswith(".json"):
                try:
                    with open(os.path.join(plans_dir, fn)) as f:
                        plan = json.load(f)
                except (OSError, ValueError):
                    continue
                drift_fires += bool(plan.get("fired"))
                refreshes += bool(plan.get("refresh"))

    allowed = gated | {initial_hash}
    violations = sorted(observed - allowed)
    report = {
        "mode": "stream", "cycles": cycles,
        "cycles_target_final": target,
        "cycles_completed": completed, "attempts": attempts,
        "kills": kills, "batches_pushed": pushed[0],
        "gated_hashes": len(gated),
        "observed_hashes": len(observed),
        "published_observed": len(observed & gated),
        "ungated_or_unverified_observed": len(violations),
        "violations": violations,
        "drift_fires": drift_fires, "cut_refreshes": refreshes,
        "replay_rc": replay_rc,
        "replay_cycles": cursor(wd2),
        "replay_gated_sequence_identical": seq_identical,
        "replay_final_bytes_identical": final_identical,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[chaos-stream] {completed}/{cycles} cycles, {kills} kills, "
          f"{len(observed)} hashes observed "
          f"({len(violations)} VIOLATIONS), {drift_fires} drift fires / "
          f"{refreshes} cut refreshes, replay identical="
          f"{seq_identical and final_identical} -> {args.out}",
          file=sys.stderr)
    ok = (not violations and completed >= cycles and kills >= 3
          and seq_identical and final_identical
          and report["published_observed"] >= 1)
    return 0 if ok else 1


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def catalog_mode(args) -> int:
    """Multi-tenant catalog chaos: two width-divergent tenants share a
    catalog fleet while per-tenant training lanes publish, a killer
    SIGKILLs lane trainers at random AND the router itself (which must
    restart from its membership snapshot with zero non-shed client
    failures).  Contract: the zero-ungated-models invariant holds PER
    TENANT, and killing one tenant's trainer never stalls the other."""
    import hashlib
    import subprocess
    import threading
    import urllib.error
    import urllib.request

    import xgboost_tpu as xgb

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaoscat_")
    os.makedirs(work, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    cycles = args.pipe_cycles
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    # width-DIVERGENT tenants: different feature counts force different
    # compiled buckets, so cross-tenant bleed would be loud
    tenants = {"a": (6, 7), "b": (4, 21)}  # name -> (features, seed)
    pub, wd, init_hash, body = {}, {}, {}, {}
    for t, (nf, seed) in tenants.items():
        _write_libsvm(os.path.join(work, f"holdout-{t}.libsvm"),
                      n=400, f=nf, seed=900 + nf)
        for c in range(cycles):
            _write_libsvm(os.path.join(work, f"fresh-{t}-{c}.libsvm"),
                          n=400, f=nf, seed=seed * 100 + c)
        X0 = np.random.RandomState(seed).rand(400, nf).astype(np.float32)
        y0 = (X0[:, 0] > 0.5).astype(np.float32)
        pub[t] = os.path.join(work, f"published-{t}.model")
        xgb.train({"objective": "binary:logistic", "max_depth": 3,
                   "eta": 0.4, "silent": 1},
                  xgb.DMatrix(X0, label=y0), 3).save_model(pub[t])
        with open(pub[t], "rb") as f:
            init_hash[t] = hashlib.sha256(f.read()).hexdigest()
        wd[t] = os.path.join(work, f"wd-{t}")
        body[t] = ",".join(f"{v:.6f}" for v in X0[0]).encode()

    # the router is a SUBPROCESS here (unlike the other fleet modes):
    # the chaos menu includes SIGKILLing it, and the restart must
    # rebuild membership from the CRC-footered fleet_state_path snapshot
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    state_path = os.path.join(work, "router.state")
    router_cmd = [sys.executable, "-m", "xgboost_tpu",
                  "task=fleet_router", "fleet_host=127.0.0.1",
                  f"fleet_port={port}", "fleet_lease_sec=3.0",
                  "fleet_hc_sec=0.5", f"fleet_state_path={state_path}",
                  "silent=1"]

    def spawn_router():
        log = open(os.path.join(work, "router.log"), "ab")
        p = subprocess.Popen(router_cmd, stdout=log, stderr=log,
                             cwd=repo, env=env)
        log.close()
        return p

    manifest = ",".join(f"{t}={pub[t]}" for t in tenants)
    replicas = {}

    def spawn_replica(i):
        log = open(os.path.join(work, f"replica-{i}.log"), "ab")
        replicas[i] = subprocess.Popen(
            [sys.executable, "-m", "xgboost_tpu", "task=serve",
             f"catalog={manifest}", "serve_port=0",
             "serve_host=127.0.0.1", f"serve_router_url={url}",
             f"serve_replica_id=c{i}", "serve_min_bucket=8",
             "serve_max_bucket=32", "serve_max_wait_ms=1.0",
             "serve_poll_sec=0.25", "silent=1"],
            stdout=log, stderr=log, cwd=repo, env=env)
        log.close()

    def wait_members(n, timeout=180.0):
        deadline = time.perf_counter() + timeout
        got = 0
        while time.perf_counter() < deadline:
            try:
                with urllib.request.urlopen(url + "/fleet/members",
                                            timeout=5) as r:
                    mem = json.load(r)
                got = mem["in_rotation"]
                if got >= n:
                    return mem
            except (OSError, ValueError):
                pass
            time.sleep(0.25)
        raise TimeoutError(f"catalog fleet not ready: {got}/{n} "
                           f"(see {work}/replica-*.log)")

    router = spawn_router()
    n_reps = args.fleet_replicas
    for i in range(n_reps):
        spawn_replica(i)
    try:
        print(f"[chaos-cat] waiting for {n_reps} catalog replicas...",
              file=sys.stderr)
        replica_urls = [m["url"]
                        for m in wait_members(n_reps)["replicas"]]
    except BaseException:
        for p in list(replicas.values()) + [router]:
            p.kill()
        raise

    observed = {t: set() for t in tenants}
    counts = {t: {"ok": 0, "shed": 0, "fail": 0} for t in tenants}
    lock = threading.Lock()
    stop = threading.Event()

    def watcher():
        # per-tenant witness: which hash is each replica serving FOR
        # EACH MODEL, sampled straight off the replicas (router-down
        # windows must not blind the contract)
        while not stop.is_set():
            for u in replica_urls:
                try:
                    with urllib.request.urlopen(u + "/healthz",
                                                timeout=2) as r:
                        rows = json.load(r).get("models", {})
                except (OSError, ValueError):
                    continue
                with lock:
                    for t in tenants:
                        h = (rows.get(t) or {}).get("model_hash")
                        if h:
                            observed[t].add(h)
            time.sleep(0.05)

    def post(path, data, patience=60.0):
        # transport failures retry until the patience deadline: a
        # SIGKILL'd router is allowed a restart window, but every
        # request must STILL end in a 200 or an explicit shed
        deadline = time.perf_counter() + patience
        while True:
            req = urllib.request.Request(url + path, data=data)
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
                    return 200
            except urllib.error.HTTPError as e:
                e.read()
                return e.code
            except OSError:
                if time.perf_counter() >= deadline:
                    return None
                time.sleep(0.2)

    def client(t):
        mine = {"ok": 0, "shed": 0, "fail": 0}
        while not stop.is_set():
            status = post(f"/predict?model={t}", body[t])
            mine["ok" if status == 200
                 else "shed" if status in (429, 503, 504)
                 else "fail"] += 1
        with lock:
            for k in mine:
                counts[t][k] += mine[k]

    threads = [threading.Thread(target=watcher)] + [
        threading.Thread(target=client, args=(t,)) for t in tenants]
    for t_ in threads:
        t_.start()

    def cursor(t):
        try:
            with open(os.path.join(wd[t], "state.json")) as f:
                return int(json.load(f).get("cycle", 0))
        except (OSError, ValueError):
            return 0

    def lane_cmd(t, remaining):
        data = os.path.join(work, "fresh-" + t + "-{cycle}.libsvm")
        return [sys.executable, "-m", "xgboost_tpu", "task=pipeline",
                f"pipeline_publish_path={pub[t]}",
                f"pipeline_dir={wd[t]}", f"pipeline_data={data}",
                f"pipeline_holdout={os.path.join(work, f'holdout-{t}.libsvm')}",
                "pipeline_rounds_per_cycle=3",
                "pipeline_max_regression=0.2",
                f"pipeline_cycles={remaining}",
                "objective=binary:logistic", "max_depth=3", "eta=0.4",
                "silent=1"]

    fault_menu = [None, None, None,
                  "bit_flip=256@candidate.model",
                  "torn_write=128@candidate.model",
                  "read_flip=64@published-"]
    lanes = {}
    lane_logs = {t: open(os.path.join(work, f"pipeline-{t}.log"), "ab")
                 for t in tenants}
    kills = router_kills = attempts = faults_armed = 0
    router_restart_sec = None
    max_attempts = 8 + cycles * 6
    try:
        while (attempts < max_attempts
               and any(cursor(t) < cycles for t in tenants)):
            for t in tenants:
                p = lanes.get(t)
                if cursor(t) >= cycles or (p is not None
                                           and p.poll() is None):
                    continue
                attempts += 1
                lenv = dict(env)
                fault = fault_menu[rng.randint(len(fault_menu))]
                if fault:
                    lenv["XGBTPU_FAULTS"] = fault
                    faults_armed += 1
                lanes[t] = subprocess.Popen(
                    lane_cmd(t, cycles - cursor(t)),
                    stdout=lane_logs[t], stderr=lane_logs[t],
                    cwd=repo, env=lenv)
                print(f"[chaos-cat] lane {t} attempt (fault={fault}, "
                      f"cursor={cursor(t)})", file=sys.stderr)
            time.sleep(float(rng.uniform(8.0, 20.0)))
            live = [t for t, p in lanes.items()
                    if p is not None and p.poll() is None]
            if live and (kills == 0 or rng.rand() < 0.7):
                # first opportunity always kills (the lane-kill leg is
                # part of the contract); later windows roll the dice
                t = live[rng.randint(len(live))]
                lanes[t].kill()
                lanes[t].wait()
                kills += 1
                print(f"[chaos-cat] SIGKILL lane {t} "
                      f"(cursor={cursor(t)})", file=sys.stderr)
            if router_kills == 0 and attempts >= 2:
                # the router restart leg: SIGKILL the front door under
                # live traffic; the replacement restores membership
                # from the snapshot and clients ride through on retry
                router.kill()
                router.wait()
                router_kills += 1
                t0 = time.perf_counter()
                router = spawn_router()
                wait_members(n_reps)
                router_restart_sec = round(time.perf_counter() - t0, 2)
                print(f"[chaos-cat] router SIGKILL -> restored "
                      f"{n_reps} members in {router_restart_sec}s",
                      file=sys.stderr)
        # let the replica pollers observe the final publishes
        time.sleep(1.5)
    finally:
        stop.set()
        for t_ in threads:
            t_.join(90.0)
        for p in list(lanes.values()) + list(replicas.values()):
            if p.poll() is None:
                p.terminate()
        if router.poll() is None:
            router.terminate()
        for p in list(lanes.values()) + list(replicas.values()) + [router]:
            try:
                p.wait(20.0)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in lane_logs.values():
            f.close()

    per_tenant = {}
    total_fail = 0
    for t in tenants:
        gated = set()
        try:
            with open(os.path.join(wd[t], "gated.log")) as f:
                gated = {parts[1] for parts in
                         (line.split() for line in f) if len(parts) >= 2}
        except OSError:
            pass
        violations = sorted(observed[t] - (gated | {init_hash[t]}))
        total_fail += counts[t]["fail"]
        per_tenant[t] = {
            "cycles_completed": cursor(t),
            "gated_hashes": len(gated),
            "observed_hashes": len(observed[t]),
            "published_observed": len(observed[t] & gated),
            "ungated_observed": len(violations),
            "violations": violations, **counts[t]}
    report = {
        "mode": "catalog", "cycles": cycles,
        "replicas": n_reps, "attempts": attempts, "kills": kills,
        "router_kills": router_kills,
        "router_restart_sec": router_restart_sec,
        "faults_armed": faults_armed,
        "tenants": per_tenant, "non_shed_failures": total_fail}
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    done = all(per_tenant[t]["cycles_completed"] >= cycles
               for t in tenants)
    clean = all(not per_tenant[t]["violations"]
                and per_tenant[t]["ok"] > 0
                and per_tenant[t]["published_observed"] >= 1
                for t in tenants)
    print(f"[chaos-cat] cycles "
          + "/".join(f"{t}:{per_tenant[t]['cycles_completed']}"
                     for t in tenants)
          + f", {kills} lane kills, {router_kills} router kills, "
          f"{total_fail} non-shed failures -> {args.out}",
          file=sys.stderr)
    ok = (done and clean and total_fail == 0
          and kills >= 1 and router_kills >= 1)
    return 0 if ok else 1


def placer_mode(args) -> int:
    """Autonomous-placement chaos (see module docstring, ``--placer``):
    SIGKILL replicas mid-rebalance AND the placer mid-push; assert zero
    non-shed failures, no tenant ever orphaned, and that a resumed
    placer converges to the target it snapshotted."""
    import hashlib
    import subprocess
    import threading
    import urllib.error
    import urllib.request

    import xgboost_tpu as xgb
    from xgboost_tpu.reliability.integrity import verify_model_bytes

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaosplc_")
    os.makedirs(work, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    # one model file, four tenant names: placement chaos is about WHERE
    # entries live, not what they predict
    model = os.path.join(work, "model.bin")
    X0 = np.random.RandomState(7).rand(300, 6).astype(np.float32)
    y0 = (X0[:, 0] + X0[:, 1] > 1.0).astype(np.float32)
    xgb.train({"objective": "binary:logistic", "max_depth": 3,
               "eta": 0.4, "silent": 1},
              xgb.DMatrix(X0, label=y0), 3).save_model(model)
    tenants = [f"t{i}" for i in range(1, 5)]
    manifest = ",".join(f"{t}={model}" for t in tenants)
    body = ",".join(f"{v:.6f}" for v in X0[0]).encode()

    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    state_path = os.path.join(work, "router.state")
    plan_path = os.path.join(work, "placer.plan")

    rlog = open(os.path.join(work, "router.log"), "ab")
    router = subprocess.Popen(
        [sys.executable, "-m", "xgboost_tpu", "task=fleet_router",
         "fleet_host=127.0.0.1", f"fleet_port={port}",
         "fleet_lease_sec=3.0", "fleet_hc_sec=0.5",
         f"fleet_state_path={state_path}", "silent=1"],
        stdout=rlog, stderr=rlog, cwd=repo, env=env)
    rlog.close()

    n_reps = args.fleet_replicas
    replicas = {}
    next_idx = [0]

    def spawn_replica():
        # a FRESH identity per spawn: a SIGKILL'd replica's lease must
        # EXPIRE (no re-register under the old id), so re-homing is the
        # placer's job, not the tracker recover path's
        i = next_idx[0]
        next_idx[0] += 1
        log = open(os.path.join(work, f"replica-{i}.log"), "ab")
        replicas[i] = subprocess.Popen(
            [sys.executable, "-m", "xgboost_tpu", "task=serve",
             f"model_in={model}", "serve_port=0", "serve_host=127.0.0.1",
             f"serve_router_url={url}", f"serve_replica_id=p{i}",
             "serve_catalog_mb=64", "serve_min_bucket=8",
             "serve_max_bucket=32", "serve_max_wait_ms=1.0",
             "serve_poll_sec=0", "serve_warmup=0", "silent=1"],
            stdout=log, stderr=log, cwd=repo, env=env)
        log.close()
        return i

    placer = [None]

    def spawn_placer():
        log = open(os.path.join(work, "placer.log"), "ab")
        placer[0] = subprocess.Popen(
            [sys.executable, "-m", "xgboost_tpu", "task=placer",
             f"placer_router_url={url}", f"placer_catalog={manifest}",
             f"placer_plan_path={plan_path}", "placer_tick_sec=0.4",
             "placer_lease_sec=3.0", "placer_replication=2",
             "silent=1"],
            stdout=log, stderr=log, cwd=repo, env=env)
        log.close()

    def members(timeout=5.0):
        with urllib.request.urlopen(url + "/fleet/members",
                                    timeout=timeout) as r:
            return json.load(r)

    def hosted_counts(mem):
        out = {t: 0 for t in tenants}
        for d in mem.get("replicas", []):
            if not d.get("in_rotation"):
                continue
            for t in tenants:
                if t in (d.get("models") or []):
                    out[t] += 1
        return out

    def wait_placed(min_hosts, timeout=180.0):
        deadline = time.perf_counter() + timeout
        last = {}
        while time.perf_counter() < deadline:
            try:
                last = hosted_counts(members())
                if all(last.get(t, 0) >= min_hosts for t in tenants):
                    return last
            except (OSError, ValueError):
                pass
            time.sleep(0.25)
        raise TimeoutError(f"placement never converged: {last} "
                           f"(see {work}/placer.log)")

    def read_plan_snapshot():
        with open(plan_path, "rb") as f:
            state = json.loads(verify_model_bytes(f.read(), plan_path))
        return state["target"]

    def router_plan(timeout=5.0):
        with urllib.request.urlopen(url + "/placer/status",
                                    timeout=timeout) as r:
            return json.load(r).get("plan") or {}

    counts = {t: {"ok": 0, "shed": 0, "fail": 0} for t in tenants}
    orphan_windows = []
    lock = threading.Lock()
    stop = threading.Event()
    watch = threading.Event()   # set once initial placement landed

    def orphan_watcher():
        # the availability contract: from first placement on, every
        # sample of the router's view shows >=1 in-rotation advertiser
        # per tenant (router-down windows don't blind the watcher —
        # there is no router kill leg in this mode)
        while not stop.is_set():
            if watch.is_set():
                try:
                    mem = members(timeout=2.0)
                except (OSError, ValueError):
                    time.sleep(0.1)
                    continue
                counts_now = hosted_counts(mem)
                bad = sorted(t for t, n in counts_now.items() if n < 1)
                if bad:
                    with lock:
                        orphan_windows.append(
                            {"t": round(time.perf_counter(), 2),
                             "orphaned": bad})
            time.sleep(0.05)

    def post(path, data, patience=60.0):
        deadline = time.perf_counter() + patience
        while True:
            req = urllib.request.Request(url + path, data=data)
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
                    return 200
            except urllib.error.HTTPError as e:
                e.read()
                return e.code
            except OSError:
                if time.perf_counter() >= deadline:
                    return None
                time.sleep(0.2)

    def client(t):
        mine = {"ok": 0, "shed": 0, "fail": 0}
        while not stop.is_set():
            if not watch.is_set():
                time.sleep(0.1)
                continue
            status = post(f"/predict?model={t}", body)
            mine["ok" if status == 200
                 else "shed" if status in (429, 503, 504)
                 else "fail"] += 1
        with lock:
            for k in mine:
                counts[t][k] += mine[k]

    threads = [threading.Thread(target=orphan_watcher)] + [
        threading.Thread(target=client, args=(t,)) for t in tenants]
    for t_ in threads:
        t_.start()

    replica_kills = placer_kills = 0
    resume_checks = []
    for _ in range(n_reps):
        spawn_replica()
    spawn_placer()
    try:
        print(f"[chaos-placer] waiting for initial placement "
              f"({n_reps} replicas x 4 tenants, replication=2)...",
              file=sys.stderr)
        wait_placed(min_hosts=2)
        watch.set()
        time.sleep(2.0)                      # traffic under steady state

        # ---- leg 1: SIGKILL a replica mid-rebalance, placer re-homes.
        # The restart uses a FRESH replica id, so the placer sees a
        # genuinely changed fleet both times.
        victim = sorted(replicas)[int(rng.randint(len(replicas)))]
        replicas[victim].kill()
        replicas[victim].wait()
        replicas.pop(victim)
        replica_kills += 1
        print(f"[chaos-placer] SIGKILL replica #{victim}",
              file=sys.stderr)
        spawn_replica()                      # keepalive replacement
        # ---- leg 2: SIGKILL the placer MID-PUSH — right inside the
        # re-homing window the replica kill just opened
        time.sleep(float(rng.uniform(0.3, 0.9)))
        placer[0].kill()
        placer[0].wait()
        placer_kills += 1
        print("[chaos-placer] SIGKILL placer mid-push", file=sys.stderr)
        spawn_placer()
        wait_placed(min_hosts=2)             # resumed placer converges
        time.sleep(2.0)

        # ---- leg 3: quiet-window placer kill pins resume determinism:
        # same fleet + snapshotted plan -> the resumed placer must
        # record the SAME target on the router
        before_snapshot = read_plan_snapshot()
        before_plan = router_plan().get("target") or {}
        placer[0].kill()
        placer[0].wait()
        placer_kills += 1
        print("[chaos-placer] SIGKILL placer (quiet window)",
              file=sys.stderr)
        spawn_placer()
        deadline = time.perf_counter() + 60.0
        after_plan = {}
        while time.perf_counter() < deadline:
            try:
                after_plan = router_plan().get("target") or {}
            except (OSError, ValueError):
                after_plan = {}
            if after_plan:
                break
            time.sleep(0.25)
        resume_checks.append({
            "snapshot_equals_recorded": before_snapshot == before_plan,
            "resumed_equals_snapshot": after_plan == before_snapshot})
        wait_placed(min_hosts=2)
        time.sleep(2.0)                      # post-chaos steady traffic
    finally:
        stop.set()
        for t_ in threads:
            t_.join(90.0)
        procs = list(replicas.values()) + [router]
        if placer[0] is not None:
            procs.append(placer[0])
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(20.0)
            except subprocess.TimeoutExpired:
                p.kill()

    total_fail = sum(c["fail"] for c in counts.values())
    total_ok = sum(c["ok"] for c in counts.values())
    resumed_plan_equal = bool(resume_checks) and all(
        rc["resumed_equals_snapshot"] for rc in resume_checks)
    report = {
        "mode": "placer", "replicas": n_reps, "tenants": len(tenants),
        "replication": 2, "replica_kills": replica_kills,
        "placer_kills": placer_kills,
        "per_tenant": counts, "non_shed_failures": total_fail,
        "orphan_windows": orphan_windows[:20],
        "orphan_window_count": len(orphan_windows),
        "resume_checks": resume_checks,
        "resumed_plan_equal": resumed_plan_equal,
        "model_sha256": hashlib.sha256(
            open(model, "rb").read()).hexdigest(),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[chaos-placer] {replica_kills} replica kills, "
          f"{placer_kills} placer kills, {total_ok} ok / "
          f"{total_fail} non-shed failures, "
          f"{len(orphan_windows)} orphan windows, resumed_plan_equal="
          f"{resumed_plan_equal} -> {args.out}", file=sys.stderr)
    ok = (total_fail == 0 and not orphan_windows and total_ok > 0
          and replica_kills >= 1 and placer_kills >= 2
          and resumed_plan_equal
          and all(c["ok"] > 0 for c in counts.values()))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    ap.add_argument("--fleet", action="store_true",
                    help="serving-tier mode: kill/restart replicas "
                         "under live traffic (see module docstring)")
    ap.add_argument("--fleet-replicas", type=int, default=3)
    ap.add_argument("--fleet-secs", type=float, default=20.0,
                    help="--fleet: how long to drive traffic")
    ap.add_argument("--kill-every", type=float, default=4.0,
                    help="--fleet: seconds between replica kills")
    ap.add_argument("--slow", action="store_true",
                    help="--fleet variant: wedge one replica with the "
                         "slow_replica fault instead of killing any; "
                         "asserts latency ejection routes around it "
                         "with zero non-shed failures")
    ap.add_argument("--slow-delay", type=float, default=0.6,
                    help="--slow: seconds each wedged predict sleeps")
    ap.add_argument("--train", action="store_true",
                    help="stall-failure training mode: stall mock "
                         "coordinates + heartbeat-watchdog gang "
                         "restarts, bit-identical resume "
                         "(TRAIN_CHAOS.json; see module docstring)")
    ap.add_argument("--stall-window", type=float, default=4.0,
                    help="--train: launcher --watchdog-stall-sec; must "
                         "cover startup + one fused segment dispatch "
                         "(compile included on the first trial)")
    ap.add_argument("--local-devices", type=int, default=2,
                    help="--train: in-process device count for the "
                         "fused_mesh cell (dsplit=row over an "
                         "N-virtual-CPU-device mesh)")
    ap.add_argument("--degrade", action="store_true",
                    help="--train addition: run the elastic degraded-"
                         "mesh cells (host_loss degrade + grow-back, "
                         "coordinator SIGKILL + re-adoption, partition "
                         "self-fence with the ring split-brain "
                         "assertion); merged into TRAIN_CHAOS.json "
                         "under 'degrade'.  --runs 0 skips the stall "
                         "cells and runs only these.")
    ap.add_argument("--selftest", action="store_true",
                    help="fast subprocess-free logic checks (partition "
                         "clock, degrade ladder, state roundtrip, "
                         "fail-loud fault parsing, lineage scanner); "
                         "prints 'selftest: OK'")
    ap.add_argument("--pipeline", action="store_true",
                    help="continuous-training mode: SIGKILL/corrupt "
                         "the train→gate→publish→reload boundary under "
                         "live fleet traffic (see module docstring)")
    ap.add_argument("--pipe-cycles", type=int, default=4,
                    help="--pipeline/--catalog: cycles each pipeline "
                         "(lane) must complete")
    ap.add_argument("--stream", action="store_true",
                    help="streaming mode: SIGKILL task=stream "
                         "trainers mid-micro-cycle while a producer "
                         "spools drifting batches; zero-ungated + "
                         "bit-identical fresh-workdir replay "
                         "(STREAM_CHAOS.json; see module docstring)")
    ap.add_argument("--stream-cycles", type=int, default=6,
                    help="--stream: micro-cycles the trainer must "
                         "complete")
    ap.add_argument("--catalog", action="store_true",
                    help="multi-tenant catalog mode: two width-"
                         "divergent tenants on a catalog fleet, "
                         "per-tenant training lanes, SIGKILLs of lane "
                         "trainers AND the router (snapshot restart); "
                         "per-tenant zero-ungated contract "
                         "(CATALOG_CHAOS.json; see module docstring)")
    ap.add_argument("--placer", action="store_true",
                    help="autonomous-placement mode: router + default-"
                         "only replicas + task=placer subprocess; "
                         "SIGKILLs replicas mid-rebalance and the "
                         "placer mid-push; zero non-shed failures, no "
                         "tenant ever orphaned, resumed placer "
                         "converges to its snapshotted plan "
                         "(PLACER_CHAOS.json; see module docstring)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.degrade and not args.train:
        ap.error("--degrade composes with --train "
                 "(use --train --degrade, optionally --runs 0)")
    if args.out is None:
        args.out = ("STREAM_CHAOS.json" if args.stream
                    else "PLACER_CHAOS.json" if args.placer
                    else "CATALOG_CHAOS.json" if args.catalog
                    else "PIPELINE_CHAOS.json" if args.pipeline
                    else "CHAOS_fleet_slow.json"
                    if args.fleet and args.slow
                    else "CHAOS_fleet.json" if args.fleet
                    else "TRAIN_CHAOS.json" if args.train
                    else "CHAOS.json")
    if args.stream:
        return stream_mode(args)
    if args.placer:
        return placer_mode(args)
    if args.catalog:
        return catalog_mode(args)
    if args.pipeline:
        return pipeline_mode(args)
    if args.fleet:
        return fleet_mode(args)
    if args.train:
        return train_stall_mode(args)

    from xgboost_tpu.cli import main as cli_main
    from xgboost_tpu.profiling import reliability_metrics
    from xgboost_tpu.reliability import faults

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaos_")
    os.makedirs(work, exist_ok=True)
    data = os.path.join(work, "train.libsvm")
    _write_libsvm(data, seed=args.seed)
    common = [f"data={data}", "task=train", f"num_round={args.rounds}",
              "silent=2", "objective=binary:logistic", "max_depth=3",
              "eta=0.5", "max_bin=16"]

    # the uninterrupted reference (checkpointing ON so the code path is
    # identical up to the injected failures)
    ref_model = os.path.join(work, "ref.model")
    rc = cli_main(common + [f"model_out={ref_model}",
                            f"checkpoint_dir={os.path.join(work, 'ck_ref')}"])
    if rc != 0:
        print(f"reference run failed (rc={rc})", file=sys.stderr)
        return 1
    ref = _state(ref_model)

    rng = np.random.RandomState(args.seed)
    rm = reliability_metrics()
    base = {"ring_fallbacks": rm.ring_fallbacks.value,
            "quarantines": rm.quarantines.value,
            "integrity_failures": rm.integrity_failures.value}
    report = {"runs": args.runs, "recoveries": 0, "bit_identical": 0,
              "mismatches": 0, "deaths": 0, "corruptions_armed": 0,
              "run_log": []}

    for run in range(args.runs):
        ck = os.path.join(work, f"ck_{run:03d}")
        out = os.path.join(work, f"m_{run:03d}.model")
        # 1-2 deaths at random round boundaries (distinct versions so
        # the second coordinate is reachable after the first restart)
        versions = sorted(rng.choice(
            np.arange(1, args.rounds), size=int(rng.randint(1, 3)),
            replace=False))
        mock = ";".join(f"{int(v)},0,{i}" for i, v in enumerate(versions))
        entry = {"run": run, "mock": mock, "fault": None}
        faults.clear_faults()
        if rng.rand() < 0.5:
            # corrupt the ring member the restart will want: the one
            # written just before the (first) death
            kind = "torn_write" if rng.rand() < 0.5 else "bit_flip"
            at = int(rng.randint(16, 1000))
            target = f"ckpt-{int(versions[0]):06d}"
            faults.inject(kind, at, path_sub=target)
            entry["fault"] = f"{kind}={at}@{target}"
            report["corruptions_armed"] += 1
        try:
            rc = cli_main(common + [f"model_out={out}",
                                    f"checkpoint_dir={ck}",
                                    f"mock={mock}", "keepalive=1"])
        except BaseException as e:  # noqa: BLE001 — recorded in the report
            entry["error"] = f"{type(e).__name__}: {e}"
            rc = -1
        finally:
            faults.clear_faults()
        report["deaths"] += len(versions)
        if rc == 0:
            report["recoveries"] += 1
            got = _state(out)
            if _states_equal(ref, got):
                report["bit_identical"] += 1
                entry["result"] = "bit_identical"
            else:
                report["mismatches"] += 1
                entry["result"] = "MISMATCH"
        else:
            report["mismatches"] += 1
            entry["result"] = f"rc={rc}"
        report["run_log"].append(entry)
        print(f"[chaos] run {run}: mock={mock} fault={entry['fault']} "
              f"-> {entry['result']}", file=sys.stderr)

    report["ring_fallbacks"] = rm.ring_fallbacks.value - base["ring_fallbacks"]
    report["quarantines"] = rm.quarantines.value - base["quarantines"]
    report["integrity_failures"] = (rm.integrity_failures.value
                                    - base["integrity_failures"])
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"[chaos] {report['bit_identical']}/{args.runs} bit-identical, "
          f"{report['ring_fallbacks']:.0f} ring fallbacks, "
          f"{report['quarantines']:.0f} quarantines -> {args.out}",
          file=sys.stderr)
    return 0 if report["mismatches"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())

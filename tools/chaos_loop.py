#!/usr/bin/env python
"""Chaos loop: repeated kill-at-random-fault-point train/resume driver.

Each run arms a RANDOM failure combination against the real training
CLI — a worker death at a random (version, seqno) collective coordinate
(``mock=`` / parallel/mock.py) plus, half the time, a torn-write or
bit-flip fault on a random checkpoint-ring member at a random byte
offset (``reliability/faults.py``) — then lets the keepalive restart
recover through the checkpoint ring and asserts the finished model is
BIT-identical to an uninterrupted reference run.

Emits ``CHAOS.json``::

    {"runs": N, "recoveries": n, "bit_identical": n, "mismatches": 0,
     "deaths": total_kills, "corruptions_armed": n,
     "ring_fallbacks": n, "quarantines": n, "integrity_failures": n}

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_loop.py --runs 10 --seed 0

Also runs as a slow-marked test
(tests/test_reliability.py::test_chaos_loop_driver).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _write_libsvm(path: str, n: int = 300, f: int = 5, seed: int = 0) -> None:
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f)
    y = (X[:, 0] > 0.5).astype(int)
    with open(path, "w") as fh:
        for i in range(n):
            feats = " ".join(f"{j}:{X[i, j]:.6f}" for j in range(f))
            fh.write(f"{y[i]} {feats}\n")


def _state(path: str):
    import xgboost_tpu as xgb
    return xgb.Booster(model_file=path).gbtree.get_state()


def _states_equal(a, b) -> bool:
    if set(a) != set(b):
        return False
    return all(np.array_equal(a[k], b[k]) for k in a)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="CHAOS.json")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir)")
    args = ap.parse_args(argv)

    from xgboost_tpu.cli import main as cli_main
    from xgboost_tpu.profiling import reliability_metrics
    from xgboost_tpu.reliability import faults

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_chaos_")
    os.makedirs(work, exist_ok=True)
    data = os.path.join(work, "train.libsvm")
    _write_libsvm(data, seed=args.seed)
    common = [f"data={data}", "task=train", f"num_round={args.rounds}",
              "silent=2", "objective=binary:logistic", "max_depth=3",
              "eta=0.5", "max_bin=16"]

    # the uninterrupted reference (checkpointing ON so the code path is
    # identical up to the injected failures)
    ref_model = os.path.join(work, "ref.model")
    rc = cli_main(common + [f"model_out={ref_model}",
                            f"checkpoint_dir={os.path.join(work, 'ck_ref')}"])
    if rc != 0:
        print(f"reference run failed (rc={rc})", file=sys.stderr)
        return 1
    ref = _state(ref_model)

    rng = np.random.RandomState(args.seed)
    rm = reliability_metrics()
    base = {"ring_fallbacks": rm.ring_fallbacks.value,
            "quarantines": rm.quarantines.value,
            "integrity_failures": rm.integrity_failures.value}
    report = {"runs": args.runs, "recoveries": 0, "bit_identical": 0,
              "mismatches": 0, "deaths": 0, "corruptions_armed": 0,
              "run_log": []}

    for run in range(args.runs):
        ck = os.path.join(work, f"ck_{run:03d}")
        out = os.path.join(work, f"m_{run:03d}.model")
        # 1-2 deaths at random round boundaries (distinct versions so
        # the second coordinate is reachable after the first restart)
        versions = sorted(rng.choice(
            np.arange(1, args.rounds), size=int(rng.randint(1, 3)),
            replace=False))
        mock = ";".join(f"{int(v)},0,{i}" for i, v in enumerate(versions))
        entry = {"run": run, "mock": mock, "fault": None}
        faults.clear_faults()
        if rng.rand() < 0.5:
            # corrupt the ring member the restart will want: the one
            # written just before the (first) death
            kind = "torn_write" if rng.rand() < 0.5 else "bit_flip"
            at = int(rng.randint(16, 1000))
            target = f"ckpt-{int(versions[0]):06d}"
            faults.inject(kind, at, path_sub=target)
            entry["fault"] = f"{kind}={at}@{target}"
            report["corruptions_armed"] += 1
        try:
            rc = cli_main(common + [f"model_out={out}",
                                    f"checkpoint_dir={ck}",
                                    f"mock={mock}", "keepalive=1"])
        except BaseException as e:  # noqa: BLE001 — recorded in the report
            entry["error"] = f"{type(e).__name__}: {e}"
            rc = -1
        finally:
            faults.clear_faults()
        report["deaths"] += len(versions)
        if rc == 0:
            report["recoveries"] += 1
            got = _state(out)
            if _states_equal(ref, got):
                report["bit_identical"] += 1
                entry["result"] = "bit_identical"
            else:
                report["mismatches"] += 1
                entry["result"] = "MISMATCH"
        else:
            report["mismatches"] += 1
            entry["result"] = f"rc={rc}"
        report["run_log"].append(entry)
        print(f"[chaos] run {run}: mock={mock} fault={entry['fault']} "
              f"-> {entry['result']}", file=sys.stderr)

    report["ring_fallbacks"] = rm.ring_fallbacks.value - base["ring_fallbacks"]
    report["quarantines"] = rm.quarantines.value - base["quarantines"]
    report["integrity_failures"] = (rm.integrity_failures.value
                                    - base["integrity_failures"])
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"[chaos] {report['bit_identical']}/{args.runs} bit-identical, "
          f"{report['ring_fallbacks']:.0f} ring fallbacks, "
          f"{report['quarantines']:.0f} quarantines -> {args.out}",
          file=sys.stderr)
    return 0 if report["mismatches"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())

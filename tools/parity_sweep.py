"""Attribute the higgs250k parity gap (VERDICT r2 item 2).

Round-2 parity left small unattributed AUC deltas vs the reference CLI
(train-auc -0.00203, test-auc -0.00077 on higgs250k).  This sweep runs
BOTH sides on several seeds of the same generator and sweeps the
quantization/precision knobs on our side:

  - default: eps-driven global sketch (~66 bins)
  - bf16 vs fp32 histogram accumulation (hist_precision)
  - fine cuts: max_bin=1024 + sketch_eps=0.003 (~600 bins)
  - near-exact cuts: max_bin=4096 + sketch_eps=0.0008

If the delta shrinks to seed-noise at fine cuts, the gap is
quantization resolution (the reference re-proposes cuts per node per
round — updater_histmaker-inl.hpp:353-462 — which adapts resolution
where the data is); if not, something else is unaccounted.

Writes PARITY_SWEEP.json and appends a summary table to PARITY.md.

Usage: python tools/parity_sweep.py [--seeds 3] [--rounds 20]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.parity import (_parse_evals, _write_libsvm, build_reference,
                          run_reference)  # noqa: E402


def make_data(workdir: str, seed: int, n: int = 250_000, n_test: int = 50_000):
    import numpy as np
    train = os.path.join(workdir, f"sweep_s{seed}.train")
    test = os.path.join(workdir, f"sweep_s{seed}.test")
    if os.path.exists(train) and os.path.exists(test):
        return train, test
    from bench import make_higgs_like
    X, y = make_higgs_like(n + n_test, seed=seed * 977 + 42)
    _write_libsvm(train, X[:n], y[:n])
    _write_libsvm(test, X[n:], y[n:])
    return train, test


REF_ARGS = ["objective=binary:logitraw", "max_depth=6", "eta=0.1",
            "eval_metric=auc", "use_buffer=0"]

OUR_CONFIGS = {
    "default_fp32": {"hist_precision": "fp32"},
    "default_bf16": {"hist_precision": "bf16"},
    "fine_fp32": {"hist_precision": "fp32", "max_bin": 1024,
                  "sketch_eps": 0.003, "sketch_ratio": 2.0},
    "xfine_fp32": {"hist_precision": "fp32", "max_bin": 4096,
                   "sketch_eps": 0.0008, "sketch_ratio": 2.0},
}


def run_ours_api(train, test, rounds, extra, workdir):
    """Run our side in a SUBPROCESS (fresh backend per config keeps jit
    caches separate and lets hist_precision/bins vary freely)."""
    script = os.path.join(workdir, "_run_ours.py")
    with open(script, "w") as f:
        f.write(f"""
import sys, json
sys.path.insert(0, {REPO!r})
import xgboost_tpu as xgb
params = {{"objective": "binary:logitraw", "max_depth": 6, "eta": 0.1,
          "eval_metric": "auc"}}
params.update({extra!r})
dtrain = xgb.DMatrix({train!r})
dtest = xgb.DMatrix({test!r}, num_col=dtrain.num_col)
res = {{}}
xgb.train(params, dtrain, {rounds},
          evals=[(dtest, "test"), (dtrain, "train")],
          evals_result=res, verbose_eval=False)
print(json.dumps({{k: v[-1] for k, v in res.items()}}))
""")
    r = subprocess.run([sys.executable, script], capture_output=True,
                       text=True, timeout=3600)
    if r.returncode != 0:
        raise RuntimeError(f"ours failed: {r.stderr[-800:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--workdir", default="/tmp/xgbtpu_parity")
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    ref_bin = build_reference(args.workdir)

    results = {"rounds": args.rounds, "seeds": {}}
    for seed in range(args.seeds):
        train, test = make_data(args.workdir, seed)
        print(f"[sweep] seed {seed}: reference ...", flush=True)
        r_ev, _, _ = run_reference(
            ref_bin, [f"data={train}", f"eval[test]={test}", "eval_train=1",
                      "model_out=NONE", f"num_round={args.rounds}"]
            + REF_ARGS, args.workdir)
        entry = {"reference": {"train-auc": r_ev["train-auc"][-1],
                               "test-auc": r_ev["test-auc"][-1]}}
        for name, extra in OUR_CONFIGS.items():
            print(f"[sweep] seed {seed}: ours {name} ...", flush=True)
            entry[name] = run_ours_api(train, test, args.rounds, extra,
                                       args.workdir)
        results["seeds"][str(seed)] = entry
        print(json.dumps(entry, indent=1), flush=True)

    with open(os.path.join(REPO, "PARITY_SWEEP.json"), "w") as f:
        json.dump(results, f, indent=1)

    # summary: mean +/- std of (ours - reference) per config/metric
    import numpy as np
    lines = ["", "## Parity attribution sweep (round 3, "
             f"{args.seeds} seeds x {args.rounds} rounds, higgs250k "
             "generator)", "",
             "Delta = ours - reference (same data both sides).", "",
             "| config | train-auc delta | test-auc delta |",
             "|---|---|---|"]
    for name in OUR_CONFIGS:
        row = [name]
        for m in ("train-auc", "test-auc"):
            ds = [results["seeds"][s][name][m]
                  - results["seeds"][s]["reference"][m]
                  for s in results["seeds"]]
            row.append(f"{np.mean(ds):+.5f} ± {np.std(ds):.5f}")
        lines.append("| " + " | ".join(row) + " |")
    with open(os.path.join(REPO, "PARITY.md"), "a") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()

import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax
from bench import make_higgs_like
import xgboost_tpu as xgb

mode = sys.argv[1]
if mode == "onehot":
    os.environ["XGBTPU_ROUTER"] = "onehot"
X, y = make_higgs_like(1_000_000)
dtrain = xgb.DMatrix(X, label=y)
params = {"objective": "binary:logistic", "max_depth": 6, "eta": 0.1}
def barrier(b):
    m = b._cache[id(dtrain)].margin
    jax.block_until_ready(m); jax.device_get(np.asarray(m.ravel()[:1]))
N_R = 50
w = xgb.Booster(params, cache=[dtrain]); w.update(dtrain, 0)
w.update_many(dtrain, 1, N_R - 1); barrier(w); del w
best = 1e9
for _ in range(3):
    b = xgb.Booster(params, cache=[dtrain]); b.update(dtrain, 0); barrier(b)
    t0 = time.perf_counter()
    b.update_many(dtrain, 1, N_R - 1); barrier(b)
    best = min(best, time.perf_counter() - t0)
print(f"router={mode:7s}: {(N_R-1)/best:6.2f} rounds/s (best of 3)")

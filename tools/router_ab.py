"""Round-throughput harness: best-of-3 fused 50-round run on higgs-1M.

Used for separate-process A/B of grower formulations: check out / edit
the variant under test, run this once per arm, compare rounds/s (the
tunnel-attached chip needs separate processes — a jitted variant choice
inside one process hits the first compilation's cache).  Historical
result recorded in PROFILE.md: an MXU one-hot router tied the default
gather router (21.1 vs 21.3 r/s), ruling routing gathers out as a
bottleneck; the experimental branch was deleted rather than committed.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np  # noqa: E402
import jax  # noqa: E402
from bench import make_higgs_like  # noqa: E402
import xgboost_tpu as xgb  # noqa: E402

label = sys.argv[1] if len(sys.argv) > 1 else "default"
X, y = make_higgs_like(1_000_000)
dtrain = xgb.DMatrix(X, label=y)
params = {"objective": "binary:logistic", "max_depth": 6, "eta": 0.1}


def barrier(b):
    m = b._cache[id(dtrain)].margin
    jax.block_until_ready(m)
    jax.device_get(np.asarray(m.ravel()[:1]))


N_R = 50
w = xgb.Booster(params, cache=[dtrain])
w.update(dtrain, 0)
w.update_many(dtrain, 1, N_R - 1)
barrier(w)
del w
best = 1e9
for _ in range(3):
    b = xgb.Booster(params, cache=[dtrain])
    b.update(dtrain, 0)
    barrier(b)
    t0 = time.perf_counter()
    b.update_many(dtrain, 1, N_R - 1)
    barrier(b)
    best = min(best, time.perf_counter() - t0)
print(f"{label:12s}: {(N_R - 1) / best:6.2f} rounds/s (best of 3)")

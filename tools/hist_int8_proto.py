"""int8 histogram kernel prototype (round-4, VERDICT item 7).

The bf16 kernel is MXU operand-volume bound (~4.7 ms/level flat in M;
tools/hist_pack2_proto.py).  int8 halves operand bytes and the v5e MXU
runs int8 x int8 -> int32 at 2x the bf16 rate (measured 156 TOP/s vs
48 TF/s on the same shape).  Gradients quantize per ROUND (g is fixed
within a round): g_i8 = round(g / s * 127); int8 products accumulate
EXACTLY in int32, so the only error is the per-element quantization
(~0.4% — vs bf16's ~0.2% mantissa truncation the bench already runs).

Measures ms/level vs the production bf16 path and checks dequantized
histogram error.
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402

N, F, B = 1_000_000, 28, 64


def make_i8_kernel(n_bin, m_pad, f_tile):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref):
        r_tile = binned_ref.shape[1]
        m2 = 2 * m_pad

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        pos = pos_ref[:, 0]
        lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
        node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
        # gh arrives pre-quantized int8 but rides VMEM as int32 for the
        # select math; narrowed to int8 right before the dot
        ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
        gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel,
                           0).astype(jnp.int8)

        bins = binned_ref[:]
        bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
        for f in range(f_tile):
            onehot = (bins[f:f + 1, :] == bin_ids).astype(jnp.int8)
            acc = jax.lax.dot_general(
                onehot, gh_exp, (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.int32)
            out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc

    return kernel


def build_i8(m_pad, r_tile=2048):
    @jax.jit
    def fn(binned_t, pos, gh_i8_as_i32):
        n_pad = binned_t.shape[1]
        kernel = make_i8_kernel(B, m_pad, F)
        return pl.pallas_call(
            kernel,
            grid=(1, 1, n_pad // r_tile),
            in_specs=[
                pl.BlockSpec((F, r_tile), lambda mi, fi, ri: (fi, ri)),
                pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
            ],
            out_specs=pl.BlockSpec((1, F * B, 2 * m_pad),
                                   lambda mi, fi, ri: (mi, fi, 0)),
            out_shape=jax.ShapeDtypeStruct((1, F * B, 2 * m_pad),
                                           jnp.int32),
        )(binned_t, pos, gh_i8_as_i32)

    return fn


def timed(fn, *args, iters=200):
    @jax.jit
    def loop(a0, rest):
        def body(c, _):
            out = fn(a0, *rest)
            return c + (jnp.asarray(out)[0, 0, 0].astype(jnp.float32)
                        % 7.0) * 1e-20 + c * 0, None
        return jax.lax.scan(body, jnp.float32(0.), None,
                            length=iters)[0]
    r = loop(args[0], args[1:]); jax.block_until_ready(r); float(r)
    t0 = time.perf_counter()
    float(loop(args[0], args[1:]))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    n_pad = _round_up(N, 8192)
    binned = jnp.asarray(rng.randint(0, B, (F, n_pad)).astype(np.int32))
    gh = rng.randn(n_pad, 2).astype(np.float32)
    gh[:, 1] = np.abs(gh[:, 1]) * 0.25
    s_g = np.abs(gh[:, 0]).max()
    s_h = gh[:, 1].max()
    gh_i8 = np.round(gh / np.array([s_g, s_h]) * 127.0).astype(np.int32)

    tot = 0.0
    for d in range(6):
        m = 1 << d
        pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
        try:
            ms = timed(build_i8(m), binned, pos, jnp.asarray(gh_i8))
        except Exception as e:
            print(f"M={m}: FAILED {type(e).__name__}: {str(e)[:200]}")
            return
        tot += ms
        print(f"int8 M={m:3d}: {ms:6.2f} ms")
    print(f"int8 total: {tot:.1f} ms/round-equiv (bf16 prod: ~28-30)")

    # accuracy: dequantized histogram vs f32 reference at M=32
    m = 32
    pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
    hi = np.asarray(build_i8(m)(binned, pos, jnp.asarray(gh_i8)))
    deq = hi[0].reshape(F, B, 2, m).astype(np.float64)
    deq[:, :, 0, :] *= s_g / 127.0
    deq[:, :, 1, :] *= s_h / 127.0
    # f64 reference
    ref = np.zeros((F, B, 2, m))
    pb = np.asarray(pos)[:, 0]
    bn = np.asarray(binned)
    for f in range(F):
        np.add.at(ref[f, :, 0, :], (bn[f], pb), gh[:, 0])
        np.add.at(ref[f, :, 1, :], (bn[f], pb), gh[:, 1])
    err_g = np.abs(deq[:, :, 0] - ref[:, :, 0]).max()
    rel = err_g / np.abs(ref[:, :, 0]).max()
    print(f"max abs G-cell error {err_g:.3f} "
          f"(rel to max cell {rel:.2e}; cells hold ~{n_pad//(B*m)} rows)")


if __name__ == "__main__":
    main()

"""No-transpose int8 histogram kernel prototype (round 4).

The (F, N) kernel operand forces a physical layout copy of the 112 MB
bins per pallas call (~0.78 ms x 6 levels + pad = ~7 ms/round at
1M x 28 — round-4 trace).  This variant feeds the ORIGINAL (N, F) u8
bins: per feature, the one-hot is built transposed (R, B) from a
static lane slice, and the dot contracts over SUBLANES —
dot_general(onehot_T (R,B), gh_exp (R,2M), contract dim 0 x dim 0).
No transpose, no pad copy, no int32 widening outside the kernel.
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402

N, F, B = 1_000_000, 28, 64


def make_kernel(n_bin, m_pad, f_tile):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref):
        r_tile = binned_ref.shape[0]
        m2 = 2 * m_pad

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        pos = pos_ref[:, 0]
        lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
        node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
        ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
        gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel,
                           0).astype(jnp.int8)

        bins = binned_ref[:].astype(jnp.int32)       # (R, F)
        bin_ids = jax.lax.broadcasted_iota(jnp.int32, (r_tile, n_bin), 1)
        for f in range(f_tile):
            onehot_t = (bins[:, f:f + 1] == bin_ids).astype(jnp.int8)
            acc = jax.lax.dot_general(
                onehot_t, gh_exp, (((0,), (0,)), ((), ())),
                precision=jax.lax.Precision.DEFAULT,
                preferred_element_type=jnp.int32)    # (B, 2M)
            out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc

    return kernel


def build(m_pad, r_tile=2048):
    @jax.jit
    def fn(binned, pos, gh_q):
        n_pad = binned.shape[0]
        kernel = make_kernel(B, m_pad, F)
        return pl.pallas_call(
            kernel,
            grid=(1, 1, n_pad // r_tile),
            in_specs=[
                pl.BlockSpec((r_tile, F), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
            ],
            out_specs=pl.BlockSpec((1, F * B, 2 * m_pad),
                                   lambda mi, fi, ri: (mi, fi, 0)),
            out_shape=jax.ShapeDtypeStruct((1, F * B, 2 * m_pad),
                                           jnp.int32),
        )(binned, pos, gh_q)

    return fn


def timed(fn, *args, iters=200):
    @jax.jit
    def loop(a0, rest):
        def body(c, _):
            out = fn(a0, *rest)
            return c + (out[0, 0, 0].astype(jnp.float32) % 7.0) * 1e-20, \
                None
        return jax.lax.scan(body, jnp.float32(0.), None, length=iters)[0]
    r = loop(args[0], args[1:]); jax.block_until_ready(r); float(r)
    t0 = time.perf_counter()
    float(loop(args[0], args[1:]))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    n_pad = _round_up(N, 8192)
    binned = jnp.asarray(rng.randint(0, B, (n_pad, F)).astype(np.uint8))
    gh = rng.randn(n_pad, 2).astype(np.float32)
    s = np.abs(gh).max(axis=0)
    gh_q = jnp.asarray(np.round(gh / s * 127).astype(np.int32))

    tot = 0.0
    for d in range(6):
        m = 1 << d
        pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
        try:
            ms = timed(build(m), binned, pos, gh_q)
        except Exception as e:
            print(f"M={m}: FAILED {type(e).__name__}: {str(e)[:300]}")
            return
        tot += ms
        print(f"notrans-int8 M={m:3d}: {ms:6.2f} ms")
    print(f"notrans-int8 total: {tot:.1f} ms/round-equiv "
          f"(transposed int8: ~3.1 + ~7 of copies)")

    # correctness vs f64 at M=4
    m = 4
    pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
    out = np.asarray(build(m)(binned, pos, gh_q))[0].reshape(F, B, 2, m)
    ref = np.zeros((F, B, 2, m))
    pb = np.asarray(pos)[:, 0]
    bn = np.asarray(binned)
    ghq = np.asarray(gh_q)
    for f in range(F):
        np.add.at(ref[f, :, 0, :], (bn[:, f], pb), ghq[:, 0])
        np.add.at(ref[f, :, 1, :], (bn[:, f], pb), ghq[:, 1])
    print("int32-exact match:", bool((out == ref).all()))


if __name__ == "__main__":
    main()

"""Rank-gradient op A/B (PROFILE.md round-5 candidate 3).

The device LambdaRank gradient (rank_device.rank_gradient) is down to
one unstable 2-key sort + one inverse-permutation scatter + two
gathers; the inv-scatter (~7 ms at 1M) is the biggest single op left.
This tool times, at the bench shape (1M rows, 10k groups of 100), the
candidate replacements amortized inside one lax.scan launch:

  sort3        — the 2-key sort alone (floor for any sort-based path)
  scatter_inv  — sort + at[order].set(iota)       (production today)
  sort_inv     — sort + SECOND sort of (order, iota) (payload = inv)
  grad_now     — full rank_gradient(ndcg, 1 pairsample) as shipped
  pad_posn     — group-PADDED formulation: pred laid out (G, L) with
                 lane padding, per-row pred-rank by an L-wide
                 broadcast-compare count (no sort, no scatter)
  pad_partner  — padded partner read: one-hot select of C=4 channels
                 over lanes as a (G, L, L) x (G, L, C) batched MXU dot
  pad_full     — pad_posn + pad_partner + the ndcg weight/sigmoid
                 math = the padded gradient candidate end-to-end

Uniform groups here let the padded layout be a literal reshape; the
real entry would pad each group to the lane boundary at ingestion
(static index maps, built once).
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N, G = 1_000_000, 10_000
GS = N // G          # true group size
L = 128              # padded lane width


def timed(fn, *args, iters=50):
    @jax.jit
    def loop(*a):
        def body(c, _):
            out = fn(a[0] + c * 1e-20, *a[1:])
            leaf = jax.tree.leaves(out)[0]
            return c + (leaf.reshape(-1)[0].astype(jnp.float32) % 7.0
                        ) * 1e-20, None
        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=iters)
        return c

    r = loop(*args); jax.block_until_ready(r); float(r)
    t0 = time.perf_counter()
    float(loop(*args))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    pred = jnp.asarray(rng.randn(N).astype(np.float32))
    labels = rng.randint(0, 5, N).astype(np.float32)
    gptr = np.arange(0, N + 1, GS)

    from xgboost_tpu.rank_device import build_prep, rank_gradient
    prep = build_prep(labels, gptr, N)
    rows = jnp.arange(N, dtype=jnp.int32)
    gkey = jnp.where(prep.group_of < 0, jnp.int32(2**31 - 1),
                     prep.group_of)

    def sort3(p):
        _, _, order = jax.lax.sort((gkey, -p, rows), dimension=0,
                                   num_keys=2, is_stable=False)
        return order

    def scatter_inv(p):
        order = sort3(p)
        return jnp.zeros(N, jnp.int32).at[order].set(rows)

    def sort_inv(p):
        order = sort3(p)
        _, inv = jax.lax.sort((order, rows), dimension=0, num_keys=1,
                              is_stable=False)
        return inv

    def grad_now(p, key):
        return rank_gradient(p, key, prep, "ndcg", 1)

    # ---- padded formulation (uniform groups -> literal reshape) ----
    lab_pad = jnp.pad(jnp.asarray(labels).reshape(G, GS),
                      ((0, 0), (0, L - GS)))
    valid_pad = jnp.pad(jnp.ones((G, GS), jnp.bool_),
                        ((0, 0), (0, L - GS)))
    lane = jnp.arange(L, dtype=jnp.int32)

    def to_pad(p):
        P = p.reshape(G, GS)
        return jnp.pad(P, ((0, 0), (0, L - GS)),
                       constant_values=-jnp.inf)

    def pad_posn(p):
        P = to_pad(p)                      # (G, L)
        # pred-rank within group: count of strictly-better peers
        gt = (P[:, None, :] > P[:, :, None]) | (
            (P[:, None, :] == P[:, :, None]) & (lane[None, None, :]
                                                < lane[None, :, None]))
        gt = gt & valid_pad[:, None, :]
        return gt.sum(axis=2).astype(jnp.int32)   # (G, L)

    # static partner index per (g, i) in [0, L): drawn once here; the
    # real path draws per round from fold_in, same shape/cost class
    partner_idx = jnp.asarray(
        rng.randint(0, GS, (G, L)).astype(np.int32))

    def pad_partner(p):
        P = to_pad(p)
        posn = pad_posn(p).astype(jnp.float32)
        n_other = jnp.broadcast_to(jnp.float32(GS), (G, L))
        tab = jnp.stack([lab_pad, P, posn, n_other], axis=2)  # (G, L, C)
        onehot = (partner_idx[:, :, None] == lane[None, None, :]
                  ).astype(jnp.bfloat16)                      # (G, L, L)
        part = jax.lax.dot_general(
            onehot, tab.astype(jnp.bfloat16),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)               # (G, L, C)
        return part

    def pad_full(p, key):
        P = to_pad(p)
        posn = pad_posn(p).astype(jnp.float32)
        n_other = jnp.broadcast_to(jnp.float32(GS), (G, L))
        tab = jnp.stack([lab_pad, P, posn, n_other], axis=2)
        u = jax.random.randint(key, (G, L), 0, 1 << 30) % GS
        onehot = (u[:, :, None] == lane[None, None, :]
                  ).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            onehot, tab.astype(jnp.bfloat16),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        lab_p, pred_p, posn_p = part[..., 0], part[..., 1], part[..., 2]
        hi = lab_pad > lab_p
        p_pos = jnp.where(hi, posn, posn_p)
        p_neg = jnp.where(hi, posn_p, posn)
        lab_hi = jnp.maximum(lab_pad, lab_p)
        lab_lo = jnp.minimum(lab_pad, lab_p)
        pos_li = 1.0 / jnp.log(p_pos + 2.0)
        neg_li = 1.0 / jnp.log(p_neg + 2.0)
        pg = 2.0 ** lab_hi - 1.0
        ng = 2.0 ** lab_lo - 1.0
        w = jnp.abs((pg * pos_li + ng * neg_li)
                    - (ng * pos_li + pg * neg_li))
        s = jax.nn.sigmoid(jnp.where(hi, P - pred_p, pred_p - P))
        g = (s - 1.0) * w
        h = jnp.maximum(s * (1.0 - s), 1e-16) * 2.0 * w
        g = jnp.where(valid_pad, jnp.where(hi, g, -g) * 2.0, 0.0)
        h = jnp.where(valid_pad, h * 2.0, 0.0)
        return jnp.stack([g, h], axis=2)

    # ragged pad/unpad gathers: if cheap, the padded gradient can run
    # on the EXISTING row layout (pad per round); if they cost like the
    # random 1M gathers (~5-8 ms), the entry must relayout at ingestion
    pad_idx = jnp.asarray(
        (np.arange(G)[:, None] * GS
         + np.minimum(np.arange(L)[None, :], GS - 1)).astype(np.int32))
    unpad_idx = jnp.asarray(
        (np.arange(N, dtype=np.int64) // GS * L
         + np.arange(N, dtype=np.int64) % GS).astype(np.int32))

    def pad_gather(p):
        return p[pad_idx]

    def unpad_gather(p):
        big = jnp.tile(p, 2)[:G * L]
        return big[unpad_idx]

    key = jax.random.PRNGKey(7)
    out = {}
    out["pad_gather"] = timed(pad_gather, pred)
    out["unpad_gather"] = timed(unpad_gather, pred)
    out["sort3"] = timed(sort3, pred)
    out["scatter_inv"] = timed(scatter_inv, pred)
    out["sort_inv"] = timed(sort_inv, pred)
    out["grad_now"] = timed(grad_now, pred, key)
    out["pad_posn"] = timed(pad_posn, pred)
    out["pad_partner"] = timed(pad_partner, pred)
    out["pad_full"] = timed(pad_full, pred, key)
    for k, v in out.items():
        print(f"{k:12s} {v:8.2f} ms")


if __name__ == "__main__":
    main()

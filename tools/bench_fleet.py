#!/usr/bin/env python
"""Fleet micro-benchmark: aggregate req/s and p99 through the router
at 1 vs 3 replicas, plus shed rate under overload.

Real topology: replica SUBPROCESSES (own interpreters, own jax
runtimes) behind the in-process router, driven by concurrent keep-alive
HTTP clients posting 1-row CSV predicts — the latency-bound
millions-of-users shape.  Writes ``BENCH_fleet.json`` in the
``BENCH_r*.json`` shape::

    JAX_PLATFORMS=cpu python tools/bench_fleet.py

Cells:

- ``direct_1proc``   — clients -> one replica, no router (the
  single-process serving baseline measured over the SAME wire).
- ``router_1`` / ``router_3`` — clients -> router -> fleet.
- ``overload``       — router in-flight budget dropped to force load
  shedding; reports the shed rate and asserts zero NON-shed failures.

Note this container is 1-CPU: replica parallelism cannot exceed one
core, so ``router_3`` measures dispatch/retry overhead and shedding
correctness more than parallel speedup — on a multi-core host the
3-replica aggregate scales with cores.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: xgboost_tpu
sys.path.insert(0, _HERE)                   # tools/: launch_fleet

import numpy as np  # noqa: E402

from launch_fleet import FleetLauncher, RetryingPredictClient  # noqa: E402

N_TRAIN, N_FEAT, ROUNDS = 20_000, 28, 20
CLIENTS = int(os.environ.get("BENCH_FLEET_CLIENTS", "16"))
REQS = int(os.environ.get("BENCH_FLEET_REQS", "1500"))
# deadline cells: the end-to-end budgets stamped on every request.
# FEASIBLE sits above the loaded p50 (most requests can finish; the
# tail shows the late/rejected split), TIGHT sits below it (the
# overload case the discipline exists for: the win is rejected-early
# ≫ completed-late — the fleet stops paying for answers nobody reads)
DEADLINE_FEASIBLE_MS = float(
    os.environ.get("BENCH_FLEET_DEADLINE_MS", "25"))
DEADLINE_TIGHT_MS = float(
    os.environ.get("BENCH_FLEET_DEADLINE_TIGHT_MS", "12"))
SERVE_ARGS = ["serve_min_bucket=8", "serve_max_bucket=64",
              "serve_max_wait_ms=1.0"]


def _train_model(path: str) -> None:
    import xgboost_tpu as xgb
    rng = np.random.RandomState(0)
    X = rng.rand(N_TRAIN, N_FEAT).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2]
         + 0.1 * rng.randn(N_TRAIN) > 0.65).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 6,
                     "eta": 0.3, "silent": 1},
                    xgb.DMatrix(X, label=y), ROUNDS)
    bst.save_model(path)


def _bodies(n: int = 64):
    rng = np.random.RandomState(1)
    return [(",".join(f"{v:.6f}" for v in rng.rand(N_FEAT))).encode()
            for _ in range(n)]


def hammer(base_url: str, total_reqs: int, clients: int,
           deadline_ms=None):
    """``clients`` threads, keep-alive connections, 1-row posts
    (retry-once semantics live in launch_fleet.RetryingPredictClient).
    Returns aggregate stats + per-request outcome counts.

    ``deadline_ms`` stamps every request with that ``X-Deadline-Ms``
    budget and splits the outcome accounting into completed-in-budget /
    completed-late / rejected-up-front (504): the deadline cell's
    claim is that under a tight budget, rejected-early ≫
    completed-late — the fleet stops paying for answers nobody reads."""
    bodies = _bodies()
    per_client = total_reqs // clients
    lat: list = []
    counts = {"ok": 0, "shed": 0, "fail": 0,
              "in_budget": 0, "late": 0, "rejected_early": 0}
    headers = ({"X-Deadline-Ms": str(deadline_ms)}
               if deadline_ms is not None else None)
    fail_details: list = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client(ci: int):
        conn = RetryingPredictClient(base_url)
        mine = dict.fromkeys(counts, 0)
        mylat = []
        details = []
        barrier.wait()
        for i in range(per_client):
            t0 = time.perf_counter()
            status, detail = conn.post(bodies[(ci + i) % len(bodies)],
                                       headers=headers)
            wall = time.perf_counter() - t0
            if status == 200:
                mine["ok"] += 1
                mylat.append(wall)
                if deadline_ms is not None:
                    key = ("in_budget" if wall * 1e3 <= deadline_ms
                           else "late")
                    mine[key] += 1
            elif status == 503:
                mine["shed"] += 1
            elif status == 504 and deadline_ms is not None:
                mine["rejected_early"] += 1
            else:
                mine["fail"] += 1
                details.append(detail if status is None
                               else f"status {status}: {detail}")
        conn.close()
        with lock:
            lat.extend(mylat)
            fail_details.extend(details)
            for k in counts:
                counts[k] += mine[k]

    ts = [threading.Thread(target=client, args=(i,))
          for i in range(clients)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    arr = np.asarray(lat) if lat else np.zeros(1)
    done = per_client * clients
    cell = {
        "clients": clients,
        "requests": done,
        "requests_per_sec": round(done / wall, 1),
        "ok_per_sec": round(counts["ok"] / wall, 1),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "ok": counts["ok"], "shed": counts["shed"],
        "failures": counts["fail"],
        "shed_rate": round(counts["shed"] / max(done, 1), 4),
    }
    if deadline_ms is not None:
        cell.update({
            "deadline_ms": deadline_ms,
            "completed_in_budget": counts["in_budget"],
            "completed_late": counts["late"],
            "rejected_early": counts["rejected_early"],
            "in_budget_rate": round(counts["in_budget"] / max(done, 1), 4),
            "rejected_early_vs_late": (
                round(counts["rejected_early"] / counts["late"], 2)
                if counts["late"] else counts["rejected_early"]),
        })
    if fail_details:
        cell["failure_detail"] = fail_details[:5]
    return cell


def _bench_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_fleet.json")


def deadline_only() -> int:
    """Run ONLY the deadline cell against a fresh 3-replica fleet and
    merge it into the committed BENCH_fleet.json (the other cells'
    numbers — measured under their own settings — stay untouched)."""
    import tempfile
    work = tempfile.mkdtemp(prefix="xgbtpu_benchdl_")
    model = os.path.join(work, "model.bin")
    print("[bench_fleet] training model...", file=sys.stderr)
    _train_model(model)
    fl = FleetLauncher(model, replicas=3,
                       workdir=os.path.join(work, "f3"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    hammer(fl.url, min(REQS, 400), CLIENTS)  # warm the service EWMAs
    feasible = hammer(fl.url, REQS, CLIENTS,
                      deadline_ms=DEADLINE_FEASIBLE_MS)
    tight = hammer(fl.url, REQS, CLIENTS, deadline_ms=DEADLINE_TIGHT_MS)
    fl.stop()
    try:
        with open(_bench_path()) as f:
            out = json.load(f)
    except OSError:
        out = {}
    out["deadline_feasible"] = feasible
    out["deadline"] = tight
    with open(_bench_path(), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"deadline_feasible": feasible, "deadline": tight}))
    return 0 if feasible["failures"] + tight["failures"] == 0 else 1


def main():
    import tempfile
    if "--deadline-only" in sys.argv[1:]:
        return deadline_only()
    work = tempfile.mkdtemp(prefix="xgbtpu_benchfleet_")
    model = os.path.join(work, "model.bin")
    print("[bench_fleet] training model...", file=sys.stderr)
    _train_model(model)
    out = {"metric": "fleet_3replica_requests_per_sec",
           "clients": CLIENTS, "requests_per_cell": REQS}

    # ---- 1 replica: direct (no router) vs via router ----
    print("[bench_fleet] 1-replica fleet...", file=sys.stderr)
    fl = FleetLauncher(model, replicas=1,
                       workdir=os.path.join(work, "f1"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    rep_url = fl.members()["replicas"][0]["url"]
    out["direct_1proc"] = hammer(rep_url, REQS, CLIENTS)
    out["router_1"] = hammer(fl.url, REQS, CLIENTS)
    fl.stop()

    # ---- 3 replicas via router; then overload with a tiny budget ----
    print("[bench_fleet] 3-replica fleet...", file=sys.stderr)
    fl = FleetLauncher(model, replicas=3,
                       workdir=os.path.join(work, "f3"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    out["router_3"] = hammer(fl.url, REQS, CLIENTS)
    # overload: shrink the global in-flight budget far below the client
    # concurrency — admission control must shed with 503, fast, and
    # everything ADMITTED must still succeed
    fl.router.inflight_budget = 4
    out["overload"] = hammer(fl.url, REQS, CLIENTS)
    out["overload"]["inflight_budget"] = 4
    # deadline: full admission again, but every request carries an
    # X-Deadline-Ms budget — feasible first, then the tight overload
    # case where the win is rejected-early ≫ completed-late
    # (reliability/deadline.py; 504s are the deadline discipline
    # working, not failures)
    fl.router.inflight_budget = 256
    out["deadline_feasible"] = hammer(fl.url, REQS, CLIENTS,
                                      deadline_ms=DEADLINE_FEASIBLE_MS)
    out["deadline"] = hammer(fl.url, REQS, CLIENTS,
                             deadline_ms=DEADLINE_TIGHT_MS)
    fl.stop()

    out["value"] = out["router_3"]["requests_per_sec"]
    out["unit"] = (f"req/s aggregate (1-row CSV via router, 3 "
                   f"subprocess replicas, {CLIENTS} clients, CPU "
                   f"{os.cpu_count()}-core; p99="
                   f"{out['router_3']['p99_ms']}ms)")
    if (os.cpu_count() or 1) <= 2:
        out["note"] = (
            f"{os.cpu_count()}-core container: the 3 replica processes "
            "share one core, so router_3 measures dispatch/retry/shed "
            "correctness rather than parallel speedup — replica "
            "scaling needs cores to scale onto (compare router_1 vs "
            "direct_1proc for the router hop overhead instead)")
    try:
        with open(os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "BENCH_serving.json")) as f:
            bs = json.load(f)
        out["bench_serving_baseline"] = {
            "headline_1row_req_per_sec": bs.get("value"),
            "concurrent_req_per_sec":
                bs.get("concurrent", {}).get("requests_per_sec"),
        }
    except OSError as e:
        out["bench_serving_baseline"] = f"unavailable: {e}"

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_fleet.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    ok = (out["overload"]["failures"] == 0
          and out["router_3"]["failures"] == 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

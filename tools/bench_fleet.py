#!/usr/bin/env python
"""Fleet micro-benchmark: aggregate req/s and p99 through the router
at 1 vs 3 replicas, plus shed rate under overload.

Real topology: replica SUBPROCESSES (own interpreters, own jax
runtimes) behind the in-process router, driven by concurrent keep-alive
HTTP clients posting 1-row CSV predicts — the latency-bound
millions-of-users shape.  Writes ``BENCH_fleet.json`` in the
``BENCH_r*.json`` shape::

    JAX_PLATFORMS=cpu python tools/bench_fleet.py

Cells:

- ``direct_1proc``   — clients -> one replica, no router (the
  single-process serving baseline measured over the SAME wire).
- ``router_1`` / ``router_3`` — clients -> router -> fleet.
- ``overload``       — router in-flight budget dropped to force load
  shedding; reports the shed rate and asserts zero NON-shed failures.
- ``catalog_1`` / ``catalog_4`` (``--catalog-only``) — one replica
  serving a 1-entry vs a 4-entry model catalog
  (``task=serve catalog=...``, xgboost_tpu.catalog) over the same
  wire, the 4-entry cell hammered by all four tenants CONCURRENTLY
  with per-tenant req/s and p99.

Note this container is 1-CPU: replica parallelism cannot exceed one
core, so ``router_3`` measures dispatch/retry overhead and shedding
correctness more than parallel speedup — on a multi-core host the
3-replica aggregate scales with cores.  Every cell records the host's
``cpu`` block (``os.cpu_count()`` + the per-process scheduler
affinity) so a reader can tell which regime a committed number was
measured under, and ``--multicore-only`` re-measures the
parallel-speedup cells (``router_3``, ``catalog_1``/``catalog_4``) and
drops the scarce-core caveats when ≥4 effective cores are available —
on a scarce-core host it is a deliberate no-op and the caveats stay.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: xgboost_tpu
sys.path.insert(0, _HERE)                   # tools/: launch_fleet

import numpy as np  # noqa: E402

from launch_fleet import FleetLauncher, RetryingPredictClient  # noqa: E402

N_TRAIN, N_FEAT, ROUNDS = 20_000, 28, 20
CLIENTS = int(os.environ.get("BENCH_FLEET_CLIENTS", "16"))
REQS = int(os.environ.get("BENCH_FLEET_REQS", "1500"))
# deadline cells: the end-to-end budgets stamped on every request.
# FEASIBLE sits above the loaded p50 (most requests can finish; the
# tail shows the late/rejected split), TIGHT sits below it (the
# overload case the discipline exists for: the win is rejected-early
# ≫ completed-late — the fleet stops paying for answers nobody reads)
DEADLINE_FEASIBLE_MS = float(
    os.environ.get("BENCH_FLEET_DEADLINE_MS", "25"))
DEADLINE_TIGHT_MS = float(
    os.environ.get("BENCH_FLEET_DEADLINE_TIGHT_MS", "12"))
SERVE_ARGS = ["serve_min_bucket=8", "serve_max_bucket=64",
              "serve_max_wait_ms=1.0"]


def _train_model(path: str) -> None:
    import xgboost_tpu as xgb
    rng = np.random.RandomState(0)
    X = rng.rand(N_TRAIN, N_FEAT).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2]
         + 0.1 * rng.randn(N_TRAIN) > 0.65).astype(np.float32)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 6,
                     "eta": 0.3, "silent": 1},
                    xgb.DMatrix(X, label=y), ROUNDS)
    bst.save_model(path)


def _bodies(n: int = 64):
    rng = np.random.RandomState(1)
    return [(",".join(f"{v:.6f}" for v in rng.rand(N_FEAT))).encode()
            for _ in range(n)]


def _cpu_info() -> dict:
    """The compute regime a cell was measured under: logical core
    count plus the per-process scheduler affinity (cgroup/taskset caps
    make these differ — affinity is what the replicas actually get)."""
    info = {"cpu_count": os.cpu_count() or 1}
    if hasattr(os, "sched_getaffinity"):
        aff = sorted(os.sched_getaffinity(0))
        info["affinity"] = aff
        info["effective_cores"] = len(aff)
    else:
        info["effective_cores"] = info["cpu_count"]
    return info


def _effective_cores() -> int:
    return _cpu_info()["effective_cores"]


def hammer(base_url: str, total_reqs: int, clients: int,
           deadline_ms=None, path: str = "/predict"):
    """``clients`` threads, keep-alive connections, 1-row posts
    (retry-once semantics live in launch_fleet.RetryingPredictClient).
    Returns aggregate stats + per-request outcome counts.

    ``deadline_ms`` stamps every request with that ``X-Deadline-Ms``
    budget and splits the outcome accounting into completed-in-budget /
    completed-late / rejected-up-front (504): the deadline cell's
    claim is that under a tight budget, rejected-early ≫
    completed-late — the fleet stops paying for answers nobody reads."""
    bodies = _bodies()
    per_client = total_reqs // clients
    lat: list = []
    counts = {"ok": 0, "shed": 0, "fail": 0,
              "in_budget": 0, "late": 0, "rejected_early": 0}
    headers = ({"X-Deadline-Ms": str(deadline_ms)}
               if deadline_ms is not None else None)
    fail_details: list = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client(ci: int):
        conn = RetryingPredictClient(base_url, path=path)
        mine = dict.fromkeys(counts, 0)
        mylat = []
        details = []
        barrier.wait()
        for i in range(per_client):
            t0 = time.perf_counter()
            status, detail = conn.post(bodies[(ci + i) % len(bodies)],
                                       headers=headers)
            wall = time.perf_counter() - t0
            if status == 200:
                mine["ok"] += 1
                mylat.append(wall)
                if deadline_ms is not None:
                    key = ("in_budget" if wall * 1e3 <= deadline_ms
                           else "late")
                    mine[key] += 1
            elif status == 503:
                mine["shed"] += 1
            elif status == 504 and deadline_ms is not None:
                mine["rejected_early"] += 1
            else:
                mine["fail"] += 1
                details.append(detail if status is None
                               else f"status {status}: {detail}")
        conn.close()
        with lock:
            lat.extend(mylat)
            fail_details.extend(details)
            for k in counts:
                counts[k] += mine[k]

    ts = [threading.Thread(target=client, args=(i,))
          for i in range(clients)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    arr = np.asarray(lat) if lat else np.zeros(1)
    done = per_client * clients
    cell = {
        "clients": clients,
        "requests": done,
        "requests_per_sec": round(done / wall, 1),
        "ok_per_sec": round(counts["ok"] / wall, 1),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "ok": counts["ok"], "shed": counts["shed"],
        "failures": counts["fail"],
        "shed_rate": round(counts["shed"] / max(done, 1), 4),
        "cpu": _cpu_info(),
    }
    if deadline_ms is not None:
        cell.update({
            "deadline_ms": deadline_ms,
            "completed_in_budget": counts["in_budget"],
            "completed_late": counts["late"],
            "rejected_early": counts["rejected_early"],
            "in_budget_rate": round(counts["in_budget"] / max(done, 1), 4),
            "rejected_early_vs_late": (
                round(counts["rejected_early"] / counts["late"], 2)
                if counts["late"] else counts["rejected_early"]),
        })
    if fail_details:
        cell["failure_detail"] = fail_details[:5]
    return cell


def _bench_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_fleet.json")


def deadline_only() -> int:
    """Run ONLY the deadline cell against a fresh 3-replica fleet and
    merge it into the committed BENCH_fleet.json (the other cells'
    numbers — measured under their own settings — stay untouched)."""
    import tempfile
    work = tempfile.mkdtemp(prefix="xgbtpu_benchdl_")
    model = os.path.join(work, "model.bin")
    print("[bench_fleet] training model...", file=sys.stderr)
    _train_model(model)
    fl = FleetLauncher(model, replicas=3,
                       workdir=os.path.join(work, "f3"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    hammer(fl.url, min(REQS, 400), CLIENTS)  # warm the service EWMAs
    feasible = hammer(fl.url, REQS, CLIENTS,
                      deadline_ms=DEADLINE_FEASIBLE_MS)
    tight = hammer(fl.url, REQS, CLIENTS, deadline_ms=DEADLINE_TIGHT_MS)
    fl.stop()
    try:
        with open(_bench_path()) as f:
            out = json.load(f)
    except OSError:
        out = {}
    out["deadline_feasible"] = feasible
    out["deadline"] = tight
    with open(_bench_path(), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"deadline_feasible": feasible, "deadline": tight}))
    return 0 if feasible["failures"] + tight["failures"] == 0 else 1


def catalog_only() -> int:
    """Run ONLY the catalog cells — one replica serving a 1-entry vs a
    4-entry model catalog over the same wire — and merge them into the
    committed BENCH_fleet.json (the other cells stay untouched).  The
    4-entry cell drives all four tenants concurrently: the number that
    matters is how much a busy multi-tenant replica costs each tenant
    vs having the replica to itself."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import urllib.request

    work = tempfile.mkdtemp(prefix="xgbtpu_benchcat_")
    print("[bench_fleet] training model...", file=sys.stderr)
    names = ["m0", "m1", "m2", "m3"]
    paths = {n: os.path.join(work, f"{n}.bin") for n in names}
    _train_model(paths["m0"])
    for n in names[1:]:
        shutil.copyfile(paths["m0"], paths[n])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def replica(manifest):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        log = open(os.path.join(work, f"replica-{port}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "xgboost_tpu", "task=serve",
             f"catalog={manifest}", f"serve_port={port}",
             "serve_host=127.0.0.1", "silent=1"] + SERVE_ARGS,
            stdout=log, stderr=log, cwd=repo,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        log.close()
        url = f"http://127.0.0.1:{port}"
        deadline = time.perf_counter() + 300.0
        while time.perf_counter() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"catalog replica died rc={proc.returncode} "
                    f"(see {work}/replica-{port}.log)")
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=2) as r:
                    json.load(r)
                return proc, url
            except (OSError, ValueError):
                time.sleep(0.25)
        proc.kill()
        raise TimeoutError("catalog replica never became healthy")

    print("[bench_fleet] catalog_1 (one resident model)...",
          file=sys.stderr)
    proc, url = replica(f"m0={paths['m0']}")
    cat1 = hammer(url, REQS, CLIENTS, path="/predict?model=m0")
    proc.terminate()
    proc.wait()

    print("[bench_fleet] catalog_4 (four resident models, "
          "concurrent tenants)...", file=sys.stderr)
    proc, url = replica(",".join(f"{n}={paths[n]}" for n in names))
    per = {}
    lock = threading.Lock()

    def tenant(n):
        cell = hammer(url, REQS // len(names),
                      max(2, CLIENTS // len(names)),
                      path=f"/predict?model={n}")
        with lock:
            per[n] = cell

    ts = [threading.Thread(target=tenant, args=(n,)) for n in names]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    proc.terminate()
    proc.wait()

    cat4 = {
        "tenants": len(names),
        "requests": sum(c["requests"] for c in per.values()),
        "requests_per_sec": round(
            sum(c["requests"] for c in per.values()) / wall, 1),
        "ok": sum(c["ok"] for c in per.values()),
        "failures": sum(c["failures"] for c in per.values()),
        "p99_ms_worst_tenant": max(c["p99_ms"] for c in per.values()),
        "per_tenant": per,
        "cpu": _cpu_info(),
    }
    if _effective_cores() <= 2:
        cat4["note"] = (
            f"{_effective_cores()}-effective-core container: all four "
            "tenant engines "
            "share one core, so catalog_4 measures multi-model "
            "interleaving fairness and per-tenant isolation overhead, "
            "not parallel speedup — aggregate req/s stays near "
            "catalog_1 while per-tenant p99 grows with the sharing")
    try:
        with open(_bench_path()) as f:
            out = json.load(f)
    except OSError:
        out = {}
    out["catalog_1"] = cat1
    out["catalog_4"] = cat4
    with open(_bench_path(), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"catalog_1": cat1, "catalog_4": cat4}))
    return 0 if cat1["failures"] + cat4["failures"] == 0 else 1


def multicore_only() -> int:
    """Re-measure the parallel-speedup cells — ``router_3`` and the
    catalog pair — and merge them into the committed BENCH_fleet.json,
    dropping the scarce-core caveats.  The committed numbers were taken
    on a 1-core container where those cells measure dispatch/isolation
    correctness, not speedup; on a host with ≥4 effective cores this
    replaces them with numbers the replica processes can actually
    scale into.  On a scarce-core host it is a deliberate NO-OP: the
    caveats stay because they are still true."""
    import tempfile
    cores = _effective_cores()
    if cores < 4:
        print(f"[bench_fleet] --multicore-only: {cores} effective "
              "core(s) (cpu_count="
              f"{os.cpu_count()}) — skipping the re-run; the committed "
              "scarce-core caveats remain accurate for this host",
              file=sys.stderr)
        return 0
    work = tempfile.mkdtemp(prefix="xgbtpu_benchmc_")
    model = os.path.join(work, "model.bin")
    print("[bench_fleet] training model...", file=sys.stderr)
    _train_model(model)
    print(f"[bench_fleet] router_3 re-run on {cores} cores...",
          file=sys.stderr)
    fl = FleetLauncher(model, replicas=3,
                       workdir=os.path.join(work, "f3"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    hammer(fl.url, min(REQS, 400), CLIENTS)  # warm the service EWMAs
    r3 = hammer(fl.url, REQS, CLIENTS)
    fl.stop()
    try:
        with open(_bench_path()) as f:
            out = json.load(f)
    except OSError:
        out = {}
    out["router_3"] = r3
    out["value"] = r3["requests_per_sec"]
    out["unit"] = (f"req/s aggregate (1-row CSV via router, 3 "
                   f"subprocess replicas, {CLIENTS} clients, "
                   f"{cores} effective cores; p99={r3['p99_ms']}ms)")
    out.pop("note", None)   # the scarce-core caveat no longer applies
    with open(_bench_path(), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"router_3": r3}))
    rc_cat = catalog_only()   # refreshes catalog_1/catalog_4 + caveat
    return rc_cat if r3["failures"] == 0 else 1


def main():
    import tempfile
    if "--deadline-only" in sys.argv[1:]:
        return deadline_only()
    if "--catalog-only" in sys.argv[1:]:
        return catalog_only()
    if "--multicore-only" in sys.argv[1:]:
        return multicore_only()
    work = tempfile.mkdtemp(prefix="xgbtpu_benchfleet_")
    model = os.path.join(work, "model.bin")
    print("[bench_fleet] training model...", file=sys.stderr)
    _train_model(model)
    out = {"metric": "fleet_3replica_requests_per_sec",
           "clients": CLIENTS, "requests_per_cell": REQS}

    # ---- 1 replica: direct (no router) vs via router ----
    print("[bench_fleet] 1-replica fleet...", file=sys.stderr)
    fl = FleetLauncher(model, replicas=1,
                       workdir=os.path.join(work, "f1"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    rep_url = fl.members()["replicas"][0]["url"]
    out["direct_1proc"] = hammer(rep_url, REQS, CLIENTS)
    out["router_1"] = hammer(fl.url, REQS, CLIENTS)
    fl.stop()

    # ---- 3 replicas via router; then overload with a tiny budget ----
    print("[bench_fleet] 3-replica fleet...", file=sys.stderr)
    fl = FleetLauncher(model, replicas=3,
                       workdir=os.path.join(work, "f3"),
                       serve_args=SERVE_ARGS, quiet=True)
    fl.start()
    fl.wait_ready()
    out["router_3"] = hammer(fl.url, REQS, CLIENTS)
    # overload: shrink the global in-flight budget far below the client
    # concurrency — admission control must shed with 503, fast, and
    # everything ADMITTED must still succeed
    fl.router.inflight_budget = 4
    out["overload"] = hammer(fl.url, REQS, CLIENTS)
    out["overload"]["inflight_budget"] = 4
    # deadline: full admission again, but every request carries an
    # X-Deadline-Ms budget — feasible first, then the tight overload
    # case where the win is rejected-early ≫ completed-late
    # (reliability/deadline.py; 504s are the deadline discipline
    # working, not failures)
    fl.router.inflight_budget = 256
    out["deadline_feasible"] = hammer(fl.url, REQS, CLIENTS,
                                      deadline_ms=DEADLINE_FEASIBLE_MS)
    out["deadline"] = hammer(fl.url, REQS, CLIENTS,
                             deadline_ms=DEADLINE_TIGHT_MS)
    fl.stop()

    out["value"] = out["router_3"]["requests_per_sec"]
    out["unit"] = (f"req/s aggregate (1-row CSV via router, 3 "
                   f"subprocess replicas, {CLIENTS} clients, CPU "
                   f"{os.cpu_count()}-core; p99="
                   f"{out['router_3']['p99_ms']}ms)")
    if _effective_cores() <= 2:
        out["note"] = (
            f"{_effective_cores()}-effective-core container: the 3 "
            "replica processes "
            "share one core, so router_3 measures dispatch/retry/shed "
            "correctness rather than parallel speedup — replica "
            "scaling needs cores to scale onto (compare router_1 vs "
            "direct_1proc for the router hop overhead instead)")
    try:
        with open(os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "BENCH_serving.json")) as f:
            bs = json.load(f)
        out["bench_serving_baseline"] = {
            "headline_1row_req_per_sec": bs.get("value"),
            "concurrent_req_per_sec":
                bs.get("concurrent", {}).get("requests_per_sec"),
        }
    except OSError as e:
        out["bench_serving_baseline"] = f"unavailable: {e}"

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_fleet.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    ok = (out["overload"]["failures"] == 0
          and out["router_3"]["failures"] == 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Microbench the primitive costs behind exact-mode redesign candidates.

Round-4 exact grower (VERDICT item 1): the round-3 design materializes
~10 (N, n_node) f32 intermediates per (feature, level).  The candidate
redesign sorts rows by (node, value) per (feature, level) so per-node
prefix sums become O(N) *segmented* scans.  This tool measures, on the
real chip, the primitives that decide between the candidates:

  a) batched int32 key sort (28, N)        -- full re-sort per level
  b) batched scatter-permutation (28, N)   -- incremental 1-bit partition
  c) segmented cumsum via associative_scan -- the per-level scan body
  d) plain (28, N) cumsum                  -- lower bound for (c)
  e) current dense (N, M) cumsum x4        -- round-3 status quo cost

All timings amortized inside one lax.scan launch of ITERS iterations
(the tunnel's fixed ~110 ms dispatch divides out; see PROFILE.md).
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 30


def timed(fn, *args, iters=ITERS):
    """Run fn in a lax.scan of `iters` iterations; return ms/iter."""

    @jax.jit
    def loop(args):
        def body(c, _):
            out = fn(*args, c)
            # fold output into carry so nothing is dead-code-eliminated
            leaves = jax.tree_util.tree_leaves(out)
            acc = sum(jnp.sum(l.astype(jnp.float32)) % 7.0 for l in leaves)
            return c + acc * 1e-20, None

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=iters)
        return c

    r = loop(args)
    jax.block_until_ready(r)
    float(r)  # true barrier (host pull)
    t0 = time.perf_counter()
    r = loop(args)
    jax.block_until_ready(r)
    float(r)
    dt = time.perf_counter() - t0
    return dt / iters * 1e3


def main():
    N = 250_000
    F = 28
    M = 64
    rng = np.random.RandomState(0)
    key = jnp.asarray(rng.randint(0, M, (F, N)).astype(np.int32))
    payload = jnp.asarray(rng.randint(0, N, (F, N)).astype(np.int32))
    gh = jnp.asarray(rng.randn(F, N).astype(np.float32))
    perm = jnp.asarray(
        np.stack([rng.permutation(N) for _ in range(F)]).astype(np.int32))

    # (a) batched sort: composite int32 key (node*N + slot keeps stability)
    def sort_composite(key, payload, c):
        comp = key * N + jnp.arange(N, dtype=jnp.int32)[None, :]
        k, p = jax.lax.sort((comp + c.astype(jnp.int32) * 0, payload),
                            dimension=1, num_keys=1)
        return k, p

    print(f"sort (F={F},N={N}) int32 composite + payload: "
          f"{timed(sort_composite, key, payload):8.2f} ms")

    # (b) batched scatter-permutation: out[perm[i]] = payload[i]
    def scatter_perm(perm, payload, c):
        return jnp.zeros_like(payload).at[
            jnp.arange(F)[:, None], perm].set(payload + c.astype(jnp.int32) * 0)

    print(f"scatter-permutation (F={F},N={N}) int32:      "
          f"{timed(scatter_perm, perm, payload):8.2f} ms")

    # (b2) gather-permutation, for comparison
    def gather_perm(perm, payload, c):
        return jnp.take_along_axis(payload + c.astype(jnp.int32) * 0, perm,
                                   axis=1)

    print(f"gather-permutation (F={F},N={N}) int32:       "
          f"{timed(gather_perm, perm, payload):8.2f} ms")

    # (c) segmented cumsum via associative_scan over (F, N)
    seg_start = jnp.asarray(
        (rng.rand(F, N) < (M / N)).astype(np.bool_))

    def seg_cumsum(gh, seg_start, c):
        def comb(a, b):
            av, af = a
            bv, bf = b
            return jnp.where(bf, bv, av + bv), af | bf

        v, _ = jax.lax.associative_scan((gh + c, seg_start), axis=1)

        return v

    # associative_scan with custom op:
    def seg_cumsum2(gh, seg_start, c):
        def comb(a, b):
            av, af = a
            bv, bf = b
            return jnp.where(bf, bv, av + bv), af | bf

        v, _ = jax.lax.associative_scan(comb, (gh + c, seg_start), axis=1)
        return v

    print(f"segmented cumsum assoc_scan (F={F},N={N}):    "
          f"{timed(seg_cumsum2, gh, seg_start):8.2f} ms")

    # (d) plain cumsum (F, N)
    def plain_cumsum(gh, c):
        return jnp.cumsum(gh + c, axis=1)

    print(f"plain cumsum (F={F},N={N}):                   "
          f"{timed(plain_cumsum, gh):8.2f} ms")

    # (e) the round-3 dense formulation: one feature's 2 cumsums + cummax
    #     + reverse cummin over (N, M)  [x F features for a level]
    pos = jnp.asarray(rng.randint(0, M, N).astype(np.int32))
    ghn = jnp.asarray(rng.randn(N, 2).astype(np.float32))
    vs = jnp.asarray(np.sort(rng.randn(N).astype(np.float32)))

    def dense_level(pos, ghn, vs, c):
        onehot = pos[:, None] == jnp.arange(M, dtype=jnp.int32)[None, :]
        oh = onehot.astype(jnp.float32)
        cg = jnp.cumsum(oh * (ghn[:, 0:1] + c), axis=0)
        ch = jnp.cumsum(oh * ghn[:, 1:2], axis=0)
        vm = jnp.where(onehot, vs[:, None], -jnp.inf)
        a_run = jax.lax.cummax(vm, axis=0)
        bm = jnp.where(onehot, vs[:, None], jnp.inf)
        b_rev = jax.lax.cummin(bm, axis=0, reverse=True)
        return cg, ch, a_run, b_rev

    ms = timed(dense_level, pos, ghn, vs)
    print(f"dense (N,{M}) 2cumsum+cummax+cummin (1 feat): {ms:8.2f} ms"
          f"  -> x{F} = {ms * F:7.1f} ms/level")

    # (f) segment max via scatter-max (F, N) -> (F, M)
    def seg_max(key, gh, c):
        return jnp.full((F, M), -jnp.inf).at[
            jnp.arange(F)[:, None], key].max(gh + c)

    print(f"segment-max scatter (F={F},N={N})->(F,{M}):   "
          f"{timed(seg_max, key, gh):8.2f} ms")


if __name__ == "__main__":
    main()

"""Benchmark: tree-batched histogram kernel vs per-tree launches.

The kernel itself lives in the package now
(:func:`xgboost_tpu.ops.pallas_hist.build_level_histogram_pallas_batched`,
dispatched by vmap via the custom_vmap rule in ops/histogram.py); this
script reproduces the measurement that motivated it (PROFILE.md).
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from tools.hist_microbench import timeit  # noqa: E402
from xgboost_tpu.ops.pallas_hist import (  # noqa: E402
    build_level_histogram_pallas, build_level_histogram_pallas_batched)


def main():
    n, f, b = 200_000, 28, 67
    T, n_node = 6, 64
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, b, size=(n, f)), jnp.int32)
    gh = jnp.asarray(rng.randn(T, n, 2), jnp.float32)
    pos = jnp.asarray(rng.randint(0, n_node, size=(T, n)), jnp.int32)

    # parity on dyadic grads (f32 sums order-independent)
    ghd = jnp.asarray(rng.randint(-512, 512, (T, 4096, 2)) / 256.0,
                      jnp.float32)
    got = np.asarray(build_level_histogram_pallas_batched(
        binned[:4096], ghd, pos[:, :4096], n_node, b, precision="fp32"))
    for t in range(T):
        ref = np.asarray(build_level_histogram_pallas(
            binned[:4096], ghd[t], pos[t, :4096], n_node, b,
            precision="fp32"))
        np.testing.assert_array_equal(got[t], ref)
    print("fp32 bitwise parity ok")

    def per_tree(binned, gh, pos):
        outs = [build_level_histogram_pallas(binned, gh[t], pos[t],
                                             n_node, b, precision="bf16")
                for t in range(T)]
        return jnp.stack(outs)

    seq = jax.jit(per_tree)
    ms = timeit(seq, binned, gh, pos)
    print(f"per-tree x{T} (sequential kernels): {ms:7.2f} ms")
    ms = timeit(build_level_histogram_pallas_batched, binned, gh, pos,
                n_node, b, "bf16")
    print(f"batched shared-onehot           : {ms:7.2f} ms")


if __name__ == "__main__":
    main()

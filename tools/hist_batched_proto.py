"""Prototype: tree-batched histogram kernel for multiclass/forest rounds.

Motivation (PROFILE.md item 3): a 6-class round grows 6 trees over the
SAME binned matrix; vmapping the per-tree kernel rebuilds the (B, R)
one-hot 6 times (measured slower than sequential launches).  Here the
one-hot is built ONCE per (feature, row-tile) and contracted against a
(R, T*2M) gh operand whose lanes pack (tree, grad/hess, node):

    hist[t, b, l] = onehot[b, r] @ gh_exp[r, t*2M + l]

Per-tree positions/gradients differ; the bins do not.  VPU work becomes
independent of T; MXU work is unchanged (same FLOPs, wider lanes).
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, ".")
from tools.hist_microbench import timeit  # noqa: E402
from xgboost_tpu.ops.pallas_hist import (  # noqa: E402
    _round_up, build_level_histogram_pallas)


def _batched_kernel(binned_ref, pos_ref, gh_ref, out_ref, *,
                    n_bin, m_pad, f_tile, T, precision_mode):
    """Grid step: (node_tile, feature_tile, row_tile).

    binned_ref: (f_tile, R) int32
    pos_ref:    (R, T) int32 per-tree node position (-1 inactive)
    gh_ref:     (R, 2*T) f32 — lane t is tree t's grad, lane T+t its hess
    out_ref:    (f_tile*n_bin, T*2*m_pad) f32
    """
    r_tile = binned_ref.shape[1]
    m2 = 2 * m_pad
    lanes = T * m2
    m_base = pl.program_id(0) * m_pad

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # lane l encodes (t, c, node): t = l // (2M), c = (l % 2M) // M,
    # node = l % M
    lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, lanes), 1)
    t_of = lane // m2
    within = lane - t_of * m2
    node_of = m_base + jnp.where(within < m_pad, within, within - m_pad)
    is_h = within >= m_pad

    # gather per-lane gh/pos by tree id via broadcast compare over T
    # (T is small: 2-16); builds (R, lanes) selects without lane gathers
    gh = gh_ref[:]                                   # (R, 2T)
    pos = pos_ref[:]                                 # (R, T)
    ghsel = jnp.zeros((r_tile, lanes), jnp.float32)
    possel = jnp.zeros((r_tile, lanes), jnp.int32)
    for t in range(T):
        sel = t_of == t
        gval = jnp.where(is_h, gh[:, T + t:T + t + 1], gh[:, t:t + 1])
        ghsel = jnp.where(sel, gval, ghsel)
        possel = jnp.where(sel, pos[:, t:t + 1], possel)
    gh_exp = jnp.where(possel == node_of, ghsel, 0.0)

    if precision_mode == "fp32":
        prec = jax.lax.Precision.HIGHEST
        hot_dtype = jnp.float32
    else:
        prec = jax.lax.Precision.DEFAULT
        hot_dtype = jnp.bfloat16
        gh_exp = gh_exp.astype(hot_dtype)

    bins = binned_ref[:]
    bin_ids = jax.lax.broadcasted_iota(jnp.int32, (n_bin, r_tile), 0)
    for f in range(f_tile):
        onehot = (bins[f:f + 1, :] == bin_ids).astype(hot_dtype)
        acc = jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=prec, preferred_element_type=jnp.float32)
        out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc


@functools.partial(jax.jit, static_argnames=(
    "n_node", "n_bin", "precision", "interpret", "r_tile"))
def build_level_histogram_batched(binned, gh, pos, n_node, n_bin,
                                  precision="bf16", interpret=False,
                                  r_tile=1024):
    """gh: (T, N, 2), pos: (T, N), binned: (N, F).
    Returns (T, n_node, F, n_bin, 2) f32."""
    T, N, _ = gh.shape
    F = binned.shape[1]
    m_pad = min(n_node, 64)
    n_m_tiles = -(-n_node // m_pad)
    lanes = T * 2 * m_pad
    # output block (f_tile*B, lanes) f32 <= ~2MB of VMEM; the sublane
    # rule needs f_tile to be a multiple of 8 (or the whole feature dim)
    f_tile = max(8, min(F, (512 * 1024) // (max(n_bin, 1) *
                                            max(lanes, 128))))
    if f_tile < F:
        f_tile = max(8, (f_tile // 8) * 8)
    n_pad = _round_up(max(N, 1), r_tile)
    f_pad = _round_up(F, f_tile)

    binned_t = binned.astype(jnp.int32).T
    if n_pad != N or f_pad != F:
        binned_t = jnp.pad(binned_t, ((0, f_pad - F), (0, n_pad - N)))
        gh = jnp.pad(gh, ((0, 0), (0, n_pad - N), (0, 0)))
        pos = jnp.pad(pos, ((0, 0), (0, n_pad - N)), constant_values=-1)

    # (T, N, 2) -> (N, 2T): first T lanes grads, next T hessians
    gh_flat = jnp.concatenate([gh[..., 0].T, gh[..., 1].T], axis=1)
    pos_t = pos.T.astype(jnp.int32)                  # (N, T)

    kernel = functools.partial(_batched_kernel, n_bin=n_bin, m_pad=m_pad,
                               f_tile=f_tile, T=T,
                               precision_mode=precision)
    out = pl.pallas_call(
        kernel,
        grid=(n_m_tiles, f_pad // f_tile, n_pad // r_tile),
        in_specs=[
            pl.BlockSpec((f_tile, r_tile), lambda mi, fi, ri: (fi, ri)),
            pl.BlockSpec((r_tile, T), lambda mi, fi, ri: (ri, 0)),
            pl.BlockSpec((r_tile, 2 * T), lambda mi, fi, ri: (ri, 0)),
        ],
        out_specs=pl.BlockSpec((1, f_tile * n_bin, lanes),
                               lambda mi, fi, ri: (mi, fi, 0)),
        out_shape=jax.ShapeDtypeStruct((n_m_tiles, f_pad * n_bin, lanes),
                                       jnp.float32),
        interpret=interpret,
    )(binned_t, pos_t, gh_flat.astype(jnp.float32))

    # (m_tiles, f_pad*B, T*2M) -> (T, m_tiles*M, F, B, 2)
    out = out.reshape(n_m_tiles, f_pad, n_bin, T, 2, m_pad)
    out = out.transpose(3, 0, 5, 1, 2, 4).reshape(
        T, n_m_tiles * m_pad, f_pad, n_bin, 2)
    return out[:, :n_node, :F, :, :]


def main():
    n, f, b = 200_000, 28, 67
    T, n_node = 6, 64
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, b, size=(n, f)), jnp.int32)
    gh = jnp.asarray(rng.randn(T, n, 2), jnp.float32)
    pos = jnp.asarray(rng.randint(0, n_node, size=(T, n)), jnp.int32)

    # parity (fp32 exact vs per-tree fp32 kernel; dyadic grads so f32
    # sums are order-independent)
    ghd = jnp.asarray(rng.randint(-512, 512, (T, 4096, 2)) / 256.0,
                      jnp.float32)
    got = np.asarray(build_level_histogram_batched(
        binned[:4096], ghd, pos[:, :4096], n_node, b,
        precision="fp32"))
    for t in range(T):
        ref = np.asarray(build_level_histogram_pallas(
            binned[:4096], ghd[t], pos[t, :4096], n_node, b,
            precision="fp32"))
        np.testing.assert_array_equal(got[t], ref)
    print("fp32 bitwise parity ok")

    def per_tree(binned, gh, pos):
        outs = [build_level_histogram_pallas(binned, gh[t], pos[t],
                                             n_node, b, precision="bf16")
                for t in range(T)]
        return jnp.stack(outs)

    seq = jax.jit(per_tree)
    ms = timeit(seq, binned, gh, pos)
    print(f"per-tree x{T} (sequential kernels): {ms:7.2f} ms")
    for r in (1024, 2048):
        try:
            ms = timeit(build_level_histogram_batched, binned, gh, pos,
                        n_node, b, r_tile=r)
            print(f"batched shared-onehot r={r:5d}   : {ms:7.2f} ms")
        except Exception as e:
            print(f"batched r={r}: FAILED {str(e)[:80]}")


if __name__ == "__main__":
    main()

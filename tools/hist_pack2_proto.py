"""Feature-pair sublane packing prototype (round-4 kernel candidate).

The production histogram dot is (B, R) @ (R, 2M) per feature.  With
B = 64 the one-hot fills only HALF the MXU's 128 rows, and real split
levels have M <= 32 (the terminal level derives from parents), so
lanes are <= 64 too: utilization tops out near 25%.  Packing TWO
features' one-hots into the sublane dim — onehot2[(f_hi, b), r] —
makes every dot (2B=128, R) @ (R, 2M): full rows, half the dot count.

Measures prod vs pack2 at every real level size M = 1..32 of the
bench shape (1M x 28, B = 64).
"""
import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from xgboost_tpu.ops.pallas_hist import _round_up  # noqa: E402

N, F, B = 1_000_000, 28, 64


def make_kernel(mode, n_bin, m_pad, f_tile):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref):
        r_tile = binned_ref.shape[1]
        m2 = 2 * m_pad

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        pos = pos_ref[:, 0]
        lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
        node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
        ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
        gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel,
                           0.0).astype(jnp.bfloat16)

        bins = binned_ref[:]
        if mode == "prod":
            bin_ids = jax.lax.broadcasted_iota(
                jnp.int32, (n_bin, r_tile), 0)
            for f in range(f_tile):
                onehot = (bins[f:f + 1, :] == bin_ids).astype(
                    jnp.bfloat16)
                acc = jax.lax.dot_general(
                    onehot, gh_exp, (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)
                out_ref[0, f * n_bin:(f + 1) * n_bin, :] += acc
        else:  # pack2: sublane s of 2B encodes (s // B -> f offset, s % B)
            sub = jax.lax.broadcasted_iota(
                jnp.int32, (2 * n_bin, r_tile), 0)
            bin_of_sub = sub % n_bin
            hi = sub >= n_bin
            for fp in range(f_tile // 2):
                b0 = bins[2 * fp:2 * fp + 1, :]
                b1 = bins[2 * fp + 1:2 * fp + 2, :]
                bsel = jnp.where(hi, b1, b0)
                onehot2 = (bsel == bin_of_sub).astype(jnp.bfloat16)
                acc = jax.lax.dot_general(
                    onehot2, gh_exp, (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.DEFAULT,
                    preferred_element_type=jnp.float32)  # (2B, 2M)
                out_ref[0, 2 * fp * n_bin:(2 * fp + 2) * n_bin, :] += acc

    return kernel


def build(mode, m_pad, r_tile=2048):
    @jax.jit
    def fn(binned_t, pos, gh):
        n_pad = binned_t.shape[1]
        kernel = make_kernel(mode, B, m_pad, F)
        return pl.pallas_call(
            kernel,
            grid=(1, 1, n_pad // r_tile),
            in_specs=[
                pl.BlockSpec((F, r_tile), lambda mi, fi, ri: (fi, ri)),
                pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
            ],
            out_specs=pl.BlockSpec((1, F * B, 2 * m_pad),
                                   lambda mi, fi, ri: (mi, fi, 0)),
            out_shape=jax.ShapeDtypeStruct((1, F * B, 2 * m_pad),
                                           jnp.float32),
        )(binned_t, pos, gh)

    return fn


def timed(fn, binned_t, pos, gh, iters=30):
    @jax.jit
    def loop(b, p, g):
        def body(c, _):
            out = fn(b, p, g + c * 1e-20)
            return c + jnp.sum(out[0, :2, :2]) % 7.0 * 1e-20, None
        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=iters)
        return c

    r = loop(binned_t, pos, gh); jax.block_until_ready(r); float(r)
    t0 = time.perf_counter()
    float(loop(binned_t, pos, gh))
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    rng = np.random.RandomState(0)
    n_pad = _round_up(N, 8192)
    binned = jnp.asarray(rng.randint(0, B, (F, n_pad)).astype(np.int32))
    gh = jnp.asarray(rng.randn(n_pad, 2).astype(np.float32))

    tot = {"prod": 0.0, "pack2": 0.0}
    print(f"{'M':>3s} {'prod ms':>8s} {'pack2 ms':>8s}")
    for d in range(6):
        m = 1 << d
        pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
        row = [m]
        for mode in ("prod", "pack2"):
            ms = timed(build(mode, m), binned, pos, gh)
            tot[mode] += ms
            row.append(ms)
        print(f"{row[0]:3d} {row[1]:8.2f} {row[2]:8.2f}")
    # correctness spot check at M=32
    pos = jnp.asarray(rng.randint(0, 32, (n_pad, 1)).astype(np.int32))
    a = build("prod", 32)(binned, pos, gh)
    b = build("pack2", 32)(binned, pos, gh)
    ok = bool(jnp.allclose(a, b, atol=1e-3, rtol=1e-3))
    print(f"\nper-round hist totals: prod {tot['prod']:.1f} ms, "
          f"pack2 {tot['pack2']:.1f} ms  (match at M=32: {ok})")


if __name__ == "__main__":
    main()


def make_onebig_kernel(n_bin, m_pad, f_tile):
    def kernel(binned_ref, pos_ref, gh_ref, out_ref):
        r_tile = binned_ref.shape[1]
        m2 = 2 * m_pad

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        pos = pos_ref[:, 0]
        lane = jax.lax.broadcasted_iota(jnp.int32, (r_tile, m2), 1)
        node_of_lane = jnp.where(lane < m_pad, lane, lane - m_pad)
        ghsel = jnp.where(lane < m_pad, gh_ref[:, 0:1], gh_ref[:, 1:2])
        gh_exp = jnp.where(pos[:, None] == node_of_lane, ghsel,
                           0.0).astype(jnp.bfloat16)

        # ONE (F*B, R) one-hot + ONE dot per row tile: the per-feature
        # loop alternates VPU one-hot builds with small MXU dots and is
        # issue-bound (flat in M); the concatenated form pipelines
        bins_rep = jnp.repeat(binned_ref[:], n_bin, axis=0)  # (F*B, R)
        sub = jax.lax.broadcasted_iota(jnp.int32, (f_tile * n_bin,
                                                   r_tile), 0)
        onehot = (bins_rep == sub % n_bin).astype(jnp.bfloat16)
        out_ref[0, :, :] += jax.lax.dot_general(
            onehot, gh_exp, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)

    return kernel


def build_onebig(m_pad, r_tile=2048):
    @jax.jit
    def fn(binned_t, pos, gh):
        n_pad = binned_t.shape[1]
        kernel = make_onebig_kernel(B, m_pad, F)
        return pl.pallas_call(
            kernel,
            grid=(1, 1, n_pad // r_tile),
            in_specs=[
                pl.BlockSpec((F, r_tile), lambda mi, fi, ri: (fi, ri)),
                pl.BlockSpec((r_tile, 1), lambda mi, fi, ri: (ri, 0)),
                pl.BlockSpec((r_tile, 2), lambda mi, fi, ri: (ri, 0)),
            ],
            out_specs=pl.BlockSpec((1, F * B, 2 * m_pad),
                                   lambda mi, fi, ri: (mi, fi, 0)),
            out_shape=jax.ShapeDtypeStruct((1, F * B, 2 * m_pad),
                                           jnp.float32),
        )(binned_t, pos, gh)

    return fn


def main_onebig():
    rng = np.random.RandomState(0)
    n_pad = _round_up(N, 8192)
    binned = jnp.asarray(rng.randint(0, B, (F, n_pad)).astype(np.int32))
    gh = jnp.asarray(rng.randn(n_pad, 2).astype(np.float32))
    tot = 0.0
    for d in range(6):
        m = 1 << d
        pos = jnp.asarray(rng.randint(0, m, (n_pad, 1)).astype(np.int32))
        try:
            for rt in (1024, 2048):
                ms = timed(build_onebig(m, rt), binned, pos, gh)
                print(f"onebig M={m:3d} r{rt}: {ms:6.2f} ms")
                if rt == 2048:
                    tot += ms
        except Exception as e:
            print(f"onebig M={m}: FAILED {type(e).__name__} {str(e)[:150]}")
            return
    pos = jnp.asarray(rng.randint(0, 32, (n_pad, 1)).astype(np.int32))
    a = build("prod", 32)(binned, pos, gh)
    b = build_onebig(32)(binned, pos, gh)
    print(f"onebig total {tot:.1f} ms/round-equiv; match: "
          f"{bool(jnp.allclose(a, b, atol=1e-3, rtol=1e-3))}")


if __name__ == "__main__" and len(sys.argv) > 1 and sys.argv[1] == "onebig":
    main_onebig()

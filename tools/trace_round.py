"""Capture + summarize a device trace of the fused binary round.

Trains the bench workload (higgs-1M, depth 6) for a warmup + a traced
30-round fused launch, then parses the xplane protobuf with
tensorboard_plugin_profile and prints the top device ops by self time.
This is the measurement tool behind the round-4/5 "where do the
milliseconds go" tables in PROFILE.md.

Usage: python tools/trace_round.py [workload]   (binary | multiclass | rank)
"""
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np  # noqa: E402
import jax  # noqa: E402

import xgboost_tpu as xgb  # noqa: E402
from bench import make_higgs_like  # noqa: E402

N_R = 30


def build(workload):
    if workload == "binary":
        X, y = make_higgs_like(1_000_000)
        d = xgb.DMatrix(X, label=y)
        params = {"objective": "binary:logistic", "max_depth": 6,
                  "eta": 0.1}
    elif workload == "multiclass":
        rng = np.random.RandomState(0)
        X = rng.rand(200_000, 28).astype(np.float32)
        y = (X[:, 0] * 6).astype(np.int32) % 6
        d = xgb.DMatrix(X, label=y)
        params = {"objective": "multi:softmax", "num_class": 6,
                  "max_depth": 6, "eta": 0.1}
    else:
        rng = np.random.RandomState(0)
        n, gs = 1_000_000, 100
        X = rng.rand(n, 28).astype(np.float32)
        y = (rng.rand(n) * 4).astype(np.int32).astype(np.float32)
        d = xgb.DMatrix(X, label=y, group=[gs] * (n // gs))
        params = {"objective": "rank:ndcg", "max_depth": 6, "eta": 0.1}
    return d, params


def barrier(b, d):
    m = b._cache[id(d)].margin
    jax.block_until_ready(m)
    jax.device_get(np.asarray(m.ravel()[:1]))


def main():
    workload = sys.argv[1] if len(sys.argv) > 1 else "binary"
    d, params = build(workload)
    bst = xgb.Booster(params, cache=[d])
    bst.update(d, 0)
    bst.update_many(d, 1, N_R - 1)
    barrier(bst, d)

    trace_dir = tempfile.mkdtemp(prefix="xgtpu_trace_")
    bst2 = xgb.Booster(params, cache=[d])
    bst2.update(d, 0)
    barrier(bst2, d)
    jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    bst2.update_many(d, 1, N_R - 1)
    barrier(bst2, d)
    dt = time.perf_counter() - t0
    jax.profiler.stop_trace()
    print(f"{workload}: {(N_R - 1) / dt:.2f} rounds/s "
          f"({dt / (N_R - 1) * 1e3:.2f} ms/round traced)")

    xs = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                   recursive=True)
    assert xs, f"no xplane under {trace_dir}"
    # NOTE use the xprof package, NOT tensorboard_plugin_profile (its
    # generated protos predate the installed protobuf and crash)
    from xprof.convert import raw_to_tool_data
    data, _ = raw_to_tool_data.xspace_to_tool_data(xs, "hlo_stats", {})
    tbl = json.loads(data) if isinstance(data, (str, bytes)) else data
    t = tbl[0] if isinstance(tbl, list) else tbl
    cols = [c["id"] for c in t["cols"]]
    rows = [dict(zip(cols, [c.get("v") for c in r["c"]]))
            for r in t["rows"]]
    rows.sort(key=lambda r: -float(r.get("total_self_time") or 0))
    tot = sum(float(r.get("total_self_time") or 0) for r in rows)
    print(f"device self-time total: {tot / 1e3:.1f} ms "
          f"({tot / 1e3 / (N_R - 1):.2f} ms/round)")
    for r in rows[:25]:
        us = float(r.get("total_self_time") or 0)
        print(f"  {us / (N_R - 1):8.1f} us/round  {us / tot * 100:5.1f}%  "
              f"{str(r.get('category'))[:14]:14s} "
              f"{str(r.get('hlo_op_expression'))[:110]}")
    print("trace dir:", trace_dir)


if __name__ == "__main__":
    main()

"""Head-to-head parity harness: reference C++ CLI vs this framework.

Runs the five BASELINE.json configs on identical data and records both
sides' metrics (and train wall-clock) into ``PARITY.json`` +
``PARITY.md`` at the repo root.  Public data beyond agaricus is not
bundled with the reference, so higgs/dermatology/rank configs use
deterministic synthetic datasets written to libsvm files that BOTH
binaries read (the comparison is still reference-vs-us on identical
inputs; only the absolute metric values differ from the historical
Kaggle numbers).

The reference binary is built from ``/root/reference`` into the scratch
dir with flags that let the 2014-era C++ compile under a modern g++
(``-std=gnu++98 -fpermissive``).

Usage:
  python tools/parity.py [--workdir DIR] [--skip-baseline]
  python tools/parity.py --baseline1m   # reference Higgs-1M CPU rate only
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = os.environ.get("XGTPU_REFERENCE", "/root/reference")
AGARICUS_TRAIN = f"{REFERENCE}/demo/data/agaricus.txt.train"
AGARICUS_TEST = f"{REFERENCE}/demo/data/agaricus.txt.test"

sys.path.insert(0, REPO)


# ------------------------------------------------------------ reference build

def build_reference(workdir: str) -> str:
    """Build the reference CLI binary in <workdir>/refbuild; returns path."""
    build = os.path.join(workdir, "refbuild")
    binary = os.path.join(build, "xgboost")
    if os.path.exists(binary):
        return binary
    print("[parity] building reference binary...", flush=True)
    if not os.path.exists(build):
        shutil.copytree(REFERENCE, build)
    flags = ("-O3 -msse2 -Wno-unknown-pragmas -fPIC -std=gnu++98 "
             "-fpermissive -w -fopenmp")
    subprocess.run(["make", "xgboost", f"CFLAGS={flags}"], cwd=build,
                   check=True, capture_output=True, timeout=600)
    return binary


# ------------------------------------------------------------------- datasets

def _write_libsvm(path: str, X, y, fmt: str = "%.6g"):
    import numpy as np
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            feats = " ".join(f"{j}:{fmt % v}" for j, v in enumerate(X[i]))
            f.write(f"{fmt % y[i]} {feats}\n")


def make_higgs(workdir: str, n: int, tag: str):
    """Synthetic Higgs-like binary data (same generator as bench.py)."""
    train = os.path.join(workdir, f"higgs{tag}.train")
    test = os.path.join(workdir, f"higgs{tag}.test")
    if os.path.exists(train) and os.path.exists(test):
        return train, test
    sys.path.insert(0, REPO)
    from bench import make_higgs_like
    X, y = make_higgs_like(n + max(50_000, n // 5))
    print(f"[parity] writing {train} ...", flush=True)
    _write_libsvm(train, X[:n], y[:n])
    _write_libsvm(test, X[n:], y[n:])
    return train, test


def make_dermatology(workdir: str):
    """Synthetic 6-class dermatology-like data (34 ordinal features)."""
    import numpy as np
    train = os.path.join(workdir, "derma.train")
    test = os.path.join(workdir, "derma.test")
    if os.path.exists(train):
        return train, test
    rng = np.random.RandomState(7)
    n = 2000
    centers = rng.randint(0, 4, size=(6, 34))
    y = rng.randint(0, 6, size=n)
    X = np.clip(centers[y] + rng.randint(-1, 2, size=(n, 34))
                + (rng.rand(n, 34) < 0.1) * rng.randint(0, 4, size=(n, 34)),
                0, 3).astype(np.float32)
    cut = int(n * 0.7)
    _write_libsvm(train, X[:cut], y[:cut], fmt="%g")
    _write_libsvm(test, X[cut:], y[cut:], fmt="%g")
    return train, test


def make_rank(workdir: str):
    """Synthetic MQ2008-like ranking data: 300 train / 100 test groups of
    8-24 docs, 46 features, graded relevance 0-2, plus .group sidecars."""
    import numpy as np
    train = os.path.join(workdir, "mq.train")
    test = os.path.join(workdir, "mq.test")
    if os.path.exists(train):
        return train, test
    rng = np.random.RandomState(11)
    w = rng.randn(46)
    for path, n_groups in ((train, 300), (test, 100)):
        rows, labels, sizes = [], [], []
        for _ in range(n_groups):
            g = rng.randint(8, 25)
            Xg = rng.randn(g, 46).astype(np.float32)
            score = Xg @ w + 1.5 * rng.randn(g)
            rel = np.zeros(g)
            order = np.argsort(-score)
            rel[order[: max(1, g // 6)]] = 2
            rel[order[max(1, g // 6): max(2, g // 3)]] = 1
            rows.append(Xg)
            labels.append(rel)
            sizes.append(g)
        X = np.concatenate(rows)
        y = np.concatenate(labels)
        _write_libsvm(path, X, y, fmt="%.5g")
        with open(path + ".group", "w") as f:
            f.write("\n".join(str(s) for s in sizes) + "\n")
    return train, test


# ------------------------------------------------------------------- running

def _parse_evals(text: str):
    """Parse '[i]\\tname-metric:value' lines -> {name-metric: [values]}."""
    out = {}
    for line in text.splitlines():
        if not line.startswith("["):
            continue
        for part in line.split("\t")[1:]:
            k, _, v = part.rpartition(":")
            try:
                out.setdefault(k.strip(), []).append(float(v))
            except ValueError:
                pass
    return out


def _parse_train_time(text: str):
    m = re.search(r"updating end, (\d+) sec in all", text)
    return int(m.group(1)) if m else None


def _conf(cwd: str) -> str:
    """Both CLIs take a config file as the first argument; share one."""
    path = os.path.join(cwd, "parity.conf")
    if not os.path.exists(path):
        with open(path, "w") as f:
            f.write("task = train\n")
    return path


def run_reference(binary: str, args: list, cwd: str, timeout=3600):
    t0 = time.perf_counter()
    r = subprocess.run([binary, _conf(cwd)] + args, cwd=cwd,
                       capture_output=True, text=True, timeout=timeout)
    wall = time.perf_counter() - t0
    if r.returncode != 0:
        raise RuntimeError(f"reference failed: {r.stderr[-800:]}")
    text = r.stdout + "\n" + r.stderr
    return _parse_evals(text), _parse_train_time(text), wall


def run_ours(args: list, cwd: str, timeout=3600):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    t0 = time.perf_counter()
    r = subprocess.run([sys.executable, "-m", "xgboost_tpu", _conf(cwd)]
                       + args, cwd=cwd, capture_output=True, text=True,
                       timeout=timeout, env=env)
    wall = time.perf_counter() - t0
    if r.returncode != 0:
        raise RuntimeError(f"ours failed: {r.stderr[-800:]}")
    text = r.stdout + "\n" + r.stderr
    return _parse_evals(text), _parse_train_time(text), wall


def _common_args(train, test, extra):
    return ([f"data={train}", f"eval[test]={test}", "eval_train=1",
             "model_out=NONE", "silent=0"] + extra)


def compare(name, ref_bin, workdir, train, test, extra, rounds,
            results, timeout=3600):
    args = _common_args(train, test, extra) + [f"num_round={rounds}"]
    print(f"[parity] {name}: reference ...", flush=True)
    r_ev, r_tt, r_wall = run_reference(ref_bin, args, workdir,
                                       timeout=timeout)
    print(f"[parity] {name}: ours ...", flush=True)
    o_ev, o_tt, o_wall = run_ours(args, workdir, timeout=timeout)
    entry = {"rounds": rounds, "reference": {}, "ours": {},
             "reference_train_sec": r_tt if r_tt is not None else r_wall,
             "ours_train_sec": o_tt if o_tt is not None else o_wall}
    for k, v in r_ev.items():
        entry["reference"][k] = v[-1]
    for k, v in o_ev.items():
        entry["ours"][k] = v[-1]
    results[name] = entry
    print(f"[parity] {name}: ref={entry['reference']} "
          f"ours={entry['ours']}", flush=True)
    return entry


def baseline_1m(ref_bin: str, workdir: str, rounds: int = 20):
    """Measure the reference's single-core Higgs-1M training rate."""
    train, test = make_higgs(workdir, 1_000_000, "1m")
    args = [f"data={train}", "model_out=NONE", "silent=0",
            "objective=binary:logistic", "max_depth=6", "eta=0.1",
            f"num_round={rounds}", "use_buffer=0"]
    print("[parity] measuring reference Higgs-1M CPU rate "
          f"({rounds} rounds, 1 thread)...", flush=True)
    _, train_sec, wall = run_reference(ref_bin, args, workdir,
                                       timeout=7200)
    sec = train_sec if train_sec else wall
    rate = 1_000_000 * rounds / max(sec, 1)
    return {"rows": 1_000_000, "rounds": rounds, "train_sec": sec,
            "rows_per_sec_1thread": rate, "nthread": 1}


# --------------------------------------------------------------------- report

def write_report(results: dict):
    with open(os.path.join(REPO, "PARITY.json"), "w") as f:
        json.dump(results, f, indent=1)
    lines = [
        "# PARITY — reference C++ CLI vs xgboost_tpu on identical data",
        "",
        "Produced by `python tools/parity.py` on this host "
        "(reference built from `/root/reference`, single-core CPU; "
        "ours run with JAX_PLATFORMS=cpu for metric parity — TPU "
        "throughput is bench.py's job).  Synthetic stand-ins are used "
        "where the reference demo data is not bundled (higgs/derma/rank); "
        "both sides read the same libsvm files.",
        "",
        "| config | metric | reference | ours | ref sec | ours sec* |",
        "|---|---|---|---|---|---|",
    ]
    for name, e in results.items():
        if name == "baseline_1m":
            continue
        keys = sorted(set(e["reference"]) & set(e["ours"]))
        for i, k in enumerate(keys):
            tail = (f"{e['reference_train_sec']:.0f} | "
                    f"{e['ours_train_sec']:.0f}" if i == 0 else " | ")
            lines.append(f"| {name if i == 0 else ''} | {k} | "
                         f"{e['reference'][k]:.6f} | {e['ours'][k]:.6f} | "
                         f"{tail} |")
    if "baseline_1m" in results:
        b = results["baseline_1m"]
        lines += [
            "",
            "## Measured CPU baseline (anchors bench.py)",
            "",
            f"Reference CLI, Higgs-1M x 28, depth 6, eta 0.1, "
            f"{b['rounds']} rounds, **1 thread** (this host has 1 core): "
            f"{b['train_sec']:.0f} s -> "
            f"**{b['rows_per_sec_1thread']:,.0f} rows/s/thread**.",
            "",
            "bench.py uses this rows/s/thread as the `vs_baseline` "
            "denominator against our rows/s/chip: with 16 chips per "
            "v5e-16 pod and 16 threads per CPU socket the factors "
            "cancel, so the single-chip ratio equals the pod-vs-socket "
            "wall-clock ratio under (generous) perfect-linear CPU "
            "scaling.",
        ]
    lines += [
        "",
        "*ours-CPU train sec includes one-off jit compilation (~10-40 s) "
        "and is not the performance claim; see BENCH_r*.json for TPU "
        "throughput.",
        "",
    ]
    # preserve appended analysis sections (the attribution sweep from
    # tools/parity_sweep.py) across regeneration
    md_path = os.path.join(REPO, "PARITY.md")
    keep = ""
    if os.path.exists(md_path):
        with open(md_path) as f:
            old = f.read()
        marker = "\n## Parity attribution sweep"
        if marker in old:
            keep = marker + old.split(marker, 1)[1]
    with open(md_path, "w") as f:
        f.write("\n".join(lines) + keep)
    print("[parity] wrote PARITY.json + PARITY.md", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="/tmp/xgtpu_parity")
    ap.add_argument("--skip-baseline", action="store_true")
    ap.add_argument("--baseline1m", action="store_true",
                    help="only (re)measure the reference 1M CPU rate")
    ap.add_argument("--higgs-rounds", type=int, default=20)
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    ref_bin = build_reference(args.workdir)

    results = {}
    parity_path = os.path.join(REPO, "PARITY.json")
    if os.path.exists(parity_path):
        with open(parity_path) as f:
            results = json.load(f)

    if args.baseline1m:
        results["baseline_1m"] = baseline_1m(ref_bin, args.workdir)
        write_report(results)
        return

    # 1. agaricus (demo/binary_classification mushroom.conf params)
    compare("agaricus", ref_bin, args.workdir,
            AGARICUS_TRAIN, AGARICUS_TEST,
            ["objective=binary:logistic", "max_depth=3", "eta=1.0",
             "gamma=1.0", "min_child_weight=1", "use_buffer=0"],
            rounds=2, results=results)

    # 2. higgs 250k (demo/kaggle-higgs params; auc on held-out)
    tr, te = make_higgs(args.workdir, 250_000, "250k")
    compare("higgs250k", ref_bin, args.workdir, tr, te,
            ["objective=binary:logitraw", "max_depth=6", "eta=0.1",
             "eval_metric=auc", "use_buffer=0"],
            rounds=args.higgs_rounds, results=results, timeout=7200)

    # 3. dermatology-like 6-class softmax (demo/multiclass params)
    tr, te = make_dermatology(args.workdir)
    compare("dermatology6", ref_bin, args.workdir, tr, te,
            ["objective=multi:softmax", "num_class=6", "max_depth=6",
             "eta=0.1", "use_buffer=0"],
            rounds=5, results=results)

    # 4. rank (demo/rank mq2008.conf params + ndcg)
    tr, te = make_rank(args.workdir)
    compare("rank_pairwise", ref_bin, args.workdir, tr, te,
            ["objective=rank:pairwise", "max_depth=6", "eta=0.1",
             "gamma=1.0", "min_child_weight=0.1", "eval_metric=ndcg",
             "use_buffer=0"],
            rounds=4, results=results)

    # 5. col-split (multi-node/col-split mushroom config): ours shards
    # features over 8 virtual devices; the reference result is the
    # equivalent single-process run (its distributed col-split is defined
    # to reproduce the single model; ours is bit-match tested in
    # tests/test_distributed.py).
    env_extra = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    args5 = _common_args(
        AGARICUS_TRAIN, AGARICUS_TEST,
        ["objective=binary:logistic", "max_depth=3", "eta=1.0",
         "gamma=1.0", "min_child_weight=1", "use_buffer=0",
         "num_round=2"])
    print("[parity] colsplit: reference (single-process equivalent) ...",
          flush=True)
    r_ev, r_tt, r_wall = run_reference(ref_bin, args5, args.workdir)
    print("[parity] colsplit: ours dsplit=col over 8 shards ...", flush=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO, **env_extra)
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu", _conf(args.workdir)] + args5 +
        ["dsplit=col", "updater=grow_colmaker,prune"],
        cwd=args.workdir, capture_output=True, text=True, env=env,
        timeout=3600)
    if r.returncode != 0:
        raise RuntimeError(f"ours colsplit failed: {r.stderr[-800:]}")
    o_ev = _parse_evals(r.stdout + "\n" + r.stderr)
    o_tt = _parse_train_time(r.stdout + "\n" + r.stderr)
    entry = {"rounds": 2,
             "reference": {k: v[-1] for k, v in r_ev.items()},
             "ours": {k: v[-1] for k, v in o_ev.items()},
             "reference_train_sec": r_tt if r_tt is not None else r_wall,
             "ours_train_sec": o_tt if o_tt is not None else
             time.perf_counter() - t0}
    results["colsplit_mushroom"] = entry
    print(f"[parity] colsplit: ref={entry['reference']} "
          f"ours={entry['ours']}", flush=True)

    if not args.skip_baseline and "baseline_1m" not in results:
        results["baseline_1m"] = baseline_1m(ref_bin, args.workdir)

    write_report(results)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Cycle-latency smoke bench for the continuous-training pipeline.

Drives an in-process :class:`~xgboost_tpu.pipeline.ContinuousTrainer`
over the deterministic synthetic source for a few cycles and reports
the cycle-loop economics: wall seconds per cycle, the publish's share
of it, and the gate verdict mix.  This is a SMOKE bench (is the cycle
loop sanely fast, did a change regress it 10x), not a training bench —
bench.py owns rows/sec.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_pipeline.py --cycles 4

Emits ``BENCH_pipeline.json``.

``--stream`` benches the streaming layer instead (PIPELINE.md
streaming section): a pre-spooled drifting batch stream is consumed by
an in-process :class:`~xgboost_tpu.stream.StreamTrainer` twice — once
with the EMA-FS feature screen on, once off — reporting micro-cycle
latency, claimed batches/s, the online drift-refresh cost
(propose ∪ live thresholds ∪ rebind wall seconds), and the screened
(C, N, F) histogram working-set reduction.  Emits
``BENCH_stream.json``.  Numbers from the 1-core CPU container are
cycle-loop SMOKE economics, not accelerator truth.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stream_bench(args) -> int:
    """The ``--stream`` cell: micro-cycle economics of the streaming
    layer, with and without the EMA-FS feature screen, over the same
    pre-spooled drifting batch stream."""
    import jax
    import numpy as np

    from xgboost_tpu.obs.metrics import stream_metrics
    from xgboost_tpu.pipeline import EvalGate
    from xgboost_tpu.stream import StreamDataSource, StreamTrainer

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_benchstream_")
    n_batches = args.cycles * 2
    batch_rows = max(args.rows // 2, 1)

    def spool(stream_dir):
        # identical batch content for both runs; the distribution
        # shifts halfway so one drift episode (and its cut refresh)
        # lands inside the measured window
        src = StreamDataSource(stream_dir, min_batches=1, max_batches=2)
        for i in range(n_batches):
            r = np.random.RandomState(100 + i)
            shift = 0.35 if i >= n_batches // 2 else 0.0
            X = (r.rand(batch_rows, args.features) + shift).astype(
                np.float32)
            y = (X[:, 0] + 0.25 * X[:, 1]
                 > 0.6 + 1.25 * shift).astype(np.float32)
            src.push(X, y)
        return src

    sm = stream_metrics()

    def run(tag, ema_fs):
        src = spool(os.path.join(work, f"stream-{tag}"))
        wd = os.path.join(work, f"wd-{tag}")
        trainer = StreamTrainer(
            os.path.join(work, f"published-{tag}.model"), src, wd,
            rounds_per_cycle=args.rounds,
            params={"objective": "binary:logistic", "max_depth": 4,
                    "eta": 0.3, "ema_fs": ema_fs, "silent": 1},
            gate=EvalGate(max_regression=0.5), quiet=True)
        base = (sm.refresh_seconds.sum, sm.cut_refreshes.value)
        cycle_s = []
        batches = 0
        for c in range(args.cycles):
            t0 = time.perf_counter()
            trainer.run_cycle()
            cycle_s.append(time.perf_counter() - t0)
            batches += len(src.batches_for(c))
            print(f"[bench-stream] {tag}: cycle {c} in "
                  f"{cycle_s[-1]:.3f}s", file=sys.stderr)
        total = sum(cycle_s)
        kept = None
        try:
            with open(os.path.join(
                    wd, "plans",
                    f"plan-{args.cycles - 1:06d}.json")) as f:
                kept = json.load(f).get("kept")
        except (OSError, ValueError):
            pass
        return {
            "ema_fs": ema_fs,
            "cycle_seconds": [round(s, 4) for s in cycle_s],
            "cycle_seconds_mean": round(total / len(cycle_s), 4),
            "cycle_seconds_steady": round(
                sum(cycle_s[1:]) / max(len(cycle_s) - 1, 1), 4),
            "batches_claimed": batches,
            "batches_per_sec": round(batches / total, 3),
            "rows_per_cycle": batch_rows * 2,
            "cut_refreshes": sm.cut_refreshes.value - base[1],
            "refresh_seconds_total": round(
                sm.refresh_seconds.sum - base[0], 4),
            "kept_features": len(kept) if kept else args.features,
        }

    off = run("off", 0.0)
    on = run("ema", args.ema_fs)
    f_kept = on["kept_features"]
    report = {
        "backend": jax.default_backend(),
        "caveat": "1-core CPU container smoke numbers — cycle-loop "
                  "economics only, not accelerator truth",
        "cycles": args.cycles,
        "rounds_per_cycle": args.rounds,
        "features": args.features,
        "stream_off": off,
        "stream_ema_fs": on,
        "working_set": {
            "full_F": args.features,
            "screened_F": f_kept,
            "fraction": round(f_kept / args.features, 4),
            "note": "fused histogram working set is (C, N, F); C and "
                    "N unchanged, F shrinks to the EMA-FS kept set",
        },
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[bench-stream] off {off['cycle_seconds_steady']}s/cycle, "
          f"ema_fs {on['cycle_seconds_steady']}s/cycle, "
          f"F {args.features}->{f_kept}, "
          f"{on['cut_refreshes']:.0f} refresh(es) in "
          f"{on['refresh_seconds_total']}s -> {args.out}",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cycles", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--out", default=None)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--stream", action="store_true",
                    help="bench the streaming layer instead "
                         "(BENCH_stream.json; see module docstring)")
    ap.add_argument("--ema-fs", type=float, default=0.9,
                    help="--stream: ema_fs fraction for the screened "
                         "run")
    args = ap.parse_args(argv)
    if args.out is None:
        args.out = "BENCH_stream.json" if args.stream \
            else "BENCH_pipeline.json"
    if args.stream:
        return stream_bench(args)

    import jax

    from xgboost_tpu.obs.metrics import pipeline_metrics
    from xgboost_tpu.pipeline import (ContinuousTrainer, EvalGate,
                                      SyntheticDataSource)

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_benchpipe_")
    publish = os.path.join(work, "published.model")
    trainer = ContinuousTrainer(
        publish, SyntheticDataSource(n_rows=args.rows,
                                     n_features=args.features, seed=0),
        os.path.join(work, "wd"), rounds_per_cycle=args.rounds,
        params={"objective": "binary:logistic", "max_depth": 4,
                "eta": 0.3, "silent": 1},
        gate=EvalGate(max_regression=0.1), quiet=True)

    pm = pipeline_metrics()
    base = {"publish_s": pm.publish_seconds.value,
            "pass": pm.gate_pass.value, "fail": pm.gate_fail.value,
            "published": pm.publishes.value}
    cycle_s = []
    statuses = []
    for _ in range(args.cycles):
        t0 = time.perf_counter()
        out = trainer.run_cycle()
        cycle_s.append(time.perf_counter() - t0)
        statuses.append(out["status"])
        print(f"[bench-pipe] cycle {out['cycle']}: {out['status']} "
              f"in {cycle_s[-1]:.3f}s", file=sys.stderr)

    report = {
        "backend": jax.default_backend(),
        "cycles": args.cycles,
        "rounds_per_cycle": args.rounds,
        "rows_per_cycle": args.rows,
        "features": args.features,
        "statuses": statuses,
        "cycle_seconds": [round(s, 4) for s in cycle_s],
        "cycle_seconds_mean": round(sum(cycle_s) / len(cycle_s), 4),
        "cycle_seconds_steady": round(
            sum(cycle_s[1:]) / max(len(cycle_s) - 1, 1), 4),
        "publish_seconds_total": round(
            pm.publish_seconds.value - base["publish_s"], 4),
        "gate_pass": pm.gate_pass.value - base["pass"],
        "gate_fail": pm.gate_fail.value - base["fail"],
        "published": pm.publishes.value - base["published"],
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[bench-pipe] steady-state cycle "
          f"{report['cycle_seconds_steady']}s "
          f"({report['published']:.0f} published) -> {args.out}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

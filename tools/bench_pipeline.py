#!/usr/bin/env python
"""Cycle-latency smoke bench for the continuous-training pipeline.

Drives an in-process :class:`~xgboost_tpu.pipeline.ContinuousTrainer`
over the deterministic synthetic source for a few cycles and reports
the cycle-loop economics: wall seconds per cycle, the publish's share
of it, and the gate verdict mix.  This is a SMOKE bench (is the cycle
loop sanely fast, did a change regress it 10x), not a training bench —
bench.py owns rows/sec.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_pipeline.py --cycles 4

Emits ``BENCH_pipeline.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cycles", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--features", type=int, default=16)
    ap.add_argument("--out", default="BENCH_pipeline.json")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args(argv)

    import jax

    from xgboost_tpu.obs.metrics import pipeline_metrics
    from xgboost_tpu.pipeline import (ContinuousTrainer, EvalGate,
                                      SyntheticDataSource)

    work = args.workdir or tempfile.mkdtemp(prefix="xgbtpu_benchpipe_")
    publish = os.path.join(work, "published.model")
    trainer = ContinuousTrainer(
        publish, SyntheticDataSource(n_rows=args.rows,
                                     n_features=args.features, seed=0),
        os.path.join(work, "wd"), rounds_per_cycle=args.rounds,
        params={"objective": "binary:logistic", "max_depth": 4,
                "eta": 0.3, "silent": 1},
        gate=EvalGate(max_regression=0.1), quiet=True)

    pm = pipeline_metrics()
    base = {"publish_s": pm.publish_seconds.value,
            "pass": pm.gate_pass.value, "fail": pm.gate_fail.value,
            "published": pm.publishes.value}
    cycle_s = []
    statuses = []
    for _ in range(args.cycles):
        t0 = time.perf_counter()
        out = trainer.run_cycle()
        cycle_s.append(time.perf_counter() - t0)
        statuses.append(out["status"])
        print(f"[bench-pipe] cycle {out['cycle']}: {out['status']} "
              f"in {cycle_s[-1]:.3f}s", file=sys.stderr)

    report = {
        "backend": jax.default_backend(),
        "cycles": args.cycles,
        "rounds_per_cycle": args.rounds,
        "rows_per_cycle": args.rows,
        "features": args.features,
        "statuses": statuses,
        "cycle_seconds": [round(s, 4) for s in cycle_s],
        "cycle_seconds_mean": round(sum(cycle_s) / len(cycle_s), 4),
        "cycle_seconds_steady": round(
            sum(cycle_s[1:]) / max(len(cycle_s) - 1, 1), 4),
        "publish_seconds_total": round(
            pm.publish_seconds.value - base["publish_s"], 4),
        "gate_pass": pm.gate_pass.value - base["pass"],
        "gate_fail": pm.gate_fail.value - base["fail"],
        "published": pm.publishes.value - base["published"],
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"[bench-pipe] steady-state cycle "
          f"{report['cycle_seconds_steady']}s "
          f"({report['published']:.0f} published) -> {args.out}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

# End-to-end R binding tests (run where R + reticulate + the Python
# package are available; the CI image for this repo has no R runtime,
# so these are exercised on developer machines — see ../../README.md).

library(testthat)
library(xgboosttpu)

agaricus_train <- Sys.getenv("XGBTPU_AGARICUS_TRAIN",
  "/root/reference/demo/data/agaricus.txt.train")
agaricus_test <- Sys.getenv("XGBTPU_AGARICUS_TEST",
  "/root/reference/demo/data/agaricus.txt.test")

test_that("dense matrix train/predict round-trips", {
  set.seed(1)
  x <- matrix(runif(200 * 4), ncol = 4)
  y <- as.numeric(x[, 1] > 0.5)
  bst <- xgboost(x, label = y,
                 params = list(objective = "binary:logistic",
                               max_depth = 2, eta = 1),
                 nrounds = 3, verbose = 0)
  p <- predict(bst, x)
  expect_equal(length(p), 200)
  expect_gt(mean((p > 0.5) == y), 0.95)

  f <- tempfile(fileext = ".model")
  xgb.save(bst, f)
  bst2 <- xgb.load(f)
  expect_identical(predict(bst2, x), p)
})

test_that("agaricus matches the reference demo error", {
  skip_if_not(file.exists(agaricus_train))
  dtrain <- xgb.DMatrix(agaricus_train)
  dtest <- xgb.DMatrix(agaricus_test)
  bst <- xgb.train(list(objective = "binary:logistic", max_depth = 3,
                        eta = 1),
                   dtrain, 2,
                   watchlist = list(train = dtrain, test = dtest),
                   verbose = 0)
  p <- predict(bst, dtest)
  err <- mean((p > 0.5) != getinfo(dtest, "label"))
  expect_lt(err, 0.01)
})

test_that("dump, importance and tree table parse", {
  set.seed(2)
  x <- matrix(runif(300 * 5), ncol = 5)
  y <- as.numeric(x[, 2] > 0.4)
  bst <- xgboost(x, label = y,
                 params = list(max_depth = 3), nrounds = 2, verbose = 0)
  txt <- xgb.dump(bst, with_stats = TRUE)
  expect_true(any(grepl("^booster\\[0\\]", txt)))
  dt <- xgb.model.dt.tree(bst)
  expect_true(all(c("Tree", "Feature", "Quality") %in% names(dt)))
  imp <- xgb.importance(bst)
  expect_equal(sum(imp$Gain), 1, tolerance = 1e-6)
  expect_equal(imp$Feature[1], "f1")  # x[,2] drives the label
})

test_that("setinfo/getinfo/slice behave", {
  x <- matrix(runif(50 * 3), ncol = 3)
  d <- xgb.DMatrix(x, label = rep(0, 50))
  setinfo(d, "weight", seq_len(50))
  expect_equal(getinfo(d, "weight"), as.numeric(seq_len(50)))
  s <- slice(d, 1:10)
  expect_equal(dim(s)[1], 10)
})

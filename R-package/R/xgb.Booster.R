# Training / prediction / model IO (counterpart of the reference
# R-package/R/xgb.train.R, xgboost.R, predict.xgb.Booster.R,
# xgb.save.R, xgb.load.R, xgb.dump.R).

.plist <- function(params) {
  # R list -> python dict of strings/numbers (eval_metric may be a vector)
  reticulate::r_to_py(params)
}

#' Train a boosted model (reference xgb.train semantics).
#'
#' @param params named list of booster parameters
#' @param data xgb.DMatrix
#' @param nrounds number of boosting rounds
#' @param watchlist named list of xgb.DMatrix to evaluate per round
#' @param early_stopping_rounds stop when no improvement for this many
#' @param verbose 0/1
#' @export
xgb.train <- function(params = list(), data, nrounds,
                      watchlist = list(), obj = NULL,
                      early_stopping_rounds = NULL, maximize = NULL,
                      verbose = 1, ...) {
  stopifnot(inherits(data, "xgb.DMatrix"))
  if (length(watchlist) > 0 &&
      (is.null(names(watchlist)) || any(names(watchlist) == "")))
    stop("every watchlist entry must be named, e.g. list(train = dtrain)")
  core <- .core()
  evals <- lapply(names(watchlist), function(n) {
    reticulate::tuple(watchlist[[n]]$handle, n)
  })
  bst <- core$train(
    .plist(c(params, list(...))), data$handle, as.integer(nrounds),
    evals = evals, obj = obj,
    early_stopping_rounds = if (is.null(early_stopping_rounds)) NULL
                            else as.integer(early_stopping_rounds),
    maximize = maximize,
    verbose_eval = verbose > 0)
  structure(list(handle = bst), class = "xgb.Booster")
}

#' Simple interface: train on a matrix + label (reference xgboost()).
#' @export
xgboost <- function(data, label = NULL, params = list(), nrounds,
                    verbose = 1, ...) {
  dtrain <- if (inherits(data, "xgb.DMatrix")) data
            else xgb.DMatrix(data, label = label)
  xgb.train(params, dtrain, nrounds, verbose = verbose, ...)
}

#' Predict with a trained booster.
#' @param outputmargin return untransformed margin scores
#' @param ntreelimit use only the first N trees
#' @param predleaf return per-tree leaf indices
#' @export
predict.xgb.Booster <- function(object, newdata, outputmargin = FALSE,
                                ntreelimit = 0, predleaf = FALSE, ...) {
  d <- if (inherits(newdata, "xgb.DMatrix")) newdata
       else xgb.DMatrix(newdata)
  out <- object$handle$predict(d$handle,
                               output_margin = outputmargin,
                               ntree_limit = as.integer(ntreelimit),
                               pred_leaf = predleaf)
  out <- reticulate::py_to_r(out)
  if (is.matrix(out) && ncol(out) == 1 && !predleaf) out <- drop(out)
  out
}

#' Save a model to a file (own npz format, or text-safe base64).
#' @export
xgb.save <- function(model, fname) {
  stopifnot(inherits(model, "xgb.Booster"))
  model$handle$save_model(fname)
  invisible(TRUE)
}

#' Serialized model as a raw vector.
#' @export
xgb.save.raw <- function(model) {
  stopifnot(inherits(model, "xgb.Booster"))
  reticulate::py_to_r(model$handle$save_raw())
}

#' Load a model (ours or a reference-format binary).
#' @export
xgb.load <- function(fname) {
  core <- .core()
  structure(list(handle = core$Booster(model_file = fname)),
            class = "xgb.Booster")
}

#' Text dump of every tree; optionally to a file with a feature map.
#' @export
xgb.dump <- function(model, fname = NULL, fmap = "", with_stats = FALSE) {
  stopifnot(inherits(model, "xgb.Booster"))
  dumps <- reticulate::py_to_r(
    model$handle$get_dump(fmap = fmap, with_stats = with_stats))
  txt <- unlist(lapply(seq_along(dumps), function(i) {
    c(sprintf("booster[%d]:", i - 1L),
      strsplit(dumps[[i]], "\n", fixed = TRUE)[[1]])
  }))
  if (is.null(fname)) return(txt)
  writeLines(txt, fname)
  invisible(TRUE)
}

#' k-fold cross validation (reference xgb.cv).
#' @export
xgb.cv <- function(params = list(), data, nrounds, nfold,
                   metrics = list(), verbose = 1, ...) {
  stopifnot(inherits(data, "xgb.DMatrix"))
  core <- .core()
  res <- core$cv(.plist(c(params, list(...))), data$handle,
                 num_boost_round = as.integer(nrounds),
                 nfold = as.integer(nfold),
                 metrics = as.list(metrics),
                 verbose_eval = verbose > 0)
  reticulate::py_to_r(res)
}

# xgb.DMatrix: data container (counterpart of the reference R package's
# xgb.DMatrix over the C ABI, R-package/R/xgb.DMatrix.R; here the core
# is reached through reticulate).

.xgbtpu_env <- new.env(parent = emptyenv())

#' Lazily import the xgboost_tpu Python package.
.core <- function() {
  if (is.null(.xgbtpu_env$core)) {
    .xgbtpu_env$core <- reticulate::import("xgboost_tpu", delay_load = FALSE)
  }
  .xgbtpu_env$core
}

#' Construct an xgb.DMatrix from a dense matrix, a dgCMatrix, or a
#' libsvm/binary file path.
#'
#' @param data matrix, Matrix::dgCMatrix, or character path
#' @param label optional numeric label vector
#' @param weight optional instance weights
#' @param missing value treated as missing in dense input (default NA)
#' @export
xgb.DMatrix <- function(data, label = NULL, weight = NULL, missing = NA,
                        ...) {
  core <- .core()
  if (is.character(data)) {
    handle <- core$DMatrix(data, ...)
  } else if (inherits(data, "dgCMatrix")) {
    # CSC -> (indptr, indices, values) CSR via Python-side transposition
    sp <- reticulate::import("scipy.sparse")
    csr <- sp$csc_matrix(reticulate::tuple(
      as.numeric(data@x), as.integer(data@i), as.integer(data@p)),
      shape = reticulate::tuple(nrow(data), ncol(data)))$tocsr()
    handle <- core$DMatrix(csr, ...)
  } else if (is.matrix(data)) {
    storage.mode(data) <- "double"
    if (!is.na(missing)) data[data == missing] <- NA_real_
    handle <- core$DMatrix(reticulate::r_to_py(data), ...)
  } else {
    stop("xgb.DMatrix: unsupported data type ", class(data)[1])
  }
  if (!is.null(label)) handle$set_label(as.numeric(label))
  if (!is.null(weight)) handle$set_weight(as.numeric(weight))
  structure(list(handle = handle), class = "xgb.DMatrix")
}

#' @export
dim.xgb.DMatrix <- function(x) {
  c(x$handle$num_row, x$handle$num_col)
}

#' Set a meta field ("label", "weight", "base_margin", "group").
#' @export
setinfo <- function(object, name, info) {
  stopifnot(inherits(object, "xgb.DMatrix"))
  if (name == "group") {
    object$handle$set_group(as.integer(info))
  } else {
    object$handle$info$set_field(name, as.numeric(info))
  }
  invisible(object)
}

#' Get a meta field.
#' @export
getinfo <- function(object, name) {
  stopifnot(inherits(object, "xgb.DMatrix"))
  as.numeric(object$handle$info$get_field(name))
}

#' Row-subset an xgb.DMatrix (1-based R indices).
#' @export
slice <- function(object, idxset) {
  stopifnot(inherits(object, "xgb.DMatrix"))
  structure(list(handle = object$handle$slice(as.integer(idxset - 1L))),
            class = "xgb.DMatrix")
}

#' Save an xgb.DMatrix to a binary cache file.
#' @export
xgb.DMatrix.save <- function(dmatrix, fname) {
  stopifnot(inherits(dmatrix, "xgb.DMatrix"))
  dmatrix$handle$save_binary(fname)
  invisible(TRUE)
}

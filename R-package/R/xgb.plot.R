# Model visualization helpers (reference R-package/R/xgb.plot.importance.R
# and xgb.plot.tree.R).  The reference renders with ggplot2/DiagrammeR;
# these analogs use base graphics for the importance bars and emit
# Graphviz DOT for trees (rendered via DiagrammeR when installed, else
# returned/written as text) so the package has no hard plotting deps.

#' Plot feature importance as a horizontal bar chart.
#'
#' @param importance_matrix data.frame from \code{xgb.importance}
#' @param numberOfClusters ignored (reference clusters bars by k-means;
#'   here bars are simply ordered by Gain)
#' @export
xgb.plot.importance <- function(importance_matrix = NULL,
                                numberOfClusters = c(1:10)) {
  if (is.null(importance_matrix) || nrow(importance_matrix) == 0) {
    stop("importance_matrix is required (from xgb.importance)")
  }
  m <- importance_matrix[order(importance_matrix$Gain), ]
  graphics::barplot(m$Gain, names.arg = m$Feature, horiz = TRUE,
                    las = 1, main = "Feature importance (Gain)",
                    xlab = "Gain")
  invisible(m)
}

#' Render a boosted tree as Graphviz DOT.
#'
#' Returns the DOT source (invisibly); renders it when the DiagrammeR
#' package is available, and writes it to \code{fname} when given.
#'
#' @param model an xgb.Booster
#' @param fmap feature map file path (see xgb.dump)
#' @param n_first_tree number of trees to include (default 1)
#' @param fname optional path to write the DOT source to
#' @export
xgb.plot.tree <- function(model = NULL, fmap = "", n_first_tree = 1,
                          fname = NULL) {
  dt <- xgb.model.dt.tree(model = model, fmap = fmap)
  dt <- dt[dt$Tree < n_first_tree, ]
  esc <- function(x) gsub('"', '\\\\"', gsub("\\\\", "\\\\\\\\", x))
  lines <- c("digraph xgb_tree {", "  rankdir=TB;",
             "  node [shape=box, fontname=\"Helvetica\"];")
  for (i in seq_len(nrow(dt))) {
    r <- dt[i, ]
    id <- sprintf("t%s_n%s", r$Tree, r$Node)
    if (r$Feature == "Leaf") {
      lines <- c(lines, sprintf(
        "  %s [label=\"leaf=%s\", style=filled, fillcolor=lightgrey];",
        id, r$Quality))
    } else {
      lines <- c(lines, sprintf(
        "  %s [label=\"%s < %s\\ngain=%s\"];", id, esc(r$Feature),
        r$Split, r$Quality))
      yes_id <- sprintf("t%s_n%s", r$Tree, r$Yes)
      no_id <- sprintf("t%s_n%s", r$Tree, r$No)
      yes_lab <- if (identical(r$Missing, r$Yes)) "yes, missing" else "yes"
      no_lab <- if (identical(r$Missing, r$No)) "no, missing" else "no"
      lines <- c(lines,
                 sprintf("  %s -> %s [label=\"%s\"];", id, yes_id, yes_lab),
                 sprintf("  %s -> %s [label=\"%s\"];", id, no_id, no_lab))
    }
  }
  lines <- c(lines, "}")
  dot <- paste(lines, collapse = "\n")
  if (!is.null(fname)) {
    writeLines(dot, fname)
  }
  if (requireNamespace("DiagrammeR", quietly = TRUE)) {
    print(DiagrammeR::grViz(dot))
  }
  invisible(dot)
}

# Feature importance + tree table, parsed from the text dump in pure R
# (counterpart of reference R-package/R/xgb.importance.R and
# xgb.model.dt.tree.R; same Gain/Cover/Frequency semantics).

#' Parse the dump into a data.frame of nodes.
#'
#' Columns: Tree, Node, Feature ("Leaf" for leaves), Split, Yes, No,
#' Missing, Quality (gain or leaf value), Cover (when dumped with
#' stats).
#' @export
xgb.model.dt.tree <- function(model = NULL, text = NULL, fmap = "") {
  if (is.null(text)) {
    stopifnot(inherits(model, "xgb.Booster"))
    text <- xgb.dump(model, fmap = fmap, with_stats = TRUE)
  }
  tree_id <- -1L
  rows <- list()
  for (line in text) {
    if (grepl("^booster\\[", line)) {
      tree_id <- tree_id + 1L
      next
    }
    s <- trimws(line)
    if (s == "") next
    node <- as.integer(sub("^([0-9]+):.*$", "\\1", s))
    if (grepl("leaf=", s, fixed = TRUE)) {
      qual <- as.numeric(sub(".*leaf=([^,]+).*", "\\1", s))
      cover <- if (grepl("cover=", s)) as.numeric(
        sub(".*cover=([^,]+).*", "\\1", s)) else NA_real_
      rows[[length(rows) + 1L]] <- data.frame(
        Tree = tree_id, Node = node, Feature = "Leaf", Split = NA_real_,
        Yes = NA_integer_, No = NA_integer_, Missing = NA_integer_,
        Quality = qual, Cover = cover, stringsAsFactors = FALSE)
    } else {
      feat <- sub("^[0-9]+:\\[([^<]+)<.*$", "\\1", s)
      split <- as.numeric(sub("^[0-9]+:\\[[^<]+<([^]]+)\\].*$", "\\1", s))
      yes <- as.integer(sub(".*yes=([0-9]+).*", "\\1", s))
      no <- as.integer(sub(".*no=([0-9]+).*", "\\1", s))
      miss <- as.integer(sub(".*missing=([0-9]+).*", "\\1", s))
      qual <- if (grepl("gain=", s)) as.numeric(
        sub(".*gain=([^,]+).*", "\\1", s)) else NA_real_
      cover <- if (grepl("cover=", s)) as.numeric(
        sub(".*cover=([^,]+).*", "\\1", s)) else NA_real_
      rows[[length(rows) + 1L]] <- data.frame(
        Tree = tree_id, Node = node, Feature = feat, Split = split,
        Yes = yes, No = no, Missing = miss, Quality = qual,
        Cover = cover, stringsAsFactors = FALSE)
    }
  }
  do.call(rbind, rows)
}

#' Per-feature importance: total Gain, Cover and split Frequency,
#' normalized to sum to 1 (reference xgb.importance semantics).
#' @export
xgb.importance <- function(model = NULL, feature_names = NULL,
                           text = NULL, fmap = "") {
  dt <- xgb.model.dt.tree(model = model, text = text, fmap = fmap)
  dt <- dt[dt$Feature != "Leaf", , drop = FALSE]
  if (nrow(dt) == 0) {
    return(data.frame(Feature = character(), Gain = numeric(),
                      Cover = numeric(), Frequency = numeric()))
  }
  agg <- aggregate(cbind(Gain = dt$Quality, Cover = dt$Cover,
                         Frequency = rep(1, nrow(dt))),
                   by = list(Feature = dt$Feature), FUN = sum)
  agg$Gain <- agg$Gain / sum(agg$Gain)
  if (!all(is.na(agg$Cover))) agg$Cover <- agg$Cover / sum(agg$Cover)
  agg$Frequency <- agg$Frequency / sum(agg$Frequency)
  agg[order(-agg$Gain), , drop = FALSE]
}

"""tools/chaos_loop.py --selftest wired as a tier-1 test (ISSUE 17
satellite): the fast jax-free path exercises the pure recovery logic —
PartitionClock fence/heal classification, the plan_degrade ladder,
coordinator-state CRC roundtrip + corruption rejection, fail-loud
fault-spec parsing (including the two-phase no-partial-arm guarantee),
and the checkpoint-ring lineage scanner — so a regression in any of
them fails CI in seconds instead of only inside the slow chaos suite.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_chaos_loop_selftest():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_loop.py"),
         "--selftest"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-3000:]
    assert "selftest: OK" in r.stdout, r.stdout[-2000:]

"""Segmented round fusion (Booster.update_many driver): K rounds per
dispatch must be BIT-identical to the per-round path — model bytes,
margins, and eval-line text — at every segment size, including sizes
that do not divide the round count, warm starts, and mid-segment
checkpoint resume."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import xgboost_tpu as xgb  # noqa: E402
from xgboost_tpu.learner import Booster  # noqa: E402


def make_data(n=1500, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] + 0.3 * X[:, 1] > 0.6) ^ (X[:, 2] > 0.7)).astype(
        np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.4}


def _run(params, n_rounds, k, evals_names=("eval", "train"),
         seed_data=0, init_model=None, n=1500):
    """Train with segment size ``k`` (0 = per-round baseline) and return
    (booster, eval_lines, dtrain)."""
    X, y = make_data(n=n, seed=seed_data)
    Xe, ye = make_data(n=500, seed=seed_data + 100)
    dtrain = xgb.DMatrix(X, label=y)
    deval = xgb.DMatrix(Xe, label=ye)
    named = {"train": dtrain, "eval": deval}
    evals = [(named[nm], nm) for nm in evals_names]
    bst = Booster(params, cache=[dtrain, deval], model_file=init_model)
    first = bst.gbtree.num_boosted_rounds if bst.gbtree is not None else 0
    lines = []
    bst.update_many(dtrain, first, n_rounds, evals=evals or None,
                    eval_callback=lambda i, msg: lines.append(msg),
                    rounds_per_dispatch=k)
    return bst, lines, dtrain


def _assert_bitwise_equal(ba, la, bb, lb, d):
    assert la == lb                       # eval-line TEXT, not approx
    np.testing.assert_array_equal(np.asarray(ba.predict(d)),
                                  np.asarray(bb.predict(d)))
    assert bytes(ba.save_raw()) == bytes(bb.save_raw())


@pytest.mark.parametrize("k", [1, 3, 4, 64])
def test_segmented_bit_parity_vs_per_round(k):
    """K ∈ {divides, does-not-divide, exceeds} 7 rounds: model bytes,
    margins and eval lines all byte-match the per-round baseline."""
    params = {**PARAMS, "eval_metric": "logloss"}
    b0, l0, d = _run(params, 7, 0)
    bk, lk, _ = _run(params, 7, k)
    assert len(lk) == 7 and lk[0].startswith("[0]")
    _assert_bitwise_equal(b0, l0, bk, lk, d)


def test_warm_start_subsample_bit_parity(tmp_path):
    """init_model continuation with subsampling: the fused path must
    replay the same fold_in(seed, iteration) keys from the warm-start
    offset, not restart the key schedule."""
    params = {**PARAMS, "subsample": 0.7, "colsample_bytree": 0.8,
              "seed": 11, "eval_metric": "error"}
    base, _, _ = _run(params, 3, 0)
    mf = str(tmp_path / "warm.model")
    base.save_model(mf)
    b0, l0, d = _run(params, 5, 0, init_model=mf)
    b4, l4, _ = _run(params, 5, 4, init_model=mf)
    assert l4[0].startswith("[3]") and l4[-1].startswith("[7]")
    _assert_bitwise_equal(b0, l0, b4, l4, d)


def test_checkpoint_resume_mid_segment(tmp_path):
    """Kill-at-a-segment-boundary resume: bytes captured by the
    segment_callback restore a booster that finishes bit-identical to
    the uninterrupted run (deterministic per-iteration seeding)."""
    X, y = make_data()
    params = {**PARAMS, "subsample": 0.8, "seed": 5}

    d_ref = xgb.DMatrix(X, label=y)
    ref = Booster(params, cache=[d_ref])
    ref.update_many(d_ref, 0, 10, rounds_per_dispatch=4)

    # interrupted run: segments of 4 -> boundaries after rounds 4, 8;
    # capture the ring write at round 8 and stop there (mid final
    # segment of the 10-round plan)
    snaps = {}
    d1 = xgb.DMatrix(X, label=y)
    b1 = Booster(params, cache=[d1])

    def seg_cb(last_i):
        snaps[last_i + 1] = bytes(b1.save_raw())

    b1.update_many(d1, 0, 8, segment_callback=seg_cb,
                   rounds_per_dispatch=4)
    assert sorted(snaps) == [4, 8]

    d2 = xgb.DMatrix(X, label=y)
    b2 = Booster(params, cache=[d2])
    b2.load_raw(snaps[8])
    assert b2.gbtree.num_boosted_rounds == 8
    b2.update_many(d2, 8, 2, rounds_per_dispatch=4)
    assert bytes(b2.save_raw()) == bytes(ref.save_raw())


def test_watchlist_metrics_multiclass_multi_metric():
    """Device-resident eval with several metrics and a train-as-eval
    slot: line text matches the per-round path character for character."""
    rng = np.random.RandomState(3)
    X = rng.rand(900, 6).astype(np.float32)
    y = (X[:, 0] * 3).astype(np.int32).clip(0, 2).astype(np.float32)
    params = {"objective": "multi:softprob", "num_class": 3,
              "max_depth": 3, "eta": 0.3,
              "eval_metric": ["merror", "mlogloss"]}
    d0 = xgb.DMatrix(X, label=y)
    b0 = Booster(params, cache=[d0])
    l0 = []
    b0.update_many(d0, 0, 5, evals=[(d0, "train")],
                   eval_callback=lambda i, m: l0.append(m),
                   rounds_per_dispatch=0)
    d3 = xgb.DMatrix(X, label=y)
    b3 = Booster(params, cache=[d3])
    l3 = []
    b3.update_many(d3, 0, 5, evals=[(d3, "train")],
                   eval_callback=lambda i, m: l3.append(m),
                   rounds_per_dispatch=3)
    assert l0 == l3
    assert "train-merror" in l3[0] and "train-mlogloss" in l3[0]
    assert bytes(b0.save_raw()) == bytes(b3.save_raw())


def test_env_override_forces_per_round(monkeypatch):
    """XGBTPU_ROUNDS_PER_DISPATCH=0 is the A/B switch: it beats both the
    param and the call-site override, and the plan reports k=0."""
    monkeypatch.setenv("XGBTPU_ROUNDS_PER_DISPATCH", "0")
    X, y = make_data(n=400)
    d = xgb.DMatrix(X, label=y)
    bst = Booster({**PARAMS, "rounds_per_dispatch": 8}, cache=[d])
    plans = []
    bst.update_many(d, 0, 3, plan_callback=plans.append,
                    rounds_per_dispatch=16)
    assert plans == [0]
    assert bst.gbtree.num_trees == 3


def test_auto_plan_from_round_model():
    """rounds_per_dispatch=-1 (the default) sizes segments from the
    fitted round model: some k in [1, 64], reported once via
    plan_callback."""
    X, y = make_data(n=400)
    d = xgb.DMatrix(X, label=y)
    bst = Booster(PARAMS, cache=[d])
    plans = []
    bst.update_many(d, 0, 2, plan_callback=plans.append)
    assert len(plans) == 1 and 1 <= plans[0] <= 64
    assert bst.gbtree.num_trees == 2


def test_segment_compile_budget(recompile_guard):
    """The fused scan compiles once per DISTINCT segment length and its
    statics are instance-independent: a second 10-round K=3 run (segment
    lengths {3, 1}, eval included) with a FRESH booster and fresh
    matrices compiles zero XLA programs.  (Tree-count-dependent host
    stack concatenates — shared with the per-round path — are the only
    shape-varying programs, so the round count must match across the
    warm and guarded runs.)"""
    params = {**PARAMS, "eval_metric": "logloss"}
    _run(params, 10, 3)         # warm: segment lengths {3, 1} + eval
    with recompile_guard.expect(0):
        _run(params, 10, 3)     # fresh booster, same shapes -> no XLA

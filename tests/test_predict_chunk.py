"""Chunked tree-parallel prediction (models/tree.py ``tree_chunk``).

Parity suite: the chunked-vmap traversal must be BIT-identical to the
sequential scan-over-trees baseline across every layout the ladder can
produce (T not a chunk multiple, T < chunk, ntree_limit windows,
n_group > 1, n_roots > 1), plus a ``recompile_guard`` budget proving
the padding ladder bounds compilation for growing ensembles.
"""

import numpy as np
import pytest


def _train(params=None, n=400, f=8, rounds=7, seed=0, num_class=0):
    import xgboost_tpu as xgb

    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    if num_class:
        y = (X[:, 0] * num_class).astype(np.int64) % num_class
        y = y.astype(np.float32)
        p = {"objective": "multi:softmax", "num_class": num_class}
    else:
        y = (X[:, 0] + 0.3 * X[:, 1] > 0.6).astype(np.float32)
        p = {"objective": "binary:logistic"}
    p.update({"max_depth": 4, "eta": 0.3, "silent": 1})
    p.update(params or {})
    d = xgb.DMatrix(X, label=y)
    return xgb.train(p, d, rounds), X, d


def _binned_of(bst, X):
    import jax.numpy as jnp
    import xgboost_tpu as xgb
    from xgboost_tpu.binning import bin_matrix
    return jnp.asarray(bin_matrix(xgb.DMatrix(X), bst.gbtree.cuts))


def _margins(bst, binned, chunk, ntree_limit=0):
    """(N, K) margins with the traversal width forced to ``chunk``
    (0 = the scan baseline)."""
    gbt = bst.gbtree
    saved = gbt.pred_chunk
    gbt.pred_chunk = chunk
    try:
        import jax.numpy as jnp
        return np.asarray(gbt.predict_margin(
            binned, jnp.zeros((), jnp.float32), ntree_limit))
    finally:
        gbt.pred_chunk = saved


def _leaves(bst, binned, chunk):
    gbt = bst.gbtree
    saved = gbt.pred_chunk
    gbt.pred_chunk = chunk
    try:
        return np.asarray(gbt.predict_leaf(binned))
    finally:
        gbt.pred_chunk = saved


def test_chunk_parity_binary_all_layouts():
    """T=7 against chunks exercising: T not a chunk multiple (4),
    non-power-of-two chunks (3, 6 — incl. the pow2-pad chunk cap),
    T < chunk (32), chunk == 2."""
    from xgboost_tpu.models.tree import padded_tree_count
    # the pow2 pad below the chunk is CAPPED at the chunk width (the
    # knob's promised vmap width): 12@12 -> 12, 5@6 -> 6, not 8/16
    assert padded_tree_count(12, 12) == 12
    assert padded_tree_count(5, 6) == 6
    assert padded_tree_count(7, 6) == 12
    bst, X, _ = _train(rounds=7)
    binned = _binned_of(bst, X)
    ref_m = _margins(bst, binned, 0)
    ref_l = _leaves(bst, binned, 0)
    for chunk in (2, 3, 4, 6, 32):
        assert np.array_equal(ref_m, _margins(bst, binned, chunk)), chunk
        assert np.array_equal(ref_l, _leaves(bst, binned, chunk)), chunk


def test_chunk_parity_multiclass():
    """n_group > 1: per-tree groups route contributions through the
    one-hot accumulation; 4 rounds x 3 classes = 12 trees, chunk 5
    (partial final chunk with mixed groups)."""
    bst, X, _ = _train(rounds=4, num_class=3)
    binned = _binned_of(bst, X)
    ref = _margins(bst, binned, 0)
    assert ref.shape[1] == 3
    for chunk in (5, 12, 32):
        assert np.array_equal(ref, _margins(bst, binned, chunk)), chunk


def test_chunk_parity_ntree_limit_windows():
    """ntree_limit re-stacks a PREFIX of the ensemble: every window
    size must hit the same ladder pad and stay bit-identical."""
    bst, X, _ = _train(rounds=9)
    binned = _binned_of(bst, X)
    for lim in (1, 2, 3, 5, 8, 9):
        ref = _margins(bst, binned, 0, ntree_limit=lim)
        assert np.array_equal(
            ref, _margins(bst, binned, 4, ntree_limit=lim)), lim


def test_chunk_parity_multi_root():
    """n_roots > 1: the per-row root slot flows through the vmapped
    traversal unbatched; end-to-end booster predict is bit-identical."""
    import xgboost_tpu as xgb

    rng = np.random.RandomState(7)
    n = 600
    X = rng.rand(n, 3).astype(np.float32)
    regime = (rng.rand(n) > 0.5).astype(np.uint32)
    y = np.where(regime == 0, X[:, 0] > 0.5, X[:, 0] <= 0.5).astype(
        np.float32)
    d = xgb.DMatrix(X, label=y)
    d.set_uint_info("root_index", regime)
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 2,
                     "eta": 1.0, "num_roots": 2, "silent": 1}, d, 3)
    gbt = bst.gbtree
    d2 = xgb.DMatrix(X, label=y)
    d2.set_uint_info("root_index", regime)
    gbt.pred_chunk = 0
    ref = bst.predict(d2)
    d3 = xgb.DMatrix(X, label=y)
    d3.set_uint_info("root_index", regime)
    gbt.pred_chunk = 4
    assert np.array_equal(ref, bst.predict(d3))
    # leaves route through root slots too
    gbt.pred_chunk = 0
    ref_l = bst.predict(d2, pred_leaf=True)
    gbt.pred_chunk = 4
    assert np.array_equal(ref_l, bst.predict(d3, pred_leaf=True))


def test_incremental_margin_matches_full_traversal():
    """The cached incremental margin (predict_incremental windows per
    round) must equal a cold full-model prediction under chunking —
    the training predict phase and one-off serving agree bitwise."""
    import xgboost_tpu as xgb
    bst, X, d = _train(rounds=6)
    cached = bst.predict(d)                  # incremental margin cache
    cold = bst.predict(xgb.DMatrix(X))       # fresh full traversal
    assert np.array_equal(cached, cold)


def test_chunk_compile_budget(recompile_guard):
    """Growing an ensemble T = 1..3*chunk recompiles the TRAVERSAL only
    when the ladder rung changes: the distinct-pad count (log2(chunk)
    + 3 here) is the fixed budget — NOT one compile per T.  The eager
    padding glue (byte-copy concats, deliberately outside the jitted
    core — see pad_predict_stack) is warmed in setup so the guarded
    region counts exactly the heavy traversal programs."""
    import jax
    import jax.numpy as jnp
    from xgboost_tpu.models.tree import (pad_predict_stack,
                                         padded_tree_count,
                                         predict_margin_binned)
    bst, X, _ = _train(rounds=12)            # 3 * chunk trees
    binned = _binned_of(bst, X)
    chunk = 4
    stack, group = bst.gbtree._stack(0)
    base = jnp.zeros((), jnp.float32)
    windows = []
    for T in range(1, 13):
        win = (jax.tree.map(lambda x: x[:T], stack), group[:T])
        windows.append(win)
        jax.block_until_ready(pad_predict_stack(win[0], win[1], chunk)[:2])
    jax.block_until_ready(jnp.int32(1))
    expected = len({padded_tree_count(T, chunk) for T in range(1, 13)})
    assert expected == 5  # {1, 2, 4, 8, 12}
    with recompile_guard.expect(expected):
        for st, gr in windows:
            jax.block_until_ready(
                predict_margin_binned(st, gr, binned, base, 4, 1,
                                      tree_chunk=chunk))
    # second pass over the same growing windows: zero compiles
    with recompile_guard.expect(0):
        for st, gr in windows:
            jax.block_until_ready(
                predict_margin_binned(st, gr, binned, base, 4, 1,
                                      tree_chunk=chunk))


def test_bin_dense_blocked_matches_single_shot(monkeypatch):
    """The row-blocked device quantize (learner size-cliff fix) is
    bit-identical to the single-buffer call and to the host
    searchsorted path, NaNs included — and densifies per CSR block
    (never a full N x F f32 host copy)."""
    import xgboost_tpu as xgb
    from xgboost_tpu.binning import bin_dense_device, bin_matrix

    bst, X, _ = _train(rounds=2, n=300, f=6)
    Xd = X.copy()
    Xd[::7, 2] = np.nan                      # missing -> bin 0
    d = xgb.DMatrix(Xd)
    one = np.asarray(bin_dense_device(
        d.to_dense(missing=np.nan), bst.gbtree.cuts.cut_values))
    # force ~5 blocks: 300 rows * 6 cols * 4B / 5
    monkeypatch.setenv("XGBTPU_BIN_BLOCK_BYTES", str(300 * 6 * 4 // 5))
    blocked = np.asarray(bst._bin_dense_blocked(d))
    assert np.array_equal(one, blocked)
    host = bin_matrix(d, bst.gbtree.cuts)
    assert np.array_equal(host, blocked)


def test_predict_over_guard_keeps_device_path(monkeypatch):
    """A dense matrix past the (shrunk) byte guard still predicts
    bit-identically through the blocked device-quantize path."""
    import xgboost_tpu as xgb
    bst, X, _ = _train(rounds=3, n=500, f=6)
    ref = bst.predict(xgb.DMatrix(X))
    monkeypatch.setenv("XGBTPU_BIN_BLOCK_BYTES", str(500 * 6 * 4 // 3))
    assert np.array_equal(ref, bst.predict(xgb.DMatrix(X)))


def test_predict_rows_metric_counts():
    """xgbtpu_predict_rows_total counts Learner.predict traffic."""
    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import predict_metrics
    bst, X, _ = _train(rounds=2, n=123)
    before = predict_metrics().rows.value
    bst.predict(xgb.DMatrix(X))
    assert predict_metrics().rows.value == before + 123


def test_engine_reports_chunk_layout_and_observes_seconds():
    """The serving engine carries the chunk layout in describe() and
    feeds the per-chunk traversal histogram on every predict — and a
    CHUNKED model serves bit-identically to Learner.predict through
    the AOT per-bucket executables."""
    import xgboost_tpu as xgb
    from xgboost_tpu.obs.metrics import predict_metrics
    from xgboost_tpu.serving import PredictEngine
    bst, X, _ = _train({"predict_tree_chunk": 8}, rounds=5)
    assert bst.gbtree.pred_chunk == 8
    eng = PredictEngine(bst, min_bucket=8, max_bucket=64)
    desc = eng.describe()
    assert desc["tree_chunk"] == 8
    assert desc["tree_chunks"] == 1          # 5 trees pad to one chunk
    pm = predict_metrics()
    n0, r0 = pm.chunk_seconds.count, pm.rows.value
    out = eng.predict(X[:10])
    assert out.shape[0] == 10
    assert pm.chunk_seconds.count == n0 + 1
    assert pm.rows.value == r0 + 10
    # bitwise parity engine (padded bucket, chunked) vs learner
    assert np.array_equal(eng.predict(X[:10]),
                          bst.predict(xgb.DMatrix(X[:10])))


def test_chunk_knob_resolution(monkeypatch):
    """XGBTPU_PREDICT_TREE_CHUNK is the end-to-end A/B seam; the -1
    auto default resolves per backend (scan on CPU — measured slower
    there, tools/predict_microbench.py); an explicit param forces."""
    import jax
    monkeypatch.setenv("XGBTPU_PREDICT_TREE_CHUNK", "8")
    bst, X, _ = _train(rounds=3)
    assert bst.gbtree.pred_chunk == 8
    monkeypatch.delenv("XGBTPU_PREDICT_TREE_CHUNK")
    bst2, _, _ = _train(rounds=3)            # auto
    expect = 32 if jax.default_backend() == "tpu" else 0
    assert bst2.gbtree.pred_chunk == expect
    bst3, _, _ = _train({"predict_tree_chunk": 16}, rounds=3)
    assert bst3.gbtree.pred_chunk == 16
    import xgboost_tpu as xgb
    p = bst.predict(xgb.DMatrix(X))
    assert np.array_equal(p, bst2.predict(xgb.DMatrix(X)))
    assert np.array_equal(p, bst3.predict(xgb.DMatrix(X)))

"""DMatrix / binning tests (reference data layer semantics, SURVEY.md §2.1 L2)."""

import os

import numpy as np
import pytest

from xgboost_tpu.binning import bin_dense, bin_matrix, compute_cuts
from xgboost_tpu.data import DMatrix, parse_libsvm

AGARICUS_TRAIN = "/root/reference/demo/data/agaricus.txt.train"


def toy_libsvm(tmp_path):
    p = tmp_path / "toy.libsvm"
    p.write_text("1 0:1.5 3:2.0\n0 1:-1.0\n1 0:0.5 2:3.5 3:1.0\n")
    return str(p)


def test_parse_libsvm(tmp_path):
    indptr, indices, values, labels = parse_libsvm(toy_libsvm(tmp_path))
    np.testing.assert_array_equal(labels, [1, 0, 1])
    np.testing.assert_array_equal(indptr, [0, 2, 3, 6])
    np.testing.assert_array_equal(indices, [0, 3, 1, 0, 2, 3])
    np.testing.assert_allclose(values, [1.5, 2.0, -1.0, 0.5, 3.5, 1.0])


def test_parse_libsvm_split_loading(tmp_path):
    path = toy_libsvm(tmp_path)
    i0, _, _, l0 = parse_libsvm(path, rank=0, nparts=2)
    i1, _, _, l1 = parse_libsvm(path, rank=1, nparts=2)
    assert len(l0) + len(l1) == 3
    np.testing.assert_array_equal(l0, [1, 1])
    np.testing.assert_array_equal(l1, [0])


def test_dmatrix_from_file(tmp_path):
    dm = DMatrix(toy_libsvm(tmp_path))
    assert dm.num_row == 3
    assert dm.num_col == 4
    np.testing.assert_array_equal(dm.get_label(), [1, 0, 1])


def test_dmatrix_from_dense_missing_nan():
    X = np.array([[1.0, np.nan], [np.nan, 2.0]], dtype=np.float32)
    dm = DMatrix(X, label=[0, 1])
    assert dm.num_row == 2 and dm.num_col == 2
    rows, vals = dm.column_values(0)
    np.testing.assert_array_equal(rows, [0])
    np.testing.assert_allclose(vals, [1.0])


def test_dmatrix_from_dense_missing_value():
    X = np.array([[1.0, -999.0], [3.0, 2.0]], dtype=np.float32)
    dm = DMatrix(X, missing=-999.0)
    d = dm.to_dense()
    assert np.isnan(d[0, 1])
    assert d[1, 1] == 2.0


def test_dmatrix_slice():
    X = np.arange(12, dtype=np.float32).reshape(4, 3)
    dm = DMatrix(X, label=[0, 1, 2, 3], weight=[1, 2, 3, 4])
    s = dm.slice([2, 0])
    assert s.num_row == 2
    np.testing.assert_array_equal(s.get_label(), [2, 0])
    np.testing.assert_array_equal(s.get_weight(), [3, 1])
    np.testing.assert_allclose(s.to_dense()[0], X[2])


def test_dmatrix_save_load_binary(tmp_path):
    X = np.random.RandomState(0).rand(10, 5).astype(np.float32)
    dm = DMatrix(X, label=np.arange(10), weight=np.ones(10))
    path = str(tmp_path / "m.npz")
    dm.save_binary(path)
    dm2 = DMatrix.load_binary(path)
    np.testing.assert_allclose(dm2.to_dense(), dm.to_dense())
    np.testing.assert_array_equal(dm2.get_label(), dm.get_label())


def test_cache_uri(tmp_path):
    path = toy_libsvm(tmp_path)
    cache = str(tmp_path / "c")
    dm = DMatrix(path + "#" + cache)
    assert os.path.exists(cache + ".npz")
    dm2 = DMatrix(path + "#" + cache)  # loads from cache
    np.testing.assert_array_equal(dm2.get_label(), dm.get_label())


def test_group_sidecar(tmp_path):
    path = toy_libsvm(tmp_path)
    with open(path + ".group", "w") as f:
        f.write("2\n1\n")
    dm = DMatrix(path)
    np.testing.assert_array_equal(dm.info.group_ptr, [0, 2, 3])


def test_set_group():
    dm = DMatrix(np.zeros((5, 2), dtype=np.float32) + 1)
    dm.set_group([2, 3])
    np.testing.assert_array_equal(dm.info.group_ptr, [0, 2, 5])


# ---------------------------------------------------------------- binning

def test_binning_roundtrip_dense():
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4).astype(np.float32)
    dm = DMatrix(X)
    cuts = compute_cuts(dm, max_bin=32)
    B = bin_matrix(dm, cuts)
    assert B.dtype == np.uint8
    assert B.shape == (500, 4)
    assert B.min() >= 1  # no missing in dense data
    # bin order preserves value order per feature
    f = 2
    order = np.argsort(X[:, f])
    assert np.all(np.diff(B[order, f].astype(int)) >= 0)
    # binning a dense matrix directly agrees with the CSR path
    np.testing.assert_array_equal(bin_dense(X, cuts), B)


def test_binning_missing_bin_zero():
    X = np.array([[1.0, np.nan], [2.0, 5.0], [3.0, 6.0]], dtype=np.float32)
    dm = DMatrix(X)
    cuts = compute_cuts(dm, max_bin=8)
    B = bin_matrix(dm, cuts)
    assert B[0, 1] == 0  # missing
    assert B[1, 1] >= 1


def test_binning_agaricus_binary_features():
    dm = DMatrix(AGARICUS_TRAIN)
    cuts = compute_cuts(dm, max_bin=256)
    B = bin_matrix(dm, cuts)
    assert B.shape[0] == 6513
    # agaricus is one-hot: present entries are all 1.0 and map to one bin
    # above the min-cut; absent entries are missing (bin 0)
    assert set(np.unique(B)) <= {0, 2}


def test_split_semantics_match_binning():
    # split at cut j: left iff v < cuts[j] iff bin <= j+1
    X = np.array([[0.0], [1.0], [2.0], [3.0]], dtype=np.float32)
    dm = DMatrix(X)
    cuts = compute_cuts(dm, max_bin=8)
    B = bin_matrix(dm, cuts)
    for j in range(cuts.n_cuts[0]):
        thr = cuts.cut_values[0, j]
        left_by_value = X[:, 0] < thr
        left_by_bin = B[:, 0] <= j + 1
        np.testing.assert_array_equal(left_by_value, left_by_bin)


def test_typed_info_accessors():
    """Generic get/set_float_info / get/set_uint_info (reference
    wrapper/xgboost.py:166-183)."""
    import pytest
    X = np.random.RandomState(0).rand(20, 3).astype(np.float32)
    d = DMatrix(X)
    # unset fields -> EMPTY arrays (reference parity: size==0 detects
    # unset, unlike get_weight()'s implicit ones)
    assert d.get_float_info("weight").size == 0
    assert d.get_uint_info("group_ptr").size == 0
    d.set_float_info("label", np.arange(20))
    np.testing.assert_array_equal(d.get_float_info("label"),
                                  np.arange(20, dtype=np.float32))
    d.set_float_info("weight", np.full(20, 2.0))
    np.testing.assert_array_equal(d.get_float_info("weight"),
                                  np.full(20, 2.0, np.float32))
    d.set_float_info("base_margin", np.full(20, 0.5))
    assert d.get_float_info("base_margin")[0] == np.float32(0.5)
    d.set_uint_info("root_index", np.zeros(20, np.uint32))
    assert d.get_uint_info("root_index").dtype == np.uint32
    assert d.get_uint_info("fold_index").size == 0  # unset -> empty
    with pytest.raises(ValueError):
        d.set_float_info("root_index", np.zeros(20))
    with pytest.raises(ValueError):
        d.get_uint_info("label")


def test_module_exports_reference_surface():
    """Module-level names a reference-wrapper user expects."""
    import xgboost_tpu as m
    for name in ("DMatrix", "Booster", "train", "cv", "mknfold", "aggcv",
                 "CVPack", "XGBModel", "XGBClassifier", "XGBRegressor"):
        assert hasattr(m, name), name


def test_set_uint_info_rejects_bad_values():
    import pytest
    X = np.zeros((4, 2), np.float32)
    d = DMatrix(X)
    with pytest.raises(ValueError):
        d.set_uint_info("root_index", np.array([-1, 0, 0, 0]))
    with pytest.raises(ValueError):
        d.set_uint_info("fold_index", np.array([0.5, 1, 2, 3]))


def test_bin_dense_device_matches_host():
    """Device-side quantization (binning.bin_dense_device, the
    prediction-time fast path) must agree bin-for-bin with the host
    searchsorted, including NaN -> missing bin 0."""
    import numpy as np
    import xgboost_tpu as xgb
    from xgboost_tpu.binning import (bin_dense_device, bin_matrix,
                                     compute_cuts)
    rng = np.random.RandomState(0)
    X = rng.rand(5000, 7).astype(np.float32)
    X[rng.rand(5000, 7) < 0.3] = np.nan
    # +inf values must land in the LAST real bin on both paths (the
    # device compare must not count the inf padding columns)
    X[rng.rand(5000, 7) < 0.02] = np.inf
    d = xgb.DMatrix(X)
    cuts = compute_cuts(d, max_bin=16)
    host = bin_matrix(d, cuts)
    dev = np.asarray(bin_dense_device(X, cuts.cut_values))
    np.testing.assert_array_equal(host, dev)
    # boundary values land in the same bin as the host side=right rule
    Xb = np.asarray(cuts.cut_values[:1, :3]).T.astype(np.float32)
    Xb = np.broadcast_to(Xb, (3, 7)).copy()
    db = xgb.DMatrix(Xb)
    np.testing.assert_array_equal(
        bin_matrix(db, cuts), np.asarray(bin_dense_device(
            Xb, cuts.cut_values)))


def test_explicit_nan_csr_is_missing_in_both_quantizers():
    """A CSR matrix STORING NaN entries must quantize them to the
    missing bin (0) on both the host searchsorted path and the device
    compare-reduce path — previously searchsorted sent NaN to the last
    bin, so the same data routed differently depending on which branch
    ran (advisor, round 4)."""
    import numpy as np
    import xgboost_tpu as xgb
    from xgboost_tpu.binning import bin_dense_device, bin_matrix, compute_cuts
    rng = np.random.RandomState(3)
    X = rng.rand(200, 4).astype(np.float32)
    d0 = xgb.DMatrix(X)
    cuts = compute_cuts(d0, max_bin=16)
    # CSR with every entry present, some values NaN
    vals = X.copy().ravel()
    vals[rng.rand(vals.size) < 0.2] = np.nan
    indptr = np.arange(0, X.size + 1, 4, dtype=np.int64)
    indices = np.tile(np.arange(4), 200).astype(np.int32)
    d = xgb.DMatrix((indptr, indices, vals, 4))
    host = bin_matrix(d, cuts)
    dev = np.asarray(bin_dense_device(vals.reshape(200, 4),
                                      cuts.cut_values))
    np.testing.assert_array_equal(host, dev)
    assert (host[np.isnan(vals.reshape(200, 4))] == 0).all()


def test_predict_sparse_input_skips_densify_fast_path():
    """Sparse one-off prediction inputs (<25% dense) keep the O(nnz)
    bin_matrix path instead of densifying host-side for the device
    quantizer (advisor, round 4); predictions agree with the cached-
    matrix path either way."""
    import numpy as np
    import xgboost_tpu as xgb
    rng = np.random.RandomState(7)
    n, f = 400, 12
    Xd = rng.rand(n, f).astype(np.float32)
    mask = rng.rand(n, f) < 0.9          # 10% dense
    Xs = Xd.copy()
    Xs[mask] = np.nan
    y = (np.nansum(Xs, axis=1) > np.nanmean(np.nansum(Xs, axis=1)))
    dtrain = xgb.DMatrix(Xs, label=y.astype(np.float32))
    bst = xgb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.5, "verbosity": 0}, dtrain, 5)
    p_cached = bst.predict(dtrain)

    # spy on the quantizers to assert ROUTING, not just parity: the
    # sparse input must take bin_matrix, never the densify+device path
    # (bin_dense_device is imported lazily inside predict -> patch the
    # binning module; bin_matrix is bound at learner import time ->
    # patch the learner's reference)
    import xgboost_tpu.binning as B
    import xgboost_tpu.learner as L
    calls = []
    real_dev, real_host = B.bin_dense_device, L.bin_matrix
    B.bin_dense_device = lambda *a, **k: (calls.append("dev"),
                                          real_dev(*a, **k))[1]
    L.bin_matrix = lambda *a, **k: (calls.append("host"),
                                    real_host(*a, **k))[1]
    try:
        p_oneoff = bst.predict(xgb.DMatrix(Xs))
        assert "dev" not in calls and "host" in calls, calls
        calls.clear()
        # dense input (100% present) takes the device fast path
        bst.predict(xgb.DMatrix(Xd))
        assert "dev" in calls, calls
    finally:
        B.bin_dense_device, L.bin_matrix = real_dev, real_host
    np.testing.assert_allclose(p_cached, p_oneoff, rtol=1e-5, atol=1e-6)

"""Degenerate-input robustness (the reference guards these with
utils::Check/Assert scattered through the core; here they must not
crash jitted code or produce NaNs)."""

import numpy as np
import pytest

import xgboost_tpu as xgb

P = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5}


def test_single_row():
    d = xgb.DMatrix(np.array([[1.0, 2.0]], np.float32), label=[1])
    bst = xgb.train(P, d, 2, verbose_eval=False)
    p = np.asarray(bst.predict(d))
    assert p.shape == (1,) and np.isfinite(p).all()


def test_constant_feature_never_split():
    """A feature with one distinct value has no cut candidates."""
    rng = np.random.RandomState(0)
    X = rng.rand(300, 3).astype(np.float32)
    X[:, 1] = 7.0
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train(P, d, 3, verbose_eval=False)
    used = {int(f) for t in bst.gbtree.trees
            for f in np.asarray(t.feature) if f >= 0}
    assert 1 not in used
    assert np.isfinite(np.asarray(bst.predict(d))).all()


def test_all_missing_feature():
    X = np.full((200, 2), np.nan, np.float32)
    X[:, 0] = np.random.RandomState(1).rand(200)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train(P, d, 3, verbose_eval=False)
    assert np.isfinite(np.asarray(bst.predict(d))).all()


def test_uniform_labels():
    """All-one-class data: no useful split, predictions drift toward the
    class, no NaNs/infs."""
    X = np.random.RandomState(2).rand(150, 4).astype(np.float32)
    d = xgb.DMatrix(X, label=np.ones(150, np.float32))
    bst = xgb.train(P, d, 3, verbose_eval=False)
    p = np.asarray(bst.predict(d))
    assert np.isfinite(p).all() and (p > 0.5).all()


def test_max_depth_zero_is_stump_free():
    """max_depth=0: the root itself is the only (leaf) node."""
    X = np.random.RandomState(3).rand(100, 3).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({**P, "max_depth": 0}, d, 2, verbose_eval=False)
    p = np.asarray(bst.predict(d))
    assert np.isfinite(p).all()
    # every tree is a single leaf: identical prediction for every row
    assert np.allclose(p, p[0])


def test_extreme_eta_and_regularization():
    X = np.random.RandomState(4).rand(200, 3).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    for extra in ({"eta": 10.0}, {"reg_lambda": 1e6}, {"reg_alpha": 1e6},
                  {"min_child_weight": 1e9}, {"max_delta_step": 0.01}):
        bst = xgb.train({**P, **extra}, d, 2, verbose_eval=False)
        assert np.isfinite(np.asarray(bst.predict(d))).all(), extra


def test_more_bins_than_rows():
    X = np.random.RandomState(5).rand(10, 2).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    d = xgb.DMatrix(X, label=y)
    bst = xgb.train({**P, "max_bin": 256}, d, 2, verbose_eval=False)
    assert np.isfinite(np.asarray(bst.predict(d))).all()


def test_predict_fewer_features_than_model():
    """A test matrix whose max feature index is below the model's
    num_feature must still predict (absent columns = missing)."""
    rng = np.random.RandomState(6)
    X = rng.rand(300, 5).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    bst = xgb.train(P, xgb.DMatrix(X, label=y), 3, verbose_eval=False)
    d_small = xgb.DMatrix((np.array([0, 1]), np.array([0]),
                           np.array([0.7], np.float32), 2))  # CSR, 2 cols
    p = np.asarray(bst.predict(d_small))
    assert p.shape == (1,) and np.isfinite(p).all()


def test_zero_weight_rows_ignored():
    rng = np.random.RandomState(7)
    X = rng.rand(400, 4).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(np.float32)
    # poison half the labels but zero their weights
    y2 = y.copy()
    y2[200:] = 1 - y2[200:]
    w = np.ones(400, np.float32)
    w[200:] = 0.0
    d_poison = xgb.DMatrix(X, label=y2, weight=w)
    d_clean = xgb.DMatrix(X[:200], label=y[:200])
    b1 = xgb.train(P, d_poison, 3, verbose_eval=False)
    b2 = xgb.train(P, d_clean, 3, verbose_eval=False)
    # zero-weight rows contribute no gradients: same error profile on
    # the clean half
    p1 = np.asarray(b1.predict(d_clean)) > 0.5
    p2 = np.asarray(b2.predict(d_clean)) > 0.5
    assert (p1 != y[:200]).mean() <= (p2 != y[:200]).mean() + 0.05


def test_nan_label_rejected():
    X = np.random.RandomState(8).rand(50, 2).astype(np.float32)
    y = np.full(50, np.nan, np.float32)
    d = xgb.DMatrix(X, label=y)
    with pytest.raises((ValueError, AssertionError)):
        xgb.train(P, d, 1, verbose_eval=False)


def test_nan_label_rejected_softmax():
    X = np.random.RandomState(9).rand(50, 2).astype(np.float32)
    y = np.zeros(50, np.float32)
    y[3] = np.nan
    d = xgb.DMatrix(X, label=y)
    with pytest.raises(ValueError):
        xgb.train({"objective": "multi:softmax", "num_class": 3,
                   "max_depth": 2}, d, 1, verbose_eval=False)

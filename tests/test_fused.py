"""Fused multi-round boosting (GBTree.do_boost_fused / Booster.update_many):
the scan-over-rounds launch must reproduce the per-round path exactly —
same fold_in keys, same kernels, same margin updates."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import xgboost_tpu as xgb  # noqa: E402
from xgboost_tpu.learner import Booster  # noqa: E402


def make_data(n=2000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    y = ((X[:, 0] + 0.3 * X[:, 1] > 0.6) ^ (X[:, 2] > 0.7)).astype(
        np.float32)
    return X, y


def seq_train(params, dtrain, n_rounds):
    bst = Booster(params, cache=[dtrain])
    for i in range(n_rounds):
        bst.update(dtrain, i)
    return bst


PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.4}


def _assert_same_model(b1, b2, d):
    assert b1.gbtree.num_trees == b2.gbtree.num_trees
    p1 = np.asarray(b1.predict(d))
    p2 = np.asarray(b2.predict(d))
    np.testing.assert_array_equal(p1, p2)


def test_fused_matches_sequential_binary():
    X, y = make_data()
    d = xgb.DMatrix(X, label=y)
    b_seq = seq_train(PARAMS, d, 6)
    d2 = xgb.DMatrix(X, label=y)
    b_fused = Booster(PARAMS, cache=[d2])
    b_fused.update_many(d2, 0, 6)
    _assert_same_model(b_seq, b_fused, d)


def test_fused_matches_sequential_subsample():
    """Row/column subsampling draws from per-round fold_in keys — the
    fused path must replay the identical key schedule."""
    X, y = make_data(seed=1)
    params = {**PARAMS, "subsample": 0.7, "colsample_bytree": 0.8,
              "seed": 9}
    d = xgb.DMatrix(X, label=y)
    b_seq = seq_train(params, d, 5)
    d2 = xgb.DMatrix(X, label=y)
    b_fused = Booster(params, cache=[d2])
    b_fused.update_many(d2, 0, 5)
    _assert_same_model(b_seq, b_fused, d)


def test_fused_matches_sequential_multiclass():
    rng = np.random.RandomState(3)
    X = rng.rand(1200, 6).astype(np.float32)
    y = (X[:, 0] * 3).astype(np.int32).clip(0, 2).astype(np.float32)
    params = {"objective": "multi:softprob", "num_class": 3,
              "max_depth": 3, "eta": 0.3}
    d = xgb.DMatrix(X, label=y)
    b_seq = seq_train(params, d, 4)
    d2 = xgb.DMatrix(X, label=y)
    b_fused = Booster(params, cache=[d2])
    b_fused.update_many(d2, 0, 4)
    _assert_same_model(b_seq, b_fused, d)


def test_fused_dsplit_row():
    """Fused rounds over the 8-device data-parallel mesh."""
    X, y = make_data(n=2003, seed=4)  # odd rows exercise padding
    params = {**PARAMS, "dsplit": "row"}
    d = xgb.DMatrix(X, label=y)
    b_seq = seq_train(params, d, 4)
    d2 = xgb.DMatrix(X, label=y)
    b_fused = Booster(params, cache=[d2])
    b_fused.update_many(d2, 0, 4)
    _assert_same_model(b_seq, b_fused, d)


def test_train_uses_fused_path_without_evals():
    """xgb.train with no evals routes through update_many and yields the
    same model as the eval'd sequential train."""
    X, y = make_data(seed=5)
    d1 = xgb.DMatrix(X, label=y)
    res = {}
    b1 = xgb.train(PARAMS, d1, 5, evals=[(d1, "train")],
                   evals_result=res, verbose_eval=False)
    d2 = xgb.DMatrix(X, label=y)
    b2 = xgb.train(PARAMS, d2, 5, verbose_eval=False)
    _assert_same_model(b1, b2, d1)


def test_fused_fallback_paths_still_work():
    """gamma>0 (host-side pruning) and gblinear fall back to per-round
    updates inside update_many."""
    X, y = make_data(seed=6)
    d = xgb.DMatrix(X, label=y)
    params = {**PARAMS, "gamma": 0.5}
    b1 = seq_train(params, d, 3)
    d2 = xgb.DMatrix(X, label=y)
    b2 = Booster(params, cache=[d2])
    b2.update_many(d2, 0, 3)
    _assert_same_model(b1, b2, d)

    lin = {"booster": "gblinear", "objective": "binary:logistic",
           "eta": 0.5}
    d3 = xgb.DMatrix(X, label=y)
    b3 = xgb.train(lin, d3, 3, verbose_eval=False)  # train() fused branch
    assert np.isfinite(np.asarray(b3.predict(d3))).all()


def test_fused_continue_training():
    """update_many after prior rounds continues the iteration numbering
    (seed schedule) exactly like sequential updates."""
    X, y = make_data(seed=7)
    d = xgb.DMatrix(X, label=y)
    b1 = seq_train(PARAMS, d, 6)
    d2 = xgb.DMatrix(X, label=y)
    b2 = Booster(PARAMS, cache=[d2])
    b2.update(d2, 0)
    b2.update(d2, 1)
    b2.update_many(d2, 2, 4)
    _assert_same_model(b1, b2, d)
